"""REST route table + handlers — the API surface.

Analog of ``rest/RestController.java:250`` (dispatch) and the
``rest/action/**`` handler classes, driven by the same path shapes the
rest-api-spec JSON contract defines.  Transport-agnostic: the HTTP server
calls ``dispatch(method, path, params, body)`` and gets (status, dict).
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Callable, Optional

from opensearch_tpu.common.errors import (
    DocumentMissingError,
    IllegalArgumentError,
    IndexNotFoundError,
    OpenSearchTpuError,
    ParsingError,
    ResourceNotFoundError,
    ValidationError,
)
from opensearch_tpu.version import __version__ as VERSION


class RestRequest:
    def __init__(self, method: str, path: str, params: dict,
                 body: Optional[bytes], content_type: str = ""):
        self.method = method
        self.path = path
        self.params = params or {}
        self.raw_body = body or b""
        self.content_type = content_type
        self.path_params: dict[str, str] = {}

    def json(self, default=None):
        """Structured body, negotiated by Content-Type (JSON default;
        YAML/CBOR via x-content, ref libs/x-content XContentType)."""
        if not self.raw_body:
            return default
        from opensearch_tpu.common.xcontent import from_bytes
        return from_bytes(self.raw_body, self.content_type)

    def param(self, name: str, default=None):
        return self.params.get(name, self.path_params.get(name, default))

    def int_param(self, name: str):
        """Integer query param, or None when absent — garbage is a
        typed 400 (the reference's number_format_exception), never a
        raw ValueError 500."""
        v = self.param(name)
        if v is None:
            return None
        try:
            return int(v)
        except (TypeError, ValueError):
            from opensearch_tpu.common.errors import IllegalArgumentError
            raise IllegalArgumentError(
                f"[{name}] must be an integer, got [{v}]")

    def flag(self, name: str) -> bool:
        v = self.params.get(name)
        return v is not None and str(v).lower() in ("", "true", "1")


def _os_stats() -> dict:
    """OsProbe analog over stdlib (loadavg + memory via sysconf)."""
    import os as _os

    try:
        la1, la5, la15 = _os.getloadavg()
    except OSError:
        la1 = la5 = la15 = 0.0
    try:
        page = _os.sysconf("SC_PAGE_SIZE")
        total = _os.sysconf("SC_PHYS_PAGES") * page
        free = _os.sysconf("SC_AVPHYS_PAGES") * page
    except (ValueError, OSError):
        total = free = 0
    return {"cpu": {"load_average": {"1m": la1, "5m": la5, "15m": la15}},
            "mem": {"total_in_bytes": total, "free_in_bytes": free}}


def _device_stats() -> dict:
    """The ``device`` section of ``_nodes/stats``: the residency
    ledger's rollups (common/device_ledger.py) — resident bytes per
    index, host↔device transfer counters split stage vs fetch-back,
    budget/eviction/restage accounting, and the per-kernel XLA compile
    registry, next to the jax backend's own ``memory_stats()`` where
    the platform provides it — plus the ``health`` block: the
    per-kernel-class circuit breakers' states, trip/close counters and
    the result-sanity guard's poisoned-result count
    (common/device_health.py)."""
    from opensearch_tpu.common.device_health import device_health
    from opensearch_tpu.common.device_ledger import device_ledger
    return {**device_ledger().stats(), "health": device_health().stats()}


def _query_engine_stats() -> dict:
    """The unified engine's `_nodes/stats` block (continuous batcher +
    search threadpool accounting, search/engine.py)."""
    from opensearch_tpu.search.engine import query_engine
    return query_engine().stats()


def _process_stats() -> dict:
    """ProcessProbe analog: CURRENT rss from /proc statm (linux), peak
    rss from getrusage (kbytes on linux, bytes on darwin)."""
    import resource
    import sys as _sys

    ru = resource.getrusage(resource.RUSAGE_SELF)
    peak = ru.ru_maxrss * (1 if _sys.platform == "darwin" else 1024)
    resident = peak
    try:
        with open("/proc/self/statm") as f:
            import os as _os
            resident = int(f.read().split()[1]) * _os.sysconf(
                "SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    return {"cpu": {"total_in_millis": int(
        (ru.ru_utime + ru.ru_stime) * 1000)},
        "mem": {"resident_in_bytes": resident,
                "peak_resident_in_bytes": peak},
        "open_file_descriptors": _count_fds()}


def _count_fds() -> int:
    import os as _os

    try:
        return len(_os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def _nest_settings(flat: dict) -> dict:
    """Dotted settings keys -> the nested tree the reference's
    Settings.toXContent(flat_settings=false) renders."""
    out: dict = {}
    for key, v in flat.items():
        node = out
        parts = str(key).split(".")
        for p in parts[:-1]:
            nxt = node.get(p)
            if not isinstance(nxt, dict):
                nxt = node[p] = {}
            node = nxt
        node[parts[-1]] = v
    return out


def _flatten_nulls(d: dict, prefix: str = ""):
    """Yield (dotted_key, None) for nulls nested anywhere in a settings
    body (Settings flattening drops them, but null means RESET)."""
    for k, v in d.items():
        key = f"{prefix}{k}"
        if v is None:
            yield key, None
        elif isinstance(v, dict):
            yield from _flatten_nulls(v, key + ".")


def _total_hits_as_int(resp: dict):
    """?rest_total_hits_as_int=true: render hits.total as the pre-7.0
    integer (RestSearchAction.TOTAL_HITS_AS_INT_PARAM), including per
    sub-response in _msearch."""
    hits = resp.get("hits")
    if isinstance(hits, dict) and isinstance(hits.get("total"), dict):
        hits["total"] = hits["total"].get("value", 0)
    for sub in resp.get("responses") or []:
        if isinstance(sub, dict):
            _total_hits_as_int(sub)


class PlainText:
    """Marker payload: the HTTP layer writes ``text`` verbatim with the
    given content type instead of running x-content negotiation — the
    Prometheus exposition format is text, not JSON."""

    __slots__ = ("text", "content_type")

    def __init__(self, text: str,
                 content_type: str = "text/plain; charset=UTF-8"):
        self.text = text
        self.content_type = content_type


class Route:
    def __init__(self, method: str, pattern: str, handler: Callable):
        self.method = method
        parts = []
        self.names: list[str] = []
        for seg in pattern.strip("/").split("/"):
            if seg.startswith("{"):
                self.names.append(seg[1:-1])
                parts.append(r"([^/]+)")
            else:
                parts.append(re.escape(seg))
        self.rx = re.compile("^/" + "/".join(parts) + "$")
        self.handler = handler


class RestController:
    def __init__(self, node):
        self.node = node
        self.routes: list[Route] = []
        self._register_all()

    def register(self, method: str, pattern: str, handler: Callable):
        self.routes.append(Route(method, pattern, handler))

    # handler-name -> transport-style action name (the reference's task
    # actions; unlisted handlers register as rest:<handler>)
    _ACTIONS = {
        "h_search": "indices:data/read/search",
        "h_msearch": "indices:data/read/msearch",
        "h_scroll_next": "indices:data/read/scroll",
        "h_bulk": "indices:data/write/bulk",
        "h_count": "indices:data/read/count",
        "h_create_snapshot": "cluster:admin/snapshot/create",
        "h_restore_snapshot": "cluster:admin/snapshot/restore",
    }

    def dispatch(self, method: str, path: str, params: dict,
                 body: Optional[bytes], content_type: str = "",
                 authorization: str = "",
                 headers: Optional[dict] = None,
                 response_headers: Optional[dict] = None
                 ) -> tuple[int, dict]:
        """``response_headers``: optional out-channel the HTTP layer
        passes so error mappings can attach headers (Retry-After on
        backpressure rejections) without changing the return shape."""
        import contextlib

        from opensearch_tpu.common import tasks as taskmod
        from opensearch_tpu.common.telemetry import metrics, tracer
        from opensearch_tpu.common.threadpool import RejectedExecutionError

        headers = headers or {}
        # request attribution: X-Opaque-Id threads into the task and all
        # downstream transport requests (Task.java HEADERS_TO_COPY)
        opaque_id = None
        for k, v in headers.items():
            if str(k).lower() == "x-opaque-id":
                opaque_id = v
                break
        req = RestRequest(method, path, params, body, content_type)
        try:
            identity = getattr(self.node, "identity", None)
            principal = (identity.check(method, path, authorization)
                         if identity is not None else None)
            for route in self.routes:
                if route.method != method:
                    continue
                m = route.rx.match(path.rstrip("/") or "/")
                if m:
                    # percent-decode captured segments: /index/_doc/中文
                    # arrives as %E4%B8%AD%E6%96%87 (RestRequest.java
                    # decodes the same way)
                    from urllib.parse import unquote
                    req.path_params = dict(zip(
                        route.names, (unquote(g) for g in m.groups())))
                    # every request runs as a registered, cancellable
                    # task (TaskManager.register analog); device loops
                    # check the contextvar between segment programs
                    handler_name = getattr(route.handler, "__name__", "?")
                    if identity is not None:
                        # authorize on the MATCHED route, not the raw
                        # path — path suffixes are forgeable via ids
                        identity.authorize(principal, method, path,
                                           handler_name)
                    action = self._ACTIONS.get(handler_name,
                                               f"rest:{handler_name}")
                    task_headers = ({"X-Opaque-Id": opaque_id}
                                    if opaque_id else None)
                    task = self.node.task_manager.register(
                        action, f"{method} {path}",
                        headers=task_headers)
                    token = taskmod.set_current(task)
                    # root span: honors an incoming W3C traceparent so
                    # client-initiated traces continue through the node
                    attrs = {"http.method": method, "http.path": path,
                             "action": action,
                             "node": getattr(self.node, "node_id",
                                             self.node.name)}
                    if opaque_id:
                        attrs["x_opaque_id"] = opaque_id
                    # search admission: a permit gate at the REST edge —
                    # saturated nodes reject (429 + Retry-After) instead
                    # of queueing unboundedly (the search_backpressure
                    # admission-control half)
                    # the client's X-Opaque-Id doubles as the tenant
                    # key: named tenants draw from their carved
                    # admission share, everyone else from the default
                    # pool (search.qos.tenant_shares)
                    bp = getattr(self.node, "search_backpressure", None)
                    admission = (bp.admission.acquire(handler_name,
                                                      tenant=opaque_id)
                                 if bp is not None and action in (
                                     "indices:data/read/search",
                                     "indices:data/read/msearch")
                                 else contextlib.nullcontext())
                    from opensearch_tpu.search import insights
                    searchish = action in ("indices:data/read/search",
                                           "indices:data/read/msearch")
                    try:
                        with admission, tracer().start_span(
                                f"rest:{action}", attributes=attrs,
                                parent=tracer().extract(headers)) as span, \
                                metrics().time_ms("rest.request_ms"), \
                                insights.collecting() as sink:
                            metrics().counter("rest.requests").inc()
                            status, resp = route.handler(req)
                            span.set_attribute("http.status", status)
                        if searchish and sink:
                            # edge-side insight enrichment: the records
                            # the execution layers emitted gain what
                            # only this layer knows — the client's
                            # X-Opaque-Id, the task's measured CPU/heap,
                            # and the response-level outcome
                            self._record_insights(sink, resp, status,
                                                  task, opaque_id)
                        if searchish:
                            # close the loop: the QoS controller gets a
                            # paced evaluation tick with the freshest
                            # admission/insights evidence (no-op when
                            # search.qos.adaptive is off)
                            qos = getattr(self.node, "qos", None)
                            if qos is not None:
                                qos.maybe_tick()
                        if params.get("rest_total_hits_as_int") == "true" \
                                and isinstance(resp, dict):
                            _total_hits_as_int(resp)
                        return status, resp
                    finally:
                        taskmod.reset_current(token)
                        self.node.task_manager.unregister(task)
            # method-mismatch vs not-found distinction
            if any(r.rx.match(path.rstrip("/") or "/") for r in self.routes):
                return 405, {"error": f"Incorrect HTTP method for uri [{path}]"
                                      f" and method [{method}]", "status": 405}
            return 400, {"error": {
                "type": "illegal_argument_exception",
                "reason": f"no handler found for uri [{path}] and method "
                          f"[{method}]"}, "status": 400}
        except OpenSearchTpuError as e:
            # overload rejections (thread-pool RejectedExecutionError,
            # admission/backpressure SearchRejectedError) ship a
            # Retry-After header and count in search.rejected so clients
            # and dashboards see the shed load, not just 429s
            from opensearch_tpu.search.backpressure import \
                SearchRejectedError
            if isinstance(e, (RejectedExecutionError,
                              SearchRejectedError)):
                metrics().counter("search.rejected").inc()
                insights = getattr(self.node, "insights", None)
                if insights is not None:
                    # rejected before any plan existed: counted in the
                    # insights totals (shed load is workload evidence),
                    # never a ring entry — attributed to the tenant
                    insights.record_rejected(opaque_id=opaque_id)
            if getattr(e, "status", None) == 429 \
                    and response_headers is not None:
                # EVERY 429 carries the hint — duress and circuit-
                # breaker rejections are as retryable as admission
                # ones, and a hintless 429 leaves clients guessing
                response_headers["Retry-After"] = str(
                    int(getattr(e, "retry_after_seconds", 1)))
            # transport-layer failures (NodeDisconnectedError /
            # ReceiveTimeoutError / NoMasterError) carry status 503 on
            # the class: the condition is retryable and the serialized
            # body keeps the precise error.type for clients
            return e.status, e.to_xcontent()
        except (TimeoutError, ConnectionError) as e:
            # stdlib-level transport failures get the same 503 treatment
            return 503, {"error": {"type": "node_disconnected_exception",
                                   "reason": f"{type(e).__name__}: {e}"},
                         "status": 503}
        except Exception as e:  # noqa: BLE001 — the REST boundary
            return 500, {"error": {"type": "internal_server_error",
                                   "reason": f"{type(e).__name__}: {e}"},
                         "status": 500}

    def _record_insights(self, sink: list, resp, status: int, task,
                         opaque_id) -> None:
        """Drain one request's emitted insight records into the node's
        QueryInsightsService, enriched with edge-only attribution."""
        service = getattr(self.node, "insights", None)
        if service is None or not service.enabled:
            return
        # fold un-checkpointed CPU into the task before reading it
        task.record_checkpoint()
        rs = task.resource_stats()
        cpu = int(rs.get("cpu_time_in_nanos", 0))
        heap = int(rs.get("peak_heap_size_in_bytes", 0))
        outcome = None
        if isinstance(resp, dict):
            shards = resp.get("_shards") or {}
            if status >= 500:
                outcome = "error"
            elif status == 429:
                outcome = "429"
            elif resp.get("timed_out"):
                outcome = "timeout"
            elif shards.get("failed"):
                failures = shards.get("failures") or []
                types = {(f.get("reason") or {}).get("type")
                         for f in failures}
                # duress sheds and device degradation get their own
                # outcome classes (workload attribution must show WHO
                # the breaker/shed degraded, not a generic "partial")
                outcome = ("shed" if "node_duress_exception" in types
                           else "device_degraded"
                           if "device_degraded_exception" in types
                           else "partial")
        n = len(sink) or 1
        for rec in sink:
            service.record(rec, opaque_id=opaque_id,
                           cpu_nanos=cpu // n, heap_bytes=heap,
                           outcome=outcome)

    # ------------------------------------------------------------------

    def _register_all(self):
        r = self.register
        r("GET", "/", self.h_root)
        r("GET", "/_cluster/health", self.h_cluster_health)
        r("GET", "/_cluster/state", self.h_cluster_state)
        r("GET", "/_cluster/stats", self.h_cluster_stats)
        r("GET", "/_nodes", self.h_nodes_info)
        r("GET", "/_nodes/stats", self.h_nodes_stats)
        r("GET", "/_nodes/trace", self.h_nodes_trace)
        r("GET", "/_nodes/hot_threads", self.h_hot_threads)
        r("GET", "/_nodes/flight_recorder", self.h_flight_recorder)
        r("GET", "/_insights/top_queries", self.h_insights_top_queries)
        r("GET", "/_metrics", self.h_metrics)
        r("GET", "/_cluster/settings", self.h_cluster_get_settings)
        r("PUT", "/_cluster/settings", self.h_cluster_put_settings)
        r("GET", "/_cat/indices", self.h_cat_indices)
        r("GET", "/_cat/health", self.h_cat_health)
        r("GET", "/_cat/count", self.h_cat_count)
        r("GET", "/_cat/count/{index}", self.h_cat_count)
        r("GET", "/_cat/shards", self.h_cat_shards)
        r("GET", "/_cat/nodes", self.h_cat_nodes)
        r("GET", "/_cat/aliases", self.h_cat_aliases)
        r("GET", "/_cat/templates", self.h_cat_templates)
        r("GET", "/_cat/segments", self.h_cat_segments)
        r("GET", "/_cat/recovery", self.h_cat_recovery)
        r("GET", "/_cat/recovery/{index}", self.h_cat_recovery)
        r("GET", "/_cat/repositories", self.h_cat_repositories)
        r("GET", "/_cat/snapshots/{repo}", self.h_cat_snapshots)
        r("GET", "/_cat/tasks", self.h_cat_tasks)
        r("GET", "/_cat/thread_pool", self.h_cat_thread_pool)
        r("GET", "/_cat/pending_tasks", self.h_cat_pending_tasks)
        r("GET", "/_cat/plugins", self.h_cat_plugins)
        r("GET", "/_cat/cluster_manager", self.h_cat_cluster_manager)
        r("GET", "/_cat/master", self.h_cat_cluster_manager)
        r("GET", "/_cat/nodeattrs", self.h_cat_nodeattrs)
        r("GET", "/_cat/allocation", self.h_cat_allocation)
        r("GET", "/_cat/fielddata", self.h_cat_fielddata)
        r("POST", "/_aliases", self.h_update_aliases)
        r("GET", "/_alias", self.h_get_alias)
        r("GET", "/_alias/{name}", self.h_get_alias)
        r("HEAD", "/_alias/{name}", self.h_alias_exists)
        r("GET", "/{index}/_alias", self.h_get_alias)
        r("PUT", "/{index}/_alias/{name}", self.h_put_alias)
        r("POST", "/{index}/_alias/{name}", self.h_put_alias)
        r("DELETE", "/{index}/_alias/{name}", self.h_delete_alias)
        r("POST", "/{index}/_rollover", self.h_rollover)
        r("POST", "/{index}/_rollover/{target}", self.h_rollover)
        r("PUT", "/{index}/_shrink/{target}", self.h_resize_shrink)
        r("POST", "/{index}/_shrink/{target}", self.h_resize_shrink)
        r("PUT", "/{index}/_split/{target}", self.h_resize_split)
        r("POST", "/{index}/_split/{target}", self.h_resize_split)
        r("PUT", "/{index}/_clone/{target}", self.h_resize_clone)
        r("POST", "/{index}/_clone/{target}", self.h_resize_clone)
        r("GET", "/{index}/_recovery", self.h_recovery)
        r("GET", "/_recovery", self.h_recovery)
        r("PUT", "/_data_stream/{name}", self.h_create_data_stream)
        r("GET", "/_data_stream", self.h_get_data_stream)
        r("GET", "/_data_stream/{name}", self.h_get_data_stream)
        r("DELETE", "/_data_stream/{name}", self.h_delete_data_stream)
        r("POST", "/_cluster/reroute", self.h_reroute)
        r("PUT", "/_index_template/{name}", self.h_put_template)
        r("POST", "/_index_template/{name}", self.h_put_template)
        r("GET", "/_index_template", self.h_get_template)
        r("GET", "/_index_template/{name}", self.h_get_template)
        r("DELETE", "/_index_template/{name}", self.h_delete_template)
        r("GET", "/_rank_eval", self.h_rank_eval)
        r("POST", "/_rank_eval", self.h_rank_eval)
        r("GET", "/{index}/_rank_eval", self.h_rank_eval)
        r("POST", "/{index}/_rank_eval", self.h_rank_eval)
        r("POST", "/_reindex", self.h_reindex)
        r("POST", "/{index}/_update_by_query", self.h_update_by_query)
        r("POST", "/{index}/_delete_by_query", self.h_delete_by_query)
        r("GET", "/_field_caps", self.h_field_caps)
        r("POST", "/_field_caps", self.h_field_caps)
        r("GET", "/{index}/_field_caps", self.h_field_caps)
        r("POST", "/{index}/_field_caps", self.h_field_caps)
        r("GET", "/{index}/_termvectors/{id}", self.h_termvectors)
        r("POST", "/{index}/_termvectors/{id}", self.h_termvectors)
        r("PUT", "/_ingest/pipeline/{id}", self.h_put_ingest)
        r("GET", "/_ingest/pipeline", self.h_get_ingest)
        r("GET", "/_ingest/pipeline/{id}", self.h_get_ingest)
        r("DELETE", "/_ingest/pipeline/{id}", self.h_delete_ingest)
        r("POST", "/_ingest/pipeline/{id}/_simulate",
          self.h_simulate_ingest)
        r("POST", "/_ingest/pipeline/_simulate", self.h_simulate_ingest)
        r("GET", "/_analyze", self.h_analyze)
        r("POST", "/_analyze", self.h_analyze)
        r("GET", "/{index}/_analyze", self.h_analyze)
        r("POST", "/{index}/_analyze", self.h_analyze)
        r("POST", "/_bulk", self.h_bulk)
        r("PUT", "/_bulk", self.h_bulk)
        r("POST", "/{index}/_bulk", self.h_bulk)
        r("PUT", "/{index}/_bulk", self.h_bulk)
        r("GET", "/_search", self.h_search)
        r("POST", "/_search", self.h_search)
        r("GET", "/_msearch", self.h_msearch)
        r("POST", "/_msearch", self.h_msearch)
        r("GET", "/_search/scroll", self.h_scroll_next)
        r("POST", "/_search/scroll", self.h_scroll_next)
        r("GET", "/_search/scroll/{scroll_id}", self.h_scroll_next)
        r("POST", "/_search/scroll/{scroll_id}", self.h_scroll_next)
        r("DELETE", "/_search/scroll/_all", self.h_scroll_clear_all)
        r("DELETE", "/_search/scroll", self.h_scroll_clear)
        r("DELETE", "/_search/scroll/{scroll_id}", self.h_scroll_clear)
        r("DELETE", "/_search/point_in_time", self.h_pit_close)
        r("GET", "/_search/pipeline", self.h_get_pipelines)
        r("GET", "/_search/pipeline/{id}", self.h_get_pipeline)
        r("PUT", "/_search/pipeline/{id}", self.h_put_pipeline)
        r("DELETE", "/_search/pipeline/{id}", self.h_delete_pipeline)
        r("GET", "/_count", self.h_count)
        r("POST", "/_count", self.h_count)
        r("GET", "/_mapping", self.h_get_mapping_all)
        r("GET", "/_refresh", self.h_refresh)
        r("POST", "/_refresh", self.h_refresh)
        r("GET", "/_security/user", self.h_security_list_users)
        r("PUT", "/_security/user/{username}", self.h_security_put_user)
        r("DELETE", "/_security/user/{username}",
          self.h_security_delete_user)
        r("GET", "/_tasks", self.h_tasks_list)
        r("GET", "/_persistent_tasks", self.h_persistent_tasks_list)
        r("GET", "/_tasks/{task_id}", self.h_task_get)
        r("POST", "/_tasks/{task_id}/_cancel", self.h_task_cancel)
        r("POST", "/_tasks/_cancel", self.h_tasks_cancel_all)
        r("POST", "/_remotestore/_restore", self.h_remotestore_restore)
        r("GET", "/_snapshot", self.h_get_repos)
        r("PUT", "/_snapshot/{repo}", self.h_put_repo)
        r("POST", "/_snapshot/{repo}", self.h_put_repo)
        r("GET", "/_snapshot/{repo}", self.h_get_repo)
        r("DELETE", "/_snapshot/{repo}", self.h_delete_repo)
        r("PUT", "/_snapshot/{repo}/{snapshot}", self.h_create_snapshot)
        r("POST", "/_snapshot/{repo}/{snapshot}", self.h_create_snapshot)
        r("GET", "/_snapshot/{repo}/{snapshot}", self.h_get_snapshot)
        r("DELETE", "/_snapshot/{repo}/{snapshot}", self.h_delete_snapshot)
        r("POST", "/_snapshot/{repo}/{snapshot}/_restore",
          self.h_restore_snapshot)

        r("PUT", "/{index}", self.h_create_index)
        r("DELETE", "/{index}", self.h_delete_index)
        r("GET", "/{index}", self.h_get_index)
        r("HEAD", "/{index}", self.h_index_exists)
        r("GET", "/{index}/_mapping", self.h_get_mapping)
        r("PUT", "/{index}/_mapping", self.h_put_mapping)
        r("GET", "/{index}/_settings", self.h_get_settings)
        r("PUT", "/{index}/_settings", self.h_put_index_settings)
        r("GET", "/{index}/_stats", self.h_index_stats)
        r("POST", "/{index}/_refresh", self.h_refresh)
        r("GET", "/{index}/_refresh", self.h_refresh)
        r("POST", "/_cache/clear", self.h_cache_clear)
        r("POST", "/{index}/_cache/clear", self.h_cache_clear)
        r("POST", "/{index}/_flush", self.h_flush)
        r("POST", "/{index}/_forcemerge", self.h_forcemerge)
        r("GET", "/{index}/_count", self.h_count)
        r("POST", "/{index}/_count", self.h_count)
        r("GET", "/{index}/_search", self.h_search)
        r("POST", "/{index}/_search", self.h_search)
        r("GET", "/{index}/_msearch", self.h_msearch)
        r("POST", "/{index}/_msearch", self.h_msearch)
        r("POST", "/{index}/_search/point_in_time", self.h_pit_open)
        r("POST", "/{index}/_doc", self.h_index_doc_auto)
        r("PUT", "/{index}/_doc/{id}", self.h_index_doc)
        r("POST", "/{index}/_doc/{id}", self.h_index_doc)
        r("GET", "/{index}/_doc/{id}", self.h_get_doc)
        r("HEAD", "/{index}/_doc/{id}", self.h_doc_exists)
        r("DELETE", "/{index}/_doc/{id}", self.h_delete_doc)
        r("GET", "/{index}/_source/{id}", self.h_get_source)
        r("PUT", "/{index}/_create/{id}", self.h_create_doc)
        r("POST", "/{index}/_create/{id}", self.h_create_doc)
        r("POST", "/{index}/_update/{id}", self.h_update_doc)
        r("POST", "/_mget", self.h_mget)
        r("POST", "/{index}/_mget", self.h_mget)
        r("GET", "/{index}/_mget", self.h_mget)

    # -- info / cluster ----------------------------------------------------

    def h_root(self, req):
        return 200, {
            "name": self.node.name,
            "cluster_name": self.node.cluster_name,
            "cluster_uuid": self.node.cluster_uuid,
            "version": {"number": VERSION,
                        "distribution": "opensearch-tpu"},
            "tagline": "The OpenSearch Project: https://opensearch.org/",
        }

    def h_cluster_health(self, req):
        indices = self.node.indices.indices
        unassigned = sum(s.num_replicas * s.num_shards
                         for s in indices.values())
        active = sum(s.num_shards for s in indices.values())
        status = "yellow" if unassigned else "green"
        # a shard copy that failed store verification (corruption
        # marker on disk) makes the cluster red — Store.verify /
        # CorruptedFileException surfaced the way the reference fails
        # the shard
        corrupted = {name: sorted(svc.corrupted_shards())
                     for name, svc in indices.items()
                     if svc.corrupted_shards()}
        if corrupted:
            status = "red"
        extra = ({"corrupted_shards": sum(len(v)
                                          for v in corrupted.values())}
                 if corrupted else {})
        return 200, {
            **extra,
            "cluster_name": self.node.cluster_name,
            "status": status,
            "timed_out": False,
            "discovered_master": True,
            "discovered_cluster_manager": True,
            "number_of_nodes": 1,
            "number_of_data_nodes": 1,
            "active_primary_shards": active,
            "active_shards": active,
            "relocating_shards": 0,
            "initializing_shards": 0,
            "unassigned_shards": unassigned,
            "delayed_unassigned_shards": 0,
            "number_of_pending_tasks": 0,
            "number_of_in_flight_fetch": 0,
            "task_max_waiting_in_queue_millis": 0,
            "active_shards_percent_as_number": 100.0,
            **self._health_indices_level(req, indices),
        }

    def _health_indices_level(self, req, indices) -> dict:
        """?level=indices|shards adds the per-index (and per-shard)
        breakdown (ClusterHealthResponse levels)."""
        level = req.param("level", "cluster")
        if level not in ("indices", "shards"):
            return {}
        out = {}
        for name, svc in indices.items():
            st = "yellow" if svc.num_replicas else "green"
            entry = {
                "status": st,
                "number_of_shards": svc.num_shards,
                "number_of_replicas": svc.num_replicas,
                "active_primary_shards": svc.num_shards,
                "active_shards": svc.num_shards,
                "relocating_shards": 0,
                "initializing_shards": 0,
                "unassigned_shards": svc.num_replicas * svc.num_shards,
            }
            if level == "shards":
                entry["shards"] = {
                    str(i): {"status": st, "primary_active": True,
                             "active_shards": 1, "relocating_shards": 0,
                             "initializing_shards": 0,
                             "unassigned_shards": svc.num_replicas}
                    for i in range(svc.num_shards)}
            out[name] = entry
        return {"indices": out}

    def h_cluster_state(self, req):
        return 200, {
            "cluster_name": self.node.cluster_name,
            "cluster_uuid": self.node.cluster_uuid,
            "metadata": {"indices": {
                name: {**svc.get_settings(), **svc.get_mapping()}
                for name, svc in self.node.indices.indices.items()}},
        }

    def h_cluster_stats(self, req):
        from opensearch_tpu.common.device_health import device_health
        from opensearch_tpu.common.device_ledger import device_ledger
        indices = self.node.indices.indices
        dev = device_ledger().stats()
        health = device_health().stats()
        return 200, {
            "cluster_name": self.node.cluster_name,
            "indices": {"count": len(indices),
                        "docs": {"count": sum(s.doc_count()
                                              for s in indices.values())}},
            "nodes": {"count": {"total": 1, "data": 1}},
            # compact device-residency + fault-tolerance rollup (full
            # detail per node in _nodes/stats `device`)
            "device": {
                "resident_bytes": dev["resident_bytes"],
                "resident_segments": dev["resident_segments"],
                "budget_bytes": dev["budget"]["budget_bytes"],
                "evictions": dev["budget"]["evictions"],
                "breaker_trips": sum(
                    b["trips"] for b in health["breakers"].values()),
                "breakers_open": sum(
                    1 for b in health["breakers"].values()
                    if b["state"] != "closed"),
                "poisoned_results": health["poisoned_results"],
            },
        }

    def h_nodes_info(self, req):
        return 200, {"cluster_name": self.node.cluster_name, "nodes": {
            self.node.node_id: {"name": self.node.name,
                                "version": VERSION,
                                "roles": ["cluster_manager", "data"]}}}

    def h_nodes_stats(self, req):
        from opensearch_tpu.common.breakers import breaker_service
        from opensearch_tpu.common.telemetry import metrics
        from opensearch_tpu.indices.request_cache import request_cache
        # probe on read: stats reflect CURRENT disk health, not boot-time
        self.node.fs_health.check()
        indices = self.node.indices.indices
        return 200, {"cluster_name": self.node.cluster_name, "nodes": {
            self.node.node_id: {
                "name": self.node.name,
                "indices": {"docs": {"count": sum(
                    s.doc_count() for s in indices.values())},
                    "request_cache": request_cache().stats(),
                    # query-hot-path observability: compiled-plan reuse
                    # and block-max segment pruning (PR-1 registry
                    # counters fed by ShardSearcher)
                    "search": {
                        "plan_cache": {
                            "hits": metrics().counter(
                                "search.plan_cache.hits").value,
                            "misses": metrics().counter(
                                "search.plan_cache.misses").value},
                        "segments_pruned": metrics().counter(
                            "search.segments_pruned").value}},
                "breakers": breaker_service().stats(),
                "tasks": {"count": len(self.node.task_manager.list())},
                "thread_pool": self.node.thread_pool.stats(),
                "fs": {"health": self.node.fs_health.stats()},
                "file_cache": self.node.indices.file_cache.stats(),
                "indexing_pressure":
                    self.node.indices.indexing_pressure.stats(),
                # overload-protection observability: duress trackers,
                # cancellation accounting, admission gate occupancy
                "search_backpressure":
                    self.node.search_backpressure.stats(),
                # coordinator-side adaptive replica selection: per-node
                # EWMAs, C3 ranks, duress verdicts, and the reroute/shed
                # counters (ResponseCollectorService / the reference's
                # AdaptiveSelectionStats in _nodes/stats)
                "adaptive_selection": {
                    "nodes": self.node.response_collector.stats(),
                    "reroutes": metrics().counter(
                        "search.replica_selection.reroutes").value,
                    "sheds": metrics().counter(
                        "search.replica_selection.sheds").value,
                    # the unified overload budget: edge 429s and
                    # coordinator duress sheds draw from ONE admission
                    # gate, so its occupancy/rejection ledger shows up
                    # here too (same numbers as search_backpressure's
                    # admission_control block, by construction)
                    "budget":
                        self.node.search_backpressure.admission.stats(),
                },
                # always-on workload attribution: record totals, rollup
                # cardinality, and the coalescability fraction (full
                # detail at GET /_insights/top_queries)
                "query_insights": self.node.insights.stats(),
                # per-tenant attribution (who sent what, at what cost,
                # how often degraded) + the adaptive QoS controller's
                # state: current knob values and the bounded audit ring
                # of every adaptation with its triggering evidence
                "tenants": self.node.insights.tenants(),
                "qos": self.node.qos.stats(),
                # the unified query engine: continuous-batcher
                # accounting (members batched / bypasses / window
                # waits / shared dispatches) + the bounded search
                # threadpool (search/engine.py)
                "search_engine": _query_engine_stats(),
                # device residency + transfer observability: ledger
                # rollups per index, stage/fetch transfer counters, the
                # device.memory.budget_bytes eviction accounting, the
                # per-kernel compile registry, and the backend's own
                # memory_stats() where the platform provides it
                "device": _device_stats(),
                # recovery observability: the recovery.* metric family
                # (incl. PR 8's corrupt-blob re-requests) + per-shard
                # store state, the JSON face of GET /_cat/recovery
                "recovery": self._recovery_stats(),
                # replication safety: per-shard (term, checkpoint)
                # positions + the fencing / rollback / resync counter
                # family (the write-path durability ledger)
                "replication": self._replication_stats(),
                "os": _os_stats(),
                "process": _process_stats(),
                # counters + latency histograms with p50/p90/p99 readout
                # (the telemetry SPI's MetricsRegistry surface)
                "telemetry": metrics().stats(),
            }}}

    def _recovery_stats(self) -> dict:
        from opensearch_tpu.common.telemetry import metrics

        m = metrics()
        shards = []
        for svc in sorted(self.node.indices.indices.values(),
                          key=lambda s: s.name):
            corrupted = svc.corrupted_shards()
            for shard_id in sorted(svc.local_shards):
                row = {"index": svc.name, "shard": shard_id,
                       "type": "store",
                       "stage": ("corrupted"
                                 if shard_id in corrupted else "done")}
                if shard_id in corrupted:
                    row["corruption"] = corrupted[shard_id]
                shards.append(row)
        return {
            "corrupt_blobs": m.counter("recovery.corrupt_blobs").value,
            "retries": {
                name: {
                    # metric-name-ok: bounded recovery action names
                    "attempts": m.counter(
                        f"retry.recovery.{name}.attempts").value,
                    # metric-name-ok: bounded recovery action names
                    "retries": m.counter(
                        f"retry.recovery.{name}.retries").value,
                    # metric-name-ok: bounded recovery action names
                    "exhausted": m.counter(
                        f"retry.recovery.{name}.exhausted").value,
                } for name in ("start", "report", "fetch")},
            # search-replica tier: remote-store segment replication
            # accounting (publishes, searcher installs/refills, CRC
            # re-fetches, bytes pulled through the FileCache)
            "segment_replication": {
                # metric-name-ok: bounded segrep counter family
                name: m.counter(f"segrep.{name}").value
                for name in ("publishes", "publish_failures",
                             "installs", "install_failures", "fetches",
                             "bytes_pulled", "corrupt_blobs",
                             "refills", "refill_failures")},
            "shards": shards,
        }

    def _replication_stats(self) -> dict:
        """Single-node face of the cluster nodes' ``replication_stats()``
        block: every local shard is its own primary, so the interesting
        signal here is the (term, local/global checkpoint) positions
        plus the process-wide replication.* counters (which a cluster
        test sharing the process also feeds)."""
        from opensearch_tpu.common.telemetry import metrics

        m = metrics()
        shards = []
        for svc in sorted(self.node.indices.indices.values(),
                          key=lambda s: s.name):
            for shard_id, engine in sorted(svc.local_shards.items()):
                shards.append({
                    "index": svc.name, "shard": shard_id,
                    "primary_term": engine.primary_term,
                    "max_seq_no": engine._seq_no,
                    "local_checkpoint": engine.local_checkpoint,
                    "global_checkpoint": engine.global_checkpoint,
                })
        return {
            "shards": shards,
            # metric-name-ok: bounded replication counter family
            "counters": {name: m.counter(f"replication.{name}").value
                         for name in ("fenced_ops",
                                      "stale_primary_rejections",
                                      "rollbacks", "resyncs",
                                      "resync_failures",
                                      "durability_checked_ops")},
        }

    def h_nodes_trace(self, req):
        """Recent finished spans from the bounded in-memory exporter —
        a debug surface over the tracing SPI (the reference exports via
        OTLP; this engine keeps a ring buffer readable over REST)."""
        from opensearch_tpu.common.telemetry import tracer
        limit = int(req.param("size", 100))
        spans = tracer().recent(limit, trace_id=req.param("trace_id"))
        return 200, {"cluster_name": self.node.cluster_name,
                     "nodes": {self.node.node_id: {
                         "name": self.node.name,
                         "spans": spans}}}

    def h_metrics(self, req):
        """Prometheus text exposition of the full MetricsRegistry —
        counters as ``*_total``, latency histograms as cumulative
        ``_bucket{le=...}`` + ``_sum``/``_count`` (milliseconds) — plus
        the query-insights per-signature series (signature is always a
        LABEL drawn from the bounded top-N path, never a metric name).
        The same underlying data ``_nodes/stats`` serves as JSON."""
        from opensearch_tpu.common.device_ledger import device_ledger
        from opensearch_tpu.common.telemetry import metrics
        text = metrics().prometheus_text()
        insights = getattr(self.node, "insights", None)
        if insights is not None:
            text += insights.prometheus_text()
        # device residency gauges (transfer/eviction counters already
        # flow through the MetricsRegistry exposition above)
        text += device_ledger().prometheus_text()
        # device breaker-state gauges (trip/close/poison counters flow
        # through the MetricsRegistry exposition above)
        from opensearch_tpu.common.device_health import device_health
        text += device_health().prometheus_text()
        return 200, PlainText(
            text,
            content_type="text/plain; version=0.0.4; charset=utf-8")

    def h_insights_top_queries(self, req):
        """Always-on top-N query attribution + per-plan-signature
        workload stats (``GET /_insights/top_queries``): ranked by
        ``?by=latency|cpu|heap``, with the per-signature rollups and
        the coalescability report the continuous batcher sizes from.
        Single-node deployments serve their local section in the same
        fan-in shape the cluster coordinator's merge produces."""
        from opensearch_tpu.search.insights import merge_sections
        by = req.param("by", "latency")
        n = req.param("size") or req.param("n")
        n = int(n) if n is not None else self.node.insights.top_n
        section = self.node.insights.section(by=by, n=n)
        merged = merge_sections({self.node.node_id: section},
                                by=by, n=n)
        merged["cluster_name"] = self.node.cluster_name
        return 200, merged

    def h_flight_recorder(self, req):
        """Recent flight-recorder captures (slow-log trips, soak SLO
        breaches): spans + counters snapshotted at trigger time."""
        from opensearch_tpu.common.telemetry import flight_recorder
        limit = int(req.param("size", 32))
        return 200, {"cluster_name": self.node.cluster_name,
                     "nodes": {self.node.node_id: {
                         "name": self.node.name,
                         "captures":
                             flight_recorder().captures(limit)}}}

    def h_hot_threads(self, req):
        """Per-thread stack dump (RestNodesHotThreadsAction analog over
        sys._current_frames — the busiest diagnostic when a query
        wedges host-side)."""
        import sys
        import threading as _threading
        import traceback

        names = {t.ident: t.name for t in _threading.enumerate()}
        lines = [f"::: {{{self.node.name}}}{{{self.node.node_id}}}"]
        for ident, frame in sorted(sys._current_frames().items()):
            lines.append(
                f"\n   thread [{names.get(ident, '?')}] id [{ident}]:")
            lines.extend(
                "     " + ln.rstrip() for ln in
                traceback.format_stack(frame))
        return 200, {"nodes": {self.node.node_id: {
            "name": self.node.name,
            "hot_threads": "\n".join(lines)}}}

    def h_cat_indices(self, req):
        rows = []
        for name, svc in sorted(self.node.indices.indices.items()):
            health = "red" if svc.corrupted_shards() else "green"
            rows.append({"health": health, "status": "open", "index": name,
                         "uuid": svc.uuid, "pri": str(svc.num_shards),
                         "rep": str(svc.num_replicas),
                         "docs.count": str(svc.doc_count())})
        return 200, rows

    def h_cat_health(self, req):
        h = self.h_cluster_health(req)[1]
        return 200, [{"cluster": h["cluster_name"], "status": h["status"],
                      "node.total": "1", "shards": str(h["active_shards"])}]

    def h_cat_count(self, req):
        targets = (self._target_indices(req)
                   if req.path_params.get("index")
                   else self.node.indices.indices.values())
        total = sum(s.doc_count() for s in targets)
        now = time.time()   # wall-clock: epoch/timestamp columns
        return 200, [{"epoch": str(int(now)),
                      "timestamp": time.strftime("%H:%M:%S",
                                                 time.gmtime(now)),
                      "count": str(total)}]

    def h_cat_shards(self, req):
        rows = []
        for name, svc in sorted(self.node.indices.indices.items()):
            for engine in svc.shards:
                rows.append({"index": name, "shard": str(engine.shard_id),
                             "prirep": "p", "state": "STARTED",
                             "docs": str(engine.doc_count())})
        return 200, rows

    # -- index admin -------------------------------------------------------

    def h_create_index(self, req):
        name = req.path_params["index"]
        self.node.indices.create(name, req.json({}))
        return 200, {"acknowledged": True, "shards_acknowledged": True,
                     "index": name}

    def h_delete_index(self, req):
        for svc in self.node.indices.resolve(req.path_params["index"]):
            self.node.indices.delete(svc.name)
        return 200, {"acknowledged": True}

    def h_get_index(self, req):
        svc = self.node.indices.get(req.path_params["index"])
        aliases = (self.node.indices.get_aliases(index=svc.name)
                   .get(svc.name, {}).get("aliases", {}))
        return 200, {svc.name: {"aliases": aliases, **svc.get_mapping(),
                                **svc.get_settings()}}

    def h_index_exists(self, req):
        if self.node.indices.exists(req.path_params["index"]):
            return 200, {}
        return 404, {}

    def h_get_mapping(self, req):
        svc = self.node.indices.get(req.path_params["index"])
        return 200, {svc.name: svc.get_mapping()}

    def h_get_mapping_all(self, req):
        return 200, {name: svc.get_mapping()
                     for name, svc in self.node.indices.indices.items()}

    def h_put_mapping(self, req):
        svc = self.node.indices.get(req.path_params["index"])
        svc.put_mapping(req.json({}))
        return 200, {"acknowledged": True}

    def h_get_settings(self, req):
        svc = self.node.indices.get(req.path_params["index"])
        return 200, {svc.name: svc.get_settings()}

    def h_index_stats(self, req):
        svc = self.node.indices.get(req.path_params["index"])
        stats = svc.stats()
        return 200, {"_all": {"primaries": stats, "total": stats},
                     "indices": {svc.name: {"primaries": stats,
                                            "total": stats}}}

    def h_refresh(self, req):
        services = self._target_indices(req)
        for svc in services:
            svc.refresh()
        n = sum(s.num_shards for s in services)
        return 200, {"_shards": {"total": n, "successful": n, "failed": 0}}

    def h_cache_clear(self, req):
        """POST [/{index}]/_cache/clear (RestClearIndicesCacheAction):
        ``?request=false`` skips the request cache — the only cache type
        with a clear hook here; fielddata/query params are accepted and
        ignored like unsupported cache types in the reference."""
        from opensearch_tpu.indices.request_cache import request_cache
        expr = req.path_params.get("index")
        services = (self.node.indices.resolve(expr) if expr
                    else list(self.node.indices.indices.values()))
        clear_request = (req.param("request") is None
                         or req.flag("request"))
        if clear_request:
            for svc in services:
                request_cache().clear(index=svc.name)
        n = sum(s.num_shards for s in services)
        return 200, {"_shards": {"total": n, "successful": n,
                                 "failed": 0}}

    def h_flush(self, req):
        svc = self.node.indices.get(req.path_params["index"])
        svc.flush()
        return 200, {"_shards": {"total": svc.num_shards,
                                 "successful": svc.num_shards, "failed": 0}}

    def h_forcemerge(self, req):
        svc = self.node.indices.get(req.path_params["index"])
        svc.force_merge(int(req.param("max_num_segments", 1)))
        return 200, {"_shards": {"total": svc.num_shards,
                                 "successful": svc.num_shards, "failed": 0}}

    def h_rank_eval(self, req):
        from opensearch_tpu.search.rank_eval import run_rank_eval

        body = req.json({}) or {}
        default_index = req.path_params.get("index")

        def search_fn(index_expr, search_body):
            if default_index and index_expr == "_all":
                index_expr = default_index
            targets = self.node.indices.resolve_with_filters(index_expr)
            if len(targets) == 1:
                svc, flt = targets[0]
                return svc.search(self._apply_alias_filter(search_body,
                                                           flt))
            return self._multi_index_search(targets, search_body)
        return 200, run_rank_eval(body, search_fn)

    # -- reindex family (scroll-read + bulk-write; modules/reindex) --------

    def _scan_all(self, svc, query):
        """Every matching (engine, _id, source) via the scroll
        materialization path, PER SHARD ENGINE — write-backs go straight
        to the owning engine, so custom-routed docs are never mis-routed
        through id-based rerouting."""
        for engine in svc.shards:
            searcher = engine.acquire_searcher()
            rows, _total = searcher.scan_rows({"query": query})
            for row in rows:
                seg = searcher.segments[row["seg"]]
                local = row["local"]
                yield engine, seg.doc_ids[local], seg.source(local)

    def _validate_reindex(self, body) -> None:
        """Cheap request checks shared by both modes — a malformed async
        request must 400 at submit time, not become a persisted failed
        task."""
        src = body.get("source") or {}
        dest = body.get("dest") or {}
        if not src.get("index") or not dest.get("index"):
            raise ValidationError(
                "[reindex] requires source.index and dest.index")
        services = self.node.indices.resolve(src["index"])
        dest_svc = self.node.indices.write_index_for(dest["index"])
        if any(svc.name == dest_svc.name for svc in services):
            raise ValidationError(
                "reindex cannot write into its own source index")

    def h_reindex(self, req):
        body = req.json({}) or {}
        self._validate_reindex(body)
        if str(req.param("wait_for_completion",
                         "true")).lower() == "false":
            # runs as a PERSISTENT task: durably recorded, resumed on
            # restart (ref persistent/PersistentTasksService.java:47;
            # reindex is idempotent — doc ids overwrite)
            task_id = self.node.persistent_tasks.submit(
                "indices:data/write/reindex", body)
            return 200, {"task": task_id}
        return 200, self._do_reindex(body)

    def _do_reindex(self, body):
        src = body.get("source") or {}
        dest = body.get("dest") or {}
        if not src.get("index") or not dest.get("index"):
            raise ValidationError(
                "[reindex] requires source.index and dest.index")
        services = self.node.indices.resolve(src["index"])
        dest_svc = self.node.indices.write_index_for(dest["index"])
        # validate BEFORE any copy: a partial write then a 400 would lie
        if any(svc.name == dest_svc.name for svc in services):
            raise ValidationError(
                "reindex cannot write into its own source index")
        pid = dest.get("pipeline")
        created = updated = total = 0
        t0 = time.monotonic()
        for svc in services:
            for _eng, doc_id, source in self._scan_all(svc,
                                                       src.get("query")):
                total += 1
                if pid:
                    source = self.node.ingest.process(pid, source)
                    if source is None:
                        continue
                r = dest_svc.index_doc(doc_id, source)
                if r.result == "created":
                    created += 1
                else:
                    updated += 1
        dest_svc.refresh()
        return {"took": int((time.monotonic() - t0) * 1000),
                "total": total, "created": created,
                "updated": updated, "deleted": 0, "failures": []}

    def h_update_by_query(self, req):
        body = req.json({}) or {}
        services = self._target_indices(req)
        if body.get("script") is not None:
            # painless update scripts mutate via ctx._source assignments
            # — unsupported; full-document transforms go through ingest
            raise ValidationError(
                "[update_by_query] with [script] is not supported — use "
                "an ingest [pipeline] instead")
        pid = req.param("pipeline")
        total = updated = 0
        t0 = time.monotonic()
        for svc in services:
            for engine, doc_id, source in self._scan_all(
                    svc, body.get("query")):
                total += 1
                if pid:
                    source = self.node.ingest.process(pid, source)
                    if source is None:
                        continue
                engine.index(doc_id, source)    # owning shard directly
                updated += 1
            for engine in svc.shards:
                engine.ensure_synced()          # durable BEFORE the ack
            svc.invalidate_searcher()
            svc.refresh()
        return 200, {"took": int((time.monotonic() - t0) * 1000),
                     "total": total, "updated": updated,
                     "failures": []}

    def h_delete_by_query(self, req):
        body = req.json({}) or {}
        if body.get("query") is None:
            raise ValidationError("[delete_by_query] requires [query]")
        services = self._target_indices(req)
        total = deleted = 0
        t0 = time.monotonic()
        for svc in services:
            for engine, doc_id, _source in self._scan_all(
                    svc, body["query"]):
                total += 1
                r = engine.delete(doc_id)   # owning shard directly
                if r.result == "deleted":
                    deleted += 1
            for engine in svc.shards:
                engine.ensure_synced()          # durable BEFORE the ack
            svc.invalidate_searcher()
            svc.refresh()
        return 200, {"took": int((time.monotonic() - t0) * 1000),
                     "total": total, "deleted": deleted,
                     "failures": []}

    # -- field_caps / termvectors ------------------------------------------

    def h_field_caps(self, req):
        body = req.json({}) or {}
        fields = req.param("fields") or body.get("fields")
        if not fields:
            raise ValidationError("[_field_caps] requires [fields]")
        if isinstance(fields, str):
            fields = [f.strip() for f in fields.split(",") if f.strip()]
        import fnmatch as _fn
        services = self._target_indices(req)
        caps: dict[str, dict] = {}
        for svc in services:
            for path, ft in svc.mapper.field_types().items():
                if not any(_fn.fnmatchcase(path, p) for p in fields):
                    continue
                entry = caps.setdefault(path, {})
                entry.setdefault(ft.type_name, {
                    "type": ft.type_name,
                    "searchable": bool(ft.index_enabled
                                       or ft.dv_kind != "none"),
                    "aggregatable": ft.dv_kind != "none",
                })
        return 200, {"indices": sorted(s.name for s in services),
                     "fields": caps}

    def h_termvectors(self, req):
        name = req.path_params["index"]
        svc = self._single_index(name)
        doc = svc.get_doc(req.path_params["id"])
        if doc is None:
            return 404, {"_index": name, "_id": req.path_params["id"],
                         "found": False}
        body = req.json({}) or {}
        wanted = body.get("fields") or req.param("fields")
        if isinstance(wanted, str):
            wanted = [f.strip() for f in wanted.split(",")]
        source = doc.get("_source") or {}
        term_vectors = {}
        for field, ft in svc.mapper.field_types().items():
            if wanted and field not in wanted:
                continue
            if not hasattr(ft, "search_terms"):
                continue
            from opensearch_tpu.ingest.service import path_get
            value = path_get(source, field)
            if value is None:
                continue
            analyzer = svc.mapper.analyzers.get(
                getattr(ft, "analyzer_name", "standard"))
            terms: dict[str, dict] = {}
            values = value if isinstance(value, list) else [value]
            pos_base = 0
            for v in values:             # arrays analyze per element
                for tok in analyzer.analyze(str(v)):
                    t = terms.setdefault(tok.term, {"term_freq": 0,
                                                    "tokens": []})
                    t["term_freq"] += 1
                    t["tokens"].append({
                        "position": pos_base + tok.position,
                        "start_offset": tok.start_offset,
                        "end_offset": tok.end_offset})
                pos_base += 100          # position_increment_gap analog
            if terms:
                term_vectors[field] = {"terms": terms}
        return 200, {"_index": name, "_id": req.path_params["id"],
                     "found": True, "term_vectors": term_vectors}

    # -- ingest pipelines --------------------------------------------------

    def h_put_ingest(self, req):
        return 200, self.node.ingest.put(req.path_params["id"],
                                         req.json({}) or {})

    def h_get_ingest(self, req):
        return 200, self.node.ingest.get(req.path_params.get("id"))

    def h_delete_ingest(self, req):
        return 200, self.node.ingest.delete(req.path_params["id"])

    def h_simulate_ingest(self, req):
        body = req.json({}) or {}
        pid = req.path_params.get("id")
        pipeline = (self.node.ingest.get(pid)[pid] if pid
                    else body.get("pipeline") or {})
        return 200, self.node.ingest.simulate(pipeline,
                                              body.get("docs") or [])

    def _ingest_pipeline_for(self, req, svc) -> Optional[str]:
        """?pipeline= param, else the index's default_pipeline setting
        (IndexSettings.DEFAULT_PIPELINE)."""
        pid = req.param("pipeline")
        if pid:
            return None if pid == "_none" else pid
        default = svc.settings.get("default_pipeline")
        return default if default and default != "_none" else None

    # -- documents ---------------------------------------------------------

    @staticmethod
    def _bulk_source_param(req):
        """URL-level _source/_source_includes/_source_excludes default
        for bulk update items."""
        if req.param("_source") is not None:
            return req.param("_source")
        inc = req.param("_source_includes")
        exc = req.param("_source_excludes")
        if inc or exc:
            spec = {}
            if inc:
                spec["includes"] = inc.split(",")
            if exc:
                spec["excludes"] = exc.split(",")
            return spec
        return None

    def _maybe_refresh(self, svc, req, doc_id=None) -> bool:
        refresh = req.param("refresh")
        if refresh is not None and str(refresh).lower() in ("", "true",
                                                            "wait_for"):
            if doc_id is not None:
                # a single-doc write refreshes only its owning shard
                svc.refresh_doc_shard(str(doc_id), req.param("routing"))
            else:
                svc.refresh()
            # wait_for reports forced_refresh=false (the write merely
            # waited); an explicit refresh reports true
            return str(refresh).lower() != "wait_for"
        return False

    def h_index_doc(self, req, doc_id=None, op_type=None):
        name = req.path_params["index"]
        svc = self.node.indices.write_index_for(name)
        doc_id = doc_id or req.path_params.get("id")
        if doc_id is not None and len(str(doc_id).encode("utf-8")) > 512:
            raise ValidationError(
                f"id is too long, must be no longer than 512 bytes but "
                f"was: {len(str(doc_id).encode('utf-8'))}")
        source = req.json()
        if not isinstance(source, dict):
            raise ParsingError("request body is required and must be a JSON "
                               "object")
        pid = self._ingest_pipeline_for(req, svc)
        if pid is not None:
            source = self.node.ingest.process(pid, source)
            if source is None:             # drop processor
                return 200, {"_index": name, "_id": doc_id,
                             "result": "noop"}
        kw = {}
        if req.param("if_seq_no") is not None:
            kw["if_seq_no"] = req.int_param("if_seq_no")
        if req.param("if_primary_term") is not None:
            kw["if_primary_term"] = req.int_param("if_primary_term")
        if req.param("version") is not None:
            kw["version"] = req.int_param("version")
            kw["version_type"] = req.param("version_type", "internal")
        if ((op_type or req.param("op_type")) == "create"
                and kw.get("version_type", "internal") != "internal"):
            raise ValidationError(
                "Validation Failed: 1: create operations only support "
                "internal versioning. use index instead;")
        if (op_type or req.param("op_type")) == "create" and doc_id is not None:
            if svc.get_doc(doc_id, req.param("routing")) is not None:
                from opensearch_tpu.common.errors import VersionConflictError
                raise VersionConflictError(doc_id, "document to be absent",
                                           "exists")
        r = svc.index_doc(doc_id, source, routing=req.param("routing"),
                          op_bytes=len(req.raw_body or b""), **kw)
        forced = self._maybe_refresh(svc, req, doc_id=r.doc_id)
        status = 201 if r.result == "created" else 200
        out = {"_index": svc.name, "_id": r.doc_id,
               "_version": r.version, "_seq_no": r.seq_no,
               # the engine's REAL primary term (bumped on promotion),
               # not a hardcoded 1 — fencing is observable to clients
               "_primary_term": r.primary_term, "result": r.result,
               "_shards": {"total": 1, "successful": 1, "failed": 0}}
        if forced:
            out["forced_refresh"] = True
        return status, out

    def h_index_doc_auto(self, req):
        return self.h_index_doc(req, doc_id=None)

    def h_create_doc(self, req):
        return self.h_index_doc(req, op_type="create")

    def h_get_doc(self, req):
        name = req.path_params["index"]
        svc = self._single_index(name)
        doc = svc.get_doc(req.path_params["id"], req.param("routing"),
                          realtime=req.param("realtime", "true") != "false")
        if doc is None:
            return 404, {"_index": name, "_id": req.path_params["id"],
                         "found": False}
        if req.param("version") is not None \
                and req.int_param("version") != doc["_version"]:
            from opensearch_tpu.common.errors import VersionConflictError
            raise VersionConflictError(req.path_params["id"],
                                       req.param("version"),
                                       doc["_version"])
        return 200, {"_index": name, **doc}

    def h_doc_exists(self, req):
        svc = self._single_index(req.path_params["index"])
        doc = svc.get_doc(req.path_params["id"], req.param("routing"))
        return (200, {}) if doc is not None else (404, {})

    def h_get_source(self, req):
        name = req.path_params["index"]
        svc = self._single_index(name)
        doc = svc.get_doc(req.path_params["id"], req.param("routing"))
        if doc is None:
            raise DocumentMissingError(name, req.path_params["id"])
        if "_source" not in doc:
            from opensearch_tpu.common.errors import ResourceNotFoundError
            raise ResourceNotFoundError(
                f"document source missing for [{name}]/"
                f"[{req.path_params['id']}]")
        return 200, doc["_source"]

    def h_delete_doc(self, req):
        name = req.path_params["index"]
        svc = self._single_index(name)
        kw = {}
        if req.param("if_seq_no") is not None:
            kw["if_seq_no"] = req.int_param("if_seq_no")
        if req.param("if_primary_term") is not None:
            kw["if_primary_term"] = req.int_param("if_primary_term")
        if req.param("version") is not None:
            kw["version"] = req.int_param("version")
            kw["version_type"] = req.param("version_type", "internal")
        r = svc.delete_doc(req.path_params["id"],
                           routing=req.param("routing"), **kw)
        forced = self._maybe_refresh(svc, req, doc_id=r.doc_id)
        if r.result == "not_found":
            return 404, {"_index": name, "_id": r.doc_id,
                         "result": "not_found",
                         "_shards": {"total": 1, "successful": 1,
                                     "failed": 0}}
        out = {"_index": name, "_id": r.doc_id, "_version": r.version,
               "_seq_no": r.seq_no, "_primary_term": r.primary_term,
               "result": "deleted",
               "_shards": {"total": 1, "successful": 1, "failed": 0}}
        if forced:
            out["forced_refresh"] = True
        return 200, out

    def h_update_doc(self, req):
        from opensearch_tpu.indices.service import deep_merge_doc

        name = req.path_params["index"]
        svc = self.node.indices.write_index_for(name)
        body = req.json({})
        doc_id = req.path_params["id"]
        cur = svc.get_doc(doc_id, req.param("routing"))
        created = cur is None
        kw = {}
        if req.param("if_seq_no") is not None:
            kw["if_seq_no"] = req.int_param("if_seq_no")
        if req.param("if_primary_term") is not None:
            kw["if_primary_term"] = req.int_param("if_primary_term")
        if kw and cur is None and "upsert" not in body \
                and not body.get("doc_as_upsert"):
            # CAS on a missing doc is document_missing, not a conflict
            raise DocumentMissingError(name, doc_id)
        if kw and cur is not None:
            # CAS params check against the CURRENT doc before any noop
            # short-circuit (UpdateHelper applies them to the write)
            from opensearch_tpu.common.errors import VersionConflictError
            cur_seq = cur["_seq_no"] if cur is not None else -1
            cur_term = cur.get("_primary_term", 1) if cur is not None else 0
            if kw.get("if_seq_no") is not None \
                    and kw["if_seq_no"] != cur_seq:
                raise VersionConflictError(
                    doc_id, f"seq_no [{kw['if_seq_no']}]",
                    f"seq_no [{cur_seq}]")
            if kw.get("if_primary_term") is not None \
                    and kw["if_primary_term"] != cur_term:
                raise VersionConflictError(
                    doc_id, f"primary_term [{kw['if_primary_term']}]",
                    f"primary_term [{cur_term}]")
        if cur is None:
            if "upsert" in body:
                merged = body["upsert"]
            elif body.get("doc_as_upsert") and "doc" in body:
                merged = body["doc"]
            else:
                raise DocumentMissingError(name, doc_id)
        else:
            if "doc" not in body:
                raise ValidationError("[_update] requires a [doc] or "
                                      "[upsert] section")
            if "_source" not in cur:
                raise ValidationError(
                    f"[{name}][{doc_id}]: source is missing — partial "
                    "updates require [_source] to be enabled")
            merged = deep_merge_doc(cur["_source"], body["doc"])
            # detect_noop (default true): an update that changes nothing
            # neither bumps the version nor writes (UpdateHelper.java)
            if merged == cur["_source"] and body.get("detect_noop", True):
                out = {"_index": name, "_id": doc_id,
                       "_version": cur["_version"],
                       "_seq_no": cur["_seq_no"],
                       "result": "noop",
                       "_shards": {"total": 0, "successful": 0,
                                   "failed": 0}}
                self._update_get_section(req, out, cur)
                return 200, out
        r = svc.index_doc(doc_id, merged, routing=req.param("routing"), **kw)
        forced = self._maybe_refresh(svc, req, doc_id=r.doc_id)
        out = {"_index": name, "_id": r.doc_id, "_version": r.version,
               "_seq_no": r.seq_no, "_primary_term": r.primary_term,
               "result": "created" if created else "updated",
               "_shards": {"total": 1, "successful": 1, "failed": 0}}
        if forced:
            out["forced_refresh"] = True
        self._update_get_section(
            req, out, svc.get_doc(doc_id, req.param("routing")))
        return 200, out

    @staticmethod
    def _update_get_section(req, out, doc):
        """?_source=... on _update returns the post-update doc inline
        (UpdateResponse.getGetResult)."""
        spec = req.param("_source")
        if spec is None or doc is None:
            return
        from opensearch_tpu.search.fetch import filter_source
        if spec in ("", "true", "false"):
            spec = spec != "false"
        else:
            spec = spec.split(",")
        src = filter_source(doc.get("_source"), spec)
        get = {"found": True, "_seq_no": doc["_seq_no"],
               "_primary_term": doc.get("_primary_term", 1)}
        if src is not None:
            get["_source"] = src
        out["get"] = get

    def h_mget(self, req):
        body = req.json({})
        default_index = req.path_params.get("index")
        docs_out = []
        specs = body.get("docs", []) or [
            {"_id": i} for i in body.get("ids", [])]
        if not specs:
            raise ValidationError(
                "Validation Failed: 1: no documents to get;")
        missing = [i + 1 for i, s in enumerate(specs) if "_id" not in s]
        if missing:
            raise ValidationError("Validation Failed: " + "".join(
                f"{i}: id is missing;" for i in missing))
        no_index = [i + 1 for i, s in enumerate(specs)
                    if s.get("_index", default_index) is None]
        if no_index:
            raise ValidationError("Validation Failed: " + "".join(
                f"{i}: index is missing;" for i in no_index))
        for spec in specs:
            name = spec.get("_index", default_index)
            doc_id = str(spec["_id"])        # ids are strings on the wire
            routing = spec.get("routing")
            try:
                svc = self.node.indices.get(name)
            except IllegalArgumentError as e:
                # e.g. an alias over multiple indices: a per-doc error,
                # not a request failure (TransportMultiGetAction)
                docs_out.append({"_index": name, "_id": doc_id, "error": {
                    "root_cause": [{"type": e.error_type,
                                    "reason": e.reason}],
                    "type": e.error_type, "reason": e.reason}})
                continue
            except OpenSearchTpuError:
                docs_out.append({"_index": name, "_id": doc_id,
                                 "found": False})
                continue
            try:
                doc = svc.get_doc(doc_id, None if routing is None
                                  else str(routing))
            except OpenSearchTpuError:
                doc = None
            if doc is None:
                docs_out.append({"_index": name, "_id": doc_id,
                                 "found": False})
            else:
                docs_out.append({"_index": name, **doc})
        return 200, {"docs": docs_out}

    # -- bulk --------------------------------------------------------------

    def h_bulk(self, req):
        default_index = req.path_params.get("index")
        lines = req.raw_body.split(b"\n")
        ops_by_index: dict[str, list] = {}
        order: list[tuple[str, int]] = []
        i = 0
        while i < len(lines):
            line = lines[i].strip()
            i += 1
            if not line:
                continue
            try:
                action_line = json.loads(line)
            except json.JSONDecodeError as e:
                raise ParsingError(f"malformed action/metadata line: {e}")
            if len(action_line) != 1:
                raise ParsingError("action/metadata line must contain a "
                                   "single action")
            action, meta = next(iter(action_line.items()))
            if action not in ("index", "create", "delete", "update"):
                raise ParsingError(f"unknown bulk action [{action}]")
            if action == "index" and meta.get("op_type") == "create":
                action = "create"    # renders as a create item, with
                # create's already-exists conflict semantics
            name = meta.get("_index", default_index)
            if name is None:
                raise ValidationError("bulk item requires _index")
            source = None
            if action != "delete":
                if i >= len(lines):
                    raise ParsingError("bulk request ends with an action "
                                       "line and no source")
                try:
                    source = json.loads(lines[i])
                except json.JSONDecodeError as e:
                    raise ParsingError(f"malformed bulk source line: {e}")
                i += 1
            require_alias = meta.get(
                "require_alias", req.param("require_alias") == "true")
            if require_alias and name not in self.node.indices.aliases:
                bucket = ops_by_index.setdefault("\x00err", [])
                order.append(("\x00err", len(bucket)))
                bucket.append({action: {
                    "_index": name, "_id": meta.get("_id"), "status": 404,
                    "error": {"type": "index_not_found_exception",
                              "reason": f"no such index [{name}] and "
                                        "[require_alias] request flag is "
                                        f"[true] and [{name}] is not an "
                                        "alias"}}})
                continue
            bucket = ops_by_index.setdefault(name, [])
            order.append((name, len(bucket)))
            bucket.append((action, meta.get("_id"), source,
                           {"routing": meta.get("routing",
                                                meta.get("_routing")),
                            "if_seq_no": meta.get("if_seq_no"),
                            "if_primary_term": meta.get(
                                "if_primary_term"),
                            "pipeline": meta.get("pipeline"),
                            "op_bytes": len(lines[i - 1])
                            if source is not None else None,
                            "_source": meta.get(
                                "_source", self._bulk_source_param(req))}))
        results_by_index = {}
        t0 = time.monotonic()
        for name, ops in ops_by_index.items():
            if name == "\x00err":     # pre-cooked require_alias failures
                results_by_index[name] = ops
                continue
            try:
                svc = self.node.indices.write_index_for(name)
            except OpenSearchTpuError as e:
                # unresolvable write target (e.g. alias without a write
                # index): item-level errors, never a request failure
                results_by_index[name] = [{action: {
                    "_index": name, "_id": doc_id, "status": 400,
                    "error": {"type": "illegal_argument_exception",
                              "reason": e.reason}}}
                    for action, doc_id, _s, _kw in ops]
                continue
            req_pid = self._ingest_pipeline_for(req, svc)
            cooked = []
            precooked = {}      # i -> ready response (drop/error)
            for i, (action, doc_id, source, kw) in enumerate(ops):
                # pipelines transform only index/create sources; an
                # update's {"doc": ...} wrapper passes through
                # untouched (IngestService skips updates too).  A
                # per-item [pipeline] in the action metadata overrides
                # the request-level one.
                pid = kw.get("pipeline") or req_pid
                if pid is not None and action in ("index", "create") \
                        and source is not None:
                    try:
                        source = self.node.ingest.process(pid, source)
                    except ResourceNotFoundError as e:
                        # a missing pipeline is a CLIENT error per item
                        # (TransportBulkAction: illegal_argument, 400)
                        precooked[i] = {action: {
                            "_index": name, "_id": doc_id, "status": 400,
                            "error": {"type": "illegal_argument_exception",
                                      "reason": e.reason}}}
                        continue
                    except OpenSearchTpuError as e:
                        # per-ITEM failure: bulk never aborts
                        precooked[i] = {action: {
                            "_index": name, "_id": doc_id,
                            "status": e.status,
                            "error": {"type": e.error_type,
                                      "reason": e.reason}}}
                        continue
                    if source is None:      # dropped
                        precooked[i] = {action: {
                            "_index": name, "_id": doc_id,
                            "result": "noop", "status": 200}}
                        continue
                cooked.append((action, doc_id, source, kw))
            results = svc.bulk(cooked)
            merged, ri = [], 0
            for i in range(len(ops)):
                if i in precooked:
                    merged.append(precooked[i])
                else:
                    merged.append(results[ri])
                    ri += 1
            results_by_index[name] = merged
            if req.param("refresh") in ("", "true", "wait_for"):
                svc.refresh()
        items = [results_by_index[name][j] for name, j in order]
        errors = any(next(iter(it.values())).get("error") for it in items)
        took = int((time.monotonic() - t0) * 1000)
        from opensearch_tpu.common.telemetry import metrics
        metrics().counter("bulk.items").inc(len(items))
        metrics().histogram("bulk.request_ms").observe(float(took))
        return 200, {"took": took, "errors": errors, "items": items}

    # -- search ------------------------------------------------------------

    def _target_indices(self, req) -> list:
        expr = req.path_params.get("index")
        if expr is None:
            return list(self.node.indices.indices.values())
        return self.node.indices.resolve(expr)

    def _target_indices_filtered(self, req) -> list:
        """[(svc, alias_filter|None)] for search-style requests."""
        expr = req.path_params.get("index")
        if expr is None:
            return [(s, None)
                    for s in self.node.indices.indices.values()]
        return self.node.indices.resolve_with_filters(expr)

    @staticmethod
    def _apply_alias_filter(body: dict, flt) -> dict:
        """AND an alias filter into the request query (the reference
        applies alias filters inside QueryShardContext)."""
        if flt is None:
            return body
        out = dict(body)
        q = body.get("query")
        out["query"] = {"bool": {"must": [q] if q else [],
                                 "filter": [flt]}}
        return out

    def _single_index(self, name: str):
        """Exactly-one-index resolution for doc-level APIs (GET/DELETE/
        UPDATE through an alias work when it targets one index)."""
        svcs = self.node.indices.resolve(name)
        if len(svcs) != 1:
            raise ValidationError(
                f"[{name}] resolves to {len(svcs)} indices — doc "
                "operations require exactly one")
        return svcs[0]

    def h_msearch(self, req):
        """NDJSON multi-search (RestMultiSearchAction analog): alternating
        header/body lines; header may name an index, else the URL index
        applies.  Same-index runs batch through ShardSearcher.msearch (one
        device program per query group — see search/batch.py)."""
        lines = [ln for ln in req.raw_body.split(b"\n") if ln.strip()]
        if len(lines) % 2 != 0:
            raise ValidationError(
                "_msearch body must be alternating header/body NDJSON lines")
        default_index = req.path_params.get("index")
        requests = []            # (index_name, body)
        for i in range(0, len(lines), 2):
            try:
                header = json.loads(lines[i])
                body = json.loads(lines[i + 1])
            except json.JSONDecodeError as e:
                raise ParsingError(f"invalid _msearch NDJSON: {e}") from e
            index = header.get("index") or default_index
            if index is None:
                raise ValidationError(
                    "_msearch header must name an [index] when the URL "
                    "does not")
            requests.append((index, body))
        # group per index expression so same-index bursts batch; errors
        # are PER sub-request (the _msearch contract: one bad body never
        # fails its neighbours)
        responses: list = [None] * len(requests)
        by_index: dict[str, list[int]] = {}
        for pos, (index, _b) in enumerate(requests):
            by_index.setdefault(index, []).append(pos)

        def err_of(e):
            err = {"error": {"type": e.error_type, "reason": e.reason},
                   "status": e.status}
            if e.status == 429:
                # sub-responses can't carry headers (the envelope is
                # 200), so the Retry-After hint rides in the body
                err["error"]["retry_after_seconds"] = int(
                    getattr(e, "retry_after_seconds", 1))
            return err

        for index, positions in by_index.items():
            try:
                svcs = self.node.indices.resolve(index)
                if not svcs:
                    raise IndexNotFoundError(index)
            except OpenSearchTpuError as e:
                for p in positions:
                    responses[p] = err_of(e)
                continue
            bodies = [requests[p][1] for p in positions]
            results = None
            if len(svcs) == 1:
                try:
                    results = svcs[0].msearch(bodies)
                except OpenSearchTpuError:
                    results = None       # retry body-by-body below
            if results is not None:
                for p, r in zip(positions, results):
                    r["status"] = 200
                    responses[p] = r
                continue
            for p, body in zip(positions, bodies):
                try:
                    r = (svcs[0].search(body) if len(svcs) == 1
                         else self._multi_index_search(
                             [(s, None) for s in svcs], body))
                    r["status"] = 200
                    responses[p] = r
                except OpenSearchTpuError as e:
                    responses[p] = err_of(e)
        return 200, {"took": max((r.get("took", 0) for r in responses),
                                 default=0),
                     "responses": responses}

    # -- scroll / PIT ------------------------------------------------------

    def _scroll_response(self, ctx, scroll_id):
        from opensearch_tpu.search.executor import ShardSearcher  # noqa: F401
        page = ctx.next_page()
        hits = ctx.searcher._hits_from_rows(page, ctx.source_spec)
        for h in hits:
            h["_index"] = ctx.index_name
        return {"_scroll_id": scroll_id, "took": 0, "timed_out": False,
                "_shards": {"total": 1, "successful": 1, "skipped": 0,
                            "failed": 0},
                "hits": {"total": {"value": ctx.total, "relation": "eq"},
                         "max_score": None, "hits": hits}}

    def h_scroll_next(self, req):
        from opensearch_tpu.search.contexts import (ScrollContext,
                                                    parse_keepalive)
        body = req.json({}) or {}
        scroll_id = (body.get("scroll_id") or req.param("scroll_id")
                     or req.path_params.get("scroll_id"))
        if not scroll_id:
            raise ValidationError("scroll_id is required")
        # only an EXPLICIT scroll param replaces the stored keepalive; a
        # bare fetch keeps the lease the client asked for at open
        raw_ka = body.get("scroll") or req.param("scroll")
        ka = parse_keepalive(raw_ka) if raw_ka else None
        ctx = self.node.contexts.get(scroll_id, ka)
        if not isinstance(ctx, ScrollContext):
            raise ValidationError(
                f"id [{scroll_id}] is a point-in-time, not a scroll")
        self._close_context_on_cancel(scroll_id)
        return 200, self._scroll_response(ctx, scroll_id)

    def _close_context_on_cancel(self, context_id: str) -> None:
        """Cancelling the task that owns a scroll/PIT page closes the
        live reader context at once — releasing its breaker reservation
        — instead of waiting for keep-alive reaping (the reference frees
        the reader context when the scroll task is cancelled)."""
        from opensearch_tpu.common import tasks as taskmod
        task = taskmod.current()
        if task is not None:
            task.add_cancellation_listener(
                lambda: self.node.contexts.close(context_id))

    def h_scroll_clear(self, req):
        body = req.json({}) or {}
        ids = (body.get("scroll_id")
               or req.path_params.get("scroll_id") or [])
        if isinstance(ids, str):
            ids = ids.split(",")
        freed = sum(1 for i in ids if self.node.contexts.close(i))
        if ids and freed == 0:
            return 404, {"succeeded": False, "num_freed": 0}
        return 200, {"succeeded": True, "num_freed": freed}

    def h_scroll_clear_all(self, req):
        return 200, {"succeeded": True,
                     "num_freed": self.node.contexts.close_all()}

    def h_pit_open(self, req):
        from opensearch_tpu.search.contexts import (PitContext,
                                                    parse_keepalive)
        services = self._target_indices(req)
        if len(services) != 1:
            raise ValidationError(
                "point-in-time requires exactly one target index")
        svc = services[0]
        # no explicit keep_alive -> the dynamic search.default_keep_alive
        ka = parse_keepalive(
            req.param("keep_alive"),
            default_ms=int(self.node.contexts.default_keep_alive_s
                           * 1000))
        ctx = PitContext(svc.searcher(), svc.name)
        pit_id = self.node.contexts.open(ctx, ka)
        return 200, {"pit_id": pit_id,
                     "_shards": {"total": svc.num_shards,
                                 "successful": svc.num_shards,
                                 "skipped": 0, "failed": 0}}

    def h_pit_close(self, req):
        body = req.json({}) or {}
        ids = body.get("pit_id") or []
        if isinstance(ids, str):
            ids = [ids]
        freed = sum(1 for i in ids if self.node.contexts.close(i))
        return 200, {"succeeded": True, "num_freed": freed}

    _SEARCH_BODY_KEYS = frozenset({
        "query", "size", "from", "sort", "aggs", "aggregations",
        "_source", "min_score", "search_after", "highlight", "explain",
        "docvalue_fields", "fields", "script_fields", "rescore",
        "collapse", "suggest", "profile", "track_total_hits",
        "track_scores", "scroll", "slice", "pit", "timeout",
        "terminate_after", "version", "seq_no_primary_term",
        "indices_boost", "stored_fields", "post_filter",
        "_hybrid_pipeline", "allow_partial_search_results"})

    def h_search(self, req):
        body = req.json({}) or {}
        unknown = set(body) - self._SEARCH_BODY_KEYS
        if unknown:
            # the reference 400s on unknown top-level search keys
            # (SearchSourceBuilder's strict parser)
            raise ParsingError(
                f"unknown key for a search request: "
                f"[{sorted(unknown)[0]}]")
        # URI-search support: ?q= runs through query_string with its df/
        # operator/lenient params (RestSearchAction.parseSearchSource)
        q = req.param("q")
        if q:
            qs = {"query": q}
            if req.param("df"):
                qs["default_field"] = req.param("df")
            if req.param("default_operator"):
                qs["default_operator"] = req.param("default_operator")
            if req.param("analyze_wildcard") is not None:
                qs["analyze_wildcard"] = (req.param("analyze_wildcard")
                                          == "true")
            if req.param("lenient") is not None:
                qs["lenient"] = req.param("lenient") == "true"
            body.setdefault("query", {"query_string": qs})
        if req.param("size") is not None:
            body["size"] = int(req.param("size"))
        if req.param("from") is not None:
            body["from"] = int(req.param("from"))
        if req.param("allow_partial_search_results") is not None:
            # request param wins over the dynamic cluster default
            # (search.default_allow_partial_search_results); consumed by
            # the cluster coordinator's scatter phase
            body["allow_partial_search_results"] = \
                str(req.param("allow_partial_search_results")).lower() \
                != "false"
        src_spec = self._bulk_source_param(req)
        if src_spec is not None:
            body["_source"] = src_spec     # URL params override the body
        if body.get("query") is not None:
            self._resolve_terms_lookup(body["query"])
        if req.param("track_total_hits") is not None \
                and "track_total_hits" not in body:
            raw_tth = req.param("track_total_hits")
            body["track_total_hits"] = (int(raw_tth)
                                        if raw_tth.lstrip("-").isdigit()
                                        else raw_tth != "false")
        if req.param("docvalue_fields") and "docvalue_fields" not in body:
            body["docvalue_fields"] = \
                req.param("docvalue_fields").split(",")
        tth0 = body.get("track_total_hits")
        if (isinstance(tth0, int) and not isinstance(tth0, bool)
                and tth0 <= 0 and tth0 != -1):
            raise IllegalArgumentError(
                "[track_total_hits] parameter must be positive or "
                f"equals to -1, got {tth0}")
        if (req.param("rest_total_hits_as_int") == "true"
                and isinstance(tth0, int)
                and not isinstance(tth0, bool)):
            raise IllegalArgumentError(
                "[rest_total_hits_as_int] cannot be used if the tracking "
                f"of total hits is not accurate, got {tth0}")
        resp_status, resp = self._h_search_inner(req, body)
        tth = body.get("track_total_hits")
        if isinstance(resp, dict):
            hits = resp.get("hits")
            if tth is False and isinstance(hits, dict):
                if req.param("rest_total_hits_as_int") == "true":
                    # the int rendering of an untracked total is -1
                    hits["total"] = {"value": -1, "relation": "eq"}
                else:
                    hits.pop("total", None)
            elif (isinstance(tth, int) and not isinstance(tth, bool)
                  and isinstance(hits, dict)
                  and isinstance(hits.get("total"), dict)
                  and hits["total"]["value"] > tth):
                # tracking cap: report the cap with relation gte
                hits["total"] = {"value": tth, "relation": "gte"}
        return resp_status, resp

    def _resolve_terms_lookup(self, node):
        """terms lookup ({"terms": {field: {index, id, path}}}) resolves
        to the referenced doc's values at the COORDINATOR, like
        TermsQueryBuilder's fetch phase."""
        if isinstance(node, dict):
            tq = node.get("terms")
            if isinstance(tq, dict):
                for f, spec in list(tq.items()):
                    if f in ("boost", "_name") or not isinstance(spec,
                                                                 dict):
                        continue
                    if "index" not in spec or "id" not in spec:
                        continue
                    svc = self.node.indices.get(spec["index"])
                    doc = svc.get_doc(str(spec["id"]),
                                      spec.get("routing"))
                    vals = []
                    if doc is not None:
                        src = doc.get("_source") or {}
                        for part in str(spec.get("path", "")).split("."):
                            src = (src.get(part)
                                   if isinstance(src, dict) else None)
                            if src is None:
                                break
                        if src is not None:
                            vals = src if isinstance(src, list) else [src]
                    tq[f] = vals
            for v in node.values():
                self._resolve_terms_lookup(v)
        elif isinstance(node, list):
            for v in node:
                self._resolve_terms_lookup(v)

    def _h_search_inner(self, req, body):
        # search pipeline: resolve the normalization-processor config the
        # hybrid combination should use (neural-search's hook)
        pid = req.param("search_pipeline")
        if pid:
            conf = self.node.search_pipelines.hybrid_conf(pid)
            if conf is not None:
                body["_hybrid_pipeline"] = conf
        # request-cache directive: strict boolean (a typo like
        # request_cache=tru must 400, not silently disable caching —
        # RestRequest.paramAsBoolean semantics)
        rc = req.param("request_cache")
        if rc is not None:
            if str(rc).lower() not in ("true", "false"):
                raise IllegalArgumentError(
                    f"Failed to parse value [{rc}] of parameter "
                    "[request_cache] as only [true] or [false] are "
                    "allowed.")
            body["request_cache"] = str(rc).lower() == "true"
        if "request_cache" in body and \
                not isinstance(body["request_cache"], bool):
            raise IllegalArgumentError(
                "[request_cache] must be a boolean")
        # PIT search: the body names a held reader; no index in the path
        if body.get("pit"):
            return 200, self._pit_search(body)
        expr = req.path_params.get("index")
        scroll = req.param("scroll") or body.get("scroll")
        if expr and ":" in expr:
            if scroll:
                raise ValidationError(
                    "scroll is not supported with cross-cluster index "
                    "expressions")
            return 200, self._ccs_search(expr, body)
        if scroll:
            if body.get("size") == 0:
                raise IllegalArgumentError(
                    "[size] cannot be [0] in a scroll context")
            if body.get("request_cache"):
                raise IllegalArgumentError(
                    "[request_cache] cannot be used in a scroll context")
            body.pop("request_cache", None)
            if int(body.get("from", 0) or 0) > 0:
                raise IllegalArgumentError(
                    "`from` parameter must be set to 0 when `scroll` is "
                    "used")
            batch = int(body.get("size", 10)
                        if body.get("size") is not None else 10)
            if batch > 10000:
                raise IllegalArgumentError(
                    f"Batch size is too large, size must be less than or "
                    f"equal to: [10000] but was [{batch}]. Scroll batch "
                    "sizes cost as much memory as result windows so they "
                    "are controlled by the [index.max_result_window] "
                    "index level setting.")
            return 200, self._open_scroll(req, body, scroll)
        from_ = int(body.get("from", 0) or 0)
        size_ = int(body.get("size", 10)
                    if body.get("size") is not None else 10)
        if from_ < 0:
            raise IllegalArgumentError(f"[from] parameter cannot be "
                                       f"negative, found [{from_}]")
        if size_ < 0:
            raise IllegalArgumentError(f"[size] parameter cannot be "
                                       f"negative, found [{size_}]")
        # per-index window/field-count limits apply in IndexService.search
        # (index.max_result_window et al are index-level settings)
        targets = self._target_indices_filtered(req)
        if not targets:
            # allow_no_indices=true default: empty result, not an error
            return 200, {"took": 0, "timed_out": False,
                         "_shards": {"total": 0, "successful": 0,
                                     "skipped": 0, "failed": 0},
                         "hits": {"total": {"value": 0, "relation": "eq"},
                                  "max_score": None, "hits": []}}
        if len(targets) == 1:
            svc, flt = targets[0]
            return 200, svc.search(self._apply_alias_filter(body, flt))
        return 200, self._multi_index_search(targets, body)

    def _ccs_search(self, expr: str, body: dict) -> dict:
        """Cross-cluster search: 'alias:expr' parts fan out to configured
        remotes over HTTP, local parts run here, hits merge like the
        multi-index coordinator (TransportSearchAction's CCS split;
        scoring is per-cluster).  Aggregations/suggest don't reduce
        across clusters yet — rejected loudly."""
        from opensearch_tpu.transport.remote import RemoteClusterService

        if (body.get("aggs") or body.get("aggregations")
                or body.get("suggest")):
            raise ValidationError(
                "cross-cluster [aggs]/[suggest] reduce is not supported "
                "— target a single cluster")
        local_exprs, remote_map = RemoteClusterService.split_indices(expr)
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        sub = dict(body)
        sub["from"] = 0
        sub["size"] = from_ + size
        responses = []
        # remotes fan out CONCURRENTLY (each seed attempt can block on
        # its timeout; latency must be the slowest cluster, not the sum)
        remote_items = sorted(remote_map.items())
        remote_resps = []
        if remote_items:
            pool = self.node.thread_pool.executor("search")
            futures = [(alias, rexpr, pool.submit(
                self.node.remotes.search, alias, rexpr, sub))
                for alias, rexpr in remote_items]
            for alias, rexpr, fut in futures:
                r = fut.result()
                for h in r["hits"]["hits"]:
                    h["_index"] = f"{alias}:{h.get('_index', rexpr)}"
                remote_resps.append(r)
        if local_exprs:
            targets = self.node.indices.resolve_with_filters(
                ",".join(local_exprs))
            responses.extend(
                svc.search(self._apply_alias_filter(sub, flt))
                for svc, flt in targets)
        responses.extend(remote_resps)
        n_clusters = len(remote_map) + (1 if local_exprs else 0)
        out = self._merge_responses(responses, body, from_, size)
        out["_clusters"] = {"total": n_clusters,
                            "successful": n_clusters, "skipped": 0}
        return out

    def _merge_responses(self, responses, body, from_, size) -> dict:
        """Shared coordinator merge (SearchPhaseController.merge analog)
        used by the multi-index and cross-cluster paths."""
        rows = []
        for resp_idx, resp in enumerate(responses):
            for pos, h in enumerate(resp["hits"]["hits"]):
                rows.append((h, resp_idx, pos))
        from opensearch_tpu.common.telemetry import tracer
        from opensearch_tpu.search.executor import merge_hit_rows

        profiling = bool(body.get("profile"))
        t_reduce = time.monotonic() if profiling else 0.0
        with tracer().start_span("coordinator.reduce",
                                 {"sources": len(responses),
                                  "rows": len(rows)}):
            all_hits = merge_hit_rows(rows, body.get("sort"))
        total = sum(r["hits"]["total"]["value"] for r in responses)
        scores = [r["hits"]["max_score"] for r in responses
                  if r["hits"]["max_score"] is not None]
        shards = sum(r.get("_shards", {}).get("total", 1)
                     for r in responses)
        out = {
            "took": max((r["took"] for r in responses), default=0),
            # partial-results flag survives the coordinator reduce: one
            # shard running out of budget marks the whole response
            "timed_out": any(r.get("timed_out") for r in responses),
            "_shards": {"total": shards, "successful": shards,
                        "skipped": 0, "failed": 0},
            "hits": {"total": {"value": total, "relation": "eq"},
                     "max_score": max(scores) if scores else None,
                     "hits": all_hits[from_: from_ + size]},
        }
        if profiling:
            # profile merge: per-source shard sections concatenate (each
            # already carries its engine attribution), the coordinator
            # block adds the merge cost only this layer can measure
            sections = []
            for r in responses:
                sections.extend((r.get("profile") or {})
                                .get("shards") or [])
            out["profile"] = {
                "shards": sections,
                "coordinator": {
                    "sources": len(responses),
                    "reduce_time_in_nanos": int(
                        (time.monotonic() - t_reduce) * 1e9)}}
        return out

    def _open_scroll(self, req, body, scroll):
        """First scroll page: pin a searcher snapshot, materialize the
        full sorted match list, serve page one (reader-context creation;
        SearchService.createContext + scroll keepalive analog)."""
        from opensearch_tpu.search.contexts import (ScrollContext,
                                                    parse_keepalive)
        services = self._target_indices(req)
        if len(services) != 1:
            raise ValidationError(
                "scroll requires exactly one target index")
        svc = services[0]
        flt = dict(self.node.indices.resolve_with_filters(
            req.path_params["index"])).get(svc) \
            if req.path_params.get("index") else None
        body = self._apply_alias_filter(body, flt)
        # keep-alive parses BEFORE any breaker reservation: a malformed
        # value must not leak the context's request-breaker charge
        keepalive_ms = parse_keepalive(scroll)
        searcher = svc.searcher()
        rows, total = searcher.scan_rows(
            {k: v for k, v in body.items() if k != "slice"},
            slice_spec=body.get("slice"))
        ctx = ScrollContext(searcher, rows, total,
                            page_size=int(body.get("size", 10)),
                            source_spec=body.get("_source"),
                            index_name=svc.name)
        try:
            scroll_id = self.node.contexts.open(ctx, keepalive_ms)
        except OpenSearchTpuError:
            ctx.release()
            raise
        self._close_context_on_cancel(scroll_id)
        return self._scroll_response(ctx, scroll_id)

    def _pit_search(self, body):
        from opensearch_tpu.search.contexts import (PitContext,
                                                    parse_keepalive)
        pit = body["pit"]
        pit_id = pit.get("id")
        if not pit_id:
            raise ValidationError("[pit] requires an [id]")
        ka = (parse_keepalive(pit["keep_alive"])
              if pit.get("keep_alive") else None)
        ctx = self.node.contexts.get(pit_id, ka)
        if not isinstance(ctx, PitContext):
            raise ValidationError(
                f"id [{pit_id}] is a scroll, not a point-in-time")
        self._close_context_on_cancel(pit_id)
        sub = {k: v for k, v in body.items() if k != "pit"}
        resp = ctx.searcher.search(sub)
        resp["pit_id"] = pit_id
        return resp

    def _multi_index_search(self, services, body):
        """Coordinator merge over several indices (scores are per-index,
        like cross-index query_then_fetch in the reference)."""
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        aggs_json = body.get("aggs") or body.get("aggregations")
        sub = dict(body)
        sub["from"] = 0
        sub["size"] = from_ + size
        responses = [svc.search(self._apply_alias_filter(sub, flt),
                                agg_partials=bool(aggs_json))
                     for svc, flt in services]
        out = self._merge_responses(responses, body, from_, size)
        if aggs_json:
            from opensearch_tpu.search.aggs import reduce_aggs
            out["aggregations"] = reduce_aggs(
                aggs_json, [r.get("aggregation_partials") or {}
                            for r in responses])
        if body.get("suggest"):
            from opensearch_tpu.search.suggest import merge_suggest
            out["suggest"] = merge_suggest(
                [r.get("suggest") for r in responses])
        return out

    # -- cluster settings / aliases / templates / analyze ------------------

    def h_cluster_get_settings(self, req):
        buckets = getattr(self.node, "settings_buckets", None) or {
            "persistent": self.node.cluster_settings.settings.as_dict(),
            "transient": {}}
        out = {"persistent": _nest_settings(buckets["persistent"]),
               "transient": _nest_settings(buckets["transient"])}
        if req.flag("include_defaults"):
            out["defaults"] = {
                k: s.default(self.node.cluster_settings.settings)
                for k, s in
                self.node.cluster_settings._registered.items()}
        return 200, out

    def h_cluster_put_settings(self, req):
        body = req.json({}) or {}
        from opensearch_tpu.common.settings import Settings

        def flat(d):
            # flatten nested keys; preserve explicit nulls (= reset)
            out = Settings(d or {}).as_dict()
            for k, v in _flatten_nulls(d or {}):
                out[k] = v
            return out

        persistent = flat(body.get("persistent"))
        transient = flat(body.get("transient"))
        if not persistent and not transient:
            raise ValidationError(
                "no settings to update: provide [persistent] or "
                "[transient]")
        out = self.node.update_cluster_settings(
            persistent=persistent, transient=transient)
        out["persistent"] = _nest_settings(out["persistent"])
        out["transient"] = _nest_settings(out["transient"])
        return 200, out

    def h_put_index_settings(self, req):
        """Dynamic per-index settings update (RestUpdateSettingsAction);
        static settings like number_of_shards are rejected."""
        body = req.json({}) or {}
        updates = body.get("settings", body) or {}
        from opensearch_tpu.common.settings import Settings
        flat = Settings(updates).as_dict()
        for svc in self.node.indices.resolve(req.path_params["index"]):
            svc.update_settings(flat)
        return 200, {"acknowledged": True}

    def h_rollover(self, req):
        body = req.json({}) or {}
        if req.path_params.get("target"):
            body["new_index"] = req.path_params["target"]
        return 200, self.node.indices.rollover(
            req.path_params["index"], body,
            dry_run=req.flag("dry_run"))

    def _h_resize(self, req, mode):
        return 200, self.node.indices.resize(
            req.path_params["index"], req.path_params["target"], mode,
            req.json({}) or {})

    def h_resize_shrink(self, req):
        return self._h_resize(req, "shrink")

    def h_resize_split(self, req):
        return self._h_resize(req, "split")

    def h_resize_clone(self, req):
        return self._h_resize(req, "clone")

    def h_recovery(self, req):
        """Per-shard recovery report (indices/recovery/RecoveryState):
        the array engine recovers locally from commit + translog, so
        every started shard reports a DONE store recovery."""
        out = {}
        targets = (self.node.indices.resolve(req.path_params["index"])
                   if req.path_params.get("index")
                   else self.node.indices.indices.values())
        for svc in targets:
            shards = []
            for engine in svc.shards:
                shards.append({
                    "id": engine.shard_id,
                    "type": "STORE",
                    "stage": "DONE",
                    "primary": True,
                    "source": {},
                    "target": {"id": self.node.node_id,
                               "name": self.node.name},
                    "index": {"size": {}, "files": {}},
                    "translog": {"recovered": 0, "total": 0,
                                 "percent": "100.0%"},
                })
            out[svc.name] = {"shards": shards}
        return 200, out

    def h_create_data_stream(self, req):
        return 200, self.node.indices.create_data_stream(
            req.path_params["name"])

    def h_get_data_stream(self, req):
        return 200, self.node.indices.get_data_streams(
            req.path_params.get("name"))

    def h_delete_data_stream(self, req):
        return 200, self.node.indices.delete_data_stream(
            req.path_params["name"])

    def h_reroute(self, req):
        """Single-node reroute: validates command names; allocation
        decisions are a no-op with one node (the decider chain lives in
        cluster/state.allocate_shards for the multi-node path)."""
        body = req.json({}) or {}
        known = {"move", "cancel", "allocate_replica",
                 "allocate_stale_primary", "allocate_empty_primary"}
        for cmd in body.get("commands") or []:
            ((name, _args),) = cmd.items()
            if name not in known:
                raise IllegalArgumentError(
                    f"unknown reroute command [{name}]")
        return 200, {"acknowledged": True,
                     "state": {"cluster_name": self.node.cluster_name}}

    def h_update_aliases(self, req):
        body = req.json({}) or {}
        return 200, self.node.indices.update_aliases(
            body.get("actions") or [])

    def h_get_alias(self, req):
        return 200, self.node.indices.get_aliases(
            index=req.path_params.get("index"),
            name=req.path_params.get("name"))

    def h_alias_exists(self, req):
        try:
            self.node.indices.get_aliases(name=req.path_params["name"])
            return 200, {}
        except ResourceNotFoundError:
            return 404, {}

    def h_put_alias(self, req):
        body = req.json({}) or {}
        action = {"index": req.path_params["index"],
                  "alias": req.path_params["name"]}
        for k in ("filter", "is_write_index", "routing"):
            if body.get(k) is not None:
                action[k] = body[k]
        return 200, self.node.indices.update_aliases([{"add": action}])

    def h_delete_alias(self, req):
        self.node.indices.get_aliases(name=req.path_params["name"])
        return 200, self.node.indices.update_aliases([{"remove": {
            "index": req.path_params["index"],
            "alias": req.path_params["name"]}}])

    def h_put_template(self, req):
        return 200, self.node.indices.put_template(
            req.path_params["name"], req.json({}) or {})

    def h_get_template(self, req):
        return 200, self.node.indices.get_template(
            req.path_params.get("name"))

    def h_delete_template(self, req):
        return 200, self.node.indices.delete_template(
            req.path_params["name"])

    def h_analyze(self, req):
        body = req.json({}) or {}
        text = body.get("text")
        if text is None:
            raise ValidationError("[_analyze] requires [text]")
        texts = text if isinstance(text, list) else [text]
        analyzer_name = body.get("analyzer")
        index = req.path_params.get("index")
        mapper = None
        if index is not None:
            mapper = self.node.indices.get(index).mapper
        if analyzer_name is None and body.get("field") and mapper:
            ft = mapper.field_type(body["field"])
            analyzer_name = getattr(ft, "analyzer_name", "standard")
        analyzers = (mapper.analyzers if mapper is not None
                     else self._default_analyzers())
        analyzer = analyzers.get(analyzer_name or "standard")
        tokens = []
        offset = 0
        pos_base = 0
        for t in texts:
            for tok in analyzer.analyze(str(t)):
                tokens.append({
                    "token": tok.term,
                    "start_offset": offset + tok.start_offset,
                    "end_offset": offset + tok.end_offset,
                    "type": "<ALPHANUM>",
                    "position": pos_base + tok.position})
            offset += len(str(t)) + 1
            pos_base += 100      # position_increment_gap analog
        return 200, {"tokens": tokens}

    @staticmethod
    def _default_analyzers():
        from opensearch_tpu.analysis.registry import AnalysisRegistry
        return AnalysisRegistry()

    def h_cat_nodes(self, req):
        """One row per known node; ``search.rank``/``search.duress``
        expose which copies this coordinator currently prefers (lowest
        rank wins — the _cat operator view of adaptive_selection)."""
        ars = self.node.response_collector.stats()

        def row(name, stats, master="-"):
            rank = (stats or {}).get("rank")
            return {"name": name, "node.role": "dimr", "master": master,
                    "ip": "127.0.0.1",
                    "search.rank": "-" if rank is None else f"{rank:.3f}",
                    "search.duress":
                        str(bool((stats or {}).get("in_duress"))).lower()}
        rows = [row(self.node.name, ars.get(self.node.name), master="*")]
        rows.extend(row(n, s) for n, s in sorted(ars.items())
                    if n != self.node.name)
        return 200, rows

    def h_cat_aliases(self, req):
        rows = []
        for alias, targets in sorted(self.node.indices.aliases.items()):
            for ix, meta in sorted(targets.items()):
                rows.append({"alias": alias, "index": ix,
                             "filter": "*" if meta.get("filter") else "-",
                             "is_write_index":
                                 str(bool(meta.get("is_write_index")))
                                 .lower()})
        return 200, rows

    def h_cat_templates(self, req):
        return 200, [{"name": n,
                      "index_patterns": str(t.get("index_patterns")),
                      "order": str(t.get("priority", 0))}
                     for n, t in sorted(self.node.indices.templates.items())]

    def h_cat_segments(self, req):
        """Per-segment rows with HOST and DEVICE footprints: ``size``
        is the host-side array footprint (device_ledger.host_footprint,
        the one source of truth) and ``size.device`` the bytes the
        residency ledger currently holds staged for the segment (0 when
        it is host-only or was budget-evicted)."""
        from opensearch_tpu.common.device_ledger import (device_ledger,
                                                         host_footprint)
        led = device_ledger()
        rows = []
        for name, svc in sorted(self.node.indices.indices.items()):
            for shard_id, engine in sorted(svc.local_shards.items()):
                for seg in engine.segments:
                    rows.append({"index": name, "shard": str(shard_id),
                                 "segment": seg.seg_id,
                                 "docs.count": str(seg.live_count()),
                                 "docs.deleted": str(
                                     seg.n_docs - seg.live_count()),
                                 "size": str(host_footprint(seg)),
                                 "size.device": str(
                                     led.device_footprint(seg))})
        return 200, rows

    def h_cat_recovery(self, req):
        """Per-shard recovery state + the recovery.* metric family
        (corrupt-blob re-requests, retry accounting) — the _cat face of
        the ``recovery`` section in _nodes/stats."""
        from opensearch_tpu.common.telemetry import metrics
        m = metrics()
        corrupt_blobs = str(m.counter("recovery.corrupt_blobs").value)
        retries = str(
            m.counter("retry.recovery.start.retries").value
            + m.counter("retry.recovery.report.retries").value)
        rows = []
        targets = (self.node.indices.resolve(req.path_params["index"])
                   if req.path_params.get("index")
                   else self.node.indices.indices.values())
        for svc in sorted(targets, key=lambda s: s.name):
            corrupted = svc.corrupted_shards()
            for shard_id, _engine in sorted(svc.local_shards.items()):
                stage = "corrupted" if shard_id in corrupted else "done"
                rows.append({"index": svc.name, "shard": str(shard_id),
                             "type": "store", "stage": stage,
                             "source_node": "-",
                             "target_node": self.node.name,
                             "files_percent": "100.0%",
                             "bytes_percent": "100.0%",
                             "corrupt_blobs": corrupt_blobs,
                             "retries": retries})
        return 200, rows

    def h_cat_repositories(self, req):
        return 200, [{"id": name, "type": meta["type"]}
                     for name, meta in sorted(
                         self.node.snapshots.get_repository().items())]

    def h_cat_snapshots(self, req):
        repo = req.path_params["repo"]
        out = self.node.snapshots.get_snapshot(repo, "_all")
        return 200, [{"id": s["snapshot"], "status": s.get("state", ""),
                      "indices": str(len(s.get("indices", [])))}
                     for s in out.get("snapshots", [])]

    def h_cat_tasks(self, req):
        return 200, [{"action": t.action,
                      "task_id": f"{self.node.node_id}:{t.id}",
                      "type": "transport",
                      "x_opaque_id": t.headers.get("X-Opaque-Id", "-")}
                     for t in sorted(self.node.task_manager.list(),
                                     key=lambda t: t.id)]

    def h_cat_thread_pool(self, req):
        rows = []
        for name, stats in sorted(self.node.thread_pool.stats().items()):
            rows.append({"node_name": self.node.name, "name": name,
                         "active": str(stats.get("active", 0)),
                         "queue": str(stats.get("queue", 0)),
                         "rejected": str(stats.get("rejected", 0))})
        return 200, rows

    def h_cat_pending_tasks(self, req):
        return 200, []               # single node: no pending state tasks

    def h_cat_plugins(self, req):
        # built-in module set (the reference lists installed plugins)
        return 200, [{"name": self.node.name, "component": c,
                      "version": VERSION}
                     for c in ("analysis-common", "ingest-common",
                               "parent-join", "percolator", "rank-eval",
                               "reindex", "search-pipeline-common")]

    def h_cat_cluster_manager(self, req):
        return 200, [{"id": self.node.node_id, "host": self.node.host,
                      "ip": self.node.host, "node": self.node.name}]

    def h_cat_nodeattrs(self, req):
        return 200, [{"node": self.node.name, "host": self.node.host,
                      "attr": "accelerator", "value": "tpu"}]

    def h_cat_allocation(self, req):
        shards = sum(s.num_shards
                     for s in self.node.indices.indices.values())
        return 200, [{"shards": str(shards), "node": self.node.name,
                      "host": self.node.host, "ip": self.node.host}]

    def h_cat_fielddata(self, req):
        """Per-field doc-value footprint from the ONE footprint source
        of truth (device_ledger.host_footprint) instead of ad-hoc
        ``nbytes`` math picking an arbitrary subset of the arrays."""
        from opensearch_tpu.common.device_ledger import host_footprint
        rows = []
        for name, svc in sorted(self.node.indices.indices.items()):
            for engine in svc.shards:
                for seg in engine.segments:
                    per = host_footprint(seg, per_field=True)
                    for (kind, field), nbytes in sorted(per.items()):
                        if kind != "ordinal":
                            continue
                        rows.append({
                            "node": self.node.name, "field": field,
                            "size": str(nbytes)})
        return 200, rows

    # -- task management ---------------------------------------------------

    def _task_payload(self, tasks):
        return {"nodes": {self.node.node_id: {
            "name": self.node.name,
            "tasks": {f"{self.node.node_id}:{t.id}": t.info()
                      for t in tasks}}}}

    def h_security_list_users(self, req):
        return 200, self.node.identity.list_users()

    def h_security_put_user(self, req):
        body = req.json({}) or {}
        # path_params directly: req.param() would let a ?username= query
        # parameter retarget the operation at a different account
        name = req.path_params["username"]
        created = self.node.identity.put_user(
            name, body.get("password") or "",
            body.get("roles"))   # None preserves roles (rotation)
        return 200, {"user": name, "created": created}

    def h_security_delete_user(self, req):
        name = req.path_params["username"]
        if not self.node.identity.delete_user(name):
            from opensearch_tpu.common.errors import \
                ResourceNotFoundError
            raise ResourceNotFoundError(f"user [{name}] not found")
        return 200, {"user": name, "deleted": True}

    def h_tasks_list(self, req):
        return 200, self._task_payload(
            self.node.task_manager.list(req.param("actions")))

    @staticmethod
    def _parse_task_id(raw: str) -> int:
        # accepts bare ids and the node_id:task_id composite form
        try:
            return int(raw.rsplit(":", 1)[-1])
        except ValueError:
            raise ValidationError(f"invalid task id [{raw}]") from None

    def h_task_get(self, req):
        raw = req.path_params["task_id"]
        # persistent tasks (reindex?wait_for_completion=false) answer
        # here too, like the reference's GET _tasks/<id> for reindex
        pt = self.node.persistent_tasks.get_or_none(raw)
        if pt is not None:
            done = pt["state"] in ("completed", "failed")
            return 200, {"completed": done,
                         "task": {"id": raw, "action": pt["action"],
                                  "state": pt["state"]},
                         **({"response": pt.get("result")}
                            if pt.get("result") else {}),
                         **({"error": pt["error"]}
                            if pt.get("error") else {})}
        tid = self._parse_task_id(raw)
        t = self.node.task_manager.get(tid)
        if t is None:
            raise ResourceNotFoundError(f"task [{tid}] isn't running")
        return 200, {"completed": False, "task": t.info()}

    def h_persistent_tasks_list(self, req):
        return 200, {"tasks": self.node.persistent_tasks.list()}

    def h_task_cancel(self, req):
        tid = self._parse_task_id(req.path_params["task_id"])
        cancelled = self.node.task_manager.cancel(task_id=tid)
        if not cancelled:
            raise ResourceNotFoundError(f"task [{tid}] isn't running")
        return 200, self._task_payload(cancelled)

    def h_tasks_cancel_all(self, req):
        return 200, self._task_payload(self.node.task_manager.cancel(
            actions=req.param("actions") or "*"))

    # -- search pipelines --------------------------------------------------

    def h_get_pipelines(self, req):
        return 200, self.node.search_pipelines.get()

    def h_get_pipeline(self, req):
        return 200, self.node.search_pipelines.get(req.path_params["id"])

    def h_put_pipeline(self, req):
        return 200, self.node.search_pipelines.put(
            req.path_params["id"], req.json({}) or {})

    def h_delete_pipeline(self, req):
        return 200, self.node.search_pipelines.delete(
            req.path_params["id"])

    def h_remotestore_restore(self, req):
        """Restore lost indices from their remote store mirrors (the
        remotestore restore action).  The index must not be open locally
        — remote store is the survivor copy after total local loss."""
        import json as _json

        from opensearch_tpu.common.blobstore import NoSuchBlobError
        from opensearch_tpu.index import remote_store as rs

        body = req.json({}) or {}
        names = body.get("indices")
        if not names:
            raise ValidationError(
                "[_remotestore/_restore] requires [indices]")
        if isinstance(names, str):
            names = [n.strip() for n in names.split(",") if n.strip()]
        restored = []
        for name in names:
            if self.node.indices.exists(name):
                raise ValidationError(
                    f"cannot restore [{name}]: an open index with that "
                    "name exists — delete it first")
            # find which repository mirrors it
            found = None
            for repo_name in self.node.snapshots.get_repository():
                repo = self.node.snapshots._repo(repo_name)
                try:
                    meta = _json.loads(repo.store.container(
                        f"remote/{name}").read_blob("_meta.json"))
                except NoSuchBlobError:
                    continue
                found = (repo, meta)
                break
            if found is None:
                raise ResourceNotFoundError(
                    f"no remote store data for index [{name}]")
            repo, meta = found
            settings = dict(meta.get("settings") or {})
            n_shards = int(settings.get("number_of_shards", 1))
            # every shard manifest must exist BEFORE any file lands:
            # a partial restore would leave resurrectable orphan dirs
            missing = [sid for sid in range(n_shards)
                       if rs.read_manifest(repo, name, sid) is None]
            if missing:
                raise ResourceNotFoundError(
                    f"remote store for [{name}] is incomplete — "
                    f"missing shard manifests {missing}")
            index_path = os.path.join(self.node.indices.data_path, name)
            try:
                for shard_id in range(n_shards):
                    rs.restore_shard(
                        repo, name, shard_id,
                        os.path.join(index_path, str(shard_id)))
                self.node.indices.open_restored(name, settings,
                                                meta.get("mappings"))
            except Exception:
                import shutil as _shutil
                _shutil.rmtree(index_path, ignore_errors=True)
                raise
            restored.append(name)
        return 200, {"remote_store": {"indices": restored},
                     "acknowledged": True}

    # -- snapshots ---------------------------------------------------------

    def h_get_repos(self, req):
        return 200, self.node.snapshots.get_repository()

    def h_put_repo(self, req):
        return 200, self.node.snapshots.put_repository(
            req.path_params["repo"], req.json({}) or {})

    def h_get_repo(self, req):
        return 200, self.node.snapshots.get_repository(
            req.path_params["repo"])

    def h_delete_repo(self, req):
        return 200, self.node.snapshots.delete_repository(
            req.path_params["repo"])

    def h_create_snapshot(self, req):
        return 200, self.node.snapshots.create_snapshot(
            req.path_params["repo"], req.path_params["snapshot"],
            req.json({}) or {})

    def h_get_snapshot(self, req):
        return 200, self.node.snapshots.get_snapshot(
            req.path_params["repo"], req.path_params["snapshot"])

    def h_delete_snapshot(self, req):
        return 200, self.node.snapshots.delete_snapshot(
            req.path_params["repo"], req.path_params["snapshot"])

    def h_restore_snapshot(self, req):
        return 200, self.node.snapshots.restore_snapshot(
            req.path_params["repo"], req.path_params["snapshot"],
            req.json({}) or {})

    def h_count(self, req):
        body = req.json({}) or {}
        unknown = set(body) - {"query"}
        if unknown:
            raise ParsingError(
                f"request does not support {sorted(unknown)}")
        q = req.param("q")
        if q and "query" not in body:
            qs = {"query": q}
            if req.param("df"):
                qs["default_field"] = req.param("df")
            if req.param("analyze_wildcard") is not None:
                qs["analyze_wildcard"] = (req.param("analyze_wildcard")
                                          == "true")
            if req.param("lenient") is not None:
                qs["lenient"] = req.param("lenient") == "true"
            if req.param("default_operator"):
                qs["default_operator"] = req.param("default_operator")
            body["query"] = {"query_string": qs}
        services = self._target_indices_filtered(req)
        total = sum(
            svc.count(self._apply_alias_filter(
                {"query": body.get("query")}, flt)["query"])
            for svc, flt in services)
        n_shards = sum(svc.num_shards for svc, _f in services)
        return 200, {"count": total,
                     "_shards": {"total": n_shards,
                                 "successful": n_shards, "skipped": 0,
                                 "failed": 0}}
