"""Threaded HTTP front-end for the REST controller.

Analog of the netty4 HTTP transport (modules/transport-netty4/...
Netty4HttpServerTransport.java) at the fidelity this slice needs: a
thread-per-connection stdlib server handing parsed (method, path, params,
body) to ``RestController.dispatch``.  _cat endpoints render text tables
unless ``format=json`` (rest/action/cat/ behavior).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlsplit


def _cat_table(rows: list[dict], want_header: bool,
               columns: str | None = None) -> bytes:
    if not rows:
        return b""
    cols = list(rows[0])
    if columns:                       # ?h=a,b column selection
        cols = [c.strip() for c in columns.split(",") if c.strip()]
    widths = {c: max(len(c) if want_header else 0,
                     *(len(str(r.get(c, ""))) for r in rows)) for c in cols}
    out = []
    if want_header:
        out.append(" ".join(c.ljust(widths[c]) for c in cols).rstrip())
    for r in rows:
        out.append(" ".join(str(r.get(c, "")).ljust(widths[c])
                            for c in cols).rstrip())
    return ("\n".join(out) + "\n").encode()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "opensearch-tpu"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _handle(self):
        from opensearch_tpu.common.breakers import (CircuitBreakingError,
                                                    breaker_service)

        split = urlsplit(self.path)
        params = dict(parse_qsl(split.query, keep_blank_values=True))
        length = int(self.headers.get("Content-Length") or 0)
        # in-flight byte accounting BEFORE the body is read into memory
        # (the reference's in_flight_requests breaker / IndexingPressure
        # admission check)
        breaker = breaker_service().in_flight
        extra_headers: dict = {}
        try:
            breaker.add_estimate(length, label=f"<http_request> "
                                               f"{split.path}")
        except CircuitBreakingError as e:
            # the body stays UNREAD (that's the point) — the connection
            # cannot be reused, or the next parse reads body bytes as a
            # request line
            self.close_connection = True
            status, payload = 429, e.to_xcontent()
            # Retry-After from the measured admission drain rate
            # (permit-release EWMA, floor/ceiling clamped) instead of a
            # hardcoded second — a wedged node tells clients to
            # actually back off
            hint = 1
            bp = getattr(self.server.controller.node,
                         "search_backpressure", None)
            if bp is not None:
                hint = bp.admission.retry_after_hint()
            extra_headers["Retry-After"] = str(hint)
        else:
            try:
                body = self.rfile.read(length) if length else b""
                status, payload = self.server.controller.dispatch(
                    self.command, split.path, params, body,
                    self.headers.get("Content-Type") or "",
                    self.headers.get("Authorization") or "",
                    headers=dict(self.headers.items()),
                    response_headers=extra_headers)
            finally:
                breaker.release(length)
        from opensearch_tpu.rest.controller import PlainText
        is_cat = split.path.startswith("/_cat") and params.get("format") != "json"
        if isinstance(payload, PlainText):
            # verbatim text surface (Prometheus /_metrics exposition):
            # no x-content negotiation, the payload IS the wire format
            data = payload.text.encode()
            ctype = payload.content_type
        elif is_cat and isinstance(payload, list):
            data = _cat_table(payload, want_header="v" in params,
                              columns=params.get("h"))
            ctype = "text/plain; charset=UTF-8"
        else:
            # response format negotiation (x-content: json/yaml/cbor via
            # ?format= or Accept); _cat keeps its table/json handling
            from opensearch_tpu.common.errors import OpenSearchTpuError
            from opensearch_tpu.common.xcontent import to_bytes
            fmt = params.get("format") or ""
            if split.path.startswith("/_cat"):
                # only format=json reaches here (tables short-circuit
                # above); pin it so Accept can't override an explicit
                # format=json request
                fmt = "json"
            try:
                data, ctype = to_bytes(payload,
                                       self.headers.get("Accept") or "",
                                       fmt)
            except OpenSearchTpuError as e:
                status = e.status
                data = (json.dumps(e.to_xcontent()) + "\n").encode()
                ctype = "application/json; charset=UTF-8"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        for k, v in extra_headers.items():
            # error-mapping headers (Retry-After on 429 rejections)
            self.send_header(k, str(v))
        opaque = self.headers.get("X-Opaque-Id")
        if opaque:
            # the reference echoes X-Opaque-Id on every response so
            # clients can correlate (Task.X_OPAQUE_ID response header)
            self.send_header("X-Opaque-Id", opaque)
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(data)

    do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _handle


class _Server(ThreadingHTTPServer):
    # accept backlog sized like the reference's netty transport, not the
    # stdlib default (5): the open-loop load harness showed bursts of
    # concurrent connects overflowing the backlog — the kernel then
    # refuses/resets, which clients see as transport errors rather than
    # an honest 429 with Retry-After.  The OS clamps to somaxconn.
    request_queue_size = 1024


class HttpServer:
    def __init__(self, controller, host: str = "127.0.0.1", port: int = 9200):
        self.httpd = _Server((host, port), _Handler)
        self.httpd.controller = controller
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="http-server", daemon=True)
        self._thread.start()

    def stop(self):
        """Idempotent, and safe WITHOUT a prior start():
        ``ThreadingHTTPServer.shutdown()`` blocks forever unless
        ``serve_forever`` is actually running, so it is only called when
        the serving thread exists."""
        thread, self._thread = self._thread, None
        if thread is not None:
            self.httpd.shutdown()
        self.httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)
