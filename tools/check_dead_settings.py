#!/usr/bin/env python
"""Lint: every dynamic setting registered in node.py must be ALIVE.

A dynamic ``_cluster/settings`` knob that validates and persists but
reaches no consumer is worse than a missing one: operators flip it, the
API acknowledges, and nothing changes (this repo shipped two —
``search_backpressure.mode`` pre-PR-4 and ``search.default_keep_alive``
pre-PR-14).  So for every ``name = Setting...("key", ..., dynamic=True)``
assignment in ``opensearch_tpu/node.py``, the assigned name must be
USED beyond merely being listed in the ``SettingsRegistry(...)``
constructor — an ``add_settings_update_consumer(name, ...)`` wiring, a
module-global setter tuple, or any other read site in the file counts.
A deliberately consumer-less setting (compat/validation-only) carries a
``# knob-ok`` annotation on the assignment line or a line above it.

Sibling of ``check_seeded_rng.py``/``check_metric_names.py``; new dead
knobs fail tier-1 (tests/test_qos.py runs this check).

Usage: python tools/check_dead_settings.py [file ...]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# knob-ok"


def _setting_assignments(tree: ast.AST) -> list[tuple[str, str, int]]:
    """(var_name, setting_key, lineno) for every ``name = Setting...(
    "key", ..., dynamic=True)`` assignment."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        call = node.value
        if not isinstance(call, ast.Call):
            continue
        fn = call.func
        # Setting(...) or Setting.int_setting(...) / .bool_setting(...)
        is_setting = (isinstance(fn, ast.Name) and fn.id == "Setting") \
            or (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "Setting")
        if not is_setting:
            continue
        if not call.args or not isinstance(call.args[0], ast.Constant) \
                or not isinstance(call.args[0].value, str):
            continue
        dynamic = any(kw.arg == "dynamic"
                      and isinstance(kw.value, ast.Constant)
                      and kw.value.value is True
                      for kw in call.keywords)
        if not dynamic:
            continue
        out.append((target.id, call.args[0].value, node.lineno))
    return out


def _registry_name_counts(tree: ast.AST) -> dict[str, int]:
    """How many times each Name is loaded INSIDE a
    ``SettingsRegistry(...)`` constructor call (those loads are mere
    registration, not consumption)."""
    counts: dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name != "SettingsRegistry":
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                        ast.Load):
                counts[sub.id] = counts.get(sub.id, 0) + 1
    return counts


def _load_counts(tree: ast.AST) -> dict[str, int]:
    counts: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            counts[node.id] = counts.get(node.id, 0) + 1
    return counts


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error ({e.msg})"]
    lines = src.splitlines()
    loads = _load_counts(tree)
    registry = _registry_name_counts(tree)
    problems = []
    for name, key, lineno in _setting_assignments(tree):
        consumed = loads.get(name, 0) - registry.get(name, 0)
        if consumed > 0:
            continue
        annotated = False
        for ln in range(max(0, lineno - 2), min(len(lines), lineno)):
            if ANNOTATION in lines[ln]:
                annotated = True
        if annotated:
            continue
        problems.append(
            f"{path}:{lineno}: dynamic setting [{key}] (var [{name}]) "
            "is registered but has no live consumer — wire an "
            "add_settings_update_consumer / module-global setter / "
            f"read site, or annotate '{ANNOTATION}'")
    return problems


def _default_roots() -> list[str]:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(repo, "opensearch_tpu", "node.py")]


def main(argv: list[str]) -> int:
    roots = argv[1:] or _default_roots()
    problems = []
    for root in roots:
        if os.path.isfile(root):
            problems.extend(check_file(root))
            continue
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in sorted(filenames):
                if name.endswith(".py"):
                    problems.extend(check_file(
                        os.path.join(dirpath, name)))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} dead dynamic setting(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
