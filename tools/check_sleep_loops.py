#!/usr/bin/env python
"""Lint: polling loops must be deadline- or backoff-bounded.

A bare ``time.sleep(...)`` inside a ``while``/``for`` loop is an
unbounded polling loop waiting to happen: when the condition it polls
never turns true (a dead peer, a lost frame) the loop spins forever and
the retry path it implements has no budget.  Every sleep call inside a
loop body under ``opensearch_tpu/`` must therefore either go through
``common/retry.py`` (BackoffPolicy/Deadline, which are budget-capped on
the monotonic clock) or carry a ``# backoff`` / ``# deadline``
annotation on the same line or the line above, asserting a human
checked the loop is bounded.

Sibling of ``check_monotonic.py``; new un-annotated sites fail tier-1
(tests/test_fault_tolerance.py runs this check).

Usage: python tools/check_sleep_loops.py [root]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATIONS = ("# backoff", "# deadline")


def _sleep_calls_in_loops(tree: ast.AST) -> list[int]:
    """Line numbers of ``time.sleep``/bare ``sleep``/``Event.wait``-free
    sleep calls lexically inside a While/For body."""
    out = []

    def walk(node: ast.AST, in_loop: bool):
        if isinstance(node, ast.Call):
            fn = node.func
            is_sleep = (isinstance(fn, ast.Attribute)
                        and fn.attr == "sleep") or \
                       (isinstance(fn, ast.Name) and fn.id == "sleep")
            if is_sleep and in_loop:
                out.append(node.lineno)
        entering_loop = isinstance(node, (ast.While, ast.For,
                                          ast.AsyncFor))
        # a nested function/class restarts the scope: its loops count on
        # their own, but an outer loop does not taint the inner def
        resets = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef, ast.Lambda))
        for child in ast.iter_child_nodes(node):
            walk(child, (in_loop or entering_loop) and not resets)

    walk(tree, False)
    return out


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error ({e.msg})"]
    lines = src.splitlines()
    problems = []
    for lineno in _sleep_calls_in_loops(tree):
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        prev = lines[lineno - 2] if lineno >= 2 else ""
        if any(a in line or a in prev for a in ANNOTATIONS):
            continue
        problems.append(
            f"{path}:{lineno}: sleep() inside a loop without a "
            "'# backoff' or '# deadline' annotation — bound it with "
            "common/retry.py (BackoffPolicy/Deadline) or annotate why "
            "the loop cannot spin forever")
    return problems


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opensearch_tpu")
    problems = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if name.endswith(".py"):
                problems.extend(check_file(os.path.join(dirpath, name)))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} unbounded sleep-in-loop site(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
