#!/usr/bin/env python
"""Lint: device staging goes through the residency ledger.

``common/device_ledger.py`` is the ONE place host arrays become device
arrays: it records the owner, exact nbytes, and transfer time of every
staging, and it is what lets `_nodes/stats` answer "what is on the
device and who put it there" — a raw ``jax.device_put(...)`` or
``jnp.asarray(...)`` elsewhere in the staging-bearing packages creates
device-resident memory the ledger (and the device-memory budget
enforcement built on it) cannot see.

Scope: ``opensearch_tpu/index/``, ``search/``, ``parallel/``, ``ops/``.
Flagged call patterns (line-based, like check_monotonic.py):

- ``jax.device_put(``
- ``jnp.asarray(`` / ``jax.numpy.asarray(``

A deliberate non-resident staging — a 4-byte query scalar, a per-query
input cached elsewhere, trace-time array creation inside a jitted
function, or ANN-builder staging that the segment ledger ``adopt``s —
carries a ``# staging-ok`` annotation on the same line or the line
above.

Sibling of ``check_hot_path_sync.py`` et al.; new un-annotated sites
fail tier-1 (tests/test_device_ledger.py runs this check).

Usage: python tools/check_device_staging.py [root]   (exit 0 = clean)
"""

from __future__ import annotations

import os
import re
import sys

ANNOTATION = "# staging-ok"

# directories (relative to the package root) whose staging is linted
SCOPES = ("index", "search", "parallel", "ops")

_PATTERNS = (
    (re.compile(r"\bjax\.device_put\s*\("), "jax.device_put(...)"),
    (re.compile(r"\bjnp\.asarray\s*\("), "jnp.asarray(...)"),
    (re.compile(r"\bjax\.numpy\.asarray\s*\("), "jax.numpy.asarray(...)"),
)


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    problems = []
    for i, line in enumerate(lines):
        for pat, what in _PATTERNS:
            if not pat.search(line):
                continue
            prev = lines[i - 1] if i else ""
            if ANNOTATION in line or ANNOTATION in prev:
                continue
            problems.append(
                f"{path}:{i + 1}: raw {what} — device staging must go "
                "through common/device_ledger.py (stage/device_put/"
                "adopt) so residency and transfer accounting stay "
                f"exact, or carry a '{ANNOTATION}' annotation on this "
                "or the previous line")
    return problems


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opensearch_tpu")
    problems = []
    for scope in SCOPES:
        scope_dir = os.path.join(root, scope)
        if not os.path.isdir(scope_dir):
            # linting a sample tree (the lint's own tests): scan root
            scope_dir = root if scope == SCOPES[0] else None
        if scope_dir is None:
            continue
        for dirpath, _dirs, files in os.walk(scope_dir):
            if "__pycache__" in dirpath:
                continue
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                problems.extend(check_file(os.path.join(dirpath, fname)))
    for p in sorted(set(problems)):
        print(p)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
