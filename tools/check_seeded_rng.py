#!/usr/bin/env python
"""Lint: the chaos/bench harness may only use SEEDED randomness.

The soak harness's whole contract is replayability: the same seed must
produce the same op stream, the same fault schedule, and the same SLO
verdicts (tests/test_soak.py asserts it), and the open-loop load
harness (``testing/loadgen.py``) extends the same contract to arrival
schedules, per-pack request streams, and retry jitter
(tests/test_loadgen.py pins those).  One unseeded
``random.Random()`` or ``np.random.default_rng()`` anywhere in the
harness silently breaks that — the run still "works", it just stops
being a regression gate.  So under ``opensearch_tpu/testing/`` (which
includes ``loadgen.py``) and in ``bench.py``, every RNG construction
must pass an explicit seed argument, or carry a ``# seeded-elsewhere``
annotation on the same line or the line above (for RNGs that are
re-seeded before use).

Sibling of ``check_monotonic.py``/``check_sleep_loops.py``; new
un-seeded sites fail tier-1 (tests/test_soak.py runs this check).

Usage: python tools/check_seeded_rng.py [root ...]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# seeded-elsewhere"

#: constructor names whose no-argument form yields an OS-entropy RNG
RNG_CTORS = ("Random", "default_rng", "RandomState", "SystemRandom")


def _unseeded_rng_calls(tree: ast.AST) -> list[int]:
    """Line numbers of RNG constructions with no seed argument."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name not in RNG_CTORS:
            continue
        seeded = bool(node.args) or any(
            kw.arg in ("seed", "x") for kw in node.keywords)
        if not seeded:
            out.append(node.lineno)
    return out


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error ({e.msg})"]
    lines = src.splitlines()
    problems = []
    for lineno in _unseeded_rng_calls(tree):
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        prev = lines[lineno - 2] if lineno >= 2 else ""
        if ANNOTATION in line or ANNOTATION in prev:
            continue
        problems.append(
            f"{path}:{lineno}: RNG constructed without an explicit seed "
            "in a replayable-harness module — pass a seed, or annotate "
            f"'{ANNOTATION}' if it is re-seeded before use")
    return problems


def _default_roots() -> list[str]:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(repo, "opensearch_tpu", "testing"),
            os.path.join(repo, "bench.py")]


def main(argv: list[str]) -> int:
    roots = argv[1:] or _default_roots()
    problems = []
    for root in roots:
        if os.path.isfile(root):
            problems.extend(check_file(root))
            continue
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in sorted(filenames):
                if name.endswith(".py"):
                    problems.extend(check_file(
                        os.path.join(dirpath, name)))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} unseeded RNG site(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
