#!/usr/bin/env python
"""Lint: device faults are never silently swallowed.

The accelerator fault-tolerance layer (common/device_health.py) only
works if every handler that catches a jax/XLA/device error leaves
EVIDENCE: a ``device.*`` metric increment, or a DeviceHealthService
record call (``record_failure`` / ``record_poison`` — which increment
``device.errors`` / ``device.poisoned_results`` internally), or one of
the ledger's counted degradations (``record_host_fallback`` /
``record_restage``).  An ``except`` that catches a device error and
does none of those turns a misbehaving accelerator into silent garbage
— exactly the failure mode the breakers, the soak SLOs, and the
``_nodes/stats`` ``device.health`` surface exist to prevent.

Scope: ``opensearch_tpu/{search,index,parallel,ops}/``.  A handler is
IN SCOPE when its exception clause names a device-error type
(``XlaRuntimeError``, ``InjectedDeviceError``, ``DeviceDegradedError``,
``DevicePoisonError``, ``MemoryError``) OR its body consults the
classifier ``is_device_error`` (the broad-catch-then-classify idiom the
executor uses).  In-scope handlers must contain one of the evidence
calls above, or carry a ``# degrade-ok`` annotation on the ``except``
line or the line above (for handlers that re-raise into an already-
counted path).

Sibling of check_device_staging.py et al.; new un-annotated sites fail
tier-1 (tests/test_device_faults.py runs this check).

Usage: python tools/check_degraded_paths.py [root]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# degrade-ok"

# directories (relative to the package root) whose handlers are linted
SCOPES = ("search", "index", "parallel", "ops")

#: exception type names whose except-clauses are device-fault handlers
DEVICE_ERROR_NAMES = frozenset({
    "XlaRuntimeError", "InjectedDeviceError", "InjectedOOMError",
    "InjectedCompileError", "InjectedDispatchError",
    "InjectedMeshLossError", "DeviceDegradedError", "DevicePoisonError",
    "MemoryError",
})

#: calls inside a handler that count as degradation evidence
EVIDENCE_CALLS = frozenset({
    "record_failure", "record_success", "record_poison",
    "record_host_fallback", "record_restage", "is_device_error",
})


def _names_of(expr) -> set:
    """Flatten an except clause's type expression into bare names."""
    out: set = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def _handler_evidence(handler: ast.ExceptHandler) -> bool:
    """True when the handler body (or its guard) carries evidence: a
    DeviceHealthService/ledger record call, a ``device.*`` metric
    increment, or the is_device_error classifier (whose False branch
    re-raises the non-device error)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name in EVIDENCE_CALLS:
                return True
            if name == "counter" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str) \
                        and arg.value.startswith("device."):
                    return True
    return False


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as exc:
        return [f"{path}:{exc.lineno}: syntax error: {exc.msg}"]
    lines = src.splitlines()
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        in_scope = False
        if node.type is not None \
                and _names_of(node.type) & DEVICE_ERROR_NAMES:
            in_scope = True
        elif any(isinstance(c, ast.Name) and c.id == "is_device_error"
                 or isinstance(c, ast.Attribute)
                 and c.attr == "is_device_error"
                 for b in node.body for c in ast.walk(b)):
            in_scope = True
        if not in_scope:
            continue
        lineno = node.lineno
        this = lines[lineno - 1] if lineno - 1 < len(lines) else ""
        prev = lines[lineno - 2] if lineno >= 2 else ""
        if ANNOTATION in this or ANNOTATION in prev:
            continue
        if _handler_evidence(node):
            continue
        problems.append(
            f"{path}:{lineno}: except handler catches device/XLA "
            "errors without evidence — increment a 'device.*' metric "
            "or call DeviceHealthService.record_failure/record_poison "
            "(common/device_health.py) so the fault is counted and "
            "the breakers see it, or annotate with "
            f"'{ANNOTATION}' on this or the previous line")
    return problems


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opensearch_tpu")
    problems = []
    for scope in SCOPES:
        scope_dir = os.path.join(root, scope)
        if not os.path.isdir(scope_dir):
            # linting a sample tree (the lint's own tests): scan root
            scope_dir = root if scope == SCOPES[0] else None
        if scope_dir is None:
            continue
        for dirpath, _dirs, files in os.walk(scope_dir):
            if "__pycache__" in dirpath:
                continue
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                problems.extend(check_file(os.path.join(dirpath, fname)))
    for p in sorted(set(problems)):
        print(p)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
