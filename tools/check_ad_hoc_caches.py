#!/usr/bin/env python
"""Lint: no new unbounded dict caches outside common/cache.py.

The ``obj._x_cache = {}`` idiom is an unbounded, unaccounted memory
leak waiting for a big tenant: nothing evicts it, no circuit breaker
sees it, no stats surface reports it.  This engine's sanctioned cache
primitive is ``opensearch_tpu.common.cache.Cache`` (weighted LRU,
breaker-accounted, telemetry-wired) with ``attached_cache`` for the
per-object pattern.

Rule: an assignment whose target name contains "cache" (attribute or
plain name, plus annotated assignments) and whose value is a dict
literal / comprehension or a ``dict()``/``OrderedDict()``/
``defaultdict()`` call — anywhere under ``opensearch_tpu/`` except
``common/cache.py`` — must either migrate to the cache primitive or
carry a ``# bounded-cache`` annotation (same line or the line above)
explaining why the mapping cannot grow without bound.

Sibling of ``check_monotonic.py`` / ``check_sleep_loops.py``; new
un-annotated sites fail tier-1 (tests/test_request_cache.py runs this).

Usage: python tools/check_ad_hoc_caches.py [root]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# bounded-cache"
EXEMPT_SUFFIXES = (os.path.join("common", "cache.py"),)

_DICT_CTORS = {"dict", "OrderedDict", "defaultdict", "WeakValueDictionary",
               "WeakKeyDictionary"}


def _is_dict_valued(value: ast.AST) -> bool:
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return True
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
        return name in _DICT_CTORS
    return False


def _target_cache_name(target: ast.AST) -> str | None:
    if isinstance(target, ast.Attribute) and "cache" in target.attr.lower():
        return target.attr
    if isinstance(target, ast.Name) and "cache" in target.id.lower():
        return target.id
    return None


def _violations(tree: ast.AST) -> list[tuple[int, str]]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not _is_dict_valued(value):
            continue
        for target in targets:
            name = _target_cache_name(target)
            if name is not None:
                out.append((node.lineno, name))
    return out


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error ({e.msg})"]
    lines = src.splitlines()
    problems = []
    for lineno, name in _violations(tree):
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        prev = lines[lineno - 2] if lineno >= 2 else ""
        if ANNOTATION in line or ANNOTATION in prev:
            continue
        problems.append(
            f"{path}:{lineno}: [{name}] assigned a raw dict — an "
            "unbounded, unaccounted cache.  Use opensearch_tpu.common."
            "cache.Cache / attached_cache (weighted LRU + breaker "
            f"accounting), or annotate with '{ANNOTATION}' and why the "
            "mapping is bounded")
    return problems


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opensearch_tpu")
    problems = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            if any(path.endswith(sfx) for sfx in EXEMPT_SUFFIXES):
                continue
            problems.extend(check_file(path))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} unbounded dict-cache site(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
