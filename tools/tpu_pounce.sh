#!/bin/bash
# Probe-and-pounce: the accelerator tunnel works in short windows and
# wedges for hours.  Loop a cheap probe; the moment it answers, run the
# staged bench (bench.py) which records any accelerator result to
# BENCH_TPU_RECORD.json.  Exits once a TPU-platform result lands.
cd /root/repo
LOG=/root/repo/bench_tpu_r05.log
while true; do
  if timeout 90 python -c "import jax; assert jax.default_backend() != 'cpu', jax.default_backend(); print(jax.devices())" >> "$LOG" 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel alive - running staged bench" >> "$LOG"
    OSTPU_BENCH_TPU_TIMEOUT=2400 OSTPU_BENCH_PROBE_TRIES=1 timeout 2700 \
      python bench.py > /tmp/bench_tpu_attempt.json 2>> "$LOG"
    echo "$(date -u +%FT%TZ) bench attempt done: $(cat /tmp/bench_tpu_attempt.json)" >> "$LOG"
    if [ -f /root/repo/BENCH_TPU_RECORD.json ]; then
      echo "$(date -u +%FT%TZ) TPU RESULT RECORDED" >> "$LOG"
      exit 0
    fi
  else
    echo "$(date -u +%FT%TZ) probe failed/wedged" >> "$LOG"
  fi
  sleep 150
done
