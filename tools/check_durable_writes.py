#!/usr/bin/env python
"""Lint: storage-layer file writes must be crash-safe.

The storage fault-tolerance work (checksummed segment manifests, atomic
commit renames, torn-write recovery) only holds if EVERY write under the
durable roots follows the tmp + fsync + atomic-rename discipline — one
bare ``open(path, "w")`` that writes a final name in place reintroduces
the torn-file window the whole subsystem exists to close.

Rule: any ``open()`` call with a literal write mode (containing ``w``,
``a``, ``x`` or ``+``) inside ``opensearch_tpu/index/``,
``opensearch_tpu/snapshots/`` or ``opensearch_tpu/cluster/gateway.py``
must live in a function whose body shows the full durable-write pattern
— a ``".tmp"`` staging name, an ``fsync``, and an ``os.replace`` — or
carry a ``# non-durable-ok`` annotation on the same line or the line
above (for writes that are durable by other means: the translog's
append-only generation file is fsynced by ``sync()`` and recovered by
CRC-based torn-tail truncation, not by rename).

Sibling of ``check_monotonic.py``/``check_seeded_rng.py``; new
non-durable sites fail tier-1 (tests/test_storage_faults.py runs this).

Usage: python tools/check_durable_writes.py [root ...]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# non-durable-ok"

_WRITE_CHARS = set("wax+")


def _literal_mode(node: ast.Call):
    """The mode string of an ``open()`` call, when statically knowable."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"                      # default mode: read
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None                         # dynamic mode: not checkable


def _write_opens(tree: ast.AST) -> list[int]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name != "open":
            continue
        mode = _literal_mode(node)
        if mode and _WRITE_CHARS & set(mode):
            out.append(node.lineno)
    return out


def _enclosing_function_src(tree: ast.AST, src_lines: list[str],
                            lineno: int) -> str:
    """Source text of the innermost function containing ``lineno``
    (module text when the write is at top level)."""
    best = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                if best is None or node.lineno > best.lineno:
                    best = node
    if best is None:
        return "\n".join(src_lines)
    return "\n".join(src_lines[best.lineno - 1:
                               getattr(best, "end_lineno", best.lineno)])


def _durable_pattern(fn_src: str) -> bool:
    return (".tmp" in fn_src and "fsync" in fn_src
            and ("os.replace" in fn_src or "os.rename" in fn_src))


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error ({e.msg})"]
    lines = src.splitlines()
    problems = []
    for lineno in _write_opens(tree):
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        prev = lines[lineno - 2] if lineno >= 2 else ""
        if ANNOTATION in line or ANNOTATION in prev:
            continue
        if _durable_pattern(_enclosing_function_src(tree, lines, lineno)):
            continue
        problems.append(
            f"{path}:{lineno}: file write without tmp + fsync + "
            "atomic-rename in a durable-storage module — stage to a "
            "'.tmp' name, fsync, os.replace (see store.write_durable), "
            f"or annotate '{ANNOTATION}' if durability is provided "
            "another way")
    return problems


def _default_roots() -> list[str]:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(repo, "opensearch_tpu", "index"),
            os.path.join(repo, "opensearch_tpu", "snapshots"),
            os.path.join(repo, "opensearch_tpu", "cluster", "gateway.py")]


def main(argv: list[str]) -> int:
    roots = argv[1:] or _default_roots()
    problems = []
    for root in roots:
        if os.path.isfile(root):
            problems.extend(check_file(root))
            continue
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in sorted(filenames):
                if name.endswith(".py"):
                    problems.extend(check_file(
                        os.path.join(dirpath, name)))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} non-durable write site(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
