#!/usr/bin/env python
"""Lint: every thread must be named and have an explicit daemon policy.

An anonymous ``threading.Thread(...)`` shows up in ``_nodes/hot_threads``
as ``Thread-37`` — useless for attributing a wedged node — and a thread
whose daemon flag was never decided either blocks interpreter shutdown
(non-daemon default) or silently dies mid-write (daemon) depending on
what the author forgot.  Every ``threading.Thread(...)`` construction in
``opensearch_tpu/`` must therefore pass BOTH ``name=`` and ``daemon=``
explicitly, or carry a ``# thread-ok`` annotation on the same line or
the line above asserting a human decided the defaults are right.

Sibling of ``check_monotonic.py`` / ``check_sleep_loops.py`` /
``check_ad_hoc_caches.py``; new un-annotated sites fail tier-1
(tests/test_backpressure.py runs this check).

Usage: python tools/check_thread_hygiene.py [root]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# thread-ok"


def _thread_calls(tree: ast.AST) -> list[tuple[int, set[str]]]:
    """(lineno, keyword-names) for every Thread(...) construction."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        is_thread = (isinstance(fn, ast.Attribute) and fn.attr == "Thread") \
            or (isinstance(fn, ast.Name) and fn.id == "Thread")
        if not is_thread:
            continue
        out.append((node.lineno,
                    {kw.arg for kw in node.keywords if kw.arg}))
    return out


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    lines = src.splitlines()
    problems = []
    for lineno, kwargs in _thread_calls(tree):
        missing = {"name", "daemon"} - kwargs
        if not missing:
            continue
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        prev = lines[lineno - 2] if lineno >= 2 else ""
        if ANNOTATION in line or ANNOTATION in prev:
            continue
        problems.append(
            f"{path}:{lineno}: threading.Thread(...) without explicit "
            f"{sorted(missing)} — name threads for hot_threads "
            "attribution and decide the daemon policy, or annotate "
            f"with '{ANNOTATION}'")
    return problems


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opensearch_tpu")
    problems = []
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fname in sorted(files):
            if fname.endswith(".py"):
                problems.extend(check_file(os.path.join(dirpath, fname)))
    for p in problems:
        print(p)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
