#!/usr/bin/env python
"""Lint: impact tables reach the device through the quantized codec.

``index/codec.py`` + the pager in ``common/device_ledger.py`` are the
ONE path by which per-posting impact tables become device-resident on
large segments: quantized to int8/int16 with per-term scales, staged in
fixed-size pages under ``device.memory.budget_bytes``, and accounted
(hits/misses/evictions/prefetches) in `_nodes/stats` ``device.pager``.
A raw f32 impact-table staging elsewhere silently quadruples the
per-segment footprint and bypasses the page budget — exactly the
regression the quantized subsystem exists to prevent.

Scope: ``opensearch_tpu/index/``, ``search/``, ``parallel/``, ``ops/``.
Flagged call patterns (line-based, like check_device_staging.py):

- ``kind="impacts"`` — staging a full-precision impact table into the
  segment ledger group
- ``.impacts(`` — requesting the f32 device impact lowering from a
  ``DeviceSegment``

A deliberate f32 lowering — small segments below the quantization
threshold, filter-context/phrase paths that never read impacts, or the
codec/pager entry points themselves — carries a ``# quantize-ok``
annotation on the same line or the line above.  ``index/codec.py`` is
exempt wholesale: it IS the codec.

Sibling of ``check_device_staging.py`` et al.; new un-annotated sites
fail tier-1 (tests/test_quantized.py runs this check).

Usage: python tools/check_quantized_staging.py [root]   (exit 0 = clean)
"""

from __future__ import annotations

import os
import re
import sys

ANNOTATION = "# quantize-ok"

# directories (relative to the package root) whose impact staging is linted
SCOPES = ("index", "search", "parallel", "ops")

# files allowed to touch the raw f32 impact path without annotation
EXEMPT = ("codec.py",)

_PATTERNS = (
    (re.compile(r"kind\s*=\s*[\"']impacts[\"']"), 'kind="impacts" staging'),
    (re.compile(r"\.impacts\s*\("), ".impacts(...) f32 lowering"),
)


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    problems = []
    for i, line in enumerate(lines):
        for pat, what in _PATTERNS:
            if not pat.search(line):
                continue
            prev = lines[i - 1] if i else ""
            if ANNOTATION in line or ANNOTATION in prev:
                continue
            problems.append(
                f"{path}:{i + 1}: raw {what} — impact tables must reach "
                "the device through index/codec.py (quantize_postings) "
                "and the device pager so the page budget and footprint "
                f"accounting stay exact, or carry a '{ANNOTATION}' "
                "annotation on this or the previous line")
    return problems


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opensearch_tpu")
    problems = []
    for scope in SCOPES:
        scope_dir = os.path.join(root, scope)
        if not os.path.isdir(scope_dir):
            # linting a sample tree (the lint's own tests): scan root
            scope_dir = root if scope == SCOPES[0] else None
        if scope_dir is None:
            continue
        for dirpath, _dirs, files in os.walk(scope_dir):
            if "__pycache__" in dirpath:
                continue
            for fname in sorted(files):
                if not fname.endswith(".py") or fname in EXEMPT:
                    continue
                problems.extend(check_file(os.path.join(dirpath, fname)))
    for p in sorted(set(problems)):
        print(p)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
