#!/usr/bin/env python
"""Lint: search-role nodes must stay out of the write path.

The search-replica tier (ingest/search separation) only holds if a
search-only node can NEVER mutate shard state: write-path transport
handlers must either be unregistered on search-role nodes or reject
with a clear verdict, and the engine's write entry points must refuse
on a search-only engine.  This check pins both invariants statically:

1. In ``opensearch_tpu/cluster/``, any ``register_handler`` call whose
   action is a write action (``A_WRITE_SHARD`` / ``A_REPLICATE_OP`` by
   name, or their literal action strings) must live inside
   ``_register_write_handlers`` — the one role-gated registration site
   — or carry a ``# searcher-ok: <why>`` annotation on the same line or
   the line above.
2. ``ClusterNode._register_write_handlers`` itself must exist and
   branch on the data role (``is_data``) with a rejection path.
3. The engine's write entry points (``index``, ``delete``,
   ``apply_replica_op`` — the chokepoint every bulk/index/translog
   write flows through) must call ``_ensure_writeable`` (the
   ``search_only`` guard) or carry the annotation.

Sibling of ``check_execution_paths.py``; new un-annotated sites fail
tier-1 (tests/test_search_tier.py runs this check).

Usage: python tools/check_searcher_write_isolation.py [repo_root]
(exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# searcher-ok"

WRITE_ACTION_NAMES = frozenset({"A_WRITE_SHARD", "A_REPLICATE_OP"})
WRITE_ACTION_STRINGS = frozenset({
    "indices:data/write/shard", "indices:data/write/shard[r]"})

#: the single sanctioned (role-gated) registration site
SANCTIONED_FN = "_register_write_handlers"

ENGINE_WRITE_ENTRIES = ("index", "delete", "apply_replica_op")
ENGINE_GUARD = "_ensure_writeable"


def _is_write_action(arg: ast.AST) -> bool:
    if isinstance(arg, ast.Name) and arg.id in WRITE_ACTION_NAMES:
        return True
    if isinstance(arg, ast.Attribute) and arg.attr in WRITE_ACTION_NAMES:
        return True
    if isinstance(arg, ast.Constant) and arg.value in WRITE_ACTION_STRINGS:
        return True
    return False


def _annotated(lines: list, lineno: int) -> bool:
    line = lines[lineno - 1] if lineno <= len(lines) else ""
    prev = lines[lineno - 2] if lineno >= 2 else ""
    return ANNOTATION in line or ANNOTATION in prev


def check_cluster_file(path: str) -> list:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    lines = src.splitlines()
    # map every node to its enclosing function name
    problems = []

    def walk(node: ast.AST, fn_name: str):
        for child in ast.iter_child_nodes(node):
            child_fn = fn_name
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_fn = child.name
            if isinstance(child, ast.Call):
                callee = child.func
                name = callee.attr if isinstance(callee, ast.Attribute) \
                    else (callee.id if isinstance(callee, ast.Name)
                          else None)
                if (name == "register_handler" and child.args
                        and _is_write_action(child.args[0])
                        and fn_name != SANCTIONED_FN
                        and not _annotated(lines, child.lineno)):
                    problems.append(
                        f"{path}:{child.lineno}: write-action handler "
                        "registered outside the role-gated "
                        f"{SANCTIONED_FN}() — a search-role node would "
                        "serve writes; move it there or annotate with "
                        f"'{ANNOTATION}: <why>'")
            walk(child, child_fn)

    walk(tree, "<module>")
    return problems


def check_registration_gate(node_path: str) -> list:
    """``_register_write_handlers`` must exist and actually branch on
    the data role with a rejection path."""
    with open(node_path, encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == SANCTIONED_FN:
            body = ast.get_source_segment(src, node) or ""
            problems = []
            if "is_data" not in body:
                problems.append(
                    f"{node_path}:{node.lineno}: {SANCTIONED_FN}() does "
                    "not branch on the data role (is_data)")
            if "_reject_write" not in body and "raise" not in body:
                problems.append(
                    f"{node_path}:{node.lineno}: {SANCTIONED_FN}() has "
                    "no rejection path for search-role nodes")
            return problems
    return [f"{node_path}:1: {SANCTIONED_FN}() is missing — write "
            "handlers have no role-gated registration site"]


def check_engine_guards(engine_path: str) -> list:
    with open(engine_path, encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src)
    lines = src.splitlines()
    problems = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name in ENGINE_WRITE_ENTRIES):
            continue
        body = ast.get_source_segment(src, node) or ""
        if ENGINE_GUARD not in body \
                and not _annotated(lines, node.lineno):
            problems.append(
                f"{engine_path}:{node.lineno}: engine write entry "
                f"[{node.name}] does not call {ENGINE_GUARD}() — a "
                "search-only engine would accept writes; add the guard "
                f"or annotate with '{ANNOTATION}: <why>'")
    return problems


def main(argv: list) -> int:
    repo = argv[1] if len(argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(repo, "opensearch_tpu")
    problems = []
    cluster_dir = os.path.join(pkg, "cluster")
    for fname in sorted(os.listdir(cluster_dir)):
        if fname.endswith(".py"):
            problems.extend(
                check_cluster_file(os.path.join(cluster_dir, fname)))
    problems.extend(check_registration_gate(
        os.path.join(cluster_dir, "node.py")))
    problems.extend(check_engine_guards(
        os.path.join(pkg, "index", "engine.py")))
    for p in problems:
        print(p)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
