#!/usr/bin/env python
"""Lint: every write-path transport handler must fence on primary term.

Primary-term fencing (the reference's ReplicationTracker / in-sync
machinery) only protects acked writes if EVERY transport entry point
that mutates shard state validates the op's ``primary_term`` against
cluster state before applying it.  A new write-path handler added
without the check re-opens the split-brain hole PR 19 closed — so this
check pins the invariant statically:

1. ``opensearch_tpu/cluster/node.py`` must define a non-empty
   ``WRITE_ACTIONS`` tuple and map every entry to a handler inside
   ``_register_write_handlers`` (the role-gated registration site that
   ``check_searcher_write_isolation.py`` already pins).
2. Every handler so registered must validate the primary term: either
   call ``_fence_floor`` (the entry-vs-engine term floor helper) or
   reference ``primary_term`` together with a fencing rejection
   (``PrimaryFencedError`` / ``VersionConflictError`` /
   ``_record_stale_primary``) — or carry an explicit
   ``# fencing-ok (<why>)`` annotation on its ``def`` line or the line
   above.

tests/test_replication_safety.py runs this check; new un-annotated
write handlers fail tier-1.

Usage: python tools/check_term_fencing.py [repo_root]
(exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# fencing-ok"

REGISTRATION_FN = "_register_write_handlers"

#: any of these inside a handler body counts as a fencing rejection
FENCE_REJECTIONS = ("PrimaryFencedError", "VersionConflictError",
                    "_record_stale_primary")


def _annotated(lines: list, lineno: int) -> bool:
    line = lines[lineno - 1] if lineno <= len(lines) else ""
    prev = lines[lineno - 2] if lineno >= 2 else ""
    return ANNOTATION in line or ANNOTATION in prev


def _write_action_names(tree: ast.AST, path: str, problems: list):
    """The names bound in the ``WRITE_ACTIONS = (...)`` tuple."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "WRITE_ACTIONS"
                for t in node.targets):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                names = [e.id for e in node.value.elts
                         if isinstance(e, ast.Name)]
                if names:
                    return names
            problems.append(
                f"{path}:{node.lineno}: WRITE_ACTIONS is not a "
                "non-empty tuple of action-name constants")
            return []
    problems.append(f"{path}:1: WRITE_ACTIONS tuple is missing — the "
                    "write-path surface is unpinned")
    return []


def _registered_handlers(tree: ast.AST, actions: list, path: str,
                         problems: list) -> dict:
    """action name -> handler method name, from the dict literal in
    ``_register_write_handlers``."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name == REGISTRATION_FN):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Dict):
                continue
            mapping = {}
            for k, v in zip(sub.keys, sub.values):
                if isinstance(k, ast.Name) and \
                        isinstance(v, ast.Attribute):
                    mapping[k.id] = v.attr
            if mapping:
                for a in actions:
                    if a not in mapping:
                        problems.append(
                            f"{path}:{sub.lineno}: write action [{a}] "
                            f"has no handler in {REGISTRATION_FN}()")
                return mapping
        problems.append(
            f"{path}:{node.lineno}: {REGISTRATION_FN}() has no "
            "action -> handler dict literal")
        return {}
    problems.append(f"{path}:1: {REGISTRATION_FN}() is missing")
    return {}


def _check_handler_fences(tree: ast.AST, src: str, lines: list,
                          handler: str, action: str, path: str,
                          problems: list):
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name == handler):
            continue
        if _annotated(lines, node.lineno):
            return
        body = ast.get_source_segment(src, node) or ""
        fenced = "_fence_floor" in body or (
            "primary_term" in body
            and any(r in body for r in FENCE_REJECTIONS))
        if not fenced:
            problems.append(
                f"{path}:{node.lineno}: write handler [{handler}] "
                f"(action {action}) does not validate primary_term "
                "against cluster state — a stale primary's op would "
                "apply unfenced; call _fence_floor()/raise "
                "PrimaryFencedError, or annotate with "
                f"'{ANNOTATION} (<why>)'")
        return
    problems.append(f"{path}:1: registered handler [{handler}] "
                    f"(action {action}) not found")


def main(argv: list) -> int:
    repo = argv[1] if len(argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "opensearch_tpu", "cluster", "node.py")
    with open(path, encoding="utf-8") as f:
        src = f.read()
    problems: list = []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        problems.append(f"{path}:{e.lineno}: syntax error: {e.msg}")
        tree = None
    if tree is not None:
        lines = src.splitlines()
        actions = _write_action_names(tree, path, problems)
        handlers = _registered_handlers(tree, actions, path, problems)
        for action, handler in sorted(handlers.items()):
            _check_handler_fences(tree, src, lines, handler, action,
                                  path, problems)
    for p in problems:
        print(p)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
