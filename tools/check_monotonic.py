#!/usr/bin/env python
"""Lint: wall-clock reads must be annotated, durations must be monotonic.

``time.time()`` is only legitimate for *timestamps* (display, epoch
columns, unique names).  Using it for elapsed-time measurement silently
corrupts latency metrics whenever the wall clock steps (NTP slew, VM
suspend) — the class of bug this PR's telemetry work exists to measure
away.  Every remaining ``time.time()`` call site in ``opensearch_tpu/``
must therefore carry a ``# wall-clock`` annotation on the same line or
the line above, asserting a human decided a timestamp is intended.
New un-annotated call sites fail tier-1 (tests/test_telemetry.py runs
this check).

Usage: python tools/check_monotonic.py [root]   (exit 0 = clean)
"""

from __future__ import annotations

import os
import re
import sys

CALL = re.compile(r"\btime\.time\(\)")
ANNOTATION = "# wall-clock"


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    problems = []
    for i, line in enumerate(lines):
        if not CALL.search(line):
            continue
        stripped = line.strip()
        if stripped.startswith("#"):
            continue                     # commented-out code
        prev = lines[i - 1] if i > 0 else ""
        if ANNOTATION in line or ANNOTATION in prev:
            continue
        problems.append(
            f"{path}:{i + 1}: time.time() without a '{ANNOTATION}' "
            "annotation — use time.monotonic() for durations, or "
            "annotate why a wall timestamp is intended")
    return problems


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opensearch_tpu")
    problems = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if name.endswith(".py"):
                problems.extend(check_file(os.path.join(dirpath, name)))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} un-annotated time.time() call site(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
