#!/usr/bin/env python
"""Lint: wall-clock reads must be annotated, durations must be monotonic.

``time.time()`` is only legitimate for *timestamps* (display, epoch
columns, unique names).  Using it for elapsed-time measurement silently
corrupts latency metrics whenever the wall clock steps (NTP slew, VM
suspend) — the class of bug this PR's telemetry work exists to measure
away.  Every remaining ``time.time()`` call site in ``opensearch_tpu/``
must therefore carry a ``# wall-clock`` annotation on the same line or
the line above, asserting a human decided a timestamp is intended.
New un-annotated call sites fail tier-1 (tests/test_telemetry.py runs
this check).

Injectable-clock modules (``INJECTABLE_CLOCK_MODULES``) get a stricter
rule: even ``time.monotonic`` is banned there, because their timing
logic (EWMA decay, duress-flag freshness) must be drivable by a fake
clock in deterministic tests.  The only allowed reference is the
injectable default parameter, annotated ``# clock-default`` on the same
line or the line above.

Usage: python tools/check_monotonic.py [root]   (exit 0 = clean)
"""

from __future__ import annotations

import os
import re
import sys

CALL = re.compile(r"\btime\.time\(\)")
ANNOTATION = "# wall-clock"

# relative paths (under the scanned root) whose timing logic must flow
# exclusively through an injectable clock parameter
INJECTABLE_CLOCK_MODULES = {
    os.path.join("cluster", "response_collector.py"),
}
MONO = re.compile(r"\btime\.monotonic\b")
CLOCK_ANNOTATION = "# clock-default"


def check_file(path: str, strict_clock: bool = False) -> list[str]:
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    problems = []
    for i, line in enumerate(lines):
        stripped = line.strip()
        if stripped.startswith("#"):
            continue                     # commented-out code
        prev = lines[i - 1] if i > 0 else ""
        if CALL.search(line) and ANNOTATION not in line \
                and ANNOTATION not in prev:
            problems.append(
                f"{path}:{i + 1}: time.time() without a '{ANNOTATION}' "
                "annotation — use time.monotonic() for durations, or "
                "annotate why a wall timestamp is intended")
        if strict_clock and MONO.search(line) \
                and CLOCK_ANNOTATION not in line \
                and CLOCK_ANNOTATION not in prev:
            problems.append(
                f"{path}:{i + 1}: direct time.monotonic reference in an "
                "injectable-clock module — route it through the clock "
                f"parameter, or annotate the default with "
                f"'{CLOCK_ANNOTATION}'")
    return problems


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opensearch_tpu")
    problems = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            problems.extend(check_file(
                path, strict_clock=rel in INJECTABLE_CLOCK_MODULES))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} clock-discipline violation(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
