#!/usr/bin/env python
"""Lint: scoring kernels may only be invoked via the unified query engine.

PR "one query engine" collapsed the four execution paths (sequential,
msearch-batched, CPU host fast path, device mesh) into backend decisions
inside ``search/engine.py``'s single entry.  The refactor only stays
collapsed if no NEW code path starts calling the scoring kernels
directly — that is exactly how the four paths grew in the first place.

Therefore: any call of a scoring-kernel function —

    impact_scores / impact_score_count / bm25_scores / bm25_score_count
    / match_count (ops/bm25.py), batch_impact_union_topk
    (search/batch.py), or a plan's host_topk

— anywhere under ``opensearch_tpu/`` must either live in
``search/engine.py`` itself, in ``ops/bm25.py`` (the definitions), or
carry a ``# engine-ok: <why>`` annotation on the same line or the line
above, asserting the site is one of the engine's sanctioned lowering
layers (plan lowering, batch backend, mesh backend).  Tests are out of
scope (they pin kernel parity directly on purpose).

Sibling of ``check_hot_path_sync.py`` / ``check_device_staging.py``;
new un-annotated sites fail tier-1 (tests/test_query_engine.py runs
this check).

Usage: python tools/check_execution_paths.py [root]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# engine-ok"

KERNELS = frozenset({
    "impact_scores", "impact_score_count", "bm25_scores",
    "bm25_score_count", "match_count", "batch_impact_union_topk",
    "host_topk",
})

# modules allowed to touch kernels without annotation: the engine entry
# itself and the kernel definitions module
_EXEMPT_SUFFIXES = (
    os.path.join("search", "engine.py"),
    os.path.join("ops", "bm25.py"),
)


def _kernel_calls(tree: ast.AST) -> list[int]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name in KERNELS:
            out.append(node.lineno)
    return out


def check_file(path: str) -> list[str]:
    if any(path.endswith(sfx) for sfx in _EXEMPT_SUFFIXES):
        return []
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    lines = src.splitlines()
    problems = []
    for lineno in _kernel_calls(tree):
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        prev = lines[lineno - 2] if lineno >= 2 else ""
        if ANNOTATION in line or ANNOTATION in prev:
            continue
        problems.append(
            f"{path}:{lineno}: scoring kernel invoked outside the "
            "unified query engine — route through search/engine.py "
            "(QueryEngine.execute/msearch) or annotate the sanctioned "
            f"lowering site with '{ANNOTATION}: <why>'")
    return problems


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opensearch_tpu")
    problems = []
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fname in sorted(files):
            if fname.endswith(".py"):
                problems.extend(check_file(os.path.join(dirpath, fname)))
    for p in problems:
        print(p)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
