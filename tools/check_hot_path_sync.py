#!/usr/bin/env python
"""Lint: no host syncs inside the async dispatch phase of the query path.

The throughput of the sequential and batched query phases rests on jax's
async dispatch: every segment's program is LAUNCHED without waiting, and
results are converted host-side in ONE sync region afterwards.  A stray
``np.asarray(...)``, ``.block_until_ready()``, or ``float()``/``int()``
on a device array inside the dispatch loop serializes the pipeline —
each segment then waits for the previous one, and on a TPU behind a
tunnel every wait is a round trip (the exact regression r4 hit with
per-query D2H transfers).

Scope: the segment-dispatch ``for`` loops (any ``for`` whose iterable
mentions ``segments`` or ``prep["segs"]``) inside the hot entry points
``ShardSearcher._topk`` / ``ShardSearcher.msearch``
(opensearch_tpu/search/executor.py) and ``BatchGroup.run``
(opensearch_tpu/search/batch.py).  Flagged calls:

- ``np.asarray(...)`` / ``numpy.asarray(...)``
- ``<expr>.block_until_ready()``
- ``float(...)`` / ``int(...)``  (device scalars sync on conversion)

A deliberate host read (e.g. harvesting an ``is_ready()`` result, which
is already on the host) carries a ``# sync-ok`` annotation on the same
line or the line above.

Sibling of ``check_monotonic.py`` / ``check_sleep_loops.py`` /
``check_ad_hoc_caches.py`` / ``check_thread_hygiene.py``; new
un-annotated sites fail tier-1 (tests/test_impacts.py runs this check).

Usage: python tools/check_hot_path_sync.py [root]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# sync-ok"

# (relative file, function name) pairs whose dispatch loops are linted
HOT_FUNCTIONS = {
    ("search/executor.py", "_topk"),
    ("search/executor.py", "msearch"),
    ("search/batch.py", "run"),
}

_BANNED_NAMES = {"float", "int"}
_BANNED_ATTRS = {"asarray", "block_until_ready"}


def _is_dispatch_loop(node: ast.For) -> bool:
    """A ``for`` whose iterable mentions the segment list."""
    src = ast.dump(node.iter)
    return "segments" in src or "'segs'" in src


def _banned_calls(loop: ast.For):
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _BANNED_NAMES:
            yield node.lineno, f"{fn.id}(...)"
        elif isinstance(fn, ast.Attribute) and fn.attr in _BANNED_ATTRS:
            yield node.lineno, f".{fn.attr}(...)"


def check_file(path: str, rel: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    lines = src.splitlines()
    wanted = {fn for (f_rel, fn) in HOT_FUNCTIONS if f_rel == rel}
    if not wanted:
        return []
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in wanted:
            continue
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.For) or not _is_dispatch_loop(stmt):
                continue
            for lineno, what in _banned_calls(stmt):
                line = lines[lineno - 1] if lineno <= len(lines) else ""
                prev = lines[lineno - 2] if lineno >= 2 else ""
                if ANNOTATION in line or ANNOTATION in prev:
                    continue
                problems.append(
                    f"{path}:{lineno}: {what} inside the async dispatch "
                    f"loop of {node.name}() — a host sync here "
                    "serializes the per-segment pipeline; move it to "
                    "the phase-2 sync region or annotate with "
                    f"'{ANNOTATION}'")
    return problems


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "opensearch_tpu")
    problems = []
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            problems.extend(check_file(path, rel))
    for p in problems:
        print(p)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
