#!/usr/bin/env python
"""Lint: metric names must be dotted lowercase STATIC string literals.

A metric name built from request or document data (an f-string over a
query term, a ``%``/``.format`` over a doc field) creates one
counter/histogram PER DISTINCT VALUE — an unbounded-cardinality
explosion that bloats the registry forever (instruments are
register-once, never evicted), wrecks the ``/_metrics`` Prometheus
exposition, and can leak document contents into dashboards.

Rule: every ``<expr>.counter(...)`` / ``<expr>.histogram(...)`` call
site in ``opensearch_tpu/`` and ``bench.py`` must pass a literal string
matching ``^[a-z][a-z0-9_]*(\\.[a-z0-9_]+)+$`` as its first argument.
The few legitimately parameterized sites (per-cache, per-retry-action
names drawn from a BOUNDED set of code-level identifiers) carry a
``# metric-name-ok`` annotation on the same line or the line above.

Sibling of ``check_monotonic.py`` / ``check_hot_path_sync.py`` et al;
new un-annotated sites fail tier-1 (tests/test_profile.py runs this
check).

Usage: python tools/check_metric_names.py [root ...]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import re
import sys

ANNOTATION = "# metric-name-ok"
NAME_RX = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")
_METHODS = {"counter", "histogram"}


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    lines = src.splitlines()
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute) or fn.attr not in _METHODS:
            continue
        if not node.args:
            continue
        arg = node.args[0]
        ok = (isinstance(arg, ast.Constant)
              and isinstance(arg.value, str)
              and NAME_RX.match(arg.value) is not None)
        if ok:
            continue
        lineno = node.lineno
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        prev = lines[lineno - 2] if lineno >= 2 else ""
        if ANNOTATION in line or ANNOTATION in prev:
            continue
        what = (f"non-literal or malformed metric name"
                if not isinstance(arg, ast.Constant)
                else f"metric name {arg.value!r}")
        problems.append(
            f"{path}:{lineno}: {what} in .{fn.attr}(...) — metric names "
            "must be dotted lowercase static string literals (cardinality "
            f"explosion guard); annotate bounded sites with "
            f"'{ANNOTATION}'")
    return problems


def main(argv: list[str]) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = argv[1:] or [os.path.join(repo, "opensearch_tpu"),
                         os.path.join(repo, "bench.py")]
    problems = []
    for root in roots:
        if os.path.isfile(root):
            problems.extend(check_file(root))
            continue
        for dirpath, _dirs, files in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            for fname in sorted(files):
                if fname.endswith(".py"):
                    problems.extend(
                        check_file(os.path.join(dirpath, fname)))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} metric-name violation(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
