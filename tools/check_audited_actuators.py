#!/usr/bin/env python
"""Lint: every fleet/QoS actuator must append to the audit ring.

PR 14 gave adaptive decisions an audit ring (``QosController._record``)
and PR 17 made the fleet itself an actuated surface (the searcher
autoscaler).  The invariant worth linting: any function that mutates
fleet membership (``add_node`` / ``remove_node`` / a raw
``submit_state_update``) or adapts a QoS knob (assigns
``SHED_OCCUPANCY`` / ``AUTO_WINDOW_MS`` on a module) must, in the same
function, append to the audit ring (call ``_record`` /
``record_adaptation`` / an ``audit`` / ``_audit`` hook) — otherwise the
system changes its own topology or knobs with no evidence trail, and
the next operator debugging a 3am scale event has nothing to read.

Functions that are legitimately unaudited — membership *primitives*
whose callers audit, operator-initiated admin handlers, fault-eviction
paths — carry a ``# actuator-ok`` annotation on the ``def`` line (or a
line above it), stating why.

Scanned roots default to ``opensearch_tpu/cluster`` and
``opensearch_tpu/search`` — the harness (``opensearch_tpu/testing``)
IS the operator in its scenarios, so it is deliberately out of scope.

Sibling of ``check_dead_settings.py``; unaudited actuators fail tier-1
(tests/test_autoscaler.py runs this check).

Usage: python tools/check_audited_actuators.py [path ...]  (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# actuator-ok"

#: calls (by attribute or bare name) that mutate fleet membership or
#: publish a cluster-state change
ACTUATOR_CALLS = {"add_node", "remove_node", "submit_state_update"}

#: attribute targets whose assignment adapts a live QoS knob
KNOB_TARGETS = {"SHED_OCCUPANCY", "AUTO_WINDOW_MS"}

#: calls that append to the audit ring (directly or via a hook)
AUDIT_CALLS = {"_record", "record_adaptation", "audit", "_audit"}


def _call_name(node: ast.Call):
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _actuates(func: ast.AST) -> list[tuple[str, int]]:
    """(what, lineno) for every actuator site inside ``func`` (not
    descending into nested function defs — they are checked on their
    own)."""
    out = []
    for node in _walk_shallow(func):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in ACTUATOR_CALLS:
                out.append((name, node.lineno))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets
                       if isinstance(node, ast.Assign) else [node.target])
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr in KNOB_TARGETS:
                    out.append((t.attr, node.lineno))
    return out


def _audits(func: ast.AST) -> bool:
    return any(isinstance(node, ast.Call)
               and _call_name(node) in AUDIT_CALLS
               for node in _walk_shallow(func))


def _walk_shallow(func: ast.AST):
    """Walk a function body without crossing into nested defs or
    classes — a nested function is a distinct scope checked on its
    own (a ``submit_state_update(update)`` closure's *call site* is in
    the enclosing function, which is where the audit belongs)."""
    for child in ast.iter_child_nodes(func):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            continue
        yield child
        yield from _walk_shallow(child)


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error ({e.msg})"]
    lines = src.splitlines()
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        sites = _actuates(node)
        if not sites or _audits(node):
            continue
        annotated = any(
            ANNOTATION in lines[ln]
            for ln in range(max(0, node.lineno - 2),
                            min(len(lines), node.lineno)))
        if annotated:
            continue
        what = ", ".join(sorted({w for w, _ in sites}))
        problems.append(
            f"{path}:{node.lineno}: [{node.name}] actuates "
            f"[{what}] without appending to the audit ring — call "
            "record_adaptation/_record (or an audit hook), or "
            f"annotate '{ANNOTATION} (<why>)'")
    return problems


def _default_roots() -> list[str]:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(repo, "opensearch_tpu", "cluster"),
            os.path.join(repo, "opensearch_tpu", "search"),
            os.path.join(repo, "opensearch_tpu", "node.py")]


def main(argv: list[str]) -> int:
    roots = argv[1:] or _default_roots()
    problems = []
    for root in roots:
        if os.path.isfile(root):
            problems.extend(check_file(root))
            continue
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in sorted(filenames):
                if name.endswith(".py"):
                    problems.extend(check_file(
                        os.path.join(dirpath, name)))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} unaudited actuator(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
