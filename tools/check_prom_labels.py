#!/usr/bin/env python
"""Lint: Prometheus label values must come from a bounded set.

The metric-name lint (``check_metric_names.py``) keeps request data out
of metric NAMES; with PR 10's query-insights exposition the registry
grew its first LABELED series — and a label value derived from request
data (a raw query string, a user id, a document field) is the same
cardinality explosion wearing a different hat: one time series per
distinct value, unbounded scrape growth, and request contents leaking
into dashboards.

Rule: any string literal (including f-string fragments — where a
rendered ``{label="`` appears as a literal part) in ``opensearch_tpu/``
or ``bench.py`` that opens a Prometheus label block
(``{name="`` after brace-unescaping) marks a label-emission site.
Every such site must carry a ``# label-ok`` annotation on the same
line or the line above, stating why the value is bounded — the
sanctioned path is the query-insights top-N ring, where every label
value is a 12-hex plan-signature hash or a node id, capped by the
ring/rollup sizes (search/insights.py).  Histogram ``le=`` bounds and
other code-level constants annotate the same way.

Sibling of ``check_metric_names.py``; new un-annotated sites fail
tier-1 (tests/test_insights.py runs this check).

Usage: python tools/check_prom_labels.py [root ...]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import re
import sys

ANNOTATION = "# label-ok"
# the start of a Prometheus label block: {name=" — JSON object literals
# ({"key": ...) don't match because their quote precedes the name
LABEL_RX = re.compile(r"\{[a-zA-Z_][a-zA-Z0-9_]*=\"")


def _string_parts(node):
    """Every literal string fragment under ``node`` (plain constants and
    the constant parts of f-strings)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node, node.value
    elif isinstance(node, ast.JoinedStr):
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(part.value,
                                                             str):
                yield node, part.value


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    lines = src.splitlines()
    problems = []
    seen: set[int] = set()
    for node in ast.walk(tree):
        for holder, text in _string_parts(node):
            if not LABEL_RX.search(text):
                continue
            lineno = holder.lineno
            if lineno in seen:
                continue
            seen.add(lineno)
            # multi-line expressions: accept the annotation anywhere
            # between the expression's first line and its end line + 1
            end = getattr(holder, "end_lineno", lineno) or lineno
            window = lines[max(0, lineno - 2): min(len(lines), end + 1)]
            if any(ANNOTATION in ln for ln in window):
                continue
            problems.append(
                f"{path}:{lineno}: Prometheus label block "
                f"{LABEL_RX.search(text).group(0)!r}... built from a "
                "string literal — label values must come from a bounded "
                "set (the insights top-N signature path or code-level "
                f"constants); annotate the site with '{ANNOTATION}: "
                "<why bounded>'")
    return problems


def main(argv: list[str]) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = argv[1:] or [os.path.join(repo, "opensearch_tpu"),
                         os.path.join(repo, "bench.py")]
    problems = []
    for root in roots:
        if os.path.isfile(root):
            problems.extend(check_file(root))
            continue
        for dirpath, _dirs, files in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            for fname in sorted(files):
                if fname.endswith(".py"):
                    problems.extend(
                        check_file(os.path.join(dirpath, fname)))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} prometheus-label violation(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
