#!/usr/bin/env python
"""Lint: load-measurement code must be coordinated-omission-safe.

The open-loop harness (``opensearch_tpu/testing/loadgen.py``) exists
because closed-loop measurement lies under overload: latencies taken
as ``monotonic() - t_sent`` inside a send-wait-send loop charge a
server stall to ONE request instead of every request scheduled to
arrive during it.  Two rules keep that from creeping back into the
measurement layer:

1. timing must use ``time.monotonic``-family clocks — ``time.time()``
   / ``datetime.now()`` timestamps jump on NTP steps and corrupt
   latency math (annotate ``# wall-clock`` only for genuinely
   wall-clock output, same convention as ``check_monotonic.py``);
2. inside a loop body, subtracting a loop-local "start" timestamp
   from a fresh clock call (``monotonic() - t0`` where ``t0`` was
   taken from the clock in the same loop body) is the closed-loop
   per-request pattern — in the harness it must be the SCHEDULED
   arrival that is subtracted, never a post-send timestamp.  bench.py
   keeps several deliberate closed-loop *service-time* measurements
   (the batched/sequential phases measure the engine, not the edge);
   those carry a ``# closed-loop-ok`` annotation on the same line or
   the line above.

Sibling of ``check_seeded_rng.py``/``check_sleep_loops.py``; new
violations fail tier-1 (tests/test_loadgen.py runs this check).

Usage: python tools/check_open_loop.py [root ...]   (exit 0 = clean)
"""

from __future__ import annotations

import ast
import os
import sys

ANNOTATION = "# closed-loop-ok"
WALL_ANNOTATION = "# wall-clock"

#: monotonic-family clock attribute/function names
MONO_CLOCKS = ("monotonic", "monotonic_ns", "perf_counter",
               "perf_counter_ns")
#: clocks that must not time anything (wall clocks / removed APIs)
BAD_CLOCKS = ("time", "now", "utcnow", "clock")


def _call_name(node: ast.AST):
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _is_mono_call(node: ast.AST) -> bool:
    return _call_name(node) in MONO_CLOCKS


def _bad_clock_calls(tree: ast.AST) -> list[int]:
    """Line numbers of wall-clock / non-monotonic clock calls."""
    out = []
    for node in ast.walk(tree):
        name = _call_name(node)
        if name not in BAD_CLOCKS:
            continue
        fn = node.func
        # only time.time()/time.clock() and datetime.now()/utcnow();
        # an arbitrary method named .now()/.time() on another object
        # is not a clock read
        if isinstance(fn, ast.Attribute):
            base = fn.value
            base_name = base.id if isinstance(base, ast.Name) else (
                base.attr if isinstance(base, ast.Attribute) else None)
            if base_name not in ("time", "datetime", "dt"):
                continue
        out.append(node.lineno)
    return out


def _closed_loop_subs(tree: ast.AST) -> list[int]:
    """Line numbers of ``monotonic() - start`` subtractions where
    ``start`` is assigned from a monotonic-family call inside the same
    loop body — the closed-loop per-iteration latency pattern."""
    out = []
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
            continue
        starts = set()
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign) and _is_mono_call(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        starts.add(tgt.id)
        if not starts:
            continue
        for node in ast.walk(loop):
            if (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and _is_mono_call(node.left)
                    and isinstance(node.right, ast.Name)
                    and node.right.id in starts):
                out.append(node.lineno)
    return sorted(set(out))


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error ({e.msg})"]
    lines = src.splitlines()

    def annotated(lineno: int, marker: str) -> bool:
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        prev = lines[lineno - 2] if lineno >= 2 else ""
        return marker in line or marker in prev

    problems = []
    for lineno in _bad_clock_calls(tree):
        if annotated(lineno, WALL_ANNOTATION):
            continue
        problems.append(
            f"{path}:{lineno}: non-monotonic clock in measurement code "
            "— use time.monotonic()/perf_counter(), or annotate "
            f"'{WALL_ANNOTATION}' for genuinely wall-clock output")
    for lineno in _closed_loop_subs(tree):
        if annotated(lineno, ANNOTATION):
            continue
        problems.append(
            f"{path}:{lineno}: closed-loop latency measurement (clock "
            "minus a post-send timestamp taken in the same loop) — "
            "charge from the SCHEDULED arrival instead, or annotate "
            f"'{ANNOTATION}' for a deliberate service-time measurement")
    return problems


def _default_roots() -> list[str]:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(repo, "opensearch_tpu", "testing",
                         "loadgen.py"),
            os.path.join(repo, "bench.py")]


def main(argv: list[str]) -> int:
    roots = argv[1:] or _default_roots()
    problems = []
    for root in roots:
        if os.path.isfile(root):
            problems.extend(check_file(root))
            continue
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in sorted(filenames):
                if name.endswith(".py"):
                    problems.extend(check_file(
                        os.path.join(dirpath, name)))
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} open-loop violation(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
