"""One query engine + continuous batching (search/engine.py).

Pins the PR's contract:

- every routed caller (single search, msearch, cluster scatter, mesh)
  returns byte-identical results with the continuous batcher on and
  off — coalescing is an execution decision, never a semantics change;
- concurrent identical-shape REST searches actually coalesce into ONE
  shared batch dispatch (counted in search.batcher.*), each caller
  getting its own response, with per-member ``batched`` group size and
  ``queue_wait_ms`` on the insight records and a ``queue`` phase in
  profiled members' breakdowns;
- non-batchable bodies and serial traffic bypass with no window wait;
- the multi-segment host fast path fans out over the engine's bounded,
  named threadpool with byte-identical results, and engine shutdown is
  an idempotent bounded join (Node.stop / ClusterNode.stop);
- the insights coalescability report's prediction brackets realized
  batch occupancy on a zipf arrival schedule (the batcher-sizing loop);
- tools/check_execution_paths.py: scoring kernels are only invoked via
  the engine's sanctioned lowering sites.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from opensearch_tpu.common.telemetry import metrics
from opensearch_tpu.indices.service import IndexService
from opensearch_tpu.ops import bm25 as bm25_ops
from opensearch_tpu.search import engine as engine_mod
from opensearch_tpu.search import insights as insights_mod
from opensearch_tpu.search.engine import ContinuousBatcher, query_engine

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")

MAPPING = {"properties": {"body": {"type": "text"},
                          "n": {"type": "long"}}}


@pytest.fixture(autouse=True)
def _restore_engine_globals():
    saved = (engine_mod.BATCHER_ENABLED, engine_mod.BATCHER_WINDOW_MS,
             engine_mod.BATCHER_MAX_BATCH, engine_mod.AUTO_WINDOW_MS,
             bm25_ops.HOST_SCORING)
    yield
    (engine_mod.BATCHER_ENABLED, engine_mod.BATCHER_WINDOW_MS,
     engine_mod.BATCHER_MAX_BATCH, engine_mod.AUTO_WINDOW_MS,
     bm25_ops.HOST_SCORING) = saved


def build_service(tmp_path, name="qe", n_docs=80, seed=5):
    svc = IndexService(name, str(tmp_path / name), {}, MAPPING)
    rng = np.random.default_rng(seed)
    vocab = [f"w{i}" for i in range(20)]
    for i in range(n_docs):
        svc.index_doc(str(i), {
            "body": " ".join(rng.choice(vocab,
                                        size=int(rng.integers(3, 12)))),
            "n": int(rng.integers(0, 50))})
    svc.refresh()
    return svc


def strip_took(resp):
    resp = json.loads(json.dumps(resp))
    resp.pop("took", None)
    resp.pop("profile", None)
    return resp


def run_concurrent(fn, n):
    """Run ``fn(i)`` on n threads released together; returns results in
    index order, re-raising the first worker error.  A tiny GIL switch
    interval makes the threads actually interleave (a warm sub-ms
    search otherwise finishes inside one 5 ms GIL slice and the
    "concurrent" calls cascade serially)."""
    import sys as _sys

    results = [None] * n
    errors = [None] * n
    barrier = threading.Barrier(n)

    def worker(i):
        try:
            barrier.wait()
            results[i] = fn(i)
        except BaseException as e:  # noqa: BLE001
            errors[i] = e

    interval0 = _sys.getswitchinterval()
    _sys.setswitchinterval(0.0002)
    try:
        threads = [threading.Thread(target=worker, args=(i,),
                                    name=f"qe-test-{i}", daemon=True)
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
    finally:
        _sys.setswitchinterval(interval0)
    for e in errors:
        if e is not None:
            raise e
    return results


def run_until_coalesced(fn, n, attempts=8):
    """Repeat a concurrent round until at least one batch dispatch
    happened (scheduling can legally serialize one round — the batcher
    never waits without live concurrency evidence).  Returns (results,
    batched_delta, dispatch_delta) of the successful round."""
    m = metrics()
    for attempt in range(attempts):
        b0 = m.counter("search.batcher.batched").value
        d0 = m.counter("search.batcher.dispatches").value
        results = run_concurrent(fn, n)
        batched = m.counter("search.batcher.batched").value - b0
        dispatches = m.counter("search.batcher.dispatches").value - d0
        if batched:
            return results, batched, dispatches
    raise AssertionError(
        f"no coalescing in {attempts} concurrent rounds of {n}")


# -- lint -------------------------------------------------------------------

def test_execution_paths_lint_repo_clean():
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS,
                                      "check_execution_paths.py")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_execution_paths_lint_catches_rogue_path(tmp_path):
    bad = tmp_path / "rogue.py"
    bad.write_text(
        "from opensearch_tpu.ops import bm25 as bm25_ops\n"
        "def fifth_path(p):\n"
        "    return bm25_ops.impact_scores(*p, n_pad=8, budget=8)\n")
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_execution_paths.py"),
         str(tmp_path)], capture_output=True, text=True)
    assert r.returncode == 1
    assert "rogue.py:3" in r.stdout
    # the annotation silences it
    bad.write_text(
        "from opensearch_tpu.ops import bm25 as bm25_ops\n"
        "def fifth_path(p):\n"
        "    return bm25_ops.impact_scores(*p, n_pad=8, budget=8)"
        "  # engine-ok: test\n")
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_execution_paths.py"),
         str(tmp_path)], capture_output=True, text=True)
    assert r.returncode == 0


# -- continuous batcher -----------------------------------------------------

def test_concurrent_searches_coalesce_byte_identical(tmp_path):
    """Single-search caller: 8 concurrent identical-shape requests share
    one batch dispatch; every response is byte-identical to the
    sequential (batcher-off) response."""
    svc = build_service(tmp_path)
    body = {"query": {"match": {"body": "w0 w2"}}, "size": 5}

    engine_mod.BATCHER_ENABLED = False
    ref = strip_took(svc.search(dict(body)))
    assert ref["hits"]["hits"]

    engine_mod.BATCHER_ENABLED = True
    engine_mod.BATCHER_WINDOW_MS = 250.0
    m = metrics()
    w0 = m.counter("search.batcher.window_waits").value
    results, batched, dispatches = run_until_coalesced(
        lambda i: svc.search(dict(body)), 8)
    waits = m.counter("search.batcher.window_waits").value - w0
    assert batched >= 2           # real coalescing happened
    assert dispatches >= 1
    assert waits >= 1
    assert batched / dispatches >= 2      # realized occupancy > 1
    for r in results:
        assert strip_took(r) == ref


def test_differing_queries_same_group_byte_identical(tmp_path):
    """Members of one (field, k) group may carry DIFFERENT terms — each
    caller still gets exactly its own sequential-path response."""
    svc = build_service(tmp_path)
    bodies = [{"query": {"match": {"body": f"w{i % 5} w{(i + 3) % 7}"}},
               "size": 4} for i in range(8)]
    engine_mod.BATCHER_ENABLED = False
    refs = [strip_took(svc.search(dict(b))) for b in bodies]
    engine_mod.BATCHER_ENABLED = True
    engine_mod.BATCHER_WINDOW_MS = 250.0
    results = run_concurrent(lambda i: svc.search(dict(bodies[i])), 8)
    for r, ref in zip(results, refs):
        assert strip_took(r) == ref


def test_serial_traffic_never_waits(tmp_path):
    """No concurrent batchable traffic -> no window wait: serial
    batchable requests take the sequential path with zero added
    latency (the bypass contract)."""
    svc = build_service(tmp_path)
    engine_mod.BATCHER_ENABLED = True
    engine_mod.BATCHER_WINDOW_MS = 5000.0    # a wait would be obvious
    m = metrics()
    w0 = m.counter("search.batcher.window_waits").value
    t0 = time.monotonic()
    for _ in range(3):
        svc.search({"query": {"match": {"body": "w1"}}, "size": 3})
    assert time.monotonic() - t0 < 4.0       # nowhere near the window
    assert m.counter("search.batcher.window_waits").value == w0


def test_non_batchable_and_disabled_bypass(tmp_path):
    svc = build_service(tmp_path)
    m = metrics()
    engine_mod.BATCHER_ENABLED = True
    y0 = m.counter("search.batcher.bypass").value
    sorted_body = {"query": {"match": {"body": "w1"}},
                   "sort": [{"n": "asc"}], "size": 3}
    r1 = svc.search(dict(sorted_body))
    assert m.counter("search.batcher.bypass").value == y0 + 1
    engine_mod.BATCHER_ENABLED = False
    y1 = m.counter("search.batcher.bypass").value
    r2 = svc.search(dict(sorted_body))
    # disabled: the batcher is not even consulted
    assert m.counter("search.batcher.bypass").value == y1
    assert strip_took(r1) == strip_took(r2)


def test_msearch_byte_identity_batcher_on_off(tmp_path):
    """msearch caller: batched groups + the threadpool-fanned fallback
    both return exactly the sequential per-body responses, batcher on
    and off."""
    svc = build_service(tmp_path)
    bodies = [
        {"query": {"match": {"body": "w0 w2"}}, "size": 5},
        {"query": {"match": {"body": "w3"}}, "size": 5},
        {"query": {"match": {"body": "w1"}}, "size": 3,
         "sort": [{"n": "asc"}]},                       # fallback
        {"query": {"range": {"n": {"gte": 10}}}, "size": 4,
         "sort": [{"n": "desc"}]},                      # fallback
    ]
    engine_mod.BATCHER_ENABLED = False
    seq = [strip_took(svc.search(dict(b))) for b in bodies]
    for flag in (True, False):
        engine_mod.BATCHER_ENABLED = flag
        out = svc.msearch([dict(b) for b in bodies])
        for got, want in zip(out, seq):
            got = strip_took(got)
            # msearch members never report timed_out=True here and the
            # shards section matches the single-search one
            assert got == want


def test_insights_batched_group_size_and_queue_wait(tmp_path):
    """Satellite: per-member batched_group_size + batcher queue-wait
    reach the insight records and the per-signature rollups."""
    from opensearch_tpu.search.insights import QueryInsightsService

    svc = build_service(tmp_path)
    engine_mod.BATCHER_ENABLED = True
    engine_mod.BATCHER_WINDOW_MS = 250.0
    body = {"query": {"match": {"body": "w0 w2"}}, "size": 5}
    sinks = []
    sink_lock = threading.Lock()

    def run(i):
        with insights_mod.collecting() as sink:
            svc.search(dict(body))
        with sink_lock:
            sinks.append(sink)

    run_until_coalesced(run, 6)
    recs = [s[0] for s in sinks if s]
    batched = [r for r in recs if r.get("batched")]
    assert batched, recs
    assert all(r["batched"] >= 2 for r in batched)
    assert all(r["queue_wait_ms"] >= 0.0 for r in batched)
    assert all(r["execution_path"] in ("host_batched", "device_batched")
               for r in batched)
    svc_ins = QueryInsightsService(node_id="t")
    for r in recs:
        svc_ins.record(dict(r))
    sig = insights_mod.signature_hash(
        insights_mod.canonical_query(body["query"]), True)
    roll = svc_ins.section()["signatures"][sig]
    assert roll["batched_members"] == len(batched)
    assert roll["batched_group_size"]["max"] >= 2
    assert roll["batched_group_size"]["mean"] >= 2
    assert roll["queue_wait_ms"]["max"] >= 0.0


def test_profile_queue_phase_on_batched_members(tmp_path):
    """Profiled members coalesce too: the shared group attribution plus
    each member's OWN queue wait land in the breakdown, and hits stay
    byte-identical."""
    svc = build_service(tmp_path)
    body = {"query": {"match": {"body": "w0 w2"}}, "size": 5}
    engine_mod.BATCHER_ENABLED = False
    ref = strip_took(svc.search(dict(body)))
    engine_mod.BATCHER_ENABLED = True
    engine_mod.BATCHER_WINDOW_MS = 250.0
    results, _batched, _disp = run_until_coalesced(
        lambda i: svc.search(dict(body, profile=True)), 4)
    batched_secs = []
    for r in results:
        assert strip_took(r) == ref
        sec = r["profile"]["shards"][0]
        bd = sec["searches"][0]["query"][0]["breakdown"]
        assert "queue" in bd and "queue_count" in bd
        if sec["engine"].get("batch"):
            batched_secs.append(sec)
    assert batched_secs            # at least one member truly coalesced
    for sec in batched_secs:
        bd = sec["searches"][0]["query"][0]["breakdown"]
        assert bd["queue"] > 0
        assert sec["engine"]["batch"]["queries"] >= 2
        assert sec["engine"]["execution_path"] in ("host_batched",
                                                   "device_batched")


# -- prediction vs realization ----------------------------------------------

def test_coalescability_report_brackets_realized_occupancy():
    """Satellite: the insights coalescability prediction must bracket
    the batcher's realized occupancy on the zipf workload.  The report
    chains arrivals (each within-window successor coalesces), the
    batcher windows from each group LEADER — so the prediction is an
    upper bound, and with bursty zipf arrivals the realization stays
    within a 3x band above 1."""
    from opensearch_tpu.search.insights import QueryInsightsService

    class FakeClock:
        def __init__(self):
            self.t = 1000.0

    clock = FakeClock()
    svc = QueryInsightsService(node_id="t", coalesce_window_ms=10.0,
                               clock=lambda: clock.t,
                               ring_capacity=4096, max_signatures=64)
    rng = np.random.default_rng(7)
    arrivals = []
    # zipf-shaped traffic: hot signatures arrive in tight bursts, cold
    # ones alone — the measured shape the batcher amortizes
    for _ in range(60):
        sig = f"q{min(int(rng.zipf(1.5)), 8)}"
        burst = int(rng.integers(1, 6)) if sig in ("q1", "q2") else 1
        for _ in range(burst):
            clock.t += float(rng.uniform(0.0005, 0.003))
            arrivals.append((clock.t, sig))
            svc.record({"signature": sig, "scored": True,
                        "took_ms": 1.0, "execution_path": "host",
                        "plan_cache": "hit"})
        clock.t += float(rng.uniform(0.05, 0.3))     # inter-burst gap
    report = svc.coalescability()
    assert 0.0 < report["coalescable_fraction"] < 1.0
    # exact chain-rule occupancy from the raw counts (the rendered
    # fraction is rounded to 4 decimals): every coalesced arrival
    # joined its predecessor's chain, so chains = arrivals - coalesced
    predicted = report["arrivals"] / (report["arrivals"]
                                      - report["coalesced"])
    realized = ContinuousBatcher.simulate_occupancy(arrivals, 0.010)
    assert realized >= 1.0
    # leader-window grouping can only SPLIT a chain, never merge two:
    # the report's prediction is a true upper bound...
    assert realized <= predicted + 1e-9
    # ...and on bursty zipf traffic it stays a tight one (brackets)
    assert realized >= 1.0 + (predicted - 1.0) / 3.0


# -- host fast path over the threadpool --------------------------------------

def test_host_parallel_multi_segment_byte_identity(tmp_path):
    """The pooled multi-segment host fast path returns exactly what the
    sequential per-segment loop returns (the profiled request pins the
    sequential loop; profiling never changes hits)."""
    svc = build_service(tmp_path, n_docs=120)
    # several refreshes -> several segments
    rng = np.random.default_rng(9)
    vocab = [f"w{i}" for i in range(20)]
    for wave in range(2):
        for i in range(40):
            svc.index_doc(f"x{wave}-{i}", {
                "body": " ".join(rng.choice(vocab,
                                            size=int(rng.integers(3, 10)))),
                "n": int(rng.integers(0, 50))})
        svc.refresh()
    searcher = svc.searcher()
    assert len(searcher.segments) >= 2
    bm25_ops.HOST_SCORING = True
    engine_mod.BATCHER_ENABLED = False
    pool0 = query_engine().pool.submitted
    body = {"query": {"match": {"body": "w0 w2"}}, "size": 8}
    par = svc.search(dict(body))
    assert query_engine().pool.submitted > pool0   # actually fanned out
    seq = svc.search(dict(body, profile=True))     # sequential loop
    assert json.dumps(par["hits"], sort_keys=True) \
        == json.dumps(seq["hits"], sort_keys=True)
    # min_score block-max pruning is still exact on the parallel path
    ms_body = dict(body, min_score=0.5)
    assert json.dumps(svc.search(dict(ms_body))["hits"],
                      sort_keys=True) \
        == json.dumps(svc.search(dict(ms_body, profile=True))["hits"],
                      sort_keys=True)


# -- threadpool / shutdown ---------------------------------------------------

def test_threadpool_named_threads_and_idempotent_shutdown():
    eng = query_engine()
    out = eng.pool.run_all([lambda: threading.current_thread().name
                            for _ in range(4)])
    assert all(n.startswith("search-engine-") for n in out)
    t0 = time.monotonic()
    eng.shutdown()
    eng.shutdown()                 # idempotent
    assert time.monotonic() - t0 < 6.0     # bounded join, no hang
    # post-shutdown work respawns workers (process-global pool serves
    # whichever node is still alive)
    out = eng.pool.run_all([lambda: 1 + 1])
    assert out == [2]


def test_node_stop_joins_engine_and_settings_wire(tmp_path):
    from opensearch_tpu.node import Node

    node = Node(str(tmp_path / "n"), port=0)
    try:
        # defaults replayed at construction
        assert engine_mod.BATCHER_ENABLED is True
        assert engine_mod.BATCHER_MAX_BATCH == 64
        node.update_cluster_settings(transient={
            "search.batcher.enabled": False,
            "search.batcher.window_ms": 25.0,
            "search.batcher.max_batch": 8,
            "search.insights.coalesce_window_ms": 7.0})
        assert engine_mod.BATCHER_ENABLED is False
        assert engine_mod.BATCHER_WINDOW_MS == 25.0
        assert engine_mod.BATCHER_MAX_BATCH == 8
        assert engine_mod.AUTO_WINDOW_MS == 7.0
    finally:
        t0 = time.monotonic()
        node.stop()
        node.stop()                # idempotent, no new stop-hang class
        assert time.monotonic() - t0 < 10.0


# -- cluster scatter ---------------------------------------------------------

def wait_until(pred, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:   # deadline-bounded poll
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_cluster_scatter_byte_identity_batcher_on_off(tmp_path):
    """Cluster caller: the data-node query phase routes through the
    engine; scatter responses are byte-identical with the batcher on
    and off (the per-payload searcher never coalesces, by design)."""
    from opensearch_tpu.cluster import response_collector as rc
    from opensearch_tpu.cluster.node import ClusterNode
    from opensearch_tpu.transport.service import (LocalTransport,
                                                  TransportService)

    # pin copy selection: adaptive C3 ranking is stateful (EWMAs move
    # between calls), which legally reorders equal-score ties across
    # runs — this test pins the BATCHER's effect, not selection's
    adaptive0 = rc.ADAPTIVE_ENABLED
    rc.ADAPTIVE_ENABLED = False
    hub = LocalTransport.Hub()
    ids = ["n0", "n1", "n2"]
    nodes = {}
    for nid in ids:
        tsvc = TransportService(nid, LocalTransport(hub))
        n = ClusterNode(nid, str(tmp_path / nid), tsvc, ids)
        n.search_backpressure.trackers["cpu_usage"].probe = lambda: 0.0
        nodes[nid] = n
    try:
        assert nodes["n0"].start_election()
        assert wait_until(lambda: all(
            nodes[i].coordinator.state().master_node == "n0"
            for i in ids))
        nodes["n0"].create_index("sc", {
            "settings": {"number_of_shards": 2, "number_of_replicas": 1},
            "mappings": {"properties": {"t": {"type": "text"}}}})

        def in_sync():
            routing = nodes["n0"].coordinator.state().routing.get(
                "sc", [])
            return routing and all(
                set(e["in_sync"]) == {e["primary"], *e["replicas"]}
                for e in routing)
        assert wait_until(in_sync)
        for i in range(24):
            nodes["n0"].index_doc("sc", str(i),
                                  {"t": f"w{i % 4} common"})
        nodes["n0"].refresh("sc")
        body = {"query": {"match": {"t": "common w1"}}, "size": 6}
        engine_mod.BATCHER_ENABLED = True
        engine_mod.BATCHER_WINDOW_MS = 50.0
        on = strip_took(nodes["n0"].search("sc", dict(body)))
        engine_mod.BATCHER_ENABLED = False
        off = strip_took(nodes["n0"].search("sc", dict(body)))
        assert on == off
        assert on["hits"]["total"]["value"] == 24
        # msearch at cluster scope too
        engine_mod.BATCHER_ENABLED = True
        mon = nodes["n0"].msearch("sc", [dict(body), dict(body)])
        engine_mod.BATCHER_ENABLED = False
        moff = nodes["n0"].msearch("sc", [dict(body), dict(body)])
        assert [strip_took(r) for r in mon["responses"]] \
            == [strip_took(r) for r in moff["responses"]]
    finally:
        rc.ADAPTIVE_ENABLED = adaptive0
        for n in nodes.values():
            n.stop()


# -- mesh caller -------------------------------------------------------------

def test_mesh_routed_caller_byte_identity_batcher_on_off(tmp_path):
    """Mesh caller: an index opted into search.mesh routes through the
    SAME engine entry; the batcher never touches it, so responses are
    identical with the flag on and off (mesh-vs-host score parity is
    pinned in tests/test_dist_search.py)."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    svc = IndexService("mesh", str(tmp_path / "mesh"),
                       {"number_of_shards": 2, "search.mesh": True},
                       MAPPING)
    rng = np.random.default_rng(3)
    vocab = [f"w{i}" for i in range(12)]
    for i in range(40):
        svc.index_doc(str(i), {
            "body": " ".join(rng.choice(vocab,
                                        size=int(rng.integers(3, 9)))),
            "n": i})
    svc.refresh()
    body = {"query": {"match": {"body": "w0 w1"}}, "size": 5}
    engine_mod.BATCHER_ENABLED = True
    on = strip_took(svc.search(dict(body)))
    engine_mod.BATCHER_ENABLED = False
    off = strip_took(svc.search(dict(body)))
    assert on == off
    assert on["hits"]["hits"]
