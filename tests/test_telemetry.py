"""Telemetry spine: Tracer/MetricsRegistry SPI, end-to-end trace
propagation over LocalTransport in cluster mode, slow logs with dynamic
thresholds, timeout budgets with partial-results flagging, X-Opaque-Id
task attribution, and the _nodes/stats | _nodes/trace surfaces."""

import json
import logging
import subprocess
import sys
import time

import pytest

from opensearch_tpu.common.telemetry import (
    MetricsRegistry,
    SpanContext,
    Tracer,
    metrics,
    tracer,
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    from opensearch_tpu.indices import service as indices_mod
    tracer().reset()
    yield
    tracer().reset()
    indices_mod.SLOWLOG_DEFAULTS.clear()


# -- tracer SPI -----------------------------------------------------------

def test_span_nesting_and_trace_ids():
    t = Tracer()
    with t.start_span("outer", {"a": 1}) as outer:
        assert t.current() is outer
        with t.start_span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_span_id == outer.span_id
    assert t.current() is None
    spans = t.recent()
    assert [s["name"] for s in spans] == ["outer", "inner"]
    assert spans[0]["duration_in_nanos"] >= 0
    assert spans[0]["attributes"] == {"a": 1}


def test_traceparent_roundtrip_and_extract():
    t = Tracer()
    with t.start_span("root") as root:
        hdrs = t.inject({})
        assert hdrs["traceparent"] == \
            f"00-{root.trace_id}-{root.span_id}-01"
    ctx = Tracer.extract(hdrs)
    assert ctx.trace_id == root.trace_id
    assert ctx.span_id == root.span_id
    # HTTP headers arrive with arbitrary casing
    assert Tracer.extract({"Traceparent": hdrs["traceparent"]}) is not None
    # malformed values are ignored, never raise
    assert Tracer.extract({"traceparent": "junk"}) is None
    assert Tracer.extract({"traceparent": "00-zz-bad-01"}) is None
    assert SpanContext.from_traceparent(None) is None


def test_explicit_parent_overrides_ambient():
    t = Tracer()
    remote = SpanContext("ab" * 16, "cd" * 8)
    with t.start_span("local-root"):
        with t.start_span("joined", parent=remote) as s:
            assert s.trace_id == remote.trace_id
            assert s.parent_span_id == remote.span_id


def test_span_buffer_is_bounded():
    t = Tracer(max_spans=10)
    for i in range(50):
        with t.start_span(f"s{i}"):
            pass
    spans = t.recent(limit=100)
    assert len(spans) == 10
    assert spans[0]["name"] == "s49"       # newest first


def test_span_records_errors():
    t = Tracer()
    with pytest.raises(ValueError):
        with t.start_span("boom"):
            raise ValueError("nope")
    assert "ValueError" in t.recent()[0]["error"]


# -- metrics SPI ----------------------------------------------------------

def test_counters_and_histogram_percentiles():
    m = MetricsRegistry()
    m.counter("c").inc()
    m.counter("c").inc(4)
    h = m.histogram("lat_ms")
    for v in range(1, 101):          # 1..100 ms uniform
        h.observe(float(v))
    stats = m.stats()
    assert stats["counters"]["c"] == 5
    hs = stats["histograms"]["lat_ms"]
    assert hs["count"] == 100
    assert hs["max_in_millis"] == 100.0
    p50 = hs["percentiles"]["50.0"]
    p99 = hs["percentiles"]["99.0"]
    assert 25 <= p50 <= 75           # bucket-interpolated estimate
    assert p99 >= p50
    assert p99 <= 250


def test_histogram_empty_and_single():
    m = MetricsRegistry()
    h = m.histogram("x")
    assert h.percentile(99) == 0.0
    h.observe(3.0)
    assert h.stats()["count"] == 1
    assert h.stats()["percentiles"]["50.0"] <= 5.0


def test_time_ms_context_manager():
    m = MetricsRegistry()
    with m.time_ms("block_ms"):
        pass
    assert m.histogram("block_ms").count == 1


# -- cluster-mode trace propagation (the acceptance criterion) ------------

def wait_until(pred, timeout=8.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def cluster(tmp_path):
    from opensearch_tpu.cluster.node import ClusterNode
    from opensearch_tpu.transport.service import (LocalTransport,
                                                  TransportService)
    hub = LocalTransport.Hub()
    ids = ["n0", "n1", "n2"]
    nodes = {}
    for nid in ids:
        svc = TransportService(nid, LocalTransport(hub))
        nodes[nid] = ClusterNode(nid, str(tmp_path / nid), svc, ids)
    assert nodes["n0"].start_election()
    wait_until(lambda: all(
        nodes[i].coordinator.state().master_node == "n0" for i in ids))
    yield hub, ids, nodes
    for n in nodes.values():
        n.stop()


def test_cluster_search_spans_share_one_trace(cluster):
    hub, ids, nodes = cluster
    nodes["n0"].create_index("traced", {
        "settings": {"number_of_shards": 6},
        "mappings": {"properties": {"body": {"type": "text"}}}})
    wait_until(lambda: all("traced" in nodes[i].indices for i in ids))
    for i in range(30):
        nodes["n0"].index_doc("traced", str(i), {"body": f"event {i}"})
    nodes["n0"].refresh("traced")

    tracer().reset()
    resp = nodes["n0"].search("traced", {"query": {"match": {
        "body": "event"}}, "size": 5})
    assert resp["hits"]["total"]["value"] == 30
    assert resp["timed_out"] is False

    spans = tracer().recent(limit=500)
    by_id = {s["span_id"]: s for s in spans}
    coord = [s for s in spans if s["name"] == "search.coordinator"]
    assert len(coord) == 1
    root = coord[0]
    assert root["parent_span_id"] is None
    trace_id = root["trace_id"]

    # the coordinator reduce ran under the same trace
    reduces = [s for s in spans if s["name"] == "coordinator.reduce"]
    assert len(reduces) == 1
    assert reduces[0]["trace_id"] == trace_id
    assert reduces[0]["parent_span_id"] == root["span_id"]

    # one query phase per participating node (shards group per node),
    # EVERY one under the coordinator's trace_id
    qp = [s for s in spans if s["name"] == "shard.query_phase"]
    assert len(qp) == len(ids)
    assert all(s["trace_id"] == trace_id for s in qp)

    # remote query phases parent through the transport server span,
    # which parents directly under the coordinator span
    remote_qp = 0
    for s in qp:
        parent = by_id.get(s["parent_span_id"])
        if parent is None:
            # parent must be the coordinator itself (local execution)
            assert s["parent_span_id"] == root["span_id"]
            continue
        if parent["name"].startswith("transport:"):
            remote_qp += 1
            assert parent["trace_id"] == trace_id
            assert parent["parent_span_id"] == root["span_id"]
        else:
            assert parent["span_id"] == root["span_id"]
    assert remote_qp == 2            # 3 nodes, coordinator is local

    # per-segment device dispatches joined the same trace
    segs = [s for s in spans if s["name"] == "segment.dispatch"]
    assert segs and all(s["trace_id"] == trace_id for s in segs)


def test_cluster_timeout_flag_survives_reduce(cluster):
    hub, ids, nodes = cluster
    nodes["n0"].create_index("budget", {
        "settings": {"number_of_shards": 3},
        "mappings": {"properties": {"body": {"type": "text"}}}})
    wait_until(lambda: all("budget" in nodes[i].indices for i in ids))
    for i in range(12):
        nodes["n0"].index_doc("budget", str(i), {"body": "x " * 5})
    nodes["n0"].refresh("budget")
    resp = nodes["n0"].search("budget", {
        "query": {"match": {"body": "x"}}, "timeout": 0})
    assert resp["timed_out"] is True


# -- timeout budget on the shard path -------------------------------------

@pytest.fixture
def svc(tmp_path):
    from opensearch_tpu.indices.service import IndexService
    s = IndexService("t", str(tmp_path / "t"), {},
                     {"properties": {"body": {"type": "text"},
                                     "n": {"type": "long"}}})
    for i in range(20):
        s.index_doc(str(i), {"body": f"word {i}", "n": i})
    s.refresh()
    yield s
    s.close()


def test_search_timeout_partial_results(svc):
    full = svc.search({"query": {"match": {"body": "word"}}})
    assert full["timed_out"] is False
    assert full["hits"]["total"]["value"] == 20

    cut = svc.search({"query": {"match": {"body": "word"}},
                      "timeout": 0})
    assert cut["timed_out"] is True
    # budget expired before the first segment: partial (empty) results
    assert cut["hits"]["total"]["value"] == 0

    # a generous budget never flags
    ok = svc.search({"query": {"match": {"body": "word"}},
                     "timeout": "30s"})
    assert ok["timed_out"] is False
    assert ok["hits"]["total"]["value"] == 20


def test_sorted_and_agg_timeout_paths(svc):
    cut = svc.search({"query": {"match": {"body": "word"}},
                      "sort": [{"n": "asc"}], "timeout": 0})
    assert cut["timed_out"] is True
    cut = svc.search({"size": 0, "timeout": 0,
                      "aggs": {"m": {"max": {"field": "n"}}}})
    assert cut["timed_out"] is True


def test_msearch_timeout_falls_back_to_sequential(svc):
    out = svc.msearch([
        {"query": {"match": {"body": "word"}}},
        {"query": {"match": {"body": "word"}}, "timeout": 0}])
    assert out[0]["timed_out"] is False
    assert out[0]["hits"]["total"]["value"] == 20
    assert out[1]["timed_out"] is True


# -- slow logs ------------------------------------------------------------

def test_indexing_slowlog_per_index_setting(tmp_path, caplog):
    from opensearch_tpu.indices.service import IndexService
    s = IndexService("w", str(tmp_path / "w"),
                     {"indexing.slowlog.threshold.index.warn": "0ms"},
                     {"properties": {"t": {"type": "text"}}})
    with caplog.at_level(
            logging.WARNING,
            logger="opensearch_tpu.index.indexing.slowlog"):
        s.index_doc("1", {"t": "hello"})
    assert any("took" in r.getMessage() for r in caplog.records)
    s.close()


def test_slowlog_dynamic_update_and_cluster_default(tmp_path):
    """_cluster/settings sets the fleet default; a per-index
    PUT /{index}/_settings overrides it (reference layering)."""
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "n"), port=0).start()
    try:
        rest = node.rest
        st, _ = rest.dispatch("PUT", "/slowidx", {}, json.dumps({
            "mappings": {"properties": {"t": {"type": "text"}}}
        }).encode())
        assert st == 200
        st, _ = rest.dispatch(
            "PUT", "/slowidx/_doc/1", {},
            json.dumps({"t": "hello"}).encode())
        assert st in (200, 201)
        rest.dispatch("POST", "/slowidx/_refresh", {}, None)

        logger = logging.getLogger("opensearch_tpu.index.search.slowlog")
        records = []

        class Grab(logging.Handler):
            def emit(self, record):
                records.append(record)
        h = Grab(level=logging.DEBUG)
        logger.addHandler(h)
        logger.setLevel(logging.DEBUG)
        try:
            body = json.dumps({"query": {"match": {"t": "hello"}}}).encode()
            # no thresholds anywhere: silent
            rest.dispatch("POST", "/slowidx/_search", {}, body)
            assert not records

            # cluster-level default catches every index
            st, _ = rest.dispatch("PUT", "/_cluster/settings", {},
                                  json.dumps({"transient": {
                                      "search.slowlog.threshold.query"
                                      ".warn": "0ms"}}).encode())
            assert st == 200
            rest.dispatch("POST", "/slowidx/_search", {}, body)
            assert len(records) == 1
            assert records[0].levelno == logging.WARNING

            # per-index override disables it for this index
            st, _ = rest.dispatch(
                "PUT", "/slowidx/_settings", {},
                json.dumps({"index": {
                    "search.slowlog.threshold.query.warn": "-1"
                }}).encode())
            assert st == 200
            rest.dispatch("POST", "/slowidx/_search", {}, body)
            assert len(records) == 1       # no new record

            # reset the cluster default (null resets, like the reference)
            st, _ = rest.dispatch("PUT", "/_cluster/settings", {},
                                  json.dumps({"transient": {
                                      "search.slowlog.threshold.query"
                                      ".warn": None}}).encode())
            assert st == 200
            from opensearch_tpu.indices.service import SLOWLOG_DEFAULTS
            assert "search.slowlog.threshold.query.warn" \
                not in SLOWLOG_DEFAULTS
        finally:
            logger.removeHandler(h)
            logger.setLevel(logging.NOTSET)
    finally:
        node.stop()


# -- X-Opaque-Id ----------------------------------------------------------

def test_x_opaque_id_reaches_task_and_cat_tasks(tmp_path):
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "n"), port=0).start()
    try:
        # the _tasks request lists ITSELF, so its own headers echo back
        st, body = node.rest.dispatch(
            "GET", "/_tasks", {}, None,
            headers={"X-Opaque-Id": "req-42"})
        assert st == 200
        tasks = next(iter(body["nodes"].values()))["tasks"]
        assert any(t.get("headers", {}).get("X-Opaque-Id") == "req-42"
                   for t in tasks.values())

        st, rows = node.rest.dispatch(
            "GET", "/_cat/tasks", {}, None,
            headers={"x-opaque-id": "req-43"})   # case-insensitive
        assert st == 200
        assert any(r.get("x_opaque_id") == "req-43" for r in rows)
    finally:
        node.stop()


# -- REST surfaces --------------------------------------------------------

def test_rest_traceparent_honored_and_stats_histograms(tmp_path):
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "n"), port=0).start()
    try:
        rest = node.rest
        rest.dispatch("PUT", "/obs", {}, json.dumps({
            "mappings": {"properties": {"t": {"type": "text"}}}
        }).encode())
        rest.dispatch("PUT", "/obs/_doc/1", {},
                      json.dumps({"t": "hello world"}).encode())
        rest.dispatch("POST", "/obs/_refresh", {}, None)

        tracer().reset()
        incoming = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        st, _ = rest.dispatch(
            "POST", "/obs/_search", {},
            json.dumps({"query": {"match": {"t": "hello"}}}).encode(),
            headers={"traceparent": incoming})
        assert st == 200
        spans = tracer().recent(limit=200)
        roots = [s for s in spans if s["name"].startswith("rest:")]
        assert roots and all(s["trace_id"] == "ab" * 16 for s in roots)
        # the REST root continues the CLIENT's trace
        assert roots[-1]["parent_span_id"] == "cd" * 8
        # the shard query phase nests under the same client trace
        qp = [s for s in spans if s["name"] == "shard.query_phase"]
        assert qp and all(s["trace_id"] == "ab" * 16 for s in qp)

        # _nodes/stats: telemetry section with non-zero latency counts
        st, body = rest.dispatch("GET", "/_nodes/stats", {}, None)
        assert st == 200
        tele = next(iter(body["nodes"].values()))["telemetry"]
        hist = tele["histograms"]["search.query_ms"]
        assert hist["count"] >= 1
        assert "50.0" in hist["percentiles"]
        assert "99.0" in hist["percentiles"]
        assert tele["histograms"]["indexing.index_ms"]["count"] >= 1
        assert tele["counters"]["search.queries"] >= 1

        # _nodes/trace: the debug span dump, filterable by trace_id
        st, body = rest.dispatch("GET", "/_nodes/trace",
                                 {"trace_id": "ab" * 16}, None)
        assert st == 200
        spans = next(iter(body["nodes"].values()))["spans"]
        assert spans and all(s["trace_id"] == "ab" * 16 for s in spans)

        # hot threads includes this very thread's stack
        st, body = rest.dispatch("GET", "/_nodes/hot_threads", {}, None)
        assert st == 200
        text = next(iter(body["nodes"].values()))["hot_threads"]
        assert "thread [" in text and "h_hot_threads" in text
    finally:
        node.stop()


def test_write_path_metrics(tmp_path):
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "n"), port=0).start()
    try:
        before = metrics().histogram("translog.sync_ms").count
        node.rest.dispatch("PUT", "/wm/_doc/1", {},
                           json.dumps({"v": 1}).encode())
        node.rest.dispatch("POST", "/wm/_refresh", {}, None)
        assert metrics().histogram("translog.sync_ms").count > before
        assert metrics().histogram("indexing.refresh_ms").count >= 1
    finally:
        node.stop()


# -- monotonic lint (the tier-1 CI hook) ----------------------------------

def test_check_monotonic_lint_passes():
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "check_monotonic.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_check_monotonic_lint_catches_violations(tmp_path):
    bad = tmp_path / "pkg"
    bad.mkdir()
    (bad / "mod.py").write_text(
        "import time\nt0 = time.time()\n"
        "ok = time.time()  # wall-clock: timestamp\n")
    out = subprocess.run(
        [sys.executable, "tools/check_monotonic.py", str(bad)],
        capture_output=True, text=True,
        cwd=__file__.rsplit("/tests/", 1)[0])
    assert out.returncode == 1
    assert "mod.py:2" in out.stdout
    assert "mod.py:3" not in out.stdout
