"""Aggregation correctness vs plain-Python oracles (nyc_taxis-style
terms/date_histogram/metrics must return oracle-identical buckets —
VERDICT round-1 item 6's 'done' bar)."""

import datetime as dt
import math

import numpy as np
import pytest

from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.mapping.types import parse_date_millis
from opensearch_tpu.search.executor import ShardSearcher

MAPPING = {"properties": {
    "color": {"type": "keyword"},
    "n": {"type": "long"},
    "price": {"type": "double"},
    "day": {"type": "date"},
    "flag": {"type": "boolean"},
    "body": {"type": "text"},
}}

COLORS = ["red", "green", "blue", "cyan"]


def build(n_docs=150, n_segments=3, seed=5):
    rng = np.random.default_rng(seed)
    mapper = DocumentMapper(MAPPING)
    writer = SegmentWriter()
    segments, raws = [], []
    per = n_docs // n_segments
    doc_no = 0
    for si in range(n_segments):
        parsed = []
        for _ in range(per):
            src = {
                "color": list(rng.choice(COLORS, size=rng.integers(1, 3),
                                         replace=False)),
                "n": int(rng.integers(0, 50)),
                "price": float(np.round(rng.uniform(1, 100), 2)),
                "day": f"2023-{rng.integers(1, 7):02d}-{rng.integers(1, 28):02d}",
                "flag": bool(rng.integers(0, 2)),
                "body": "match me" if rng.uniform() < 0.5 else "skip this",
            }
            if rng.uniform() < 0.15:
                del src["price"]
            raws.append(src)
            parsed.append(mapper.parse(str(doc_no), src))
            doc_no += 1
        segments.append(writer.build(parsed, f"s{si}"))
    return ShardSearcher(segments, mapper), raws


@pytest.fixture(scope="module")
def corpus():
    return build()


def agg_resp(searcher, aggs, query=None, size=0):
    body = {"aggs": aggs, "size": size}
    if query:
        body["query"] = query
    return searcher.search(body)["aggregations"]


def test_terms_keyword(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {"by_color": {"terms": {"field": "color"}}})
    expected = {}
    for src in raws:
        for c in set(src["color"]):
            expected[c] = expected.get(c, 0) + 1
    buckets = out["by_color"]["buckets"]
    exp_sorted = sorted(expected.items(), key=lambda kv: (-kv[1], kv[0]))
    assert [(b["key"], b["doc_count"]) for b in buckets] == exp_sorted[:10]
    assert out["by_color"]["sum_other_doc_count"] == (
        sum(expected.values()) - sum(b["doc_count"] for b in buckets))


def test_terms_keyword_key_order_and_size(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {"a": {"terms": {
        "field": "color", "size": 2, "order": {"_key": "asc"}}}})
    keys = [b["key"] for b in out["a"]["buckets"]]
    assert keys == sorted(set(c for src in raws for c in src["color"]))[:2]


def test_terms_long_and_boolean(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {
        "by_n": {"terms": {"field": "n", "size": 5}},
        "by_flag": {"terms": {"field": "flag"}}})
    expected_n = {}
    for src in raws:
        expected_n[src["n"]] = expected_n.get(src["n"], 0) + 1
    exp_sorted = sorted(expected_n.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
    assert [(b["key"], b["doc_count"]) for b in out["by_n"]["buckets"]] == exp_sorted
    flags = {b["key_as_string"]: b["doc_count"] for b in out["by_flag"]["buckets"]}
    assert flags["true"] == sum(1 for s in raws if s["flag"])
    assert flags["false"] == sum(1 for s in raws if not s["flag"])


def test_metrics(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {
        "mx": {"max": {"field": "price"}},
        "mn": {"min": {"field": "price"}},
        "sm": {"sum": {"field": "price"}},
        "av": {"avg": {"field": "price"}},
        "vc": {"value_count": {"field": "price"}},
        "st": {"stats": {"field": "n"}},
        "card": {"cardinality": {"field": "color"}},
        "pct": {"percentiles": {"field": "n", "percents": [50]}},
    })
    prices = [s["price"] for s in raws if "price" in s]
    ns = [s["n"] for s in raws]
    assert out["mx"]["value"] == pytest.approx(max(prices))
    assert out["mn"]["value"] == pytest.approx(min(prices))
    assert out["sm"]["value"] == pytest.approx(sum(prices), rel=1e-9)
    assert out["av"]["value"] == pytest.approx(sum(prices) / len(prices))
    assert out["vc"]["value"] == len(prices)
    assert out["st"] == {"count": len(ns), "min": min(ns), "max": max(ns),
                         "avg": pytest.approx(sum(ns) / len(ns)),
                         "sum": pytest.approx(sum(ns))}
    assert out["card"]["value"] == len(set(c for s in raws for c in s["color"]))
    assert out["pct"]["values"]["50.0"] == pytest.approx(
        float(np.percentile(np.asarray(ns, float), 50)))


def test_terms_with_sub_metrics(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {"by_color": {
        "terms": {"field": "color", "size": 10},
        "aggs": {"avg_n": {"avg": {"field": "n"}},
                 "sum_price": {"sum": {"field": "price"}}}}})
    for b in out["by_color"]["buckets"]:
        docs = [s for s in raws if b["key"] in s["color"]]
        assert b["doc_count"] == len(docs)
        assert b["avg_n"]["value"] == pytest.approx(
            sum(s["n"] for s in docs) / len(docs))
        assert b["sum_price"]["value"] == pytest.approx(
            sum(s.get("price", 0) for s in docs), rel=1e-9)


def test_date_histogram_month(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {"per_month": {
        "date_histogram": {"field": "day", "calendar_interval": "month"},
        "aggs": {"stats_n": {"stats": {"field": "n"}}}}})
    expected = {}
    for s in raws:
        month = s["day"][:7]
        expected.setdefault(month, []).append(s["n"])
    buckets = out["per_month"]["buckets"]
    got = {b["key_as_string"][:7]: b for b in buckets}
    assert set(got) == set(expected)
    for month, ns in expected.items():
        b = got[month]
        assert b["doc_count"] == len(ns)
        assert b["stats_n"]["sum"] == pytest.approx(sum(ns))
        assert b["stats_n"]["min"] == min(ns)
    # keys are millis at month boundaries, ascending
    keys = [b["key"] for b in buckets]
    assert keys == sorted(keys)


def test_date_histogram_fixed_interval(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {"weekly": {"date_histogram": {
        "field": "day", "fixed_interval": "7d"}}})
    total = sum(b["doc_count"] for b in out["weekly"]["buckets"])
    assert total == len(raws)
    keys = [b["key"] for b in out["weekly"]["buckets"]]
    assert all((k2 - k1) % (7 * 86400000) == 0 for k1, k2 in zip(keys, keys[1:]))


def test_histogram_numeric(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {"h": {"histogram": {"field": "n", "interval": 10}}})
    expected = {}
    for s in raws:
        b = (s["n"] // 10) * 10
        expected[float(b)] = expected.get(float(b), 0) + 1
    got = {b["key"]: b["doc_count"] for b in out["h"]["buckets"]
           if b["doc_count"]}
    assert got == expected


def test_filter_and_filters(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {
        "cheap": {"filter": {"range": {"n": {"lt": 25}}},
                  "aggs": {"colors": {"terms": {"field": "color"}}}},
        "split": {"filters": {"filters": {
            "low": {"range": {"n": {"lt": 25}}},
            "high": {"range": {"n": {"gte": 25}}}}}},
    })
    low = [s for s in raws if s["n"] < 25]
    assert out["cheap"]["doc_count"] == len(low)
    exp_colors = {}
    for s in low:
        for c in set(s["color"]):
            exp_colors[c] = exp_colors.get(c, 0) + 1
    got = {b["key"]: b["doc_count"] for b in out["cheap"]["colors"]["buckets"]}
    assert got == exp_colors
    assert out["split"]["buckets"]["low"]["doc_count"] == len(low)
    assert out["split"]["buckets"]["high"]["doc_count"] == len(raws) - len(low)


def test_range_agg(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {"r": {
        "range": {"field": "n", "ranges": [
            {"to": 20}, {"from": 20, "to": 40, "key": "mid"}, {"from": 40}]},
        "aggs": {"avg_price": {"avg": {"field": "price"}}}}})
    b0, b1, b2 = out["r"]["buckets"]
    assert b0["doc_count"] == sum(1 for s in raws if s["n"] < 20)
    assert b1["key"] == "mid"
    assert b1["doc_count"] == sum(1 for s in raws if 20 <= s["n"] < 40)
    assert b2["doc_count"] == sum(1 for s in raws if s["n"] >= 40)
    mid = [s for s in raws if 20 <= s["n"] < 40 and "price" in s]
    assert b1["avg_price"]["value"] == pytest.approx(
        sum(s["price"] for s in mid) / len(mid))


def test_global_and_missing(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher,
                   {"all": {"global": {},
                            "aggs": {"c": {"value_count": {"field": "n"}}}},
                    "no_price": {"missing": {"field": "price"}}},
                   query={"match": {"body": "match"}})
    assert out["all"]["doc_count"] == len(raws)
    assert out["all"]["c"]["value"] == len(raws)
    matched = [s for s in raws if "match" in s["body"]]
    assert out["no_price"]["doc_count"] == sum(
        1 for s in matched if "price" not in s)


def test_aggs_respect_query(corpus):
    searcher, raws = corpus
    out = agg_resp(searcher, {"s": {"sum": {"field": "n"}}},
                   query={"match": {"body": "match"}})
    expected = sum(s["n"] for s in raws if "match" in s["body"])
    assert out["s"]["value"] == pytest.approx(expected)


def test_aggs_with_hits(corpus):
    searcher, raws = corpus
    resp = searcher.search({"query": {"match_all": {}}, "size": 5,
                            "aggs": {"mx": {"max": {"field": "n"}}}})
    assert len(resp["hits"]["hits"]) == 5
    assert resp["aggregations"]["mx"]["value"] == max(s["n"] for s in raws)


def test_percentiles_device_centroids_bounded_and_accurate():
    """Past PCT_RAW_MAX the device sorts+bins values into equal-weight
    centroids; quantiles stay within ~1% of exact while the partial holds
    only O(1024) numbers (r3 Weak #5 / VERDICT item 7)."""
    import opensearch_tpu.search.aggs as A

    rng = np.random.default_rng(5)
    vals = (rng.normal(size=8000) * 50 + 100).astype(np.float64)
    mapper = DocumentMapper({"properties": {"v": {"type": "double"}}})
    writer = SegmentWriter()
    per = len(vals) // 2
    segs = [writer.build([mapper.parse(f"{si}-{i}",
                                       {"v": float(vals[si * per + i])})
                          for i in range(per)], f"pc{si}")
            for si in range(2)]
    searcher = ShardSearcher(segs, mapper)
    old = A.PCT_RAW_MAX
    A.PCT_RAW_MAX = 1000                     # force the device path
    try:
        seg_views = [(seg, seg.device(),
                      searcher.ctx.live_jnp(seg, seg.device()))
                     for seg in searcher.segments]
        partial = A.AggregationExecutor(searcher.ctx).collect(
            {"p": {"percentiles": {"field": "v"}}}, seg_views)
        assert partial["p"]["kind"] == "cent"
        assert len(partial["p"]["m"]) <= 4096  # bounded partial
        resp = searcher.search({"size": 0, "aggs": {"p": {"percentiles": {
            "field": "v", "percents": [5.0, 50.0, 95.0]}}}})
    finally:
        A.PCT_RAW_MAX = old
    for p, got in resp["aggregations"]["p"]["values"].items():
        exact = float(np.percentile(vals, float(p)))
        assert abs(got - exact) < 2.0, (p, got, exact)


def test_cardinality_streams_to_hll_past_threshold():
    """Distinct counts past precision_threshold degrade to HLL with
    bounded memory; the estimate stays within a few percent."""
    n = 6000
    mapper = DocumentMapper({"properties": {"v": {"type": "long"}}})
    writer = SegmentWriter()
    per = n // 2
    segs = [writer.build([mapper.parse(f"{si}-{i}", {"v": si * per + i})
                          for i in range(per)], f"cd{si}")
            for si in range(2)]
    searcher = ShardSearcher(segs, mapper)
    resp = searcher.search({"size": 0, "aggs": {
        "c": {"cardinality": {"field": "v",
                              "precision_threshold": 100}}}})
    est = resp["aggregations"]["c"]["value"]
    assert abs(est - n) / n < 0.05
    # below the threshold stays exact
    resp = searcher.search({"size": 0, "aggs": {
        "c": {"cardinality": {"field": "v",
                              "precision_threshold": 40000}}}})
    assert resp["aggregations"]["c"]["value"] == n
