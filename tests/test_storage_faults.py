"""Storage fault tolerance: checksummed segment commits (manifest as the
single atomic commit point), disk fault injection, corruption-driven
copy failover, and FsHealth-driven node eviction.

Analog coverage: Lucene ``CodecUtil.checkFooter`` CRCs + ``Store.verify``
/ ``CorruptedFileException`` markers + ``monitor/fs/FsHealthService``
(the reference fails unhealthy nodes out of the cluster).  Includes the
crash-point commit matrix (exception-injected kills between every
segment-commit step) and the tier-1 ``check_durable_writes`` lint.
"""

import errno
import json
import os
import subprocess
import sys
import time

import pytest

from opensearch_tpu.common.fshealth import FsHealthService
from opensearch_tpu.index import store
from opensearch_tpu.index.engine import InternalEngine
from opensearch_tpu.index.store import CorruptIndexError
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.testing.fault_injection import DiskFaultInjector

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MAPPING = {"properties": {"body": {"type": "text"},
                          "n": {"type": "long"}}}


def make_engine(path) -> InternalEngine:
    return InternalEngine(str(path), DocumentMapper(MAPPING))


def seed_engine(engine, n=6, offset=0):
    for i in range(offset, offset + n):
        engine.index(str(i), {"body": f"event t{i}", "n": i})


def committed_segment(path):
    commit = json.load(open(os.path.join(str(path), "commit.json")))
    return commit["segments"][0]


# -- checksummed segment commits --------------------------------------------


def test_save_segment_writes_manifest_and_verifies(tmp_path):
    e = make_engine(tmp_path)
    seed_engine(e)
    e.flush()
    e.close()
    seg_dir = str(tmp_path / "segments")
    sid = committed_segment(tmp_path)
    m = store.read_segment_manifest(seg_dir, sid)
    assert set(m["files"]) == {sid + ".json", sid + ".npz", sid + ".src"}
    for entry in m["files"].values():
        assert entry["length"] > 0 and "crc32" in entry
    assert store.verify_segment(seg_dir, sid) is True


@pytest.mark.parametrize("suffix", [".json", ".npz", ".src"])
def test_bit_flip_detected_and_names_file(tmp_path, suffix):
    e = make_engine(tmp_path)
    seed_engine(e)
    e.flush()
    e.close()
    seg_dir = str(tmp_path / "segments")
    sid = committed_segment(tmp_path)
    p = os.path.join(seg_dir, sid + suffix)
    data = bytearray(open(p, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(p, "wb").write(bytes(data))
    with pytest.raises(CorruptIndexError, match=sid + suffix.replace(
            ".", r"\.")):
        store.load_segment(seg_dir, sid)
    with pytest.raises(CorruptIndexError):
        store.verify_segment(seg_dir, sid)


def test_truncation_detected(tmp_path):
    e = make_engine(tmp_path)
    seed_engine(e)
    e.flush()
    e.close()
    seg_dir = str(tmp_path / "segments")
    sid = committed_segment(tmp_path)
    p = os.path.join(seg_dir, sid + ".npz")
    data = open(p, "rb").read()
    open(p, "wb").write(data[: len(data) // 2])
    with pytest.raises(CorruptIndexError, match="length mismatch"):
        store.load_segment(seg_dir, sid)


def test_legacy_directory_without_manifest_still_loads(tmp_path):
    e = make_engine(tmp_path)
    seed_engine(e)
    e.flush()
    e.close()
    seg_dir = str(tmp_path / "segments")
    sid = committed_segment(tmp_path)
    os.remove(os.path.join(seg_dir, sid + store.MANIFEST_SUFFIX))
    # pre-manifest stores load (unverifiable) instead of refusing
    seg = store.load_segment(seg_dir, sid)
    assert seg.n_docs == 6
    assert store.verify_segment(seg_dir, sid) is False


def test_liv_sidecar_self_checksum(tmp_path):
    e = make_engine(tmp_path)
    seed_engine(e)
    e.flush()
    e.delete("2")
    e.flush()                              # save_live rewrite
    e.close()
    seg_dir = str(tmp_path / "segments")
    sid = committed_segment(tmp_path)
    p = os.path.join(seg_dir, sid + ".liv")
    assert os.path.exists(p)
    seg = store.load_segment(seg_dir, sid)
    assert seg.live_count() == 5
    data = bytearray(open(p, "rb").read())
    data[-1] ^= 0xFF
    open(p, "wb").write(bytes(data))
    with pytest.raises(CorruptIndexError, match=r"\.liv"):
        store.load_segment(seg_dir, sid)


def test_corrupt_store_refuses_to_open_and_serves_nothing(tmp_path):
    e = make_engine(tmp_path)
    seed_engine(e)
    e.flush()
    e.close()
    seg_dir = str(tmp_path / "segments")
    sid = committed_segment(tmp_path)
    p = os.path.join(seg_dir, sid + ".src")
    data = bytearray(open(p, "rb").read())
    data[0] ^= 0xFF
    open(p, "wb").write(bytes(data))
    e2 = make_engine(tmp_path)
    assert e2.corruption is not None
    # the verdict persisted as a corrupted_<seg> marker
    markers = store.find_corruption_markers(seg_dir)
    assert markers and markers[0]["segment"] == sid
    with pytest.raises(CorruptIndexError):
        e2.get("1")
    with pytest.raises(CorruptIndexError):
        e2.index("x", {"body": "y", "n": 1})
    e2.close()
    # marker alone (even with the file healed) blocks reopen until the
    # copy is dropped — Store.failIfCorrupted
    open(p, "wb").write(bytes(data[:1]) + bytes(data[1:]))
    e3 = make_engine(tmp_path)
    assert e3.corruption is not None
    e3.close()


def test_wire_blob_checksums_detect_inflight_damage(tmp_path):
    e = make_engine(tmp_path)
    seed_engine(e)
    e.refresh()
    blobs = store.segment_to_blobs(e.segments[0])
    assert set(blobs["checksums"]) == {"json", "npz", "src"}
    roundtrip = store.segment_from_blobs(blobs)
    assert roundtrip.n_docs == 6
    damaged = dict(blobs)
    b = bytearray(damaged["npz"])
    b[len(b) // 3] ^= 0xFF
    damaged["npz"] = bytes(b)
    with pytest.raises(CorruptIndexError, match="npz"):
        store.segment_from_blobs(damaged)
    e.close()


# -- crash-point commit matrix (satellite) ----------------------------------


class _Killed(Exception):
    pass


class _ReplaceKiller:
    """Raise on the k-th os.replace whose destination lives under
    ``within`` — the deterministic 'kill -9 between commit steps'."""

    def __init__(self, k: int, within: str):
        self.k = k
        self.within = str(within)
        self.calls = 0
        self._real = os.replace

    def __enter__(self):
        def fake(src, dst):
            if str(dst).startswith(self.within):
                if self.calls == self.k:
                    self.calls += 1
                    raise _Killed(f"killed at replace #{self.k}: {dst}")
                self.calls += 1
            return self._real(src, dst)
        os.replace = fake
        return self

    def __exit__(self, *exc):
        os.replace = self._real
        return False


def test_crash_at_every_segment_commit_step_never_mixes(tmp_path):
    """Kill between EACH rename of the segment-commit sequence: reopen
    must see a loadable commit (complete old or complete new segment
    set) and recover every acked doc via the translog — never a
    mixed/corrupt set."""
    root = tmp_path / "shard"
    e = make_engine(root)
    seed_engine(e, 4)                      # docs 0-3
    e.flush()                              # committed baseline
    e.close()

    k = 0
    while True:
        e = make_engine(root)
        seed_engine(e, 3, offset=100 + 10 * k)   # fresh uncommitted docs
        new_ids = {str(100 + 10 * k + j) for j in range(3)}
        killed = False
        with _ReplaceKiller(k, str(root)) as killer:
            try:
                e.flush()
            except _Killed:
                killed = True
        e.close()
        # reopen from disk: commit must load cleanly and the translog
        # must recover every acked op
        e2 = make_engine(root)
        assert e2.corruption is None, f"crash point {k} corrupted store"
        got = {d for d in map(str, range(4))}
        have = set()
        for seg in e2.segments:
            have.update(seg.doc_ids)
        have.update(d for d, entry in e2._version_map.items()
                    if not entry.deleted)
        assert got <= have, f"crash point {k} lost committed docs"
        assert new_ids <= have, f"crash point {k} lost acked (translog) docs"
        e2.verify_store()                  # checksums hold at every point
        e2.flush()                         # leave a clean commit behind
        e2.close()
        if not killed:
            assert killer.calls >= 1
            break
        k += 1
    assert k >= 4        # 3 data files + manifest + translog ckp + commit


def test_crash_at_translog_roll_and_checkpoint_replace(tmp_path):
    from opensearch_tpu.index.translog import Translog

    root = tmp_path / "tl"
    k = 0
    while True:
        tl = Translog(str(root / f"case{k}"))
        for i in range(3):
            tl.add({"op": "index", "id": str(i), "source": {"n": i},
                    "seq_no": i, "version": 1})
        tl.sync()                          # acked high-water mark
        killed = False
        with _ReplaceKiller(k, str(root / f"case{k}")) as killer:
            try:
                tl.roll_generation()
                tl.add({"op": "index", "id": "9", "source": {"n": 9},
                        "seq_no": 3, "version": 1})
                tl.sync()
            except _Killed:
                killed = True
        tl._file.close()
        # reopen: every acked (synced) op must replay
        tl2 = Translog(str(root / f"case{k}"))
        acked = {op["id"] for op in tl2.read_ops()}
        assert {"0", "1", "2"} <= acked, f"crash point {k} lost acked ops"
        tl2.close()
        if not killed:
            assert killer.calls >= 1
            break
        k += 1
    assert k >= 2


# -- disk fault injection ----------------------------------------------------


def test_disk_injector_bitflip_truncate_and_one_shot(tmp_path):
    p = str(tmp_path / "x.bin")
    open(p, "wb").write(b"A" * 64)
    disk = DiskFaultInjector(seed=7)
    disk.corrupt_read(p, times=1)
    with disk:
        assert open(p, "rb").read() != b"A" * 64      # damaged
        assert open(p, "rb").read() == b"A" * 64      # one-shot spent
    assert open(p, "rb").read() == b"A" * 64          # deactivated
    trunc = DiskFaultInjector(seed=7)
    trunc.corrupt_read(p, mode="truncate")
    with trunc:
        assert len(open(p, "rb").read()) < 64


def test_disk_injector_errors_and_fsync(tmp_path):
    p = str(tmp_path / "y.bin")
    open(p, "wb").write(b"data")
    disk = DiskFaultInjector(seed=1)
    disk.fail_read(str(tmp_path / "y*"))
    disk.enospc(str(tmp_path / "z*"))
    disk.fail_fsync(str(tmp_path / "w*"))
    with disk:
        with pytest.raises(OSError) as ei:
            open(p, "rb")
        assert ei.value.errno == errno.EIO
        with pytest.raises(OSError) as ei:
            open(str(tmp_path / "z.bin"), "wb")
        assert ei.value.errno == errno.ENOSPC
        f = open(str(tmp_path / "w.bin"), "wb")
        f.write(b"x")
        with pytest.raises(OSError):
            os.fsync(f.fileno())
        f.close()


def test_disk_injector_seeded_determinism(tmp_path):
    p = str(tmp_path / "d.bin")
    open(p, "wb").write(bytes(range(256)))
    out = []
    for _ in range(2):
        d = DiskFaultInjector(seed=42)
        d.corrupt_read(p)
        with d:
            out.append(open(p, "rb").read())
    assert out[0] == out[1]


def test_slow_fsync_marks_fshealth_unhealthy(tmp_path):
    fh = FsHealthService(str(tmp_path), slow_path_logging_threshold_ms=5)
    disk = DiskFaultInjector(seed=2)
    disk.slow_fsync(os.path.join(str(tmp_path), FsHealthService.PROBE_FILE),
                    seconds=0.05)
    with disk:
        assert fh.check() is False
        assert "slow-path" in fh.stats()["reason"]
    assert fh.check() is True


def test_fshealth_periodic_probe_thread(tmp_path):
    fh = FsHealthService(str(tmp_path))
    fh.start_probe(interval_s=0.01, name="t")
    disk = DiskFaultInjector(seed=3)
    disk.fail_fsync(os.path.join(str(tmp_path), FsHealthService.PROBE_FILE))
    with disk:
        deadline = time.monotonic() + 5.0
        while fh.healthy and time.monotonic() < deadline:   # deadline
            time.sleep(0.01)                                # deadline
        assert not fh.healthy
    fh.stop_probe()


# -- cluster fixtures --------------------------------------------------------


@pytest.fixture
def cluster(tmp_path):
    from opensearch_tpu.cluster.node import ClusterNode
    from opensearch_tpu.transport.service import (LocalTransport,
                                                  TransportService)
    hub = LocalTransport.Hub()
    ids = ["n0", "n1", "n2"]
    nodes = {}
    for nid in ids:
        svc = TransportService(nid, LocalTransport(hub))
        nodes[nid] = ClusterNode(nid, str(tmp_path / nid), svc, ids)
    assert nodes["n0"].start_election()
    assert wait_until(lambda: all(
        nodes[i].coordinator.state().master_node == "n0" for i in ids))
    yield tmp_path, ids, nodes
    for n in nodes.values():
        n.stop()


def wait_until(pred, timeout=15.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:   # deadline
        if pred():
            return True
        time.sleep(0.03)                     # deadline
    return False


def in_sync_full(nodes, index="docs", leader="n0"):
    st = nodes[leader].coordinator.state()
    routing = st.routing.get(index, [])
    want = min(1, len(st.nodes) - 1)
    return bool(routing) and all(
        e.get("primary")
        and set(e["in_sync"]) == {e["primary"], *e["replicas"]}
        and len(e["replicas"]) >= want for e in routing)


def make_index(nodes, docs=30):
    nodes["n1"].create_index("docs", {
        "settings": {"number_of_shards": 2, "number_of_replicas": 1},
        "mappings": MAPPING})
    assert wait_until(lambda: in_sync_full(nodes))
    for i in range(docs):
        nodes["n1"].index_doc("docs", str(i), {"body": f"event {i}", "n": i})
    nodes["n1"].refresh("docs")


def flip_byte(path, where=0.5):
    data = bytearray(open(path, "rb").read())
    data[int(len(data) * where) % len(data)] ^= 0xFF
    open(path, "wb").write(bytes(data))


# -- acceptance 1: replica bit-flip -> detect, fail, re-recover --------------


def test_replica_corruption_failover_acceptance(cluster):
    """Seeded bit-flip in one replica's segment file on a 3-node
    cluster: corruption detected, marker written, copy failed via
    A_FAIL_COPY, local data dropped, re-recovered from the primary, and
    post-drain doc count + checksum converge with zero unexpected
    failures."""
    import zlib

    from opensearch_tpu.common.telemetry import metrics

    tmp_path, ids, nodes = cluster
    make_index(nodes)
    routing = nodes["n0"].coordinator.state().routing["docs"]
    victim = shard = None
    for s, e in enumerate(routing):
        if e["replicas"]:
            victim, shard = e["replicas"][0], s
            break
    assert victim is not None

    def checksum(node):
        resp = node.search("docs", {
            "query": {"match_all": {}}, "size": 100,
            "sort": [{"n": "asc"}],
            "allow_partial_search_results": False})
        assert resp["_shards"]["failed"] == 0
        docs = [(h["_id"], json.dumps(h["_source"], sort_keys=True))
                for h in resp["hits"]["hits"]]
        return resp["hits"]["total"]["value"], zlib.crc32(
            json.dumps(docs).encode())
    before = checksum(nodes["n1"])

    engine = nodes[victim].indices["docs"].engine_for(shard)
    docs_before = engine.doc_count()
    engine.flush()
    seg_dir = os.path.join(engine.data_path, "segments")
    target = [f for f in sorted(os.listdir(seg_dir))
              if f.endswith(".npz")][0]
    flip_byte(os.path.join(seg_dir, target), where=1 / 3)

    corruptions0 = metrics().counter("store.corruptions").value
    report = nodes[victim].verify_local_stores("docs")
    bad = [r for r in report if r.get("corrupted")]
    assert bad and bad[0]["shard"] == shard
    assert target.rsplit(".", 1)[0] in bad[0]["reason"]
    assert metrics().counter("store.corruptions").value == corruptions0 + 1

    # the copy left the in-sync set the instant the failure was reported
    # (it may already be back if recovery won the race — assert via the
    # eventual full recovery below, and that the engine was reset)
    def recovered():
        for nid in ids:
            if nid in nodes:
                nodes["n0"].coordinator.run_checks_once()
        eng = nodes[victim].indices["docs"].engine_for(shard)
        return (in_sync_full(nodes) and eng.corruption is None
                and eng.doc_count() == docs_before)
    assert wait_until(recovered)
    # marker cleaned up with the dropped copy
    eng = nodes[victim].indices["docs"].engine_for(shard)
    assert not store.find_corruption_markers(
        os.path.join(eng.data_path, "segments"))
    # convergence: same docs, same checksum, from every coordinator,
    # zero shard failures (no client-visible 5xx)
    for nid in ids:
        assert checksum(nodes[nid]) == before
    assert nodes["n0"].cluster_health()["status"] == "green"


def test_primary_corruption_promotes_in_sync_replica(cluster):
    tmp_path, ids, nodes = cluster
    make_index(nodes)
    shard = 0
    entry = nodes["n0"].coordinator.state().routing["docs"][shard]
    victim, old_term = entry["primary"], entry["primary_term"]
    engine = nodes[victim].indices["docs"].engine_for(shard)
    engine.flush()
    seg_dir = os.path.join(engine.data_path, "segments")
    target = [f for f in sorted(os.listdir(seg_dir))
              if f.endswith(".src")][0]
    flip_byte(os.path.join(seg_dir, target))
    nodes[victim].verify_local_stores("docs")

    def promoted():
        for nid in ids:
            nodes["n0"].coordinator.run_checks_once()
        e = nodes["n0"].coordinator.state().routing["docs"][shard]
        return (e["primary"] != victim
                and e["primary_term"] == old_term + 1
                and in_sync_full(nodes))
    assert wait_until(promoted)
    # writes carry the bumped term — fencing observable to clients
    r = nodes["n1"].index_doc("docs", "post-promo", {"body": "x", "n": 1})
    if r["_shard"] == shard:
        assert r["_primary_term"] == old_term + 1
    e = nodes["n0"].coordinator.state().routing["docs"][shard]
    assert e["primary_term"] == old_term + 1


# -- acceptance 2: unhealthy-fsync node evicted, traffic rerouted ------------


def test_unhealthy_fsync_node_evicted_and_rerouted(cluster):
    tmp_path, ids, nodes = cluster
    make_index(nodes, docs=20)
    victim = "n2"
    disk = DiskFaultInjector(seed=5)
    disk.fail_fsync(os.path.join(str(tmp_path / victim),
                                 FsHealthService.PROBE_FILE))
    with disk:
        assert nodes[victim].fs_health.check() is False
        assert nodes[victim]._load_stats()["fs_healthy"] is False

        def evicted():
            nodes["n0"].coordinator.run_checks_once()
            return victim not in nodes["n0"].coordinator.state().nodes
        assert wait_until(evicted)
        assert wait_until(lambda: in_sync_full(nodes))
        # search traffic rerouted with zero client-visible failures
        for nid in ("n0", "n1"):
            resp = nodes[nid].search("docs", {
                "query": {"match_all": {}}, "size": 50})
            assert resp["hits"]["total"]["value"] == 20
            assert resp["_shards"]["failed"] == 0
        # an unhealthy node refuses to stand for election
        assert nodes[victim].start_election() is False
    # heal: probe recovers, node readmits, copies recover
    assert nodes[victim].fs_health.check() is True
    nodes["n0"].coordinator.add_node(victim, {"name": victim})
    assert wait_until(
        lambda: victim in nodes["n0"].coordinator.state().nodes)
    assert wait_until(lambda: in_sync_full(nodes))


def test_unhealthy_leader_abdicates(cluster):
    tmp_path, ids, nodes = cluster
    disk = DiskFaultInjector(seed=6)
    disk.fail_fsync(os.path.join(str(tmp_path / "n0"),
                                 FsHealthService.PROBE_FILE))
    with disk:
        assert nodes["n0"].fs_health.check() is False
        from opensearch_tpu.cluster.coordination import Mode
        nodes["n0"].coordinator.run_checks_once()
        assert nodes["n0"].coordinator.mode == Mode.CANDIDATE
        # while unhealthy it cannot re-stand
        assert nodes["n0"].start_election() is False

        # a healthy follower notices the abdicated leader and wins
        def new_leader():
            for nid in ("n1", "n2"):
                nodes[nid].coordinator.run_checks_once()
            return nodes["n1"].coordinator.state().master_node in ("n1",
                                                                   "n2")
        assert wait_until(new_leader)


# -- recovery re-requests corrupt blobs --------------------------------------


def test_recovery_rerequests_corrupt_blob(cluster):
    from opensearch_tpu.cluster.node import A_START_RECOVERY
    from opensearch_tpu.common.telemetry import metrics

    tmp_path, ids, nodes = cluster
    make_index(nodes)
    routing = nodes["n0"].coordinator.state().routing["docs"]
    victim = shard = None
    for s, e in enumerate(routing):
        if e["replicas"]:
            victim, shard = e["replicas"][0], s
            break
    primary = routing[shard]["primary"]

    # the primary's first recovery response ships one damaged blob
    orig = nodes[primary]._h_start_recovery
    state = {"damaged": 0}

    def corrupting(payload):
        resp = orig(payload)
        if state["damaged"] == 0 and resp.get("blobs"):
            state["damaged"] += 1
            sid = sorted(resp["blobs"])[0]
            blob = dict(resp["blobs"][sid])
            b = bytearray(blob["npz"])
            b[len(b) // 2] ^= 0xFF
            blob["npz"] = bytes(b)
            blobs = dict(resp["blobs"])
            blobs[sid] = blob
            resp = dict(resp)
            resp["blobs"] = blobs
        return resp
    nodes[primary].transport.register_handler(A_START_RECOVERY, corrupting)

    # force a full re-recovery of the victim's copy
    corrupt0 = metrics().counter("recovery.corrupt_blobs").value
    engine = nodes[victim].indices["docs"].engine_for(shard)
    docs_before = engine.doc_count()
    engine.flush()
    seg_dir = os.path.join(engine.data_path, "segments")
    target = [f for f in sorted(os.listdir(seg_dir))
              if f.endswith(".npz")][0]
    flip_byte(os.path.join(seg_dir, target))
    nodes[victim].verify_local_stores("docs")

    def recovered():
        for nid in ids:
            nodes["n0"].coordinator.run_checks_once()
        eng = nodes[victim].indices["docs"].engine_for(shard)
        return in_sync_full(nodes) and eng.doc_count() == docs_before
    assert wait_until(recovered)
    # the corrupt response was counted and re-requested, not installed
    assert metrics().counter("recovery.corrupt_blobs").value > corrupt0
    assert state["damaged"] == 1


# -- snapshot restore verification (satellite) -------------------------------


def test_snapshot_restore_verifies_blob_checksums(tmp_path):
    from opensearch_tpu.indices.service import IndicesService
    from opensearch_tpu.snapshots.service import (SnapshotRestoreError,
                                                  SnapshotsService)

    indices = IndicesService(str(tmp_path / "indices"))
    snaps = SnapshotsService(indices, str(tmp_path),
                             path_repo=[str(tmp_path)])
    svc = indices.create("src", {"mappings": MAPPING})
    for i in range(8):
        svc.index_doc(str(i), {"body": f"event {i}", "n": i})
    svc.refresh()
    snaps.put_repository("backups", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo")}})
    snaps.create_snapshot("backups", "snap1", {})

    # clean restore regenerates commit manifests (verifiable store)
    out = snaps.restore_snapshot("backups", "snap1", {
        "indices": "src", "rename_pattern": "src",
        "rename_replacement": "copy1"})
    assert out["snapshot"]["indices"] == ["copy1"]
    copy1 = indices.get("copy1")
    assert copy1.doc_count() == 8
    for engine in copy1.shards:
        engine.verify_store()
        for sid in engine._persisted_segments:
            assert store.verify_segment(
                os.path.join(engine.data_path, "segments"), sid) is True

    # bit-rot a repository blob: restore must refuse it and NAME it
    blob_dir = str(tmp_path / "repo" / "blobs")
    victim_blob = sorted(
        n for n in os.listdir(blob_dir)
        if os.path.getsize(os.path.join(blob_dir, n)) > 64)[0]
    flip_byte(os.path.join(blob_dir, victim_blob))
    with pytest.raises(SnapshotRestoreError, match=victim_blob):
        snaps.restore_snapshot("backups", "snap1", {
            "indices": "src", "rename_pattern": "src",
            "rename_replacement": "copy2"})
    indices.close()


def test_remote_store_restore_verifies_blobs(tmp_path):
    from opensearch_tpu.index.remote_store import (RemoteStoreError,
                                                   restore_shard,
                                                   upload_shard)
    from opensearch_tpu.snapshots.service import Repository

    repo = Repository("r", "fs", {"location": str(tmp_path / "repo")})
    e = make_engine(tmp_path / "shard0")
    seed_engine(e)
    commit = e.flush()
    upload_shard(repo, "idx", 0, e, commit)
    e.close()
    out_dir = str(tmp_path / "restored")
    restore_shard(repo, "idx", 0, out_dir)
    e2 = InternalEngine(out_dir, DocumentMapper(MAPPING))
    assert e2.doc_count() == 6
    e2.verify_store()                    # manifests regenerated
    e2.close()
    blob_dir = str(tmp_path / "repo" / "blobs")
    victim = sorted(
        n for n in os.listdir(blob_dir)
        if os.path.getsize(os.path.join(blob_dir, n)) > 64)[0]
    flip_byte(os.path.join(blob_dir, victim))
    with pytest.raises(RemoteStoreError, match="failed content"):
        restore_shard(repo, "idx", 0, str(tmp_path / "restored2"))


# -- primary-term plumbing (satellite) ---------------------------------------


def test_cluster_write_response_carries_routing_primary_term(cluster):
    tmp_path, ids, nodes = cluster
    make_index(nodes, docs=4)
    r = nodes["n1"].index_doc("docs", "pt", {"body": "x", "n": 1})
    entry = nodes["n0"].coordinator.state().routing["docs"][r["_shard"]]
    assert r["_primary_term"] == entry["primary_term"]


def test_opresult_and_bulk_carry_primary_term(tmp_path):
    from opensearch_tpu.indices.service import IndexService
    svc = IndexService("idx", str(tmp_path / "idx"), {}, MAPPING)
    r = svc.index_doc("a", {"body": "x", "n": 1})
    assert r.primary_term == 1
    items = svc.bulk([("index", "b", {"body": "y", "n": 2}, {}),
                      ("delete", "a", None, {})])
    assert items[0]["index"]["_primary_term"] == 1
    assert items[1]["delete"]["_primary_term"] == 1
    svc.close()


# -- health surfaces ---------------------------------------------------------


def test_cluster_health_surfaces_corruption(cluster):
    tmp_path, ids, nodes = cluster
    make_index(nodes, docs=6)
    assert nodes["n0"].cluster_health()["status"] == "green"
    assert all(r["health"] == "green"
               for r in nodes["n0"].cat_indices())
    # poison one local copy WITHOUT running failover: health must go red
    routing = nodes["n0"].coordinator.state().routing["docs"]
    victim = shard = None
    for s, e in enumerate(routing):
        if "n0" in ([e["primary"]] + e["replicas"]):
            victim, shard = "n0", s
            break
    engine = nodes[victim].indices["docs"].engine_for(shard)
    engine.flush()
    seg_dir = os.path.join(engine.data_path, "segments")
    sid = sorted(engine._persisted_segments)[0]
    store.write_corruption_marker(seg_dir, sid, "test marker")
    health = nodes[victim].cluster_health()
    assert health["status"] == "red"
    assert health["corrupted_shards"] >= 1
    assert "docs" in health["corruption_markers"]
    assert any(r["health"] == "red" for r in nodes[victim].cat_indices())
    store.clear_corruption_markers(seg_dir)


def test_rest_health_and_cat_surface_corruption(tmp_path):
    from opensearch_tpu.node import Node
    n = Node(str(tmp_path / "node"), port=0).start()
    try:
        svc = n.indices.create("idx", {"mappings": MAPPING})
        svc.index_doc("1", {"body": "x", "n": 1})
        svc.refresh()
        engine = svc.shards[0]
        engine.flush()
        code, h = n.rest.h_cluster_health(_FakeReq())
        assert h["status"] == "green"
        seg_dir = os.path.join(engine.data_path, "segments")
        sid = sorted(engine._persisted_segments)[0]
        store.write_corruption_marker(seg_dir, sid, "test marker")
        code, h = n.rest.h_cluster_health(_FakeReq())
        assert h["status"] == "red" and h["corrupted_shards"] == 1
        code, rows = n.rest.h_cat_indices(_FakeReq())
        assert rows[0]["health"] == "red"
    finally:
        n.stop()


class _FakeReq:
    path_params: dict = {}

    def param(self, name, default=None):
        return default


# -- fault schedule + lint ---------------------------------------------------


def test_fault_schedule_includes_disk_directives():
    from opensearch_tpu.testing.workload import FaultSchedule, SoakConfig
    schedule = FaultSchedule.generate(SoakConfig())
    faults = [d["fault"] for d in schedule]
    assert "corrupt_segment" in faults
    assert "disk_unhealthy" in faults and "disk_heal" in faults
    assert faults.index("disk_unhealthy") < faults.index("disk_heal")
    # schedule is still seed-deterministic with the disk directives
    assert schedule == FaultSchedule.generate(SoakConfig())


def test_durable_writes_lint_repo_clean():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "check_durable_writes.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_durable_writes_lint_flags_and_escapes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def save(p, data):\n"
                   "    with open(p, 'w') as f:\n"
                   "        f.write(data)\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "check_durable_writes.py"),
         str(bad)], capture_output=True, text=True)
    assert out.returncode == 1 and "bad.py:2" in out.stdout

    ok = tmp_path / "ok.py"
    ok.write_text(
        "import os\n"
        "def save(p, data):\n"
        "    tmp = p + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write(data)\n"
        "        f.flush()\n"
        "        os.fsync(f.fileno())\n"
        "    os.replace(tmp, p)\n"
        "def append(p, data):\n"
        "    with open(p, 'ab') as f:  # non-durable-ok\n"
        "        f.write(data)\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "check_durable_writes.py"),
         str(ok)], capture_output=True, text=True)
    assert out.returncode == 0, out.stdout
