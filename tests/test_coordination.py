"""Coordination protocol: elections, two-phase publication, quorum loss,
leader failover — driven deterministically over the in-process transport
(the CoordinatorTests / DisruptableMockTransport technique, SURVEY §4.3)."""

import time

import pytest

from opensearch_tpu.cluster.coordination import (
    Coordinator,
    CoordinationError,
    FailedToCommitError,
    Mode,
)
from opensearch_tpu.cluster.state import ClusterState, allocate_shards
from opensearch_tpu.transport.service import LocalTransport, TransportService


def make_cluster(n=3, check_retries=2):
    hub = LocalTransport.Hub()
    ids = [f"node_{i}" for i in range(n)]
    coords = {}
    applied = {i: [] for i in ids}
    for node_id in ids:
        svc = TransportService(node_id, LocalTransport(hub))
        coords[node_id] = Coordinator(
            node_id, svc, voting_nodes=ids,
            node_info={"name": node_id},
            on_apply=lambda s, nid=node_id: applied[nid].append(s),
            check_retries=check_retries)
    return hub, ids, coords, applied


def teardown(coords):
    for c in coords.values():
        c.stop()
        c.transport.close()


def wait_until(pred, timeout=8.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_election_and_publication():
    hub, ids, coords, applied = make_cluster()
    assert coords["node_0"].start_election() is True
    assert coords["node_0"].mode == Mode.LEADER
    # first publication reached everyone: all followers, same state
    assert wait_until(lambda: all(
        coords[i].state().master_node == "node_0" for i in ids))
    assert coords["node_1"].mode == Mode.FOLLOWER
    assert coords["node_2"].mode == Mode.FOLLOWER
    st = coords["node_0"].state()
    assert set(st.nodes) == set(ids)
    assert all(applied[i] for i in ids)
    teardown(coords)


def test_state_update_propagates():
    hub, ids, coords, applied = make_cluster()
    coords["node_0"].start_election()
    wait_until(lambda: all(coords[i].state().version >= 1 for i in ids))

    def add_index(state):
        indices = dict(state.indices)
        indices["logs"] = {"settings": {"number_of_shards": 4}}
        return allocate_shards(state.with_(indices=indices))
    coords["node_0"].submit_state_update(add_index)
    assert wait_until(lambda: all(
        "logs" in coords[i].state().indices for i in ids))
    routing = coords["node_0"].state().routing["logs"]
    assert len(routing) == 4
    assert {e["primary"] for e in routing} <= set(ids)   # spread over nodes
    teardown(coords)


def test_non_leader_cannot_update():
    hub, ids, coords, applied = make_cluster()
    coords["node_0"].start_election()
    wait_until(lambda: coords["node_1"].mode == Mode.FOLLOWER)
    with pytest.raises(CoordinationError):
        coords["node_1"].submit_state_update(lambda s: s.with_())
    teardown(coords)


def test_publication_fails_without_quorum():
    hub, ids, coords, applied = make_cluster()
    coords["node_0"].start_election()
    wait_until(lambda: all(coords[i].state().version >= 1 for i in ids))
    hub.disconnect("node_1")
    hub.disconnect("node_2")
    with pytest.raises(FailedToCommitError):
        coords["node_0"].submit_state_update(
            lambda s: s.with_(indices={"x": {"settings": {}}}))
    assert coords["node_0"].mode == Mode.CANDIDATE   # stepped down
    teardown(coords)


def test_competing_candidates_one_leader_per_term():
    hub, ids, coords, applied = make_cluster()
    r0 = coords["node_0"].start_election()
    r1 = coords["node_1"].start_election()
    leaders = [i for i in ids if coords[i].mode == Mode.LEADER]
    # at most one leader; and if both claimed, terms differ — settle by
    # running another round from the loser
    assert len(leaders) >= 1
    terms = {coords[i].current_term for i in leaders}
    assert len(terms) == len(leaders)
    teardown(coords)


def test_leader_failover():
    hub, ids, coords, applied = make_cluster(check_retries=2)
    coords["node_0"].start_election()
    wait_until(lambda: all(coords[i].state().master_node == "node_0"
                           for i in ids))
    hub.disconnect("node_0")
    # followers detect the dead leader and elect a new one
    for _ in range(4):
        coords["node_1"].run_checks_once()
        coords["node_2"].run_checks_once()
    assert wait_until(lambda: any(
        coords[i].mode == Mode.LEADER for i in ("node_1", "node_2")), 5.0)
    new_leader = next(i for i in ("node_1", "node_2")
                      if coords[i].mode == Mode.LEADER)
    assert coords[new_leader].state().master_node == new_leader
    assert coords[new_leader].current_term > 1
    teardown(coords)


def test_committed_state_survives_failover():
    hub, ids, coords, applied = make_cluster(check_retries=1)
    coords["node_0"].start_election()
    wait_until(lambda: all(coords[i].state().version >= 1 for i in ids))
    coords["node_0"].submit_state_update(
        lambda s: s.with_(indices={"keepme": {"settings": {}}}))
    assert wait_until(lambda: all(
        "keepme" in coords[i].state().indices for i in ids))
    hub.disconnect("node_0")
    # both followers must detect the dead leader before a pre-vote can
    # be granted (leader-liveness gates grants — election safety)
    for _ in range(3):
        coords["node_2"].run_checks_once()
        coords["node_1"].run_checks_once()
    assert wait_until(lambda: any(
        coords[i].mode == Mode.LEADER for i in ("node_1", "node_2")), 5.0)
    new_leader = next(i for i in ("node_1", "node_2")
                      if coords[i].mode == Mode.LEADER)
    assert "keepme" in coords[new_leader].state().indices
    teardown(coords)


def test_allocate_shards_stability():
    st = ClusterState(nodes={"a": {}, "b": {}},
                      indices={"i": {"settings": {"number_of_shards": 4}}})
    st = allocate_shards(st)
    before = [e["primary"] for e in st.routing["i"]]
    # add a node: existing primary assignments stay put
    st2 = allocate_shards(st.with_(nodes={"a": {}, "b": {}, "c": {}}))
    assert [e["primary"] for e in st2.routing["i"]] == before
    # remove node b: only b's shards move (all land on a)
    st3 = allocate_shards(st.with_(nodes={"a": {}}))
    assert [e["primary"] for e in st3.routing["i"]] == ["a"] * 4
    teardown({})


def test_allocate_shards_replicas_and_promotion():
    st = ClusterState(nodes={"a": {}, "b": {}, "c": {}},
                      indices={"i": {"settings": {"number_of_shards": 2,
                                                  "number_of_replicas": 1}}})
    st = allocate_shards(st)
    for e in st.routing["i"]:
        assert e["primary"] is not None
        assert len(e["replicas"]) == 1
        assert e["replicas"][0] != e["primary"]
        assert e["in_sync"] == [e["primary"]]   # replicas join via recovery
        assert e["primary_term"] == 1
    # mark replicas in-sync (recovery completed)
    routing = {"i": [dict(e, in_sync=[e["primary"]] + e["replicas"])
                     for e in st.routing["i"]]}
    st = st.with_(routing=routing)
    # kill the primary of shard 0: its in-sync replica is promoted with a
    # term bump and a replacement replica is allocated elsewhere
    dead = st.routing["i"][0]["primary"]
    survivor = st.routing["i"][0]["replicas"][0]
    alive = {n: {} for n in ("a", "b", "c") if n != dead}
    st2 = allocate_shards(st.with_(nodes=alive))
    e = st2.routing["i"][0]
    assert e["primary"] == survivor
    assert e["primary_term"] == 2
    assert e["in_sync"][0] == survivor
    assert len(e["replicas"]) == 1 and e["replicas"][0] != survivor
    assert e["replicas"][0] not in e["in_sync"]  # fresh copy must recover
    teardown({})


def test_lag_detector_removes_stuck_follower():
    """A follower that answers checks but never applies published states
    is removed after check_retries rounds (coordination/LagDetector.java
    analog)."""
    hub, ids, coords, applied = make_cluster(check_retries=2)
    try:
        assert coords["node_0"].start_election()
        leader = coords["node_0"]
        # wedge node_2's state application: it still ACKS follower
        # checks but silently drops publishes from now on (the handler
        # table holds the bound method, so patch it there)
        from opensearch_tpu.cluster.coordination import PUBLISH
        stuck = coords["node_2"]
        orig_publish = stuck.transport._handlers[PUBLISH]
        stuck.transport._handlers[PUBLISH] = lambda p: {
            "accepted": False, "term": stuck.current_term}
        leader.submit_state_update(
            lambda s: s.with_(indices={**s.indices,
                                       "i1": {"settings": {},
                                              "mappings": {}}}))
        assert "node_2" in leader.state().nodes
        leader.run_checks_once()       # lag round 1
        leader.run_checks_once()       # lag round 2 -> removed
        assert "node_2" not in leader.state().nodes
        # healthy follower stays
        assert "node_1" in leader.state().nodes
        stuck.transport._handlers[PUBLISH] = orig_publish
    finally:
        teardown(coords)
