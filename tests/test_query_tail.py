"""Query DSL tail: boosting, terms_set, distance_feature, query_string,
function_score, more_like_this, geo queries (VERDICT r3 missing #8; ref
index/query/ 47 builders, SURVEY Appendix A)."""

import numpy as np
import pytest

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher

MAPPING = {"properties": {
    "title": {"type": "text"},
    "body": {"type": "text"},
    "tags": {"type": "keyword"},
    "views": {"type": "long"},
    "score_f": {"type": "double"},
    "required_matches": {"type": "long"},
    "published": {"type": "date"},
    "loc": {"type": "geo_point"},
}}

DOCS = [
    {"title": "red fox", "body": "quick red fox jumps", "tags": ["animal"],
     "views": 100, "score_f": 2.0, "required_matches": 2,
     "published": "2024-01-01T00:00:00Z", "loc": {"lat": 40.7, "lon": -74.0}},
    {"title": "red dog", "body": "lazy red dog sleeps", "tags": ["animal"],
     "views": 50, "score_f": 1.0, "required_matches": 1,
     "published": "2024-06-01T00:00:00Z", "loc": {"lat": 40.8, "lon": -73.9}},
    {"title": "blue bird", "body": "blue bird sings red songs",
     "tags": ["animal", "sky"], "views": 10, "score_f": 4.0,
     "required_matches": 3, "published": "2023-01-01T00:00:00Z",
     "loc": {"lat": 51.5, "lon": -0.1}},
    {"title": "green tree", "body": "tall green tree", "tags": ["plant"],
     "views": 500, "score_f": 0.5, "required_matches": 1,
     "published": "2022-01-01T00:00:00Z", "loc": {"lat": 48.9, "lon": 2.3}},
]


@pytest.fixture(scope="module")
def searcher():
    mapper = DocumentMapper(MAPPING)
    writer = SegmentWriter()
    half = len(DOCS) // 2
    segs = [writer.build([mapper.parse(str(i), d)
                          for i, d in enumerate(DOCS[:half])], "q0"),
            writer.build([mapper.parse(str(half + i), d)
                          for i, d in enumerate(DOCS[half:])], "q1")]
    return ShardSearcher(segs, mapper)


def ids(resp):
    return [h["_id"] for h in resp["hits"]["hits"]]


def scores(resp):
    return {h["_id"]: h["_score"] for h in resp["hits"]["hits"]}


def test_boosting_demotes_negative_matches(searcher):
    plain = scores(searcher.search(
        {"query": {"match": {"body": "red"}}, "size": 10}))
    resp = searcher.search({"query": {"boosting": {
        "positive": {"match": {"body": "red"}},
        "negative": {"term": {"tags": "sky"}},
        "negative_boost": 0.2}}, "size": 10})
    got = scores(resp)
    assert set(got) == set(plain)
    assert got["0"] == pytest.approx(plain["0"], rel=1e-5)
    assert got["2"] == pytest.approx(plain["2"] * 0.2, rel=1e-5)


def test_terms_set_per_doc_minimum(searcher):
    # docs match when >= required_matches of [red, fox, sleeps] hit
    resp = searcher.search({"query": {"terms_set": {"body": {
        "terms": ["red", "fox", "sleeps"],
        "minimum_should_match_field": "required_matches"}}}, "size": 10})
    # doc0: red+fox = 2 >= 2 YES; doc1: red+sleeps = 2 >= 1 YES;
    # doc2: red = 1 >= 3 NO; doc3: 0 matches NO
    assert sorted(ids(resp)) == ["0", "1"]
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"terms_set": {"body": {
            "terms": ["x"], "minimum_should_match_field": "title"}}}})


def test_distance_feature_date_and_geo(searcher):
    resp = searcher.search({"query": {"distance_feature": {
        "field": "published", "origin": "2024-06-01T00:00:00Z",
        "pivot": "30d"}}, "size": 10})
    assert ids(resp)[0] == "1"              # exact origin scores highest
    s = scores(resp)
    assert s["1"] == pytest.approx(1.0, rel=1e-5)
    assert s["1"] > s["0"] > s["2"] > s["3"]
    resp = searcher.search({"query": {"distance_feature": {
        "field": "loc", "origin": {"lat": 40.7, "lon": -74.0},
        "pivot": "100km"}}, "size": 10})
    assert ids(resp)[0] == "0" and scores(resp)["0"] == pytest.approx(1.0)


def test_geo_distance_and_bbox(searcher):
    resp = searcher.search({"query": {"geo_distance": {
        "distance": "50km", "loc": {"lat": 40.7, "lon": -74.0}}},
        "size": 10})
    assert sorted(ids(resp)) == ["0", "1"]   # NYC pair only
    resp = searcher.search({"query": {"geo_bounding_box": {"loc": {
        "top_left": {"lat": 52.0, "lon": -1.0},
        "bottom_right": {"lat": 48.0, "lon": 3.0}}}}, "size": 10})
    assert sorted(ids(resp)) == ["2", "3"]   # London + Paris


def test_query_string_full_syntax(searcher):
    resp = searcher.search({"query": {"query_string": {
        "query": "title:red AND body:fox"}}, "size": 10})
    assert ids(resp) == ["0"]
    resp = searcher.search({"query": {"query_string": {
        "query": "(title:red OR title:blue) -body:sleeps"}}, "size": 10})
    assert sorted(ids(resp)) == ["0", "2"]
    resp = searcher.search({"query": {"query_string": {
        "query": 'body:"red fox"'}}, "size": 10})
    assert ids(resp) == ["0"]
    resp = searcher.search({"query": {"query_string": {
        "query": "views:[50 TO 200]"}}, "size": 10})
    assert sorted(ids(resp)) == ["0", "1"]
    resp = searcher.search({"query": {"query_string": {
        "query": "tit*:red"}}, "size": 10})  # wildcard VALUE on a field
    # field names don't wildcard here; bare wildcard terms do:
    resp = searcher.search({"query": {"query_string": {
        "query": "title:re*"}}, "size": 10})
    assert sorted(ids(resp)) == ["0", "1"]
    resp = searcher.search({"query": {"query_string": {
        "query": "red tree", "fields": ["title", "body"],
        "default_operator": "or"}}, "size": 10})
    assert set(ids(resp)) == {"0", "1", "2", "3"}
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"query_string": {
            "query": "(red AND"}}})


def test_function_score_fvf_and_modes(searcher):
    base = scores(searcher.search(
        {"query": {"match": {"body": "red"}}, "size": 10}))
    resp = searcher.search({"query": {"function_score": {
        "query": {"match": {"body": "red"}},
        "field_value_factor": {"field": "score_f", "factor": 2.0,
                               "modifier": "none"},
        "boost_mode": "multiply"}}, "size": 10})
    got = scores(resp)
    for did in base:
        assert got[did] == pytest.approx(
            base[did] * 2.0 * DOCS[int(did)]["score_f"], rel=1e-4)
    # replace + weight + filter: only docs matching the filter get the
    # function; others keep factor 1
    resp = searcher.search({"query": {"function_score": {
        "query": {"match": {"body": "red"}},
        "functions": [{"filter": {"term": {"tags": "sky"}},
                       "weight": 10.0}],
        "boost_mode": "replace"}}, "size": 10})
    got = scores(resp)
    assert got["2"] == pytest.approx(10.0)
    assert got["0"] == pytest.approx(1.0)


def test_function_score_decay_gauss(searcher):
    resp = searcher.search({"query": {"function_score": {
        "query": {"match_all": {}},
        "gauss": {"views": {"origin": 100, "scale": 100}},
        "boost_mode": "replace"}}, "size": 10})
    got = scores(resp)
    assert got["0"] == pytest.approx(1.0, rel=1e-5)     # at origin
    assert got["3"] == pytest.approx(0.5 ** ((400 / 100) ** 2), rel=1e-3)
    assert got["0"] > got["1"] > got["3"]


def test_function_score_random_is_deterministic(searcher):
    body = {"query": {"function_score": {
        "query": {"match_all": {}},
        "random_score": {"seed": 42}, "boost_mode": "replace"}},
        "size": 10}
    a = scores(searcher.search(body))
    b = scores(searcher.search(body))
    assert a == b
    c = scores(searcher.search({"query": {"function_score": {
        "query": {"match_all": {}},
        "random_score": {"seed": 7}, "boost_mode": "replace"}},
        "size": 10}))
    assert c != a                            # seed changes the ordering
    assert all(0.0 <= v < 1.0 for v in a.values())


def test_more_like_this(searcher):
    resp = searcher.search({"query": {"more_like_this": {
        "fields": ["body"], "like": [{"_id": "0"}],
        "min_term_freq": 1, "min_doc_freq": 1,
        "minimum_should_match": "1"}}, "size": 10})
    assert "0" not in ids(resp)              # liked doc excluded (default)
    assert "1" in ids(resp)                  # shares "red"
    resp = searcher.search({"query": {"more_like_this": {
        "fields": ["body"], "like": "red songs sings",
        "min_term_freq": 1, "min_doc_freq": 1,
        "minimum_should_match": "2"}}, "size": 10})
    assert ids(resp) == ["2"]                # only doc2 has 2+ terms


def test_review_fixes_query_tail(searcher):
    """Round-4 review regressions: default-field expansion, truncation
    errors, MLT self-exclusion, nearest-value distance, field boosts."""
    # bare query_string with no fields searches every text field
    resp = searcher.search({"query": {"query_string": {
        "query": "fox"}}, "size": 10})
    assert ids(resp) == ["0"]
    # unbalanced quote errors instead of silently truncating
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"query_string": {
            "query": 'foo "bar'}}})
    # MLT excludes the liked doc by default; include:true restores it
    resp = searcher.search({"query": {"more_like_this": {
        "fields": ["body"], "like": [{"_id": "0"}],
        "min_term_freq": 1, "min_doc_freq": 1,
        "minimum_should_match": "1"}}, "size": 10})
    assert "0" not in ids(resp) and "1" in ids(resp)
    resp = searcher.search({"query": {"more_like_this": {
        "fields": ["body"], "like": [{"_id": "0"}], "include": True,
        "min_term_freq": 1, "min_doc_freq": 1,
        "minimum_should_match": "1"}}, "size": 10})
    assert "0" in ids(resp)
    # field boost suffix carries
    a = scores(searcher.search({"query": {"query_string": {
        "query": "red", "fields": ["title^3"]}}, "size": 10}))
    b = scores(searcher.search({"query": {"query_string": {
        "query": "red", "fields": ["title"]}}, "size": 10}))
    for did in a:
        assert a[did] == pytest.approx(b[did] * 3, rel=1e-5)
    # weighted avg score_mode
    resp = searcher.search({"query": {"function_score": {
        "query": {"match_all": {}},
        "functions": [{"weight": 3.0}, {"weight": 1.0}],
        "score_mode": "avg", "boost_mode": "replace"}}, "size": 10})
    got = scores(resp)
    # avg of w=3 (value 3) and w=1 (value 1) = (3+1)/(3+1) = 1.0
    assert all(v == pytest.approx(1.0) for v in got.values())


def _tail_searcher():
    from opensearch_tpu.index.segment import SegmentWriter
    from opensearch_tpu.mapping.mapper import DocumentMapper
    from opensearch_tpu.search.executor import ShardSearcher

    mapper = DocumentMapper({"properties": {
        "body": {"type": "text"},
        "loc": {"type": "geo_point"},
        "pagerank": {"type": "rank_feature"},
    }})
    w = SegmentWriter()
    docs = [
        ("1", {"body": "quick brown fox", "loc": {"lat": 1, "lon": 1},
               "pagerank": 8.0}),
        ("2", {"body": "quick brown foam", "loc": {"lat": 5, "lon": 5},
               "pagerank": 2.0}),
        ("3", {"body": "brown quick fox", "loc": {"lat": 9, "lon": 9},
               "pagerank": 0.5}),
        ("4", {"body": "slow green turtle", "loc": {"lat": 2, "lon": 8}}),
    ]
    segs = [w.build([mapper.parse(i, s) for i, s in docs[:2]], "a"),
            w.build([mapper.parse(i, s) for i, s in docs[2:]], "b")]
    return ShardSearcher(segs, mapper)


def _hit_ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


def test_match_phrase_prefix():
    s = _tail_searcher()
    resp = s.search({"query": {"match_phrase_prefix": {"body": "quick brown fo"}}})
    assert _hit_ids(resp) == ["1", "2"]       # fox + foam, ordered phrase
    resp = s.search({"query": {"match_phrase_prefix": {"body": {
        "query": "quick brown fo", "max_expansions": 1}}}})
    assert len(resp["hits"]["hits"]) == 1     # expansion cap
    resp = s.search({"query": {"match_phrase_prefix": {"body": "brown zz"}}})
    assert _hit_ids(resp) == []


def test_match_bool_prefix():
    s = _tail_searcher()
    # terms in ANY order, last token a prefix
    resp = s.search({"query": {"match_bool_prefix": {"body": "fox qui"}}})
    assert _hit_ids(resp) == ["1", "2", "3"]  # OR semantics
    resp = s.search({"query": {"match_bool_prefix": {"body": {
        "query": "fox qui", "operator": "and"}}}})
    assert _hit_ids(resp) == ["1", "3"]


def test_wrapper_query():
    import base64
    import json

    s = _tail_searcher()
    inner = base64.b64encode(json.dumps(
        {"term": {"body": "turtle"}}).encode()).decode()
    resp = s.search({"query": {"wrapper": {"query": inner}}})
    assert _hit_ids(resp) == ["4"]
    from opensearch_tpu.common.errors import ParsingError
    with pytest.raises(ParsingError):
        s.search({"query": {"wrapper": {"query": "!!!notbase64"}}})


def test_geo_polygon():
    s = _tail_searcher()
    # triangle covering (1,1) and (5,5) but not (9,9) or (2,8)
    resp = s.search({"query": {"geo_polygon": {"loc": {"points": [
        {"lat": 0, "lon": 0}, {"lat": 0, "lon": 7},
        {"lat": 7, "lon": 7}, {"lat": 7, "lon": 0}]}}}})
    assert _hit_ids(resp) == ["1", "2"]
    # concave polygon: L-shape that excludes (5,5)
    resp = s.search({"query": {"geo_polygon": {"loc": {"points": [
        {"lat": 0, "lon": 0}, {"lat": 10, "lon": 0},
        {"lat": 10, "lon": 3}, {"lat": 3, "lon": 3},
        {"lat": 3, "lon": 10}, {"lat": 0, "lon": 10}]}}}})
    assert _hit_ids(resp) == ["1", "4"]


def test_rank_feature():
    s = _tail_searcher()
    resp = s.search({"query": {"rank_feature": {"field": "pagerank",
                                                "saturation":
                                                {"pivot": 2.0}}}})
    ids = [h["_id"] for h in resp["hits"]["hits"]]
    assert ids == ["1", "2", "3"]            # by feature desc; doc 4 absent
    assert resp["hits"]["hits"][0]["_score"] == pytest.approx(
        8.0 / (8.0 + 2.0))
    assert resp["hits"]["hits"][1]["_score"] == pytest.approx(0.5)
    # log curve
    resp = s.search({"query": {"rank_feature": {"field": "pagerank",
                                                "log": {"scaling_factor":
                                                        1.0}}}})
    import math
    assert resp["hits"]["hits"][0]["_score"] == pytest.approx(
        math.log(1 + 8.0))
    # positive-only validation at index time
    from opensearch_tpu.common.errors import MapperParsingError
    from opensearch_tpu.mapping.mapper import DocumentMapper
    m = DocumentMapper({"properties": {"f": {"type": "rank_feature"}}})
    with pytest.raises(MapperParsingError):
        m.parse("x", {"f": -1})
