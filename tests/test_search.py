"""Query-phase correctness: device results vs an independent pure-Python
oracle (the AbstractQueryTestCase-style correctness bar from SURVEY §4:
top-k must match scalar BM25 bit-for-bit with Lucene's tie-break)."""

import math

import numpy as np
import pytest

from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.mapping.types import parse_date_millis
from opensearch_tpu.search.executor import ShardSearcher

K1, B = 1.2, 0.75

MAPPING = {
    "properties": {
        "title": {"type": "text"},
        "body": {"type": "text"},
        "tags": {"type": "keyword"},
        "price": {"type": "long"},
        "rating": {"type": "double"},
        "ts": {"type": "date"},
        "active": {"type": "boolean"},
    }
}

VOCAB = ("alpha bravo charlie delta echo foxtrot golf hotel india juliet "
         "kilo lima mike november oscar papa quebec romeo sierra tango").split()
TAGS = ["red", "green", "blue", "yellow", "purple"]


def build_corpus(n_docs=240, n_segments=3, seed=7):
    rng = np.random.default_rng(seed)
    mapper = DocumentMapper(MAPPING)
    writer = SegmentWriter()
    segments = []
    parsed_by_seg = []
    per_seg = n_docs // n_segments
    doc_no = 0
    for si in range(n_segments):
        parsed = []
        for _ in range(per_seg):
            title = " ".join(rng.choice(VOCAB, size=rng.integers(2, 6)))
            body = " ".join(rng.choice(VOCAB, size=rng.integers(5, 30)))
            src = {
                "title": title,
                "body": body,
                "tags": list(rng.choice(TAGS, size=rng.integers(1, 4), replace=False)),
                "price": int(rng.integers(0, 1000)),
                "rating": float(np.round(rng.uniform(0, 5), 2)),
                "ts": f"2023-{rng.integers(1, 13):02d}-{rng.integers(1, 28):02d}",
                "active": bool(rng.integers(0, 2)),
            }
            if rng.uniform() < 0.1:
                del src["price"]          # some docs missing the field
            doc = mapper.parse(str(doc_no), src)
            doc.seq_no = doc_no
            parsed.append(doc)
            doc_no += 1
        segments.append(writer.build(parsed, f"seg_{si}"))
        parsed_by_seg.append(parsed)
    return mapper, segments, parsed_by_seg


@pytest.fixture(scope="module")
def corpus():
    mapper, segments, parsed = build_corpus()
    searcher = ShardSearcher(segments, mapper, index_name="test")
    oracle = Oracle(parsed, mapper)
    return searcher, oracle


# ---------------------------------------------------------------------------
# Oracle: independent scalar implementation of Lucene BM25 + query semantics.
# ---------------------------------------------------------------------------


class Oracle:
    def __init__(self, parsed_by_seg, mapper):
        self.segs = parsed_by_seg
        self.mapper = mapper

    def docs(self):
        for si, seg in enumerate(self.segs):
            for li, doc in enumerate(seg):
                yield (si, li), doc

    def field_stats(self, field):
        doc_count, total_len = 0, 0.0
        ft = self.mapper.field_type(field)
        is_text = ft is not None and ft.type_name == "text"
        for _, doc in self.docs():
            if is_text:
                n = doc.field_lengths.get(field, 0)
                if n > 0:
                    doc_count += 1
                    total_len += n
            else:
                if any(t == field for t in doc.tokens) and doc.tokens.get(field):
                    doc_count += 1
                    total_len += 1.0
        return doc_count, (total_len / doc_count if doc_count else 1.0)

    def df(self, field, term):
        n = 0
        for _, doc in self.docs():
            if any(t == term for t, _ in doc.tokens.get(field, [])):
                n += 1
        return n

    def idf(self, field, term):
        doc_count, _ = self.field_stats(field)
        df = self.df(field, term)
        return math.log(1.0 + (doc_count - df + 0.5) / (df + 0.5))

    def doc_len(self, doc, field):
        ft = self.mapper.field_type(field)
        if ft is not None and ft.type_name == "text":
            return float(doc.field_lengths.get(field, 0))
        return 1.0

    def tf(self, doc, field, term):
        return sum(1 for t, _ in doc.tokens.get(field, []) if t == term)

    def score_bag(self, field, terms, required=1, boost=1.0):
        """OR/AND bag of BM25-scored terms -> {(si,li): score}."""
        _, avgdl = self.field_stats(field)
        idfs = {t: self.idf(field, t) for t in set(terms)}
        out = {}
        for key, doc in self.docs():
            matched = 0
            score = 0.0
            for t in terms:
                tf = self.tf(doc, field, t)
                if tf > 0:
                    matched += 1
                    dl = self.doc_len(doc, field)
                    norm = K1 * (1 - B + B * dl / avgdl)
                    score += boost * idfs[t] * tf / (tf + norm)
            if matched >= required:
                out[key] = score
        return out

    def eval(self, q, scored=True):
        """query json -> {(si,li): score}"""
        name, body = next(iter(q.items()))
        fn = getattr(self, f"_q_{name}")
        return fn(body, scored)

    def _q_match_all(self, body, scored):
        boost = body.get("boost", 1.0)
        return {key: boost for key, _ in self.docs()}

    def _q_term(self, body, scored):
        field, v = next(iter(body.items()))
        boost, value = 1.0, v
        if isinstance(v, dict):
            boost, value = v.get("boost", 1.0), v.get("value")
        ft = self.mapper.field_type(field)
        if ft.dv_kind in ("long", "double") and ft.type_name != "boolean":
            want = ft.term_for_query(value)
            vals_attr = "longs" if ft.dv_kind == "long" else "doubles"
            return {key: boost for key, doc in self.docs()
                    if want in getattr(doc, vals_attr).get(field, [])}
        return self.score_bag(field, [ft.term_for_query(value)], 1, boost)

    def _q_terms(self, body, scored):
        boost = body.get("boost", 1.0)
        field, vals = next(iter((k, v) for k, v in body.items() if k != "boost"))
        ft = self.mapper.field_type(field)
        if ft.dv_kind in ("long", "double") and ft.type_name != "boolean":
            want = {ft.term_for_query(v) for v in vals}
            attr = "longs" if ft.dv_kind == "long" else "doubles"
            return {key: boost for key, doc in self.docs()
                    if want & set(getattr(doc, attr).get(field, []))}
        want = {ft.term_for_query(v) for v in vals}
        out = {}
        for key, doc in self.docs():
            if want & {t for t, _ in doc.tokens.get(field, [])}:
                out[key] = boost
        return out

    def _q_match(self, body, scored):
        field, v = next(iter(body.items()))
        boost, operator, msm = 1.0, "or", None
        if isinstance(v, dict):
            boost = v.get("boost", 1.0)
            operator = v.get("operator", "or").lower()
            msm = v.get("minimum_should_match")
            text = v["query"]
        else:
            text = v
        ft = self.mapper.field_type(field)
        if ft.type_name != "text":
            return self._q_term({field: {"value": text, "boost": boost}}, scored)
        terms = ft.search_terms(text, self.mapper.analyzers)
        if not terms:
            return {}
        if operator == "and":
            required = len(terms)
        elif msm is not None:
            required = max(1, int(msm))
        else:
            required = 1
        return self.score_bag(field, terms, required, boost)

    def _q_match_phrase(self, body, scored):
        field, v = next(iter(body.items()))
        boost = 1.0
        if isinstance(v, dict):
            boost, text = v.get("boost", 1.0), v["query"]
        else:
            text = v
        ft = self.mapper.field_type(field)
        analyzer = self.mapper.analyzers.get(ft.search_analyzer_name)
        toks = analyzer.analyze(str(text))
        if len(toks) == 1:
            return self.score_bag(field, [toks[0].term], 1, boost)
        _, avgdl = self.field_stats(field)
        idf_sum = sum(self.idf(field, t.term) for t in toks)
        out = {}
        for key, doc in self.docs():
            positions = {}
            for t, p in doc.tokens.get(field, []):
                positions.setdefault(t, set()).add(p)
            first = positions.get(toks[0].term, set())
            count = 0
            for p0 in first:
                if all((p0 + t.position - toks[0].position) in positions.get(t.term, set())
                       for t in toks[1:]):
                    count += 1
            if count > 0:
                dl = self.doc_len(doc, field)
                norm = K1 * (1 - B + B * dl / avgdl)
                out[key] = boost * idf_sum * count / (count + norm)
        return out

    def _q_bool(self, body, scored):
        must = [self.eval(q, scored) for q in _aslist(body.get("must", []))]
        should = [self.eval(q, scored) for q in _aslist(body.get("should", []))]
        must_not = [self.eval(q, False) for q in _aslist(body.get("must_not", []))]
        filt = [self.eval(q, False) for q in _aslist(body.get("filter", []))]
        boost = body.get("boost", 1.0)
        msm = body.get("minimum_should_match")
        if msm is not None:
            required = int(msm)
        else:
            required = 0 if (body.get("must") or body.get("filter")) else (
                1 if should else 0)
        out = {}
        for key, _ in self.docs():
            if any(key not in m for m in must):
                continue
            if any(key not in f for f in filt):
                continue
            if any(key in n for n in must_not):
                continue
            s_cnt = sum(1 for s in should if key in s)
            if should and s_cnt < required:
                continue
            score = sum(m[key] for m in must) + sum(s.get(key, 0.0) for s in should)
            out[key] = score * boost
        return out

    def _q_range(self, body, scored):
        field, v = next(iter(body.items()))
        ft = self.mapper.field_type(field)
        boost = v.get("boost", 1.0)
        out = {}
        if ft.dv_kind == "ordinal":
            for key, doc in self.docs():
                for val in doc.ordinals.get(field, []):
                    ok = True
                    if v.get("gte") is not None and not (val >= v["gte"]):
                        ok = False
                    if v.get("gt") is not None and not (val > v["gt"]):
                        ok = False
                    if v.get("lte") is not None and not (val <= v["lte"]):
                        ok = False
                    if v.get("lt") is not None and not (val < v["lt"]):
                        ok = False
                    if ok:
                        out[key] = boost
                        break
            return out
        attr = "longs" if ft.dv_kind == "long" else "doubles"
        bounds = {k: ft.range_bound(v[k]) for k in ("gte", "gt", "lte", "lt")
                  if v.get(k) is not None}
        for key, doc in self.docs():
            for val in getattr(doc, attr).get(field, []):
                ok = True
                if "gte" in bounds and not (val >= bounds["gte"]):
                    ok = False
                if "gt" in bounds and not (val > bounds["gt"]):
                    ok = False
                if "lte" in bounds and not (val <= bounds["lte"]):
                    ok = False
                if "lt" in bounds and not (val < bounds["lt"]):
                    ok = False
                if ok:
                    out[key] = boost
                    break
        return out

    def _q_exists(self, body, scored):
        field = body["field"]
        boost = body.get("boost", 1.0)
        ft = self.mapper.field_type(field)
        out = {}
        for key, doc in self.docs():
            if ft.dv_kind == "long" and doc.longs.get(field):
                out[key] = boost
            elif ft.dv_kind == "double" and doc.doubles.get(field):
                out[key] = boost
            elif ft.dv_kind == "ordinal" and doc.ordinals.get(field):
                out[key] = boost
            elif ft.dv_kind == "none" and doc.field_lengths.get(field, 0) > 0:
                out[key] = boost
        return out

    def _q_ids(self, body, scored):
        wanted = set(map(str, body["values"]))
        return {key: 1.0 for key, doc in self.docs() if doc.doc_id in wanted}

    def _q_prefix(self, body, scored):
        field, v = next(iter(body.items()))
        value = v["value"] if isinstance(v, dict) else v
        boost = v.get("boost", 1.0) if isinstance(v, dict) else 1.0
        out = {}
        for key, doc in self.docs():
            if any(t.startswith(value) for t, _ in doc.tokens.get(field, [])):
                out[key] = boost
        return out

    def _q_wildcard(self, body, scored):
        import fnmatch
        field, v = next(iter(body.items()))
        value = v["value"] if isinstance(v, dict) else v
        out = {}
        for key, doc in self.docs():
            if any(fnmatch.fnmatchcase(t, value)
                   for t, _ in doc.tokens.get(field, [])):
                out[key] = 1.0
        return out

    def _q_constant_score(self, body, scored):
        inner = self.eval(body["filter"], False)
        boost = body.get("boost", 1.0)
        return {k: boost for k in inner}

    def _q_dis_max(self, body, scored):
        subs = [self.eval(q, scored) for q in body["queries"]]
        tie = body.get("tie_breaker", 0.0)
        out = {}
        keys = set().union(*[set(s) for s in subs]) if subs else set()
        for key in keys:
            vals = [s.get(key, 0.0) for s in subs]
            best = max(vals)
            out[key] = best + tie * (sum(vals) - best)
        return out


def _aslist(x):
    return x if isinstance(x, list) else [x]


def check(searcher, oracle, query, size=30, places=4):
    """Device top-k must equal oracle top-k: ids in order + scores."""
    resp = searcher.search({"query": query, "size": size})
    expected = oracle.eval(query)
    exp_rows = sorted(expected.items(), key=lambda kv: (-kv[1], kv[0]))[:size]
    got = resp["hits"]["hits"]
    assert resp["hits"]["total"]["value"] == len(expected), query
    assert len(got) == min(size, len(exp_rows))
    for hit, ((si, li), score) in zip(got, exp_rows):
        exp_id = oracle.segs[si][li].doc_id
        assert hit["_id"] == exp_id, (
            f"id mismatch for {query}: got {hit['_id']} want {exp_id} "
            f"(scores {hit['_score']} vs {score})")
        assert hit["_score"] == pytest.approx(score, rel=10**-places), query
    return resp


QUERIES = [
    {"match_all": {}},
    {"match_all": {"boost": 2.5}},
    {"term": {"tags": "red"}},
    {"term": {"tags": {"value": "blue", "boost": 3.0}}},
    {"term": {"price": 500}},
    {"term": {"active": True}},
    {"terms": {"tags": ["red", "green"]}},
    {"terms": {"price": [1, 2, 3, 500]}},
    {"match": {"title": "alpha bravo"}},
    {"match": {"title": {"query": "alpha bravo charlie", "operator": "and"}}},
    {"match": {"body": {"query": "echo foxtrot golf hotel",
                        "minimum_should_match": 3}}},
    {"match": {"title": {"query": "delta", "boost": 0.5}}},
    {"match_phrase": {"body": "alpha bravo"}},
    {"match_phrase": {"title": "charlie delta echo"}},
    {"range": {"price": {"gte": 200, "lt": 700}}},
    {"range": {"rating": {"gt": 1.5, "lte": 4.0}}},
    {"range": {"ts": {"gte": "2023-04-01", "lt": "2023-09-01"}}},
    {"range": {"tags": {"gte": "green", "lte": "red"}}},
    {"exists": {"field": "price"}},
    {"exists": {"field": "title"}},
    {"prefix": {"tags": {"value": "g"}}},
    {"wildcard": {"tags": {"value": "*e*"}}},
    {"constant_score": {"filter": {"term": {"tags": "red"}}, "boost": 4.0}},
    {"dis_max": {"queries": [{"match": {"title": "alpha"}},
                             {"match": {"body": "alpha"}}],
                 "tie_breaker": 0.3}},
    {"bool": {"must": [{"match": {"title": "alpha"}}],
              "filter": [{"range": {"price": {"gte": 100}}}]}},
    {"bool": {"should": [{"match": {"title": "bravo"}},
                         {"match": {"body": "charlie"}}]}},
    {"bool": {"must": [{"match": {"body": "delta"}}],
              "must_not": [{"term": {"tags": "red"}}]}},
    {"bool": {"should": [{"term": {"tags": "red"}},
                         {"term": {"tags": "green"}},
                         {"term": {"tags": "blue"}}],
              "minimum_should_match": 2}},
]


@pytest.mark.parametrize("query", QUERIES, ids=[str(q)[:60] for q in QUERIES])
def test_query_vs_oracle(corpus, query):
    searcher, oracle = corpus
    check(searcher, oracle, query)


def test_ids_query(corpus):
    searcher, oracle = corpus
    resp = searcher.search({"query": {"ids": {"values": ["3", "77", "150"]}},
                            "size": 10})
    assert sorted(h["_id"] for h in resp["hits"]["hits"]) == ["150", "3", "77"]


def test_pagination(corpus):
    searcher, oracle = corpus
    q = {"match": {"body": "alpha"}}
    full = searcher.search({"query": q, "size": 20})["hits"]["hits"]
    page = searcher.search({"query": q, "size": 5, "from": 5})["hits"]["hits"]
    assert [h["_id"] for h in page] == [h["_id"] for h in full[5:10]]


def test_sort_by_field(corpus):
    searcher, oracle = corpus
    resp = searcher.search({
        "query": {"match_all": {}},
        "sort": [{"price": {"order": "asc"}}, {"ts": {"order": "desc"}}],
        "size": 25,
    })
    hits = resp["hits"]["hits"]
    expected = []
    for (si, li), doc in oracle.docs():
        price = doc.longs.get("price", [None])
        ts = doc.longs.get("ts", [None])
        expected.append((price[0] if price[0] is not None else float("inf"),
                         -(ts[0] or 0), si, li, doc.doc_id))
    expected.sort()
    assert [h["_id"] for h in hits] == [e[4] for e in expected[:25]]
    assert hits[0]["sort"][0] == expected[0][0]
    assert hits[0]["_score"] is None


def test_sort_by_keyword(corpus):
    searcher, oracle = corpus
    resp = searcher.search({
        "query": {"match_all": {}},
        "sort": [{"tags": {"order": "asc"}}],
        "size": 10,
    })
    firsts = [h["sort"][0] for h in resp["hits"]["hits"]]
    assert firsts == sorted(firsts)


def test_source_filtering(corpus):
    searcher, _ = corpus
    resp = searcher.search({"query": {"match_all": {}}, "size": 1,
                            "_source": ["title", "price"]})
    src = resp["hits"]["hits"][0].get("_source", {})
    assert set(src) <= {"title", "price"}
    resp = searcher.search({"query": {"match_all": {}}, "size": 1,
                            "_source": False})
    assert "_source" not in resp["hits"]["hits"][0]


def test_count(corpus):
    searcher, oracle = corpus
    q = {"term": {"tags": "red"}}
    assert searcher.count(q) == len(oracle.eval(q))


def test_min_score_restricts_total(corpus):
    searcher, oracle = corpus
    q = {"match": {"body": "alpha"}}
    scores = sorted(oracle.eval(q).values(), reverse=True)
    # place the cutoff strictly BELOW an attained value so float32
    # engine scores (the oracle is float64) can never straddle it: a
    # cutoff landing exactly on a tied score would make the expected
    # count depend on last-ulp rounding, not on min_score semantics
    cutoff = scores[len(scores) // 2] * (1.0 - 1e-6)
    resp = searcher.search({"query": q, "size": 3, "min_score": cutoff})
    expected_total = sum(1 for s in scores if s >= cutoff)
    assert resp["hits"]["total"]["value"] == expected_total
    assert all(h["_score"] >= cutoff for h in resp["hits"]["hits"])


def test_exists_matches_zero_token_text():
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    writer = SegmentWriter()
    docs = [mapper.parse("0", {"body": ""}),        # present, zero tokens
            mapper.parse("1", {"body": "hello"}),
            mapper.parse("2", {}),                   # absent
            mapper.parse("3", {"body": None})]       # null -> absent
    seg = writer.build(docs, "s0")
    searcher = ShardSearcher([seg], mapper)
    resp = searcher.search({"query": {"exists": {"field": "body"}}, "size": 10})
    assert sorted(h["_id"] for h in resp["hits"]["hits"]) == ["0", "1"]


def test_deletes_respected(corpus):
    mapper, segments, parsed = build_corpus(n_docs=60, n_segments=2, seed=11)
    victim = segments[0].doc_ids[5]
    segments[0].delete_local(5)
    searcher = ShardSearcher(segments, mapper)
    resp = searcher.search({"query": {"match_all": {}}, "size": 100})
    ids = {h["_id"] for h in resp["hits"]["hits"]}
    assert victim not in ids
    assert resp["hits"]["total"]["value"] == 59


# ---------------------------------------------------------------------------
# Batched multi-search (_msearch analog; search/batch.py)
# ---------------------------------------------------------------------------


def _msearch_searcher(docs):
    mapper = DocumentMapper({"properties": {"title": {"type": "text"},
                                            "n": {"type": "long"}}})
    writer = SegmentWriter()
    half = len(docs) // 2
    segments = []
    for si, chunk in enumerate((docs[:half], docs[half:])):
        parsed = [mapper.parse(str(si * half + i), d)
                  for i, d in enumerate(chunk)]
        segments.append(writer.build(parsed, f"ms{si}"))
    return ShardSearcher(segments, mapper)


def test_msearch_matches_sequential_search():
    """Every batched response must be byte-identical (minus took) to the
    sequential search for the same body — same kernels, same tie-breaks."""
    searcher = _msearch_searcher([
        {"title": "red fox jumps", "n": 1},
        {"title": "red dog", "n": 2},
        {"title": "blue fox", "n": 3},
        {"title": "red red red", "n": 4},
        {"title": "unrelated words here", "n": 5},
    ] * 3)
    bodies = [
        {"query": {"match": {"title": "red fox"}}, "size": 5},
        {"query": {"match": {"title": "blue"}}, "size": 5},
        {"query": {"term": {"title": "dog"}}, "size": 3},
        {"query": {"match": {"title": {"query": "red fox",
                                       "operator": "and"}}}, "size": 4},
        # non-batchable shapes exercise the fallback path in the same call
        {"query": {"range": {"n": {"gte": 3}}}, "size": 10},
        {"query": {"match": {"title": "red"}}, "size": 2,
         "sort": [{"n": "desc"}]},
    ]
    batched = searcher.msearch(bodies)
    for body, got in zip(bodies, batched):
        want = searcher.search(body)
        got = {k: v for k, v in got.items() if k != "took"}
        want = {k: v for k, v in want.items() if k != "took"}
        assert got == want, body


def test_msearch_segment_missing_field_keeps_seg_indices():
    """A segment with no postings for the queried field is skipped by the
    batch kernel — hits must still resolve against the ORIGINAL segment
    list (round-4 review finding: filtered per-seg list shifted ids)."""
    mapper = DocumentMapper({"properties": {"title": {"type": "text"},
                                            "other": {"type": "text"}}})
    writer = SegmentWriter()
    seg0 = writer.build([mapper.parse("a", {"other": "nothing here"})], "f0")
    seg1 = writer.build([mapper.parse("b", {"title": "target words"})], "f1")
    searcher = ShardSearcher([seg0, seg1], mapper)
    got = searcher.msearch([{"query": {"match": {"title": "target"}},
                             "size": 5}])[0]
    assert [h["_id"] for h in got["hits"]["hits"]] == ["b"]
    assert got["hits"]["hits"][0]["_source"] == {"title": "target words"}
