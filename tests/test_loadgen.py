"""Open-loop load harness tests (testing/loadgen.py).

Three contracts under test, mirroring the soak determinism pins:

- seeded determinism: identical seed => identical arrival schedule,
  identical per-pack request sequence, identical verdict key set;
- coordinated-omission-free measurement: latency is charged from the
  SCHEDULED arrival, so a stalled server inflates the tail by the
  queue time it caused (a closed-loop recorder would hide it);
- real-edge behavior: the packs run clean against a booted node at low
  offered load (zero 5xx), tenant attribution cross-checks hold, and
  every 429 under a squeezed admission limit carries a Retry-After
  hint the client surfaces.

Plus the two tier-1 lints this PR adds/extends:
``tools/check_open_loop.py`` (closed-loop measurement patterns) and
``tools/check_seeded_rng.py`` coverage of the loadgen module.
"""

import subprocess
import sys
import threading
import time

import pytest

from opensearch_tpu.testing.loadgen import (
    ENVELOPES,
    LoadgenRunner,
    arrival_schedule,
    default_packs,
    run_latency_under_load,
)

REPO = __file__.rsplit("/tests/", 1)[0]
TOOLS = REPO + "/tools"


def _ok_executor(op, tenant):
    return {"status": 200}


# -- arrival processes ------------------------------------------------------

def test_arrival_schedule_deterministic_sorted_bounded():
    for env in sorted(ENVELOPES):
        s1 = arrival_schedule(80, 2.0, seed=7, envelope=env)
        s2 = arrival_schedule(80, 2.0, seed=7, envelope=env)
        assert s1 == s2, env
        assert s1 == sorted(s1)
        assert all(0.0 <= t < 2.0 for t in s1)
        # thinning is normalized by the envelope mean: the realized
        # count stays near rate*duration for EVERY envelope shape
        assert 80 <= len(s1) <= 240, (env, len(s1))
    assert arrival_schedule(80, 2.0, seed=7) != \
        arrival_schedule(80, 2.0, seed=8)
    assert arrival_schedule(0, 2.0, seed=7) == []


def test_arrival_schedule_unknown_envelope_rejected():
    with pytest.raises(ValueError, match="unknown arrival envelope"):
        arrival_schedule(10, 1.0, seed=7, envelope="lunar")


# -- determinism pins (soak-style: two runs, same seed) ---------------------

def test_pack_request_sequences_deterministic():
    for pack in default_packs(n_docs=50, vocab_size=100):
        r1 = pack.requests(42, 8)
        r2 = pack.requests(42, 8)
        assert r1 == r2, pack.name
        assert len(r1) == 8
        assert pack.requests(42, 8) != pack.requests(43, 8), pack.name


def test_two_run_determinism():
    packs = default_packs(n_docs=50, vocab_size=100)
    run1 = LoadgenRunner(packs, _ok_executor, seed=42, duration_s=0.3)
    run2 = LoadgenRunner(packs, _ok_executor, seed=42, duration_s=0.3)
    for qps in (20, 60):
        assert run1.schedule(qps) == run2.schedule(qps)
    assert run1.schedule(20) != LoadgenRunner(
        packs, _ok_executor, seed=43, duration_s=0.3).schedule(20)
    # verdict KEYS are a pure function of the pack set — identical
    # across runs whether or not any 429/5xx occurred
    s1 = run1.sweep([20, 60])
    s2 = run2.sweep([20, 60])
    k1 = [v["slo"] for v in run1.verdicts(s1)]
    k2 = [v["slo"] for v in run2.verdicts(s2)]
    assert k1 == k2
    assert "server_errors_at_lowest_load" in k1
    for p in packs:
        assert f"retry_after_hint.{p.name}" in k1
        assert f"transport_errors.{p.name}" in k1
    # and the per-pack sent counts equal the schedules exactly
    for r1, r2 in zip(s1["points"], s2["points"]):
        assert {n: pr["sent"] for n, pr in r1["packs"].items()} == \
            {n: pr["sent"] for n, pr in r2["packs"].items()}


# -- coordinated-omission-free recording ------------------------------------

def test_latency_charged_from_scheduled_arrival():
    """A single-threaded stalled server: each request holds a lock for
    30ms.  Open-loop accounting must charge waiting requests their full
    queue delay — the tail reflects the backlog (hundreds of ms), not
    the 30ms service time a closed-loop recorder would report."""
    lock = threading.Lock()

    def stalled(op, tenant):
        with lock:
            time.sleep(0.03)
        return {"status": 200}

    packs = default_packs(n_docs=50, vocab_size=100)
    runner = LoadgenRunner(packs, stalled, seed=42, duration_s=0.5)
    point = runner.run_point(100)
    sent = sum(pr["sent"] for pr in point["packs"].values())
    assert sent >= 30
    worst_p99 = max(pr["p99_ms"] for pr in point["packs"].values()
                    if pr["sent"])
    # ~50 requests x 30ms serialized service => the last arrivals wait
    # most of a second; anything near 30ms means the recorder went
    # closed-loop
    assert worst_p99 > 300, worst_p99


def test_retry_honors_hint_and_counts_compliance():
    """429s are retried no earlier than the Retry-After hint (plus
    seeded jitter), and hint presence/absence is tallied per pack."""
    calls = []
    times = []
    lock = threading.Lock()

    def flaky(op, tenant):
        with lock:
            calls.append(op)
            times.append(time.monotonic())
            if len(calls) == 1:
                return {"status": 429, "retry_after": 0.2}
            if len(calls) == 2:
                return {"status": 200}
            return {"status": 429}          # hintless terminal 429

    packs = default_packs(n_docs=50, vocab_size=100)[:1]
    runner = LoadgenRunner(packs, flaky, seed=42, duration_s=0.05,
                           retry_limit=1, retry_jitter_s=0.0)
    # duration 0.05s at 40 qps -> at least 1 request; cap workers so
    # the call order above is meaningful only for the first request
    runner.max_workers = 1
    point = runner.run_point(40)
    pr = point["packs"][packs[0].name]
    assert pr["retries_429"] >= 1
    assert pr["retry_after_present"] >= 1
    # the retry of call #1 respected the 0.2s hint
    assert times[1] - times[0] >= 0.2
    if len(calls) > 2:                      # later requests hit hintless 429s
        assert pr["retry_after_missing"] >= 1


# -- real REST edge ---------------------------------------------------------

def test_real_edge_low_load_and_attribution(tmp_path):
    """One low offered-load point against a booted node: zero 5xx, all
    five tenant packs served, verdicts (including the admission- and
    insights-attribution cross-checks) all green."""
    rep = run_latency_under_load(
        str(tmp_path), seed=42, points=(10.0,), duration_s=1.0,
        n_docs=60, vocab_size=200, retry_wait_cap_s=0.5)
    assert rep["slo_ok"], [v for v in rep["verdicts"] if not v["ok"]]
    point = rep["points"][0]
    assert sum(pr["server_error"] for pr in point["packs"].values()) == 0
    assert sum(pr["ok"] for pr in point["packs"].values()) > 0
    slos = [v["slo"] for v in rep["verdicts"]]
    for tenant in ("lg-lexical", "lg-rag", "lg-analytics", "lg-paging",
                   "lg-ingest"):
        assert f"attribution.{tenant}" in slos
    assert set(rep["packs"]) == {
        "zipf_lexical", "rag_hybrid", "analytics_aggs", "paging_walk",
        "bulk_ingest"}


def test_real_edge_429_all_carry_retry_after(tmp_path):
    """Squeeze admission to one concurrent search: the swarm must see
    429s, and EVERY one must carry a Retry-After hint the client
    exposes (TransportError.retry_after) — a hintless 429 anywhere in
    the edge fails the per-pack compliance verdict."""
    rep = run_latency_under_load(
        str(tmp_path), seed=42, points=(40.0,), duration_s=1.5,
        n_docs=60, vocab_size=200, admission_max_concurrent=1,
        retry_limit=1, retry_wait_cap_s=0.2)
    point = rep["points"][0]
    total_429 = sum(pr["retry_after_present"] + pr["retry_after_missing"]
                    for pr in point["packs"].values())
    assert total_429 > 0, "squeezed admission produced no 429s"
    missing = sum(pr["retry_after_missing"]
                  for pr in point["packs"].values())
    assert missing == 0
    for v in rep["verdicts"]:
        if v["slo"].startswith("retry_after_hint."):
            assert v["ok"], v


def test_client_surfaces_retry_after_header(tmp_path):
    """The bundled client parses Retry-After off 429 error responses
    (satellite: the hint used to be discarded with the rest of the
    error headers)."""
    from opensearch_tpu.client import OpenSearch, TransportError
    from opensearch_tpu.node import Node

    node = Node(str(tmp_path), port=0).start()
    try:
        cli = OpenSearch([f"http://127.0.0.1:{node.port}"],
                         headers={"X-Opaque-Id": "ra-probe"})
        cli.indices.create("ra", {"settings": {
            "number_of_shards": 1, "number_of_replicas": 0}})
        cli.bulk([{"index": {"_id": "1"}}, {"body": "t1 t2"}],
                 index="ra")
        cli.indices.refresh("ra")
        cli.cluster.put_settings({"transient": {
            "search_backpressure.max_concurrent_searches": 1}})
        body = {"query": {"match": {"body": "t1"}}}
        saw = None
        barrier = threading.Barrier(8)

        def swarm():
            nonlocal saw
            barrier.wait()
            for _ in range(6):
                try:
                    cli.search(index="ra", body=body)
                except TransportError as e:
                    if e.status_code == 429:
                        saw = e
                        return

        threads = [threading.Thread(target=swarm) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert saw is not None, "no 429 under max_concurrent=1 swarm"
        assert saw.retry_after is not None and saw.retry_after >= 1.0
        assert "Retry-After" in saw.headers
    finally:
        node.stop()


# -- shared corpus shape ----------------------------------------------------

def test_make_doc_delegates_to_shared_corpus_doc():
    """The soak's make_doc and the module-level corpus_doc must stay
    byte-identical for the same seed — the loadgen corpus rides on the
    soak's determinism contract."""
    from opensearch_tpu.testing.workload import (
        MixedWorkload, SoakConfig, corpus_doc)

    wl = MixedWorkload(SoakConfig(seed=7))
    for i in (0, 3, 11):
        assert wl.make_doc(i) == corpus_doc(
            7, i, wl.config.vocab_size, wl.tags)


# -- bench multi-segment geometry -------------------------------------------

def test_bench_make_segments_covers_corpus_and_prunes():
    import importlib.util

    import numpy as np

    spec = importlib.util.spec_from_file_location(
        "bench", REPO + "/bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    from opensearch_tpu.common.telemetry import metrics
    from opensearch_tpu.mapping.mapper import DocumentMapper
    from opensearch_tpu.search.executor import ShardSearcher

    raw = bench.build_raw_corpus(2_000)
    segs = bench.make_segments(raw, 8)
    assert len(segs) == 8
    assert sum(s.n_docs for s in segs) == 2_000
    # the split preserves every posting: per-term df sums back to the
    # monolith's df
    df_sum = np.zeros_like(raw["df"])
    for s in segs:
        df_sum += s.postings["body"].df
    assert (df_sum == raw["df"]).all()

    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    searcher = ShardSearcher(segs, mapper, index_name="bench")
    # zipf head term lives everywhere; hit totals must match monolith
    mono = ShardSearcher([bench.make_segment(raw)], mapper,
                         index_name="bench_mono")
    q = {"query": {"match": {"body": "t0 t5"}}, "size": 10}
    assert searcher.search(dict(q))["hits"]["total"]["value"] == \
        mono.search(dict(q))["hits"]["total"]["value"]
    # a tail term present in few segments exercises can-match pruning —
    # the counter the single-monolith bench pinned to 0
    df = raw["df"]
    rare = int(np.argmax(df == 1)) if (df == 1).any() else int(
        np.argmin(np.where(df > 0, df, df.max() + 1)))
    before = metrics().counter("search.segments_pruned").value
    searcher.search({"query": {"match": {"body": f"t{rare}"}},
                     "size": 10})
    assert metrics().counter("search.segments_pruned").value > before


# -- bench phase wiring -----------------------------------------------------

def test_bench_latency_under_load_phase(tmp_path, monkeypatch):
    """The latency_under_load phase emits one line per (pack, offered
    point) with the full percentile set, plus a summary line carrying
    per-pack max_sustainable_qps — the ISSUE's acceptance surface."""
    import importlib.util
    import json

    phases = tmp_path / "phases.jsonl"
    monkeypatch.setenv("OSTPU_BENCH_PHASES", str(phases))
    monkeypatch.setenv("OSTPU_BENCH_LOAD_QPS", "6,12,24")
    monkeypatch.setenv("OSTPU_BENCH_LOAD_DURATION", "0.6")
    monkeypatch.setenv("OSTPU_BENCH_LOAD_DOCS", "60")
    spec = importlib.util.spec_from_file_location(
        "bench", REPO + "/bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench.run_latency_under_load_phase("cpu")
    lines = [json.loads(ln) for ln in phases.read_text().splitlines()]
    points = [ln for ln in lines if ln["phase"] == "latency_under_load"]
    # >= 3 offered-load points for each of the 5 packs
    per_pack: dict = {}
    for ln in points:
        per_pack.setdefault(ln["pack"], []).append(ln)
        for k in ("offered_qps", "sent", "p50_ms", "p99_ms", "p999_ms",
                  "ok", "rejected", "server_error", "achieved_qps"):
            assert k in ln, (k, ln)
    assert len(per_pack) == 5
    assert all(len(v) >= 3 for v in per_pack.values())
    summary = [ln for ln in lines
               if ln["phase"] == "latency_under_load_summary"]
    assert len(summary) == 1
    assert set(summary[0]["max_sustainable_qps"]) == set(per_pack)


# -- tier-1 lints -----------------------------------------------------------

def test_check_open_loop_repo_clean():
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_open_loop.py"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_check_open_loop_catches_violations(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n"
        "def measure(send, reqs):\n"
        "    lat = []\n"
        "    for r in reqs:\n"
        "        t0 = time.monotonic()\n"
        "        send(r)\n"
        "        lat.append(time.monotonic() - t0)\n"          # line 7
        "    return lat\n"
        "def service_time(send, reqs):\n"
        "    lat = []\n"
        "    for r in reqs:\n"
        "        t0 = time.monotonic()\n"
        "        send(r)\n"
        "        # closed-loop-ok\n"
        "        lat.append(time.monotonic() - t0)\n"          # annotated
        "    return lat\n"
        "def stamp():\n"
        "    return time.time()\n")                            # line 18
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_open_loop.py", str(bad)],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert "bad.py:7:" in out.stdout
    assert "bad.py:18:" in out.stdout
    assert "bad.py:15:" not in out.stdout
    # scheduled-arrival subtraction (the open-loop pattern) is fine:
    # the start isn't a clock read taken inside the loop
    good = tmp_path / "good.py"
    good.write_text(
        "import time\n"
        "def run(schedule, send):\n"
        "    base = time.monotonic()\n"
        "    lat = []\n"
        "    for t, r in schedule:\n"
        "        send(r)\n"
        "        lat.append(time.monotonic() - (base + t))\n"
        "    return lat\n")
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_open_loop.py", str(good)],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout


def test_check_seeded_rng_covers_loadgen():
    loadgen = (REPO
               + "/opensearch_tpu/testing/loadgen.py")
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_seeded_rng.py", loadgen],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
