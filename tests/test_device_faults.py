"""Accelerator fault tolerance (PR 15).

Covers the per-kernel-class circuit breakers
(``common/device_health.py``), the seeded ``DeviceFaultInjector``
(``testing/fault_injection.py``), byte-identity of every degraded path
(tripped-breaker host scores == healthy device scores; poison-recompute
== clean run), restage-failure eviction, partial-results degradation of
non-fallbackable plans, mesh demotion to the counted host scatter, the
QoS controller's device-duress adaptation, the ``device_oom`` /
``device_poison`` / ``device_slow`` / ``device_mesh_loss`` /
``device_heal`` soak directives with their SLOs and two-run
determinism, the ``_nodes/stats`` ``device.health`` / ``/_metrics``
surfaces, the bench ``device_faults`` phase, and the
``tools/check_degraded_paths.py`` tier-1 lint.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from opensearch_tpu.common.device_health import (DeviceDegradedError,
                                                 DeviceHealthService,
                                                 check_finite,
                                                 device_health,
                                                 is_device_error)
from opensearch_tpu.common.device_ledger import device_ledger
from opensearch_tpu.common.telemetry import flight_recorder, metrics
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.ops import bm25 as bm25_ops
from opensearch_tpu.search.executor import ShardSearcher
from opensearch_tpu.testing.fault_injection import (DeviceFaultInjector,
                                                    InjectedDeviceError,
                                                    InjectedDispatchError,
                                                    InjectedOOMError)

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _clean_device_state():
    """Health service, ledger, and host-scoring override are
    process-global: reset them around every test."""
    device_health().reset()
    device_ledger().reset()
    prev = bm25_ops.HOST_SCORING
    yield
    bm25_ops.HOST_SCORING = prev
    device_health().reset()
    device_ledger().reset()


MAPPING = {"properties": {"t": {"type": "text"},
                          "k": {"type": "keyword"},
                          "n": {"type": "long"}}}


def _searcher(n_segs=3):
    mapper = DocumentMapper(MAPPING)
    texts = [["alpha beta", "beta gamma", "alpha alpha gamma"],
             ["beta beta delta", "alpha gamma", "gamma delta"],
             ["alpha delta", "beta", "alpha beta gamma delta"]]
    segs = []
    for i in range(n_segs):
        parsed = [mapper.parse(str(i * 3 + j),
                               {"t": t, "k": f"g{j % 2}", "n": i * 3 + j})
                  for j, t in enumerate(texts[i % len(texts)])]
        segs.append(SegmentWriter().build(parsed, f"s{i}"))
    return ShardSearcher(segs, mapper, index_name="faultix")


BODY = {"query": {"match": {"t": "alpha gamma"}}, "size": 5}


# -- classifier + sanity guard ---------------------------------------------

def test_is_device_error_classifier():
    assert is_device_error(InjectedOOMError("RESOURCE_EXHAUSTED"))
    assert is_device_error(InjectedDispatchError("boom"))
    assert is_device_error(MemoryError("alloc"))
    assert not is_device_error(ValueError("query"))
    assert not is_device_error(KeyError("x"))
    from opensearch_tpu.common.breakers import CircuitBreakingError
    assert not is_device_error(CircuitBreakingError("breaker tripped"))


def test_check_finite_accepts_neginf_sentinel():
    assert check_finite(np.array([1.0, -np.inf, 0.0], np.float32)) == 0
    assert check_finite(np.array([1.0, np.nan], np.float32)) == 1
    assert check_finite(np.array([np.inf, np.nan], np.float32)) == 2
    assert check_finite(np.array([1, 2, 3], np.int32)) == 0


# -- the breaker state machine ---------------------------------------------

def test_breaker_state_machine_trip_probe_close():
    clock = FakeClock()
    dh = DeviceHealthService(clock=clock)
    dh.set_failure_threshold(2)
    dh.set_open_interval_s(5.0)
    assert dh.allow("dispatch")
    dh.record_failure("dispatch", InjectedDispatchError("a"))
    assert dh.allow("dispatch")          # one failure: still closed
    dh.record_failure("dispatch", InjectedDispatchError("b"))
    st = dh.stats()["breakers"]["dispatch"]
    assert st["state"] == "open" and st["trips"] == 1
    assert not dh.allow("dispatch")      # open, inside cooldown
    clock.advance(4.0)
    assert not dh.allow("dispatch")
    clock.advance(1.5)
    assert dh.allow("dispatch")          # cooldown elapsed: half-open
    assert dh.stats()["breakers"]["dispatch"]["state"] == "half_open"
    # failed probe re-opens WITHOUT a new trip
    dh.record_failure("dispatch", InjectedDispatchError("c"))
    st = dh.stats()["breakers"]["dispatch"]
    assert st["state"] == "open" and st["trips"] == 1
    clock.advance(5.5)
    assert dh.allow("dispatch")
    dh.record_success("dispatch")        # successful probe closes
    st = dh.stats()["breakers"]["dispatch"]
    assert st["state"] == "closed" and st["closes"] == 1
    assert dh.breaker_states()["dispatch"] == "closed"
    assert dh.tripped_kinds() == ["dispatch"]


def test_breaker_success_resets_streak_and_disabled_never_trips():
    dh = DeviceHealthService(clock=FakeClock())
    dh.set_failure_threshold(2)
    dh.record_failure("batch", InjectedDispatchError("a"))
    dh.record_success("batch")
    dh.record_failure("batch", InjectedDispatchError("b"))
    assert dh.stats()["breakers"]["batch"]["state"] == "closed"
    dh.set_enabled(False)
    for _ in range(5):
        dh.record_failure("mesh", InjectedDispatchError("x"))
    assert dh.stats()["breakers"]["mesh"]["state"] == "closed"
    assert dh.allow("mesh")


def test_record_failure_dedups_one_exception_across_layers():
    dh = DeviceHealthService(clock=FakeClock())
    exc = InjectedOOMError("once")
    dh.record_failure("staging", exc)
    dh.record_failure("dispatch", exc)   # layered handler: same fault
    st = dh.stats()["breakers"]
    assert st["staging"]["failures"] == 1
    assert st["dispatch"]["failures"] == 0


# -- the injector -----------------------------------------------------------

def test_injector_seeded_probabilistic_determinism():
    def fired_pattern(seed):
        inj = DeviceFaultInjector(seed=seed)
        rule = inj.dispatch_error(probability=0.5)
        return [rule.matches("dispatch", ("run_topk",))
                for _ in range(32)]
    assert fired_pattern(7) == fired_pattern(7)
    assert fired_pattern(7) != fired_pattern(8)


def test_injector_rule_matching_and_bounds():
    inj = DeviceFaultInjector(seed=1)
    rule = inj.oom("seg_a*", times=2)
    assert not rule.matches("dispatch", ("seg_a1",))   # wrong op
    assert not rule.matches("stage", ("seg_b1",))      # wrong name
    assert rule.matches("stage", ("seg_a1", "postings"))
    assert rule.matches("stage", ("seg_a2",))
    assert not rule.matches("stage", ("seg_a3",))      # times exhausted
    sticky = inj.dispatch_error()
    for _ in range(5):
        assert sticky.matches("dispatch", ("run_full",))
    inj.remove(sticky)
    assert inj._match("dispatch", ("run_full",)) is None
    inj.clear()
    assert inj.stats()["rules"] == 0


# -- byte-identity of the degraded paths ------------------------------------

def test_tripped_breaker_host_results_byte_identical():
    bm25_ops.HOST_SCORING = False
    s = _searcher()
    clean = s.search(dict(BODY))
    assert clean["hits"]["hits"]
    dh = device_health()
    dh.set_failure_threshold(2)
    trips0 = metrics().counter("device.breaker.trips").value
    inj = DeviceFaultInjector(seed=4)
    inj.dispatch_error()                 # sticky: every dispatch dies
    with inj:
        r1 = s.search(dict(BODY))        # faults -> per-segment host
    assert json.dumps(r1["hits"], sort_keys=True) == \
        json.dumps(clean["hits"], sort_keys=True)
    assert dh.stats()["breakers"]["dispatch"]["trips"] >= 1
    assert metrics().counter("device.breaker.trips").value > trips0
    # breaker held open (real cooldown): the host route serves without
    # touching the device at all, still byte-identical
    dh.set_open_interval_s(3600.0)
    r2 = s.search(dict(BODY))
    assert json.dumps(r2["hits"], sort_keys=True) == \
        json.dumps(clean["hits"], sort_keys=True)
    # the trip left a flight-recorder capture
    assert any(c["trigger"] == "device_breaker_trip"
               for c in flight_recorder().captures())


def test_poison_recompute_byte_identical_with_capture():
    bm25_ops.HOST_SCORING = False
    s = _searcher()
    clean = s.search(dict(BODY))
    inj = DeviceFaultInjector(seed=3)
    inj.poison_topk(times=2)
    with inj:
        poisoned = s.search(dict(BODY))
    assert json.dumps(poisoned["hits"], sort_keys=True) == \
        json.dumps(clean["hits"], sort_keys=True)
    assert device_health().stats()["poisoned_results"] >= 1
    assert metrics().counter("device.poisoned_results").value >= 1
    caps = [c for c in flight_recorder().captures()
            if c["trigger"] == "device_poisoned_result"]
    assert caps and caps[0]["detail"]["kernel"] == "run_topk"


def test_staging_oom_marks_evicted_and_falls_back():
    bm25_ops.HOST_SCORING = False
    s = _searcher()
    clean = s.search(dict(BODY))
    led = device_ledger()
    led.set_budget(1)                    # force-evict every staging
    led.set_budget(None)
    rf0 = metrics().counter("device.restage_failures").value
    inj = DeviceFaultInjector(seed=5)
    inj.oom()                            # sticky RESOURCE_EXHAUSTED
    with inj:
        r = s.search(dict(BODY))         # term-bag: host fallback
        assert json.dumps(r["hits"], sort_keys=True) == \
            json.dumps(clean["hits"], sort_keys=True)
        with pytest.raises(InjectedOOMError):
            s.segments[0].device()       # direct restage still fails
    assert metrics().counter("device.restage_failures").value > rf0
    assert s.segments[0]._device_evicted
    # healed: the next device() restages and re-counts
    restages0 = device_ledger().restages
    s.segments[0].device()
    assert device_ledger().restages == restages0 + 1
    assert not s.segments[0]._device_evicted


def test_non_fallbackable_plan_degrades_partial_not_500(tmp_path):
    from opensearch_tpu.indices.service import IndicesService
    bm25_ops.HOST_SCORING = False
    svc = IndicesService(str(tmp_path))
    svc.create("ix", {"settings": {"number_of_shards": 1},
                      "mappings": MAPPING})
    ix = svc.get("ix")
    try:
        for i in range(8):
            ix.index_doc(str(i), {"t": f"alpha w{i % 3}", "n": i})
        ix.refresh()
        sort_body = {"query": {"match_all": {}}, "size": 3,
                     "sort": [{"n": "asc"}]}
        ok = ix.search(dict(sort_body))
        assert ok["_shards"]["failed"] == 0
        led = device_ledger()
        led.set_budget(1)
        led.set_budget(None)
        deg0 = metrics().counter("device.degraded_searches").value
        inj = DeviceFaultInjector(seed=6)
        inj.oom()
        with inj:
            r = ix.search(dict(sort_body))
            assert r["_shards"]["failed"] >= 1
            assert r["_shards"]["failures"][0]["reason"]["type"] == \
                "device_degraded_exception"
            assert r["hits"]["hits"] == []
            # all-or-nothing semantics still raise (503-class), not 500
            with pytest.raises(DeviceDegradedError):
                ix.search(dict(sort_body,
                               allow_partial_search_results=False))
        assert metrics().counter(
            "device.degraded_searches").value > deg0
        # healed: full results come back
        r = ix.search(dict(sort_body))
        assert r["_shards"]["failed"] == 0 and r["hits"]["hits"]
    finally:
        svc.close()


def test_batch_group_device_fault_falls_back_byte_identical():
    bm25_ops.HOST_SCORING = False
    s = _searcher()
    bodies = [{"query": {"match": {"t": "alpha"}}, "size": 4},
              {"query": {"match": {"t": "gamma delta"}}, "size": 4}]
    clean = s.msearch([dict(b) for b in bodies])
    inj = DeviceFaultInjector(seed=9)
    inj.dispatch_error("batch_impact_union_topk")
    with inj:
        faulted = s.msearch([dict(b) for b in bodies])
    assert json.dumps([r["hits"] for r in faulted], sort_keys=True) == \
        json.dumps([r["hits"] for r in clean], sort_keys=True)
    assert device_health().stats()["breakers"]["batch"]["failures"] >= 1
    # poisoned batch kernel: sanity guard discards + recomputes
    inj2 = DeviceFaultInjector(seed=10)
    inj2.poison_topk("batch_impact_union_topk", times=1)
    with inj2:
        poisoned = s.msearch([dict(b) for b in bodies])
    assert json.dumps([r["hits"] for r in poisoned],
                      sort_keys=True) == \
        json.dumps([r["hits"] for r in clean], sort_keys=True)
    assert device_health().stats()["poisoned_results"] >= 1


def test_mesh_demotes_to_host_scatter(tmp_path):
    from opensearch_tpu.indices.service import IndicesService
    svc = IndicesService(str(tmp_path))
    svc.create("mx", {"settings": {"number_of_shards": 2},
                      "mappings": MAPPING})
    ix = svc.get("mx")
    try:
        for i in range(10):
            ix.index_doc(str(i), {"t": f"alpha w{i % 3}", "n": i})
        ix.refresh()
        body = {"query": {"match": {"t": "alpha"}}, "size": 5}
        fb0 = metrics().counter("search.mesh.fallback").value
        inj = DeviceFaultInjector(seed=11)
        inj.lose_mesh_member()
        with inj:
            # drive the mesh entry directly: member loss (or a mesh
            # that cannot build on a 1-device host) must demote to the
            # host scatter fallback, never raise
            r = ix._mesh_search(dict(body))
        assert r["hits"]["total"]["value"] > 0
        assert metrics().counter("search.mesh.fallback").value > fb0
        assert device_health().stats()["breakers"]["mesh"][
            "failures"] >= 1
        # an OPEN mesh breaker routes straight to the fallback without
        # re-attempting the collective
        dh = device_health()
        dh.set_failure_threshold(1)
        dh.set_open_interval_s(3600.0)
        dh.record_failure("mesh", InjectedDispatchError("down"))
        fb1 = metrics().counter("search.mesh.fallback").value
        r2 = ix._mesh_search(dict(body))
        assert r2["hits"]["total"]["value"] > 0
        assert metrics().counter("search.mesh.fallback").value > fb1
    finally:
        svc.close()


# -- QoS: device duress adapts the node_duress thresholds -------------------

class _StubAdmission:
    tenant_shares: dict = {}
    default_share = 1.0

    def __init__(self):
        self.tenant_penalty = {}

    def stats(self):
        return {"rejected_count": 0, "shed_count": 0, "occupancy": 0.2,
                "tenants": {}}


class _StubInsights:
    coalesce_window_ms = 10.0

    def stats(self):
        return {"records": 0, "coalescable_fraction": 0.0}


def test_qos_device_evidence_tightens_and_relaxes_duress_thresholds():
    from opensearch_tpu.common.tasks import TaskManager
    from opensearch_tpu.search.backpressure import \
        SearchBackpressureService
    from opensearch_tpu.search.qos import QosController

    bp = SearchBackpressureService(TaskManager("t"), clock=FakeClock(),
                                   cpu_load_fn=lambda: 0.0,
                                   cpu_threshold=0.9,
                                   heap_threshold=0.85)
    ctl = QosController(admission=_StubAdmission(),
                        insights=_StubInsights(), backpressure=bp,
                        clock=FakeClock())
    ctl.set_enabled(True)
    ctl.hysteresis_ticks = 1
    ctl.run_once()                       # baseline snapshot
    # device duress: breaker trips + poisoned results since last tick
    metrics().counter("device.breaker.trips").inc()
    metrics().counter("device.poisoned_results").inc(2)
    out = ctl.run_once()
    knobs = [a["knob"] for a in out["adapted"]]
    assert "node_duress.cpu_threshold" in knobs
    assert "node_duress.heap_threshold" in knobs
    assert bp.trackers["cpu_usage"].threshold == pytest.approx(0.45)
    assert bp.trackers["heap_usage"].threshold == pytest.approx(0.425)
    rec = next(a for a in out["adapted"]
               if a["knob"] == "node_duress.cpu_threshold")
    assert rec["evidence"]["device_trips"] == 1
    assert rec["evidence"]["poisoned_results"] == 2
    assert "node_duress" in ctl.stats()["knobs"]
    # clean ticks relax additively back toward the configured base
    out = ctl.run_once()
    assert any(a["knob"].startswith("node_duress.")
               for a in out["adapted"])
    assert bp.trackers["cpu_usage"].threshold == pytest.approx(0.5)
    for _ in range(12):
        ctl.run_once()
    assert bp.trackers["cpu_usage"].threshold == pytest.approx(0.9)
    assert bp.trackers["heap_usage"].threshold == pytest.approx(0.85)


# -- soak: the device-fault directive class ---------------------------------

def test_device_soak_schedule_two_run_determinism():
    from opensearch_tpu.testing.workload import FaultSchedule, SoakConfig
    cfg = SoakConfig.device(seed=42)
    s1 = FaultSchedule.generate(cfg)
    s2 = FaultSchedule.generate(SoakConfig.device(seed=42))
    assert s1 == s2
    kinds = [d["fault"] for d in s1]
    for want in ("device_slow", "device_poison", "device_oom",
                 "device_mesh_loss", "device_heal"):
        assert want in kinds, kinds
    # paired windows stay ordered under the jitter
    assert kinds.index("device_poison") < kinds.index("device_heal")
    steps = [d["step"] for d in s1 if d["fault"].startswith("device_")]
    assert steps == sorted(steps)
    # a different seed moves the schedule
    assert FaultSchedule.generate(SoakConfig.device(seed=43)) != s1
    # the base (non-device) schedule is untouched by the flag
    base = FaultSchedule.generate(SoakConfig(seed=42))
    assert [d for d in s1 if not d["fault"].startswith("device_")] == base


def test_device_soak_slos(tmp_path):
    """The acceptance scenario: OOM + poison + slow + mesh-loss + heal
    under traffic — zero unexpected 5xx, doc/score convergence vs the
    uninjected control, >= 1 breaker trip visible, breakers re-closed
    after heal, >= 1 poisoned result caught."""
    from opensearch_tpu.testing.workload import run_device_soak
    rep = run_device_soak(str(tmp_path / "devsoak"), seed=42)
    by_slo = {v["slo"]: v for v in rep["verdicts"]}
    assert by_slo["unexpected_errors"]["ok"], \
        rep["chaos"]["unexpected_errors"]
    assert by_slo["convergence"]["ok"]
    assert by_slo["device_breaker_trip"]["ok"]
    assert by_slo["device_breaker_reclose"]["ok"]
    assert by_slo["device_poison_detected"]["ok"]
    assert rep["slo_ok"], rep["verdicts"]
    dev = rep["chaos"]["device"]
    assert dev["breaker_trips"] >= 1
    assert dev["poisoned"] >= 1
    assert dev["restage_failures"] >= 1
    assert dev["host_fallbacks"] >= 1
    assert dev["mesh_fallbacks"] >= 1
    assert dev["breaker_states"]["staging"] == "closed"
    assert dev["breaker_states"]["dispatch"] == "closed"
    # the injector's patches are gone and the globals restored
    assert bm25_ops.HOST_SCORING is None
    assert "stage" not in device_ledger().__dict__


@pytest.mark.slow
def test_device_soak_two_run_verdict_determinism(tmp_path):
    from opensearch_tpu.testing.workload import run_device_soak
    r1 = run_device_soak(str(tmp_path / "a"), seed=7)
    r2 = run_device_soak(str(tmp_path / "b"), seed=7)
    assert r1["chaos"]["schedule"] == r2["chaos"]["schedule"]
    assert [(v["slo"], v["ok"]) for v in r1["verdicts"]] == \
        [(v["slo"], v["ok"]) for v in r2["verdicts"]]
    assert r1["chaos"]["final_state"] == r2["chaos"]["final_state"]


# -- surfaces ---------------------------------------------------------------

def test_nodes_stats_health_metrics_and_dynamic_settings(tmp_path):
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "node"), port=0)
    try:
        device_health().record_failure(
            "dispatch", InjectedDispatchError("x"))
        s, stats = node.rest.dispatch("GET", "/_nodes/stats", {}, None,
                                      "application/json", headers={})
        assert s == 200
        health = stats["nodes"][node.node_id]["device"]["health"]
        assert health["enabled"] is True
        assert health["breakers"]["dispatch"]["failures"] == 1
        assert set(health["breakers"]) >= {"staging", "dispatch",
                                           "batch", "mesh"}
        s, text = node.rest.dispatch("GET", "/_metrics", {}, None,
                                     "application/json", headers={})
        assert s == 200
        body = text.text if hasattr(text, "text") else str(text)
        assert 'opensearch_tpu_device_breaker_open{kernel="dispatch"}' \
            in body
        # dynamic knobs reach the process-global service immediately
        s, _ = node.rest.dispatch(
            "PUT", "/_cluster/settings", {},
            json.dumps({"transient": {
                "device.health.failure_threshold": 7,
                "device.health.open_interval_s": 1.5,
                "device.health.enabled": False}}).encode(),
            "application/json", headers={})
        assert s == 200
        dh = device_health()
        assert dh.failure_threshold == 7
        assert dh.open_interval_s == 1.5
        assert dh.enabled is False
        s, cstats = node.rest.dispatch("GET", "/_cluster/stats", {},
                                       None, "application/json",
                                       headers={})
        assert s == 200
        assert "breaker_trips" in cstats["device"]
        assert "poisoned_results" in cstats["device"]
    finally:
        node.stop()


def test_insight_outcome_device_degraded(tmp_path):
    from opensearch_tpu.node import Node
    bm25_ops.HOST_SCORING = False
    node = Node(str(tmp_path / "node"), port=0)
    try:
        def call(method, path, body=None, ndjson=None):
            if ndjson is not None:
                raw = ("\n".join(json.dumps(x) for x in ndjson)
                       + "\n").encode()
                ctype = "application/x-ndjson"
            else:
                raw = (json.dumps(body).encode()
                       if body is not None else None)
                ctype = "application/json"
            return node.rest.dispatch(method, path, {}, raw, ctype,
                                      headers={})
        s, _ = call("PUT", "/dix", {"mappings": MAPPING})
        assert s == 200
        lines = []
        for i in range(6):
            lines.append({"index": {"_index": "dix", "_id": str(i)}})
            lines.append({"t": f"alpha w{i}", "n": i})
        s, r = call("POST", "/_bulk", ndjson=lines)
        assert s == 200
        node.indices.get("dix").refresh()
        led = device_ledger()
        led.set_budget(1)
        led.set_budget(None)
        inj = DeviceFaultInjector(seed=12)
        inj.oom()
        with inj:
            s, r = call("POST", "/dix/_search",
                        {"query": {"match_all": {}}, "size": 3,
                         "sort": [{"n": "asc"}]})
        # REST response: 200 with partial _shards, never a 500
        assert s == 200, r
        assert r["_shards"]["failed"] >= 1
        outcomes = node.insights.stats().get("outcomes", {})
        assert outcomes.get("device_degraded", 0) >= 1
    finally:
        node.stop()


# -- bench phase ------------------------------------------------------------

def test_bench_devfaults_phase(tmp_path, monkeypatch):
    import bench
    monkeypatch.setenv("OSTPU_BENCH_PHASES",
                       str(tmp_path / "phases.jsonl"))
    s = _searcher()
    queries = [dict(BODY), {"query": {"match": {"t": "beta"}},
                            "size": 5}] * 4
    data = bench.run_devfaults_phase(s, queries, len(queries), "cpu")
    assert data["qps_healthy"] > 0
    assert data["qps_under_trip"] > 0
    assert data["breaker_trips"] >= 1
    assert data["probe_recoveries"] >= 1
    assert data["breaker_states"]["dispatch"] == "closed"
    lines = [json.loads(ln) for ln in
             (tmp_path / "phases.jsonl").read_text().splitlines()]
    assert any(ln["phase"] == "device_faults" for ln in lines)
    # the phase restored the process-global state
    assert device_health().failure_threshold == 3
    assert bm25_ops.HOST_SCORING is None


# -- tier-1 lint ------------------------------------------------------------

def test_check_degraded_paths_lint_clean_on_repo():
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_degraded_paths.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_check_degraded_paths_lint_catches_and_annotates(tmp_path):
    tool = os.path.join(TOOLS, "check_degraded_paths.py")
    bad = tmp_path / "search"
    bad.mkdir()
    (bad / "swallow.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except XlaRuntimeError:\n"
        "        pass\n")
    out = subprocess.run([sys.executable, tool, str(tmp_path)],
                         capture_output=True, text=True)
    assert out.returncode == 1
    assert "swallow.py:4" in out.stdout
    # the classify-idiom (broad except + is_device_error) is in scope
    (bad / "swallow.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:\n"
        "        if is_device_error(e):\n"
        "            return None\n"
        "        raise\n")
    out = subprocess.run([sys.executable, tool, str(tmp_path)],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stdout  # classifier IS evidence
    (bad / "swallow.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except DeviceDegradedError:\n"
        "        return None\n")
    out = subprocess.run([sys.executable, tool, str(tmp_path)],
                         capture_output=True, text=True)
    assert out.returncode == 1
    # evidence (device.* metric) passes
    (bad / "swallow.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except DeviceDegradedError:\n"
        "        metrics().counter(\"device.degraded\").inc()\n")
    out = subprocess.run([sys.executable, tool, str(tmp_path)],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stdout
    # the degrade-ok annotation passes
    (bad / "swallow.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except DeviceDegradedError:  # degrade-ok\n"
        "        return None\n")
    out = subprocess.run([sys.executable, tool, str(tmp_path)],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stdout
