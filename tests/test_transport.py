"""Wire serialization round-trips + transport RPC over both the
in-process hub (with disruption rules) and real TCP sockets
(VERDICT round-1 item 10)."""

import threading
import time

import pytest

from opensearch_tpu.common.errors import (
    NodeDisconnectedError,
    OpenSearchTpuError,
)
from opensearch_tpu.transport.service import (
    LocalTransport,
    ReceiveTimeoutError,
    RemoteTransportError,
    TcpTransport,
    TransportService,
    decode_frame,
    encode_frame,
)
from opensearch_tpu.transport.wire import StreamInput, StreamOutput


def test_wire_roundtrip_primitives():
    out = StreamOutput()
    out.write_vint(0)
    out.write_vint(127)
    out.write_vint(300)
    out.write_vint(2**40)
    out.write_zlong(-1)
    out.write_zlong(2**62)
    out.write_zlong(-(2**62))
    out.write_long(-42)
    out.write_double(3.5)
    out.write_bool(True)
    out.write_string("héllo wörld")
    out.write_optional_string(None)
    out.write_optional_string("x")
    out.write_string_list(["a", "b"])
    inp = StreamInput(out.bytes())
    assert [inp.read_vint() for _ in range(4)] == [0, 127, 300, 2**40]
    assert [inp.read_zlong() for _ in range(3)] == [-1, 2**62, -(2**62)]
    assert inp.read_long() == -42
    assert inp.read_double() == 3.5
    assert inp.read_bool() is True
    assert inp.read_string() == "héllo wörld"
    assert inp.read_optional_string() is None
    assert inp.read_optional_string() == "x"
    assert inp.read_string_list() == ["a", "b"]
    assert inp.remaining() == 0


def test_wire_roundtrip_generic_values():
    value = {"query": {"match": {"title": "foo"}}, "size": 10,
             "seq": [1, 2.5, None, True, "s", b"\x00\x01"],
             "nested": {"a": {"b": [{"c": -5}]}}}
    out = StreamOutput()
    out.write_value(value)
    got = StreamInput(out.bytes()).read_value()
    assert got == value


def test_frame_roundtrip():
    frame = encode_frame(7, 0, "indices:data/read/search", {"q": 1})
    assert frame[:2] == b"OT"
    version, action, payload = decode_frame(frame[6 + 9:])
    assert action == "indices:data/read/search"
    assert payload == {"q": 1}


def make_local_pair():
    hub = LocalTransport.Hub()
    a = TransportService("node_a", LocalTransport(hub))
    b = TransportService("node_b", LocalTransport(hub))
    return hub, a, b


def test_local_request_response():
    hub, a, b = make_local_pair()
    b.register_handler("echo", lambda p: {"got": p, "from": "b"})
    resp = a.send_request("node_b", "echo", {"x": 1}, timeout=5)
    assert resp == {"got": {"x": 1}, "from": "b"}
    a.close()
    b.close()


def test_local_error_propagation():
    hub, a, b = make_local_pair()

    def boom(p):
        raise OpenSearchTpuError("kaput")
    b.register_handler("boom", boom)
    with pytest.raises(RemoteTransportError, match="kaput"):
        a.send_request("node_b", "boom", {}, timeout=5)
    with pytest.raises(RemoteTransportError, match="no handler"):
        a.send_request("node_b", "nope", {}, timeout=5)
    a.close()
    b.close()


def test_local_drop_rule_times_out():
    hub, a, b = make_local_pair()
    b.register_handler("echo", lambda p: p)
    hub.disconnect("node_b")
    with pytest.raises((ReceiveTimeoutError, NodeDisconnectedError)):
        a.send_request("node_b", "echo", {}, timeout=0.5)
    hub.clear_rules()
    assert a.send_request("node_b", "echo", {"ok": 1}, timeout=5) == {"ok": 1}
    a.close()
    b.close()


def test_local_delay_rule():
    hub, a, b = make_local_pair()
    b.register_handler("echo", lambda p: p)
    hub.add_rule(lambda s, d, f: 0.2)
    t0 = time.monotonic()
    a.send_request("node_b", "echo", {}, timeout=5)
    assert time.monotonic() - t0 >= 0.2
    a.close()
    b.close()


def test_concurrent_requests_correlate():
    hub, a, b = make_local_pair()
    b.register_handler("double", lambda p: {"y": p["x"] * 2})
    futs = [a.submit_request("node_b", "double", {"x": i})
            for i in range(20)]
    assert [f.result(timeout=5)["y"] for f in futs] == [i * 2
                                                        for i in range(20)]
    a.close()
    b.close()


def test_tcp_transport_roundtrip():
    ta = TcpTransport()
    tb = TcpTransport()
    a = TransportService("node_a", ta)
    b = TransportService("node_b", tb)
    ta.add_node("node_b", "127.0.0.1", tb.port)
    tb.add_node("node_a", "127.0.0.1", ta.port)
    b.register_handler("sum", lambda p: {"total": sum(p["nums"])})
    a.register_handler("ping", lambda p: {"pong": True})
    resp = a.send_request("node_b", "sum", {"nums": [1, 2, 3]}, timeout=5)
    assert resp == {"total": 6}
    # reverse direction
    resp = b.send_request("node_a", "ping", {}, timeout=5)
    assert resp == {"pong": True}
    # errors over tcp
    with pytest.raises(RemoteTransportError):
        a.send_request("node_b", "unknown_action", {}, timeout=5)
    a.close()
    b.close()


def test_tcp_peer_down():
    ta = TcpTransport()
    a = TransportService("node_a", ta)
    ta.add_node("node_b", "127.0.0.1", 1)   # nothing listening
    with pytest.raises((NodeDisconnectedError, ReceiveTimeoutError)):
        a.send_request("node_b", "echo", {}, timeout=1.0)
    a.close()


def test_handshake_negotiates_min_version():
    """TransportHandshaker analog: both sides speak the min version and
    the result is cached per peer."""
    from opensearch_tpu.transport.service import (HANDSHAKE,
                                                  LocalTransport,
                                                  TransportService)
    from opensearch_tpu.version import TRANSPORT_PROTOCOL_VERSION

    hub = LocalTransport.Hub()
    a = TransportService("a", LocalTransport(hub))
    b = TransportService("b", LocalTransport(hub))
    try:
        assert a.negotiated_version("b") == TRANSPORT_PROTOCOL_VERSION
        assert a._peer_versions["b"] == TRANSPORT_PROTOCOL_VERSION
        # a peer one minor behind negotiates down
        b._handlers[HANDSHAKE] = lambda p: {
            "version": TRANSPORT_PROTOCOL_VERSION - 1, "node": "b"}
        a._peer_versions.clear()
        assert a.negotiated_version("b") == TRANSPORT_PROTOCOL_VERSION - 1
    finally:
        a.close()
        b.close()


def test_handshake_rejects_major_mismatch():
    from opensearch_tpu.common.errors import OpenSearchTpuError
    from opensearch_tpu.transport.service import (HANDSHAKE,
                                                  LocalTransport,
                                                  TransportService)
    from opensearch_tpu.version import TRANSPORT_PROTOCOL_VERSION

    hub = LocalTransport.Hub()
    a = TransportService("a", LocalTransport(hub))
    b = TransportService("b", LocalTransport(hub))
    try:
        b._handlers[HANDSHAKE] = lambda p: {
            "version": TRANSPORT_PROTOCOL_VERSION + 100, "node": "b"}
        with pytest.raises(OpenSearchTpuError):
            a.negotiated_version("b")
        assert "b" not in a._peer_versions    # incompatibility not cached
    finally:
        a.close()
        b.close()


def test_large_frames_compress_on_the_wire():
    """Bodies above the threshold ship zlib-compressed with the header
    flag set, transparently to handlers (TcpHeader compressed flag)."""
    import struct as _struct
    import zlib as _zlib

    from opensearch_tpu.transport.service import (STATUS_COMPRESSED,
                                                  LocalTransport,
                                                  TransportService,
                                                  encode_frame)

    big = {"blob": "x" * 50_000}
    frame = encode_frame(7, 0, "test/echo", big)
    _req, status = _struct.unpack(">QB", frame[6:15])
    assert status & STATUS_COMPRESSED
    assert len(frame) < 5_000        # 50k of 'x' compresses hard
    # round trip through a live pair
    hub = LocalTransport.Hub()
    a = TransportService("a", LocalTransport(hub))
    b = TransportService("b", LocalTransport(hub))
    try:
        b.register_handler("test/echo", lambda p: p)
        assert a.send_request("b", "test/echo", big) == big
    finally:
        a.close()
        b.close()
