"""Per-tenant QoS + adaptive overload control (PR 14; ROADMAP item 7).

The loop closer: weighted per-tenant admission shares carved from the
SearchAdmissionController keyed by X-Opaque-Id, tenant-weighted shed /
cancellation priority, per-tenant insights attribution, the AIMD
QosController adapting shed-occupancy / batcher-window / tenant-share
knobs from measured 429/breach evidence with an audit ring, the
measured-drain-rate Retry-After, the C3-ranked recovery source, the
response-collector eviction-tombstone fix, the dead-settings lint, and
the noisy-neighbor soak acceptance (two-run verdict determinism).
"""

import contextlib
import json
import subprocess
import sys
import types

import pytest

from opensearch_tpu.cluster import response_collector as rc
from opensearch_tpu.cluster.node import ClusterNode
from opensearch_tpu.cluster.response_collector import \
    ResponseCollectorService
from opensearch_tpu.common.errors import IllegalArgumentError
from opensearch_tpu.common.telemetry import flight_recorder, metrics, \
    tracer
from opensearch_tpu.node import Node
from opensearch_tpu.search import engine as engine_mod
from opensearch_tpu.search.backpressure import (SearchBackpressureService,
                                                SearchRejectedError)
from opensearch_tpu.search.insights import QueryInsightsService
from opensearch_tpu.search.qos import (DEFAULT_POOL, QosController,
                                       parse_tenant_shares, tenant_label)
from opensearch_tpu.testing.workload import run_noisy_neighbor
from opensearch_tpu.transport.service import (LocalTransport,
                                              TransportService)

REPO = __file__.rsplit("/tests/", 1)[0]
TOOLS = REPO + "/tools"


@pytest.fixture(autouse=True)
def _clean_state():
    tracer().reset()
    flight_recorder().reset()
    saved = (rc.SHED_OCCUPANCY, engine_mod.AUTO_WINDOW_MS,
             engine_mod.BATCHER_WINDOW_MS)
    yield
    (rc.SHED_OCCUPANCY, engine_mod.AUTO_WINDOW_MS,
     engine_mod.BATCHER_WINDOW_MS) = saved
    tracer().reset()
    flight_recorder().reset()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _bp(clock=None, **kw):
    """A standalone backpressure service over an empty task manager."""
    tm = types.SimpleNamespace(list=lambda: [])
    return SearchBackpressureService(tm, clock=clock or FakeClock(),
                                     **kw)


# -- tenant share parsing ---------------------------------------------------

def test_parse_tenant_shares():
    assert parse_tenant_shares("") == {}
    assert parse_tenant_shares(None) == {}
    assert parse_tenant_shares("a:4, b:1") == {"a": 4.0, "b": 1.0}
    assert parse_tenant_shares({"a": 2}) == {"a": 2.0}
    with pytest.raises(IllegalArgumentError):
        parse_tenant_shares("a")
    with pytest.raises(IllegalArgumentError):
        parse_tenant_shares("a:zebra")
    with pytest.raises(IllegalArgumentError):
        parse_tenant_shares("a:0")
    assert tenant_label(None) == DEFAULT_POOL
    assert len(tenant_label("x" * 500)) == 64


# -- per-tenant admission carving -------------------------------------------

def test_tenant_admission_shares_carve_the_budget():
    """Named tenants draw from weighted carved pools; the flooding
    tenant exhausts its OWN share and 429s while other tenants' permits
    stay available; unlabeled traffic uses the default pool."""
    adm = _bp().admission
    adm.max_concurrent = 8
    adm.set_tenant_shares({"vip": 6.0, "noisy": 1.0})   # default: 1.0
    with contextlib.ExitStack() as stack:
        stack.enter_context(adm.acquire("s", tenant="noisy"))
        # noisy's carve = max(1, 8*1/8) = 1: second concurrent -> 429
        with pytest.raises(SearchRejectedError) as ei:
            with adm.acquire("s", tenant="noisy"):
                pass
        assert "tenant [noisy]" in str(ei.value)
        # vip (carve 6) and unlabeled (default pool, carve 1) are fine
        for _ in range(6):
            stack.enter_context(adm.acquire("s", tenant="vip"))
        stack.enter_context(adm.acquire("s"))
        stats = adm.stats()
        assert stats["tenants"]["noisy"]["rejected"] == 1
        assert stats["tenants"]["noisy"]["max_concurrent"] == 1
        assert stats["tenants"]["vip"]["max_concurrent"] == 6
        assert stats["tenants"][DEFAULT_POOL]["max_concurrent"] == 1
    # all released
    assert adm.stats()["current"] == 0


def test_no_shares_means_legacy_single_pool():
    adm = _bp().admission
    adm.max_concurrent = 4
    with contextlib.ExitStack() as stack:
        for i in range(4):
            stack.enter_context(adm.acquire("s", tenant=f"t{i}"))
        with pytest.raises(SearchRejectedError) as ei:
            with adm.acquire("s", tenant="t0"):
                pass
        # global saturation, not a tenant-share rejection
        assert "too many concurrent searches" in str(ei.value)


def test_tenant_penalty_squeezes_share_but_never_below_one_permit():
    adm = _bp().admission
    adm.max_concurrent = 16
    adm.set_tenant_shares({"a": 3.0, "b": 1.0})   # total 5 with default
    base = None
    with adm.acquire("s", tenant="a"):
        base = adm.stats()["tenants"]["a"]["max_concurrent"]
    assert base == int(16 * 3 / 5)
    adm.set_tenant_penalty("a", 0.25)
    with adm.acquire("s", tenant="a"):
        assert adm.stats()["tenants"]["a"]["max_concurrent"] == \
            max(1, int(base * 0.25))
    # a penalty can never deny the last permit
    adm.set_tenant_penalty("b", 0.01)
    with adm.acquire("s", tenant="b"):
        assert adm.stats()["tenants"]["b"]["max_concurrent"] == 1
    # penalty of 1.0 clears the entry
    adm.set_tenant_penalty("a", 1.0)
    assert "a" not in adm.tenant_penalty


def test_shed_priority_and_shed_attribution():
    adm = _bp().admission
    adm.set_tenant_shares({"a": 1.0})
    assert adm.shed_priority("a") == 1.0
    adm.set_tenant_penalty("a", 0.5)
    assert adm.shed_priority("a") == 0.5
    assert adm.shed_priority("unknown-tenant") == 1.0
    adm.record_shed(tenant="a")
    assert adm.stats()["tenants"]["a"]["shed"] == 1
    assert adm.stats()["shed_count"] == 1


# -- tenant-weighted cancellation (duress victim election) ------------------

def test_backpressure_victim_election_is_tenant_weighted():
    """Equal resource overshoot: the low-share tenant's task is
    elected for cancellation before the premium tenant's."""

    def task(tid, opaque):
        return types.SimpleNamespace(
            id=tid, action="indices:data/read/search",
            cancellable=True, cancelled=False,
            cpu_time_nanos=int(20e9), heap_bytes=0, elapsed_nanos=0,
            headers={"X-Opaque-Id": opaque})

    tasks = [task(1, "vip"), task(2, "noisy")]
    tm = types.SimpleNamespace(list=lambda: list(tasks))
    svc = SearchBackpressureService(tm, clock=FakeClock())
    # no shares: deterministic legacy order (task id ties)
    assert [t.id for t, _ in svc._eligible_tasks()] == [1, 2]
    svc.admission.set_tenant_shares({"vip": 8.0, "noisy": 1.0})
    assert [t.id for t, _ in svc._eligible_tasks()] == [2, 1]
    # a QoS penalty biases the election further against the tenant
    svc.admission.set_tenant_shares({"vip": 1.0, "noisy": 1.0})
    svc.admission.set_tenant_penalty("noisy", 0.5)
    assert [t.id for t, _ in svc._eligible_tasks()] == [2, 1]


# -- measured-drain-rate Retry-After ----------------------------------------

def test_retry_after_tracks_permit_release_ewma():
    clock = FakeClock()
    adm = _bp(clock).admission
    assert adm.retry_after_hint() == 1           # no samples: floor
    for _ in range(6):
        with adm.acquire("s"):
            pass
        clock.advance(5.0)                       # releases 5s apart
    assert adm.retry_after_hint() == 5
    # ceiling clamp
    for _ in range(8):
        with adm.acquire("s"):
            pass
        clock.advance(500.0)
    assert adm.retry_after_hint() == 30
    # the rejection error carries the measured hint
    adm.max_concurrent = 1
    with adm.acquire("held"):
        with pytest.raises(SearchRejectedError) as ei:
            with adm.acquire("s"):
                pass
    assert ei.value.retry_after_seconds == 30


def test_rest_429_ships_measured_retry_after(tmp_path):
    node = Node(str(tmp_path / "n"), port=0)
    try:
        adm = node.search_backpressure.admission
        # seed the drain EWMA at ~7s between releases
        adm._release_interval_ewma = 7.0
        adm.max_concurrent = 1
        headers = {}
        with adm.acquire("held"):
            status, resp = node.rest.dispatch(
                "GET", "/_search", {}, None, response_headers=headers)
        assert status == 429
        assert headers["Retry-After"] == "7"
    finally:
        node.stop()


# -- per-tenant insights attribution ----------------------------------------

def _rec(sig="q1", took=5.0, **kw):
    rec = {"signature": sig, "scored": True, "took_ms": took,
           "execution_path": "host", "plan_cache": "miss"}
    rec.update(kw)
    return rec


def test_insights_tenant_rollups_and_429_attribution():
    clock = FakeClock()
    svc = QueryInsightsService(node_id="n", clock=clock)
    svc.record(_rec(took=10.0), opaque_id="tenant-a")
    svc.record(_rec(took=30.0), opaque_id="tenant-a", outcome="partial")
    svc.record(_rec(took=2.0))                 # unlabeled -> _default
    svc.record_rejected(opaque_id="tenant-b")
    tenants = svc.tenants()
    assert set(tenants) == {"tenant-a", "tenant-b", DEFAULT_POOL}
    a = tenants["tenant-a"]
    assert a["count"] == 2
    assert a["latency_ms"]["avg"] == 20.0
    assert a["latency_ms"]["max"] == 30.0
    assert a["outcomes"] == {"ok": 1, "partial": 1}
    assert tenants["tenant-b"] == {
        "tenant": "tenant-b", "count": 0, "rejected": 1,
        "latency_ms": {"avg": 0.0, "max": 0.0},
        "cpu_time_in_nanos": 0, "outcomes": {}, "top_signatures": {}}
    st = svc.stats()
    assert st["tenants"] == 3
    assert st["outcomes"] == {"ok": 2, "partial": 1}
    totals = svc.tenant_totals()
    assert totals["tenant-a"] == {"count": 2, "rejected": 0}
    # section carries tenants; by=tenant is served (latency ranking)
    sec = svc.section(by="tenant")
    assert "tenant-a" in sec["tenants"]
    # bounded: LRU eviction past max_tenants
    small = QueryInsightsService(node_id="n", clock=clock,
                                 max_tenants=2)
    for i in range(4):
        small.record(_rec(), opaque_id=f"t{i}")
    assert len(small.tenants()) == 2
    assert "t3" in small.tenants()


def test_insights_prometheus_tenant_series_and_merge():
    clock = FakeClock()
    svc = QueryInsightsService(node_id="n1", clock=clock)
    svc.record(_rec(), opaque_id="tenant-a")
    svc.record_rejected(opaque_id="tenant-a")
    text = svc.prometheus_text()
    assert ('opensearch_tpu_insights_tenant_queries_total'
            '{tenant="tenant-a",node="n1"} 1') in text
    assert ('opensearch_tpu_insights_tenant_rejected_total'
            '{tenant="tenant-a",node="n1"} 1') in text
    # cluster fan-in merge sums per-tenant across nodes, keeps per-node
    # detail, and is insertion-order independent
    from opensearch_tpu.search.insights import merge_sections
    svc2 = QueryInsightsService(node_id="n2", clock=clock)
    svc2.record(_rec(took=9.0), opaque_id="tenant-a")
    sections = {"n1": svc.section(), "n2": svc2.section()}
    out1 = merge_sections(sections)
    out2 = merge_sections(dict(reversed(list(sections.items()))))
    assert out1["tenants"] == out2["tenants"]
    merged = out1["tenants"]["tenant-a"]
    assert merged["count"] == 2
    assert merged["rejected"] == 1
    assert set(merged["nodes"]) == {"n1", "n2"}


def test_rest_top_queries_by_tenant_and_nodes_stats(tmp_path):
    node = Node(str(tmp_path / "n"), port=0)
    try:
        node.rest.dispatch("PUT", "/idx", {}, json.dumps(
            {"mappings": {"properties": {"v": {"type": "long"}}}}
        ).encode(), "application/json")
        body = json.dumps({"query": {"match_all": {}}}).encode()
        status, _ = node.rest.dispatch(
            "POST", "/idx/_search", {}, body, "application/json",
            headers={"X-Opaque-Id": "tenant-a"})
        assert status == 200
        status, resp = node.rest.dispatch(
            "GET", "/_insights/top_queries", {"by": "tenant"}, None)
        assert status == 200
        assert "tenant-a" in resp["tenants"]
        assert resp["tenants"]["tenant-a"]["count"] == 1
        # only "tenant" is tolerated beyond the rank keys: anything
        # else still rejects (regression caught by the verify drive)
        status, resp = node.rest.dispatch(
            "GET", "/_insights/top_queries", {"by": "zebra"}, None)
        assert status == 400
        assert resp["error"]["type"] == "illegal_argument_exception"
        # _nodes/stats: tenant block + qos controller block
        status, stats = node.rest.dispatch("GET", "/_nodes/stats", {},
                                           None)
        nstats = stats["nodes"][node.node_id]
        assert "tenant-a" in nstats["tenants"]
        assert nstats["qos"]["enabled"] is False
        assert "audit" in nstats["qos"]
        assert "shed_occupancy" in nstats["qos"]["knobs"]
        adm = nstats["search_backpressure"]["admission_control"]
        assert "tenants" in adm and "retry_after_s" in adm
    finally:
        node.stop()


# -- the AIMD controller ----------------------------------------------------

class _StubAdmission:
    def __init__(self):
        self.rejected_count = 0
        self.shed_count = 0
        self.tenant_shares = {}
        self.default_share = 1.0
        self.tenant_penalty = {}
        self.tenant_rows = {}

    def set_tenant_penalty(self, label, penalty):
        if penalty >= 1.0:
            self.tenant_penalty.pop(label, None)
        else:
            self.tenant_penalty[label] = penalty

    def stats(self):
        return {"rejected_count": self.rejected_count,
                "shed_count": self.shed_count, "occupancy": 0.5,
                "tenants": {k: dict(v)
                            for k, v in self.tenant_rows.items()}}


class _StubInsights:
    def __init__(self):
        self.records = 0
        self.coalescable = 0.0
        self.coalesce_window_ms = 10.0

    def stats(self):
        return {"records": self.records,
                "coalescable_fraction": self.coalescable}


def _controller(clock=None):
    adm, ins = _StubAdmission(), _StubInsights()
    ctl = QosController(admission=adm, insights=ins,
                        clock=clock or FakeClock())
    ctl.set_enabled(True)
    return ctl, adm, ins


def test_controller_aimd_shed_occupancy_with_hysteresis():
    ctl, adm, ins = _controller()
    rc.SHED_OCCUPANCY = 0.8
    engine_mod.BATCHER_WINDOW_MS = 1.0   # pin: window knob stays put
    ctl.run_once()                       # baseline snapshot
    # one hot tick is NOT enough (hysteresis_ticks = 2)
    adm.rejected_count += 50
    ins.records += 50
    assert ctl.run_once()["adapted"] == []
    assert rc.SHED_OCCUPANCY == 0.8
    # second consecutive hot tick acts: multiplicative decrease
    adm.rejected_count += 50
    ins.records += 50
    out = ctl.run_once()
    assert [a["knob"] for a in out["adapted"]] == ["shed_occupancy"]
    assert rc.SHED_OCCUPANCY == 0.4
    rec = out["adapted"][0]
    assert rec["old"] == 0.8 and rec["new"] == 0.4
    assert rec["evidence"]["reject_rate"] == 0.5
    # the audit ring and the flight recorder both carry the record
    assert ctl.audit()[0]["knob"] == "shed_occupancy"
    caps = [c for c in flight_recorder().captures()
            if c["trigger"] == "qos_adaptation"]
    assert caps and caps[0]["detail"]["knob"] == "shed_occupancy"
    # healthy ticks recover additively (also hysteresis-gated)
    ins.records += 100
    assert ctl.run_once()["adapted"] == []
    ins.records += 100
    out = ctl.run_once()
    assert rc.SHED_OCCUPANCY == pytest.approx(0.45)
    assert out["adapted"][0]["new"] == pytest.approx(0.45)


def test_controller_widens_auto_batch_window_when_coalescable():
    ctl, adm, ins = _controller()
    ctl.hysteresis_ticks = 1
    rc.SHED_OCCUPANCY = 0.0
    engine_mod.BATCHER_WINDOW_MS = 0.0   # auto mode
    engine_mod.AUTO_WINDOW_MS = 10.0
    ins.coalescable = 0.6
    ctl.run_once()
    adm.rejected_count += 10
    ins.records += 10
    out = ctl.run_once()
    assert engine_mod.AUTO_WINDOW_MS == 15.0
    assert any(a["knob"] == "batcher_auto_window_ms"
               for a in out["adapted"])
    # healthy: decays back toward the configured base, never below
    ins.coalescable = 0.0
    ins.records += 100
    ctl.run_once()
    assert engine_mod.AUTO_WINDOW_MS == 10.0
    # operator-pinned window: controller keeps its hands off
    engine_mod.BATCHER_WINDOW_MS = 5.0
    adm.rejected_count += 10
    ins.records += 10
    ctl.run_once()
    assert engine_mod.AUTO_WINDOW_MS == 10.0


def test_controller_penalizes_dominant_tenant_with_evidence():
    ctl, adm, ins = _controller()
    ctl.hysteresis_ticks = 1
    rc.SHED_OCCUPANCY = 0.0
    engine_mod.BATCHER_WINDOW_MS = 1.0
    adm.tenant_shares = {"vip": 6.0, "noisy": 1.0}
    adm.tenant_rows = {"noisy": {"admitted": 0, "rejected": 0},
                       "vip": {"admitted": 0, "rejected": 0}}
    ctl.run_once()
    adm.tenant_rows["noisy"] = {"admitted": 2, "rejected": 48}
    adm.rejected_count += 48
    ins.records += 2
    out = ctl.run_once()
    pens = [a for a in out["adapted"] if a["knob"] == "tenant_penalty"]
    assert pens and pens[0]["tenant"] == "noisy"
    assert adm.tenant_penalty["noisy"] == 0.5
    assert pens[0]["evidence"]["attempt_share"] == 1.0
    # healthy windows recover the penalty additively until cleared
    for _ in range(3):
        ins.records += 10
        ctl.run_once()
    assert "noisy" not in adm.tenant_penalty


def test_controller_own_audit_captures_are_not_breach_evidence():
    """Regression: every adaptation records a flight capture; the next
    tick must not read its own capture as an SLO breach (the hot loop
    would then self-sustain forever)."""
    ctl, adm, ins = _controller()
    ctl.hysteresis_ticks = 1
    rc.SHED_OCCUPANCY = 0.8
    engine_mod.BATCHER_WINDOW_MS = 1.0
    ctl.run_once()
    adm.rejected_count += 10
    ins.records += 10
    assert ctl.run_once()["adapted"]          # hot: adapts + captures
    ins.records += 100                        # quiet traffic
    out = ctl.run_once()
    assert out["hot"] is False
    assert all(a["knob"] != "shed_occupancy" or a["new"] > a["old"]
               for a in out["adapted"])


def test_qos_dynamic_settings_wire_through(tmp_path):
    node = Node(str(tmp_path / "n"), port=0)
    try:
        adm = node.search_backpressure.admission
        assert adm.tenant_shares == {}
        assert node.qos.enabled is False
        node.update_cluster_settings(transient={
            "search.qos.tenant_shares": "a:4,b:1",
            "search.qos.default_share": 2.0,
            "search.qos.adaptive": True,
            "search.qos.interval_s": 0.25})
        assert adm.tenant_shares == {"a": 4.0, "b": 1.0}
        assert adm.default_share == 2.0
        assert node.qos.enabled is True
        assert node.qos.interval_s == 0.25
        with pytest.raises(IllegalArgumentError):
            node.update_cluster_settings(transient={
                "search.qos.tenant_shares": "nonsense"})
        node.update_cluster_settings(transient={
            "search.qos.tenant_shares": None,
            "search.qos.adaptive": None})
        assert adm.tenant_shares == {}
        assert node.qos.enabled is False
    finally:
        node.stop()


def test_responses_byte_identical_with_qos_enabled(tmp_path):
    """Per-tenant attribution is byte-neutral: serial search responses
    are identical with tenant shares + adaptive control on vs off
    (same pin discipline as insights/profile)."""
    node = Node(str(tmp_path / "n"), port=0)
    try:
        node.rest.dispatch("PUT", "/idx", {}, json.dumps(
            {"mappings": {"properties": {"body": {"type": "text"}}}}
        ).encode(), "application/json")
        for i in range(8):
            node.rest.dispatch(
                "PUT", f"/idx/_doc/{i}", {"refresh": "true"},
                json.dumps({"body": f"hello world t{i}"}).encode(),
                "application/json")
        body = json.dumps({"query": {"match": {"body": "hello"}},
                           "size": 5}).encode()

        def run():
            status, resp = node.rest.dispatch(
                "POST", "/idx/_search", {}, body, "application/json",
                headers={"X-Opaque-Id": "tenant-a"})
            assert status == 200
            resp = dict(resp)
            resp.pop("took", None)
            return json.dumps(resp, sort_keys=True)

        baseline = run()
        node.update_cluster_settings(transient={
            "search.qos.tenant_shares": "tenant-a:4,tenant-b:1",
            "search.qos.adaptive": True})
        assert run() == baseline
        node.update_cluster_settings(transient={
            "search.qos.tenant_shares": None,
            "search.qos.adaptive": None})
        assert run() == baseline
    finally:
        node.stop()


# -- satellite: C3-ranked recovery source -----------------------------------

def test_recovery_source_prefers_least_loaded_in_sync_copy(tmp_path):
    hub = LocalTransport.Hub()
    svc = TransportService("a", LocalTransport(hub))
    node = ClusterNode("a", str(tmp_path / "a"), svc, ["a"])
    try:
        entry = {"primary": "b", "replicas": ["c", "d"],
                 "in_sync": ["b", "c"], "primary_term": 1}
        # no evidence: legacy order -> the primary
        assert node._recovery_source(entry) == "b"
        col = node.response_collector
        # the primary is measurably slower than the in-sync replica
        for _ in range(4):
            col.record_response("b", 50e6, load={"queue_size": 40})
            col.record_response("c", 1e6, load={"queue_size": 0})
        assert node._recovery_source(entry) == "c"
        # d is NOT in-sync: never a recovery source even if fast
        for _ in range(4):
            col.record_response("d", 0.1e6, load={"queue_size": 0})
        assert node._recovery_source(entry) == "c"
        # the recovering node itself never self-sources
        entry_self = {"primary": "b", "replicas": ["a"],
                      "in_sync": ["b", "a"], "primary_term": 1}
        assert node._recovery_source(entry_self) == "b"
    finally:
        node.stop()


# -- satellite: collector eviction tombstones -------------------------------

def test_evicted_node_samples_do_not_resurrect_entry():
    """Regression: a LATE in-flight response (or ping) from a node the
    state apply just removed must not resurrect its stats entry — the
    resurrected duress flag would carry a refreshed TTL and shed the
    dead node's shards until the next purge."""
    clock = FakeClock()
    col = ResponseCollectorService(clock=clock)
    col.record_response("gone", 5e6, load={"duress": True})
    assert col.in_duress("gone")
    col.remove_node("gone")
    assert "gone" not in col.tracked()
    # the late in-flight sample arrives after the eviction
    col.record_response("gone", 5e6, load={"duress": True})
    col.record_ping_load("gone", {"duress": True})
    col.record_duress("gone", True)
    col.incr_outstanding("gone")
    assert "gone" not in col.tracked()
    assert not col.in_duress("gone")
    assert col.outstanding("gone") == 0
    # rejoin via state apply clears the tombstone immediately
    col.readmit("gone")
    col.record_response("gone", 5e6)
    assert "gone" in col.tracked()
    # without a readmit, the tombstone expires after the duress TTL
    col.remove_node("gone")
    clock.advance(col.duress_ttl_s + 0.1)
    col.record_response("gone", 5e6)
    assert "gone" in col.tracked()


# -- satellite: dead-settings lint ------------------------------------------

def test_check_dead_settings_lint_passes_repo():
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_dead_settings.py"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_check_dead_settings_lint_catches_violations(tmp_path):
    (tmp_path / "bad.py").write_text(
        "from opensearch_tpu.common.settings import (Setting, Settings,"
        " SettingsRegistry)\n"
        "dead = Setting.int_setting('a.dead', 1, dynamic=True)\n"
        "live = Setting.int_setting('a.live', 1, dynamic=True)\n"
        "static = Setting.int_setting('a.static', 1)\n"
        "# knob-ok: deliberately consumer-less\n"
        "waived = Setting.bool_setting('a.waived', True, dynamic=True)\n"
        "reg = SettingsRegistry(Settings({}), [dead, live, waived])\n"
        "reg.add_settings_update_consumer(live, print)\n")
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_dead_settings.py",
         str(tmp_path / "bad.py")],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert "bad.py:2" in out.stdout and "a.dead" in out.stdout
    assert "a.live" not in out.stdout
    assert "a.static" not in out.stdout      # non-dynamic: out of scope
    assert "a.waived" not in out.stdout      # annotated


# -- acceptance: the noisy-neighbor soak ------------------------------------

def test_noisy_neighbor_soak_isolates_victim_deterministically(tmp_path):
    """Two tenants, one flooding the zipf head far over its carved
    admission share: the victim's p99 and 429-rate SLOs hold while the
    aggressor's flood is shed at the gate, the adaptive controller
    records its adaptations (with evidence) in the audit ring, and two
    identical-seed runs produce identical verdicts."""
    r1 = run_noisy_neighbor(str(tmp_path / "a"), seed=42)
    r2 = run_noisy_neighbor(str(tmp_path / "b"), seed=42)
    v1 = [(v["slo"], v["ok"]) for v in r1["verdicts"]]
    v2 = [(v["slo"], v["ok"]) for v in r2["verdicts"]]
    assert v1 == v2
    assert r1["slo_ok"], r1["verdicts"]
    assert r1["unexpected_errors"] == []
    tenants = r1["tenants"]
    assert tenants["tenant-victim"]["rejected"] == 0
    assert tenants["tenant-aggressor"]["rejected"] > 0
    # the controller actually closed the loop, with recorded evidence
    assert r1["qos"]["adaptations"] >= 1
    audit = r1["qos"]["audit"]
    assert audit and "evidence" in audit[0]
    knobs = {a["knob"] for a in audit}
    assert "shed_occupancy" in knobs
    assert any(a.get("tenant") == "tenant-aggressor"
               for a in audit if a["knob"] == "tenant_penalty")
    # per-tenant attribution reached the insights surfaces too
    assert set(r1["insights_tenants"]) >= {"tenant-victim",
                                           "tenant-aggressor"}
    adm = r1["admission"]["tenants"]
    assert adm["tenant-aggressor"]["rejected"] > 0
    assert adm["tenant-victim"]["rejected"] == 0
    # the knobs were restored after the run (no suite-wide pollution)
    assert rc.SHED_OCCUPANCY == 0.0


def test_bench_qos_phase_emits_line(tmp_path, monkeypatch):
    import importlib.util
    phases = tmp_path / "phases.jsonl"
    monkeypatch.setenv("OSTPU_BENCH_PHASES", str(phases))
    monkeypatch.setenv("OSTPU_BENCH_QOS_OPS", "8")
    spec = importlib.util.spec_from_file_location("bench_under_test",
                                                  REPO + "/bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench.run_qos_phase("cpu")
    lines = [json.loads(ln) for ln in phases.read_text().splitlines()]
    assert len(lines) == 1
    line = lines[0]
    assert line["phase"] == "qos"
    assert {"slo_ok", "victim_p99_ms", "victim_429_rate",
            "aggressor_429_rate", "qos_adaptations",
            "knobs_adapted"} <= set(line)
    assert line["unexpected_errors"] == 0
