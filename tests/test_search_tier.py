"""Search-only replica tier (ROADMAP item 4): stateless searchers over
the remote store that survive kill/add churn under traffic.

Covers the tier end to end — roles-aware allocation
(``number_of_search_replicas`` over search-role nodes), primary
publish-to-remote on refresh, searcher installs that pull blob digests
through the FileCache with CRC verification, pure-remote refill
recovery (zero primary-directed RPCs, pinned via transport accounting),
checkpoint-lag deranking in the C3 selector, live fleet scaling, the
soak directive class (kill/add searcher, remote-store stall), and the
PR's satellites: the ``_h_publish_ckpt`` retry fix, FileCache
concurrency semantics, the ``search.replication.max_lag`` setting, and
the write-isolation lint."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from opensearch_tpu.cluster import response_collector as rc
from opensearch_tpu.cluster.node import (A_FETCH_SEGMENTS,
                                         A_PUBLISH_SEARCH_CKPT,
                                         A_START_RECOVERY, ClusterNode)
from opensearch_tpu.cluster.state import (ClusterState, allocate_shards,
                                          search_copies_of)
from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.common.telemetry import metrics
from opensearch_tpu.index.filecache import FileCache
from opensearch_tpu.testing.workload import (FaultSchedule, MixedWorkload,
                                             SoakConfig, SoakRunner)
from opensearch_tpu.transport.service import (LocalTransport,
                                              TransportService)

REPO = __file__.rsplit("/tests/", 1)[0]
LINT = REPO + "/tools/check_searcher_write_isolation.py"


@pytest.fixture(autouse=True)
def _restore_selector_globals():
    saved = (rc.SEARCH_MAX_LAG, rc.ADAPTIVE_ENABLED)
    yield
    rc.SEARCH_MAX_LAG, rc.ADAPTIVE_ENABLED = saved


def wait_until(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# -- allocation (cluster/state.py) ------------------------------------------

def _state(nodes, settings, routing=None):
    return ClusterState(
        nodes=nodes,
        indices={"idx": {"settings": settings}},
        routing={"idx": routing} if routing else {})


def test_allocate_search_replicas_on_search_nodes_only():
    st = allocate_shards(_state(
        {"d0": {}, "d1": {},
         "s0": {"name": "s0", "roles": ["search"]},
         "s1": {"name": "s1", "roles": ["search"]}},
        {"number_of_shards": 2, "number_of_replicas": 1,
         "number_of_search_replicas": 2}))
    for e in st.routing["idx"]:
        # write copies never land on search-only nodes
        assert e["primary"] in ("d0", "d1")
        assert all(r in ("d0", "d1") for r in e["replicas"])
        assert sorted(e["search_replicas"]) == ["s0", "s1"]
        # fresh slots start OUTSIDE the serving set
        assert e["search_in_sync"] == []
        assert search_copies_of(e) == []


def test_allocate_search_replicas_scale_and_dead_node_drop():
    st = allocate_shards(_state(
        {"d0": {}, "s0": {"name": "s0", "roles": ["search"]},
         "s1": {"name": "s1", "roles": ["search"]}},
        {"number_of_shards": 1, "number_of_search_replicas": 2}))
    e = st.routing["idx"][0]
    assert sorted(e["search_replicas"]) == ["s0", "s1"]
    # scale down trims slots (and their serving-set membership)
    e["search_in_sync"] = list(e["search_replicas"])
    st2 = allocate_shards(st.with_(indices={"idx": {"settings": {
        "number_of_shards": 1, "number_of_search_replicas": 1}}}))
    e2 = st2.routing["idx"][0]
    assert len(e2["search_replicas"]) == 1
    assert set(e2["search_in_sync"]) <= set(e2["search_replicas"])
    # a dead searcher leaves its slots; the survivor takes over
    st3 = allocate_shards(st.with_(
        nodes={"d0": {}, "s1": {"name": "s1", "roles": ["search"]}}))
    assert st3.routing["idx"][0]["search_replicas"] == ["s1"]


def test_entries_unchanged_without_search_setting():
    st = allocate_shards(_state(
        {"d0": {}, "d1": {}},
        {"number_of_shards": 1, "number_of_replicas": 1}))
    e = st.routing["idx"][0]
    assert "search_replicas" not in e and "search_in_sync" not in e


# -- FileCache concurrency (satellites 2 + 3) -------------------------------

def test_filecache_fetch_failure_propagates_to_waiters(tmp_path):
    cache = FileCache(str(tmp_path / "fc"))
    gate = threading.Event()
    calls = []

    def failing_fetch():
        calls.append(1)
        gate.wait(timeout=5.0)
        raise OSError("repository down")

    errors = []

    def get():
        try:
            cache.get("sha1", failing_fetch)
        except OSError as e:
            errors.append(str(e))

    threads = [threading.Thread(target=get) for _ in range(4)]
    for t in threads:
        t.start()
    wait_until(lambda: calls, what="fetcher started")
    gate.set()
    for t in threads:
        t.join(timeout=5.0)
        assert not t.is_alive(), "waiter hung on a failed fetch"
    # ONE fetch ran; every thread observed the SAME error
    assert len(calls) == 1
    assert errors == ["repository down"] * 4
    # the failure left no in-flight residue: a later get retries fresh
    assert cache.stats()["in_flight"] == 0
    path = cache.get("sha1", lambda: b"recovered")
    with open(path, "rb") as f:
        assert f.read() == b"recovered"


def test_filecache_eviction_racing_get(tmp_path):
    cache = FileCache(str(tmp_path / "fc"), max_bytes=32)
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            cache.get(f"bulk{i % 8}", lambda: b"y" * 24)
            i += 1

    t = threading.Thread(target=churn)
    t.start()
    try:
        for _ in range(200):
            # the pin discipline every reader uses (materialize_shard,
            # the searcher's _fetch_remote_segment): a pinned entry
            # survives concurrent eviction churn between the get() and
            # the read, no matter how small the budget
            with cache.pin({"hot"}):
                p = cache.get("hot", lambda: b"x" * 24)
                with open(p, "rb") as f:
                    assert f.read() == b"x" * 24
    finally:
        stop.set()
        t.join(timeout=5.0)
    # unpinned entries DID churn out around it the whole time
    assert cache.evictions > 0


def test_filecache_pin_unpin_composition(tmp_path):
    cache = FileCache(str(tmp_path / "fc"), max_bytes=8)
    cache.get("keep", lambda: b"k" * 8)
    outer = cache.pin({"keep"})
    inner = cache.pin({"keep"})
    with outer:
        with inner:
            pass
        # still pinned by the OUTER pin: pressure cannot evict it
        cache.get("other", lambda: b"o" * 8)
        assert os.path.exists(cache.path("keep"))
        assert cache.stats()["pinned_bytes"] == 8
    # both pins released: the entry is evictable again
    cache.get("other2", lambda: b"p" * 8)
    cache.set_max_bytes(8)
    assert cache.stats()["pinned_bytes"] == 0


def test_filecache_warm_restart_ignores_tmp(tmp_path):
    d = tmp_path / "fc"
    cache = FileCache(str(d))
    cache.get("real", lambda: b"data")
    # a crashed fetch leaves a .tmp behind; restart must not index it
    with open(d / "ghost.tmp.123", "wb") as f:
        f.write(b"partial")
    reopened = FileCache(str(d))
    st = reopened.stats()
    assert st["entries"] == 1
    assert st["size_in_bytes"] == 4
    assert reopened.get("real", lambda: (_ for _ in ()).throw(
        AssertionError("should hit"))) == reopened.path("real")


def test_filecache_invalidate_forces_refetch(tmp_path):
    cache = FileCache(str(tmp_path / "fc"))
    fetched = []
    cache.get("sha", lambda: fetched.append(1) or b"v1")
    cache.invalidate("sha")
    cache.get("sha", lambda: fetched.append(1) or b"v2")
    assert len(fetched) == 2
    with open(cache.path("sha"), "rb") as f:
        assert f.read() == b"v2"


# -- cluster tier plumbing --------------------------------------------------

def build_cluster(root, data_nodes=("n0", "n1", "n2"),
                  searchers=("s0",), shards=2, replicas=1,
                  search_replicas=None, docs=0):
    """3-data-node cluster + search tier over one shared remote store;
    returns (nodes, hub).  Soak-style: no background timers — tests
    drive checks explicitly."""
    hub = LocalTransport.Hub()
    remote = os.path.join(root, "remote")
    nodes = {}

    def build(nid, roles):
        svc = TransportService(nid, LocalTransport(hub))
        node = ClusterNode(nid, os.path.join(root, nid), svc,
                           list(data_nodes), roles=roles,
                           remote_store_path=remote)
        node.search_backpressure.trackers["cpu_usage"].probe = \
            lambda: 0.0
        node.search_rpc_timeout = 2.0
        node.recovery_timeout = 5.0
        return node

    for nid in data_nodes:
        nodes[nid] = build(nid, ("master", "data"))
    for sid in searchers:
        nodes[sid] = build(sid, ("search",))
    assert nodes[data_nodes[0]].start_election()
    for sid in searchers:
        nodes[data_nodes[0]].coordinator.add_node(
            sid, {"name": sid, "roles": ["search"],
                  "master_eligible": False})
    if search_replicas is None:
        search_replicas = len(searchers)
    nodes[data_nodes[0]].create_index("tier", {
        "settings": {"number_of_shards": shards,
                     "number_of_replicas": replicas,
                     "number_of_search_replicas": search_replicas},
        "mappings": {"properties": {
            "body": {"type": "text"}, "ts": {"type": "date"},
            "tag": {"type": "keyword"}, "v": {"type": "long"}}}})
    wait_until(lambda: searchers_ready(nodes[data_nodes[0]],
                                       search_replicas),
               what="initial searcher refill")
    client = nodes[data_nodes[0]]
    for i in range(docs):
        client.index_doc("tier", str(i), {"body": f"hello t{i % 7}",
                                          "ts": 1_700_000_000_000,
                                          "tag": "t", "v": i})
    if docs:
        client.refresh("tier")
        for sid in searchers:
            wait_until(lambda s=sid: nodes[s].search_lag() == 0,
                       what=f"[{sid}] catch-up")
    return nodes, hub


def searchers_ready(leader, want):
    routing = leader.coordinator.state().routing.get("tier", [])
    return bool(routing) and all(
        len(e.get("search_replicas") or []) >= want
        and set(e.get("search_replicas") or [])
        == set(e.get("search_in_sync") or []) for e in routing)


def stop_all(nodes):
    for n in list(nodes.values()):
        n.stop()


def searcher_docs(node, index="tier"):
    return sum(e.doc_count() for e in node.indices[index].shards)


def test_searcher_installs_published_checkpoints(tmp_path):
    nodes, _ = build_cluster(str(tmp_path), docs=30)
    try:
        s0 = nodes["s0"]
        assert searcher_docs(s0) == 30
        assert s0.search_lag() == 0
        # searches from the searcher serve LOCALLY (tier offload)
        resp = s0.search("tier", {"query": {"match": {"body": "hello"}},
                                  "size": 5})
        assert resp["_shards"]["failed"] == 0
        assert resp["hits"]["total"]["value"] == 30
        # deletes travel with the checkpoint
        nodes["n0"].delete_doc("tier", "0")
        nodes["n0"].refresh("tier")
        wait_until(lambda: searcher_docs(s0) == 29,
                   what="delete visible on the searcher")
        # cat_shards reports the search tier with its lag
        srows = [r for r in nodes["n0"].cat_shards()
                 if r["prirep"] == "s"]
        assert len(srows) == 2
        assert all(r["state"] == "STARTED" for r in srows)
        assert all(r["node"] == "s0" for r in srows)
        # the searcher's own tier stats
        st = s0.search_tier_stats()
        assert st["max_lag"] == 0
        assert st["segrep"]["installs"] > 0
        assert st["file_cache"]["entries"] > 0
    finally:
        stop_all(nodes)


def test_searcher_rejects_writes(tmp_path):
    nodes, _ = build_cluster(str(tmp_path), docs=5)
    try:
        s0 = nodes["s0"]
        # engine-level guard (bulk/index/translog chokepoint)
        engine = s0.indices["tier"].shards[0]
        assert engine.search_only
        with pytest.raises(OpenSearchTpuError):
            engine.index("x", {"body": "nope"})
        with pytest.raises(OpenSearchTpuError):
            engine.delete("0")
        with pytest.raises(OpenSearchTpuError):
            engine.apply_replica_op({"op": "index", "id": "x",
                                     "source": {}, "seq_no": 99,
                                     "version": 1, "primary_term": 1})
        # transport-level rejection: a misrouted write action fails
        # loud with the role verdict
        from opensearch_tpu.cluster.node import A_WRITE_SHARD
        with pytest.raises(OpenSearchTpuError, match="search"):
            nodes["n1"].transport.send_request(
                "s0", A_WRITE_SHARD,
                {"index": "tier", "shard": 0, "op": "index", "id": "y",
                 "source": {"body": "z"}}, timeout=5.0)
    finally:
        stop_all(nodes)


def test_scale_search_replicas_live(tmp_path):
    nodes, _ = build_cluster(str(tmp_path), searchers=("s0", "s1"),
                             search_replicas=1, docs=12)
    try:
        leader = nodes["n0"]
        for e in leader.coordinator.state().routing["tier"]:
            assert len(e["search_replicas"]) == 1
        # scale UP live: the new slots refill from the remote store
        leader.update_index_settings(
            "tier", {"number_of_search_replicas": 2})
        wait_until(lambda: searchers_ready(leader, 2),
                   what="scale-up refill")
        for sid in ("s0", "s1"):
            wait_until(lambda s=sid: searcher_docs(nodes[s]) == 12,
                       what=f"[{sid}] docs after scale-up")
        # scale DOWN live: slots trim on the next applied state
        leader.update_index_settings(
            "tier", {"number_of_search_replicas": 1})
        wait_until(lambda: all(
            len(e["search_replicas"]) == 1
            for e in leader.coordinator.state().routing["tier"]),
            what="scale-down trim")
        # number_of_shards stays immutable
        with pytest.raises(OpenSearchTpuError):
            leader.update_index_settings("tier",
                                         {"number_of_shards": 4})
    finally:
        stop_all(nodes)


def test_corrupt_remote_blob_refetched_and_marked(tmp_path):
    nodes, _ = build_cluster(str(tmp_path), docs=8)
    try:
        s0 = nodes["s0"]
        before = metrics().counter("segrep.corrupt_blobs").value
        # a repository serving bytes that do not match the checkpoint
        # CRC: the blob is dropped from the cache, re-fetched once, and
        # only a repeat failure raises (counted both times)
        s0.remote_store.blobs.write_blob("deadbeef", b"garbage")
        with pytest.raises(OpenSearchTpuError, match="CRC"):
            s0._fetch_blob_verified({"name": "seg_x.npz",
                                     "blob": "deadbeef", "crc32": 1234})
        assert metrics().counter("segrep.corrupt_blobs").value \
            == before + 2          # first mismatch + post-refetch
        # ...and a repaired repository heals on the next fetch: the bad
        # cache entry was invalidated, so the good bytes come through
        import zlib as _zlib
        good = b"repaired"
        s0.remote_store.blobs.write_blob("deadbeef", good)
        ok = s0._fetch_blob_verified({
            "name": "seg_x.npz", "blob": "deadbeef",
            "crc32": _zlib.crc32(good) & 0xFFFFFFFF})
        assert ok == good
    finally:
        stop_all(nodes)


def test_lagging_searcher_deranked_and_recovers():
    collector = rc.ResponseCollectorService(clock=lambda: 100.0)
    rc.SEARCH_MAX_LAG = 8
    # evidence for all copies so ranks exist
    for n in ("s0", "d0", "d1"):
        collector.record_response(n, 1e6, {"queue_size": 0,
                                           "service_time_ewma_nanos": 1e6})
    collector.record_ping_load("s0", {"search_lag": 50})
    assert collector.lagging("s0")
    ordered, _ = collector.rank_copies(["s0", "d0", "d1"])
    assert ordered[-1] == "s0"      # deranked like duress, retained
    stats = collector.stats()
    assert stats["s0"]["search_lag"] == 50
    assert stats["s0"]["search_lagging"] is True
    # the lag flag heals on the next piggybacked snapshot
    collector.record_ping_load("s0", {"search_lag": 0})
    assert not collector.lagging("s0")
    ordered, _ = collector.rank_copies(["s0", "d0", "d1"])
    assert ordered[0] == "s0"


def test_copy_candidates_prefer_ready_searchers(tmp_path):
    nodes, _ = build_cluster(str(tmp_path), docs=6)
    try:
        n1 = nodes["n1"]
        entry = n1.coordinator.state().routing["tier"][0]
        cands = n1._copy_candidates(entry)
        # the ready searcher leads (tier offload); write copies remain
        # as fallback so a dead tier degrades instead of failing
        assert cands[0] == "s0"
        assert set(cands) >= {"s0", entry["primary"]}
        # a searcher past the lag bound falls to last resort
        n1.response_collector.record_ping_load("s0", {"search_lag": 999})
        cands = n1._copy_candidates(entry)
        assert cands[0] != "s0" and "s0" in cands
    finally:
        stop_all(nodes)


# -- the acceptance bar -----------------------------------------------------

def _run_mixed_op(client, op):
    if op["op"] in ("search", "agg"):
        return client.search("tier", dict(op["body"]))
    if op["op"] == "msearch":
        return client.msearch("tier",
                              [dict(b) for b in op["bodies"]])
    if op["op"] == "bulk":
        for doc_id, source in op["docs"]:
            client.index_doc("tier", doc_id, source)
        if op.get("delete"):
            client.delete_doc("tier", op["delete"])
        if op.get("refresh"):
            client.refresh("tier")
        return None
    if op["op"] == "scroll":
        return client.search("tier", {"query": {"match_all": {}},
                                      "size": op["page_size"],
                                      "sort": [{"v": "asc"}]})
    raise AssertionError(op["op"])


def _evict_via_checks(nodes, leader, victim):
    retries = nodes[leader].coordinator.follower_checker.settings.retries

    def gone():
        for _ in range(retries + 1):
            nodes[leader].coordinator.run_checks_once()
        return victim not in nodes[leader].coordinator.state().nodes
    wait_until(gone, timeout=20.0, what=f"eviction of [{victim}]")


def _tier_docs(node, index="tier"):
    """Live (shard, id, source) set straight from the node's engines —
    the parity probe that bypasses routing entirely."""
    out = set()
    for sid, eng in sorted(node.indices[index].local_shards.items()):
        for seg in eng.acquire_searcher().segments:
            for doc_id, local in seg.id_to_local.items():
                if seg.live[local]:
                    out.add((sid, doc_id,
                             json.dumps(seg.source(local),
                                        sort_keys=True)))
    return out


def test_acceptance_searcher_churn_and_primary_failover(tmp_path):
    """ISSUE 13 acceptance: 3-node cluster + 2 search replicas under
    the mixed workload — kill a searcher mid-traffic, add a fresh one,
    and separately kill a primary-holding data node; zero
    primary-directed RPCs during searcher recovery (transport
    accounting), searchers keep serving within the lag bound during
    primary failover, and post-drain doc-count+checksum parity between
    every primary and every searcher."""
    nodes, hub = build_cluster(str(tmp_path), searchers=("s0", "s1"),
                               docs=24)
    leader = "n0"
    client = nodes["n0"]
    workload = MixedWorkload(SoakConfig(seed=1301, n_ops=36))
    ops = workload.ops()
    fresh = None
    try:
        for i, op in enumerate(ops):
            if i == 8:
                # kill a searcher mid-traffic
                nodes["s0"].stop()
                nodes.pop("s0")
                _evict_via_checks(nodes, leader, "s0")
            if i == 16:
                # add a FRESH searcher: recovery is pure cache refill
                svc = TransportService("s2", LocalTransport(hub))
                fresh = ClusterNode(
                    "s2", os.path.join(str(tmp_path), "s2"), svc,
                    ["n0", "n1", "n2"], roles=("search",),
                    remote_store_path=os.path.join(str(tmp_path),
                                                   "remote"))
                fresh.search_rpc_timeout = 2.0
                nodes["s2"] = fresh
                nodes[leader].coordinator.add_node(
                    "s2", {"name": "s2", "roles": ["search"],
                           "master_eligible": False})
                wait_until(lambda: searchers_ready(nodes[leader], 2),
                           timeout=30.0, what="fresh searcher refill")
                # ZERO primary-directed recovery RPCs: the searcher
                # never asked any node for segments or recovery
                assert fresh.transport.requests_sent(
                    action=A_START_RECOVERY) == 0
                assert fresh.transport.requests_sent(
                    action=A_FETCH_SEGMENTS) == 0
                assert fresh.transport.requests_sent(
                    action=A_PUBLISH_SEARCH_CKPT) == 0
            if i == 24:
                # separately: kill a primary-holding data node (not the
                # leader/client) and let failover run
                routing = nodes[leader].coordinator.state() \
                    .routing["tier"]
                victim = next(e["primary"] for e in routing
                              if e["primary"] != leader)
                nodes[victim].stop()
                nodes.pop(victim)
                # searchers keep serving DURING the failover window,
                # within the lag bound
                resp = nodes["s1"].search(
                    "tier", {"query": {"match_all": {}}, "size": 3})
                assert resp["_shards"]["failed"] == 0
                assert nodes["s1"].search_lag() <= rc.SEARCH_MAX_LAG
                _evict_via_checks(nodes, leader, victim)
            try:
                _run_mixed_op(client, op)
            except OpenSearchTpuError as exc:
                # allowed degradation classes only (429 / transient
                # transport); anything else fails the acceptance
                assert getattr(exc, "status", 0) in (429, 503), exc
        # drain: converge the tier, then byte-level parity
        def caught_up():
            client.refresh("tier")
            state = nodes[leader].coordinator.state()
            for s, e in enumerate(state.routing["tier"]):
                eng = nodes[e["primary"]].indices["tier"].engine_for(s)
                for r in e.get("search_replicas") or []:
                    if r not in nodes or nodes[r].search_installed_seq(
                            "tier", s) < eng._seq_no:
                        return False
            return True
        wait_until(caught_up, timeout=30.0, what="post-drain catch-up")
        state = nodes[leader].coordinator.state()
        primary_docs = set()
        for s, e in enumerate(state.routing["tier"]):
            primary_docs |= {
                d for d in _tier_docs(nodes[e["primary"]])
                if d[0] == s}
        assert primary_docs, "write tier lost its documents"
        for sid in ("s1", "s2"):
            assert _tier_docs(nodes[sid]) == primary_docs, \
                f"searcher [{sid}] diverged from the write tier"
    finally:
        stop_all(nodes)


# -- soak directives --------------------------------------------------------

def test_tier_schedule_is_seed_deterministic_with_directives():
    cfg = SoakConfig.tier(seed=77)
    s1 = FaultSchedule.generate(cfg)
    s2 = FaultSchedule.generate(SoakConfig.tier(seed=77))
    assert s1 == s2
    faults = [d["fault"] for d in s1]
    assert {"kill_searcher", "add_searcher", "stall_remote_store",
            "release_remote_store"} <= set(faults)
    # the legacy menu is untouched for non-tier configs
    base = FaultSchedule.generate(SoakConfig(seed=77))
    assert not {"kill_searcher", "add_searcher"} & {
        d["fault"] for d in base}
    # paired directives keep their order under the jitter
    by = {d["fault"]: d["step"] for d in s1}
    assert by["stall_remote_store"] <= by["release_remote_store"]
    assert by["kill_searcher"] <= by["add_searcher"]


def test_tier_soak_two_run_determinism(tmp_path):
    """Satellite: the deterministic two-run seed check extended to the
    searcher directive class — same seed, same schedule, same verdicts,
    clean SLOs, convergence across the rebalancing fleet."""
    r1 = SoakRunner(str(tmp_path / "a"),
                    SoakConfig.tier(seed=1302)).run()
    r2 = SoakRunner(str(tmp_path / "b"),
                    SoakConfig.tier(seed=1302)).run()
    assert r1["chaos"]["schedule"] == r2["chaos"]["schedule"]
    v1 = [(v["slo"], v["ok"]) for v in r1["verdicts"]]
    v2 = [(v["slo"], v["ok"]) for v in r2["verdicts"]]
    assert v1 == v2
    assert r1["slo_ok"] and r2["slo_ok"], (r1["verdicts"],
                                           r1["chaos"]["unexpected_errors"])
    applied = [d["fault"] for d in r1["chaos"]["applied"]]
    assert {"kill_searcher", "add_searcher",
            "stall_remote_store"} <= set(applied)
    assert r1["chaos"]["searcher_refills"] > 0
    assert r1["chaos"]["remote_bytes_pulled"] > 0
    assert r1["chaos"]["final_state"] == r2["chaos"]["final_state"]


# -- satellites -------------------------------------------------------------

def test_publish_ckpt_fetch_goes_through_retry(tmp_path):
    """Satellite 1: the replica's segment fetch retries transient drops
    under the configurable recovery budget and counts into
    retry.recovery.fetch.* instead of failing the install on one bare
    RPC."""
    from opensearch_tpu.cluster.node import A_PUBLISH_CKPT
    from opensearch_tpu.testing.fault_injection import FaultInjector
    hub = LocalTransport.Hub()
    nodes = {}
    for nid in ("n0", "n1"):
        svc = TransportService(nid, LocalTransport(hub))
        nodes[nid] = ClusterNode(nid, str(tmp_path / nid), svc,
                                 ["n0", "n1"])
        nodes[nid].recovery_timeout = 0.4
    try:
        assert nodes["n0"].start_election()
        nodes["n0"].create_index("logs", {
            "settings": {"number_of_shards": 1,
                         "number_of_replicas": 1},
            "mappings": {"properties": {"body": {"type": "text"}}}})
        wait_until(lambda: all(
            set(e["in_sync"]) == {"n0", "n1"}
            for e in nodes["n0"].coordinator.state().routing["logs"]),
            what="replica in-sync")
        before = metrics().counter(
            "retry.recovery.fetch.retries").value
        faults = FaultInjector(hub, seed=3)
        faults.drop(A_FETCH_SEGMENTS, times=1, silent=True)
        owner = nodes["n0"].coordinator.state() \
            .routing["logs"][0]["primary"]
        other = "n1" if owner == "n0" else "n0"
        nodes[owner].index_doc("logs", "1", {"body": "hello"})
        nodes[owner].refresh("logs")   # publish -> replica fetch (drop)
        # the replica's retried fetch runs async of the publish RPC:
        # wait for the retry counter AND the recovered install
        wait_until(lambda: metrics().counter(
            "retry.recovery.fetch.retries").value > before,
            what="retried segment fetch")
        wait_until(lambda: nodes[other].indices["logs"]
                   .shards[0].doc_count() == 1,
                   what="replica installed after retried fetch")
        faults.clear()
    finally:
        stop_all(nodes)


def test_max_lag_dynamic_setting(tmp_path):
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "node"), port=0)
    try:
        assert rc.SEARCH_MAX_LAG == 8
        node.update_cluster_settings(
            transient={"search.replication.max_lag": 3})
        assert rc.SEARCH_MAX_LAG == 3
        node.update_cluster_settings(
            transient={"search.replication.max_lag": None})
        assert rc.SEARCH_MAX_LAG == 8
    finally:
        node.stop()


def test_nodes_stats_surfaces_filecache_and_segrep(tmp_path):
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "node"), port=0)
    try:
        status, resp = node.rest.dispatch("GET", "/_nodes/stats", {},
                                          None)
        assert status == 200
        stats = resp["nodes"][node.node_id]
        fc = stats["file_cache"]
        # satellite 2: mount/refill pressure is observable
        assert {"pinned_bytes", "pinned_entries", "in_flight"} <= set(fc)
        rec = stats["recovery"]
        assert "fetch" in rec["retries"]
        assert {"publishes", "installs", "bytes_pulled",
                "corrupt_blobs", "refills"} <= set(
            rec["segment_replication"])
    finally:
        node.stop()


def test_transport_request_accounting():
    hub = LocalTransport.Hub()
    a = TransportService("a", LocalTransport(hub))
    b = TransportService("b", LocalTransport(hub))
    b.register_handler("x:action", lambda p: {"ok": True})
    try:
        a.send_request("b", "x:action", {}, timeout=5.0)
        a.send_request("b", "x:action", {}, timeout=5.0)
        assert a.requests_sent(action="x:action", target="b") == 2
        assert a.requests_sent(action="x:action") == 2
        assert a.requests_sent(target="nope") == 0
        assert a.requests_sent(action="x:") == 2   # prefix match
    finally:
        a.close()
        b.close()


# -- bench phase ------------------------------------------------------------

def test_bench_tier_phase_emits_line(tmp_path, monkeypatch):
    import importlib.util
    phases = tmp_path / "phases.jsonl"
    monkeypatch.setenv("OSTPU_BENCH_PHASES", str(phases))
    monkeypatch.setenv("OSTPU_BENCH_TIER_DOCS", "200")
    spec = importlib.util.spec_from_file_location("bench_under_test",
                                                  REPO + "/bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    data = bench.run_tier_phase("cpu")
    assert data["docs"] == 200
    assert data["refill_ms"] > 0
    assert data["remote_bytes_per_recovery"] > 0
    assert data["recovery_primary_rpcs"] == 0
    line = json.loads(phases.read_text().splitlines()[-1])
    assert line["phase"] == "tier"
    assert {"searcher_lag_p99_ops", "refill_ms",
            "remote_bytes_per_recovery"} <= set(line)


# -- lint -------------------------------------------------------------------

def test_write_isolation_lint_repo_clean():
    proc = subprocess.run([sys.executable, LINT, REPO],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_write_isolation_lint_catches_violations(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location("wlint", LINT)
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    bad = tmp_path / "bad_cluster.py"
    bad.write_text(
        "def setup(t, self):\n"
        "    t.register_handler(A_REPLICATE_OP, self._h)\n")
    problems = lint.check_cluster_file(str(bad))
    assert len(problems) == 1 and "role-gated" in problems[0]
    ok = tmp_path / "ok_cluster.py"
    ok.write_text(
        "def setup(t, self):\n"
        "    # searcher-ok: test fixture\n"
        "    t.register_handler(A_WRITE_SHARD, self._h)\n")
    assert lint.check_cluster_file(str(ok)) == []
    # engine guard check: a write entry without _ensure_writeable fails
    eng = tmp_path / "engine.py"
    eng.write_text(
        "class E:\n"
        "    def index(self, doc_id):\n"
        "        return doc_id\n")
    problems = lint.check_engine_guards(str(eng))
    assert problems and "_ensure_writeable" in problems[0]
