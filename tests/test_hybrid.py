"""Hybrid BM25+kNN with score normalization (BASELINE config #4;
VERDICT r3 item 9; ref search/pipeline/SearchPipelineService.java:1 +
the neural-search normalization processor)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from opensearch_tpu.node import Node
from opensearch_tpu.search.pipeline import (NormalizationConfig,
                                            combine_scores,
                                            normalize_scores)

DIM = 8


@pytest.fixture()
def node(tmp_path):
    n = Node(str(tmp_path / "node"), port=0).start()
    rng = np.random.default_rng(11)
    call(n, "PUT", "/hyb", {"mappings": {"properties": {
        "text": {"type": "text"},
        "vec": {"type": "knn_vector", "dimension": DIM,
                "space_type": "l2"}}}})
    vecs = rng.normal(size=(20, DIM)).astype(np.float32)
    words = ["alpha", "beta", "gamma"]
    for i in range(20):
        call(n, "PUT", f"/hyb/_doc/{i}", {
            "text": f"{words[i % 3]} common token{i}",
            "vec": vecs[i].tolist()})
    call(n, "POST", "/hyb/_refresh")
    n._test_vecs = vecs
    yield n
    n.stop()


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


def test_normalize_and_combine_units():
    s = np.asarray([1.0, 3.0, 5.0])
    assert normalize_scores(s, "min_max").tolist() == [0.0, 0.5, 1.0]
    l2 = normalize_scores(s, "l2")
    assert l2 @ l2 * (s @ s) == pytest.approx((s @ s))
    assert normalize_scores(np.asarray([2.0, 2.0]),
                            "min_max").tolist() == [1.0, 1.0]
    assert combine_scores([0.4, 0.8], [1, 1], "arithmetic_mean") == \
        pytest.approx(0.6)
    assert combine_scores([0.4, 0.8], [3, 1],
                          "arithmetic_mean") == pytest.approx(0.5)
    assert combine_scores([0.0, 0.8], [1, 1],
                          "geometric_mean") == pytest.approx(0.8)
    assert combine_scores([0.5, 0.0], [1, 1],
                          "harmonic_mean") == pytest.approx(0.5)


def test_hybrid_deterministic_normalized_scores(node):
    """min_max + arithmetic_mean over a BM25 and a knn sub-query must be
    reproducible from the two sub-searches run independently."""
    qv = node._test_vecs[4].tolist()
    hybrid_body = {"query": {"hybrid": {"queries": [
        {"match": {"text": "alpha"}},
        {"knn": {"vec": {"vector": qv, "k": 10}}},
    ]}}, "size": 10}
    code, hresp = call(node, "POST", "/hyb/_search", hybrid_body)
    assert code == 200
    hybrid_scores = {h["_id"]: h["_score"] for h in hresp["hits"]["hits"]}
    assert hybrid_scores

    # oracle: run the two sub-queries, min_max each, arithmetic-mean
    _, bm = call(node, "POST", "/hyb/_search",
                 {"query": {"match": {"text": "alpha"}}, "size": 10})
    _, kn = call(node, "POST", "/hyb/_search",
                 {"query": {"knn": {"vec": {"vector": qv, "k": 10}}},
                  "size": 10})

    def mm(resp):
        hits = resp["hits"]["hits"]
        sc = np.asarray([h["_score"] for h in hits])
        norm = normalize_scores(sc, "min_max")
        return {h["_id"]: float(n) for h, n in zip(hits, norm)}

    n1, n2 = mm(bm), mm(kn)
    for did, score in hybrid_scores.items():
        want = (n1.get(did, 0.0) + n2.get(did, 0.0)) / 2.0
        assert score == pytest.approx(want, rel=1e-6), did
    # the top hybrid doc must satisfy BOTH signals better than a
    # BM25-only loser: every doc in the hybrid top beats docs absent
    # from both sub-query tops (trivially, they weren't returned)
    assert hresp["hits"]["max_score"] == max(hybrid_scores.values())


def test_hybrid_with_named_pipeline_weights(node):
    code, _ = call(node, "PUT", "/_search/pipeline/nlp", {
        "phase_results_processors": [{"normalization-processor": {
            "normalization": {"technique": "l2"},
            "combination": {"technique": "arithmetic_mean",
                            "parameters": {"weights": [0.3, 0.7]}}}}]})
    assert code == 200
    qv = node._test_vecs[2].tolist()
    code, resp = call(node, "POST",
                      "/hyb/_search?search_pipeline=nlp",
                      {"query": {"hybrid": {"queries": [
                          {"match": {"text": "beta"}},
                          {"knn": {"vec": {"vector": qv, "k": 5}}}]}},
                       "size": 5})
    assert code == 200 and resp["hits"]["hits"]
    # pipeline CRUD surface
    code, resp = call(node, "GET", "/_search/pipeline/nlp")
    assert code == 200 and "nlp" in resp
    code, resp = call(node, "DELETE", "/_search/pipeline/nlp")
    assert code == 200
    code, resp = call(node, "GET", "/_search/pipeline/nlp")
    assert code == 404
    code, resp = call(node, "GET", "/hyb/_search?search_pipeline=nlp")
    assert code == 404                     # vanished pipeline -> error


def test_hybrid_rejects_sort_aggs_and_bad_pipeline(node):
    body = {"query": {"hybrid": {"queries": [{"match_all": {}}]}},
            "sort": [{"_score": "desc"}]}
    code, _ = call(node, "POST", "/hyb/_search", body)
    assert code == 400
    code, _ = call(node, "PUT", "/_search/pipeline/bad", {
        "phase_results_processors": [{"normalization-processor": {
            "normalization": {"technique": "softmax"}}}]})
    assert code == 400
    code, _ = call(node, "PUT", "/_search/pipeline/bad2", {
        "phase_results_processors": [{"not-a-processor": {}}]})
    assert code == 400
