"""Replication safety (PR 19): primary-term fencing of the whole write
transport surface, global-checkpoint tracking + promotion resync and
divergence rollback, and the acked-write durability audit
(testing/history.py) that turns Jepsen-style history checking into soak
SLO verdicts — plus the REST/client optimistic-concurrency 409 surface
and the tier-1 ``check_term_fencing`` lint."""

import json
import subprocess
import sys
import time

import pytest

from opensearch_tpu.client import ConflictError, OpenSearch
from opensearch_tpu.cluster.node import ClusterNode
from opensearch_tpu.common.errors import (PrimaryFencedError,
                                          VersionConflictError)
from opensearch_tpu.common.telemetry import metrics
from opensearch_tpu.index.engine import InternalEngine
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.node import Node
from opensearch_tpu.testing.history import (DurabilityChecker,
                                            HistoryRecorder, canonical)
from opensearch_tpu.testing.workload import SoakConfig, SoakRunner
from opensearch_tpu.transport.service import (LocalTransport,
                                              TransportService)

REPO = __file__.rsplit("/tests/", 1)[0]
TOOLS = REPO + "/tools"

MAPPING = {"properties": {"body": {"type": "text"},
                          "n": {"type": "long"}}}


def new_engine(path):
    return InternalEngine(str(path), DocumentMapper(MAPPING),
                          index_name="idx")


def wait_until(pred, timeout=8.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:   # deadline-bounded poll
        if pred():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def cluster(tmp_path):
    hub = LocalTransport.Hub()
    ids = ["n0", "n1", "n2"]
    nodes = {}
    for nid in ids:
        svc = TransportService(nid, LocalTransport(hub))
        nodes[nid] = ClusterNode(nid, str(tmp_path / nid), svc, ids)
    assert nodes["n0"].start_election()
    wait_until(lambda: all(
        nodes[i].coordinator.state().master_node == "n0" for i in ids))
    yield hub, ids, nodes
    for n in nodes.values():
        n.stop()


# -- engine-level fencing, rollback, digests -------------------------------

def test_apply_replica_op_fences_stale_primary_term(tmp_path):
    """The engine-level fence: an op stamped below the engine's current
    primary term is rejected (the deposed-primary signature); the
    ``fence=False`` bypass exists ONLY for promotion-resync replay,
    where ops legitimately keep their original (older) terms."""
    prim = new_engine(tmp_path / "p")
    prim.index("1", {"body": "a", "n": 1})
    prim.index("2", {"body": "b", "n": 2})
    ops = prim.ops_since(-1)
    assert [o["seq_no"] for o in ops] == [0, 1]

    rep = new_engine(tmp_path / "r")
    newer = dict(ops[0], primary_term=2)
    rep.apply_replica_op(newer)
    assert rep.primary_term == 2          # term advances with the op
    stale = dict(ops[1], primary_term=1)
    with pytest.raises(VersionConflictError):
        rep.apply_replica_op(stale)
    # resync replay: same op is legal when the transport handler already
    # validated the resync's term
    rep.apply_replica_op(stale, fence=False)
    assert rep.primary_term == 2          # never moves backwards
    prim.close()
    rep.close()


def test_local_checkpoint_advances_contiguously(tmp_path):
    """Local checkpoint = highest seq below which NO gaps exist — an
    out-of-order replica apply parks it until the hole fills (the
    LocalCheckpointTracker contract the global checkpoint builds on)."""
    prim = new_engine(tmp_path / "p")
    for i in range(3):
        prim.index(str(i), {"body": f"d{i}", "n": i})
    ops = prim.ops_since(-1)
    rep = new_engine(tmp_path / "r")
    rep.apply_replica_op(ops[0])
    rep.apply_replica_op(ops[2])          # gap at seq 1
    assert rep.local_checkpoint == 0
    rep.apply_replica_op(ops[1])          # hole filled
    assert rep.local_checkpoint == 2
    prim.close()
    rep.close()


def test_rollback_above_discards_divergence_durably(tmp_path):
    """``rollback_above`` (the deposed copy's divergence discard): ops
    above the global checkpoint vanish, the newest RETAINED op per
    affected doc is re-exposed, and the trim survives restart via the
    translog trim marker."""
    path = tmp_path / "e"
    eng = new_engine(path)
    eng.index("1", {"body": "keep", "n": 1})       # seq 0
    eng.index("2", {"body": "keep", "n": 2})       # seq 1
    eng.index("1", {"body": "divergent", "n": 9})  # seq 2
    eng.index("3", {"body": "divergent", "n": 3})  # seq 3
    dropped = eng.rollback_above(1)
    assert dropped == 2
    assert eng.get("1")["_source"]["n"] == 1       # retained op re-wins
    assert eng.get("3") is None                    # divergent doc gone
    d = eng.replication_digest()
    assert max(row[0] for row in d["docs"].values()) <= 1
    eng.close()
    # the trim marker is durable: replaying the translog after restart
    # must NOT resurrect the rolled-back ops
    eng2 = new_engine(path)
    assert eng2.get("1")["_source"]["n"] == 1
    assert eng2.get("3") is None
    eng2.close()


def test_replication_digest_copy_parity(tmp_path):
    """Two copies that applied the same ops produce the identical
    term-aware digest; the termless ``seq_digest`` is what the
    (term-agnostic) search tier is compared against."""
    prim = new_engine(tmp_path / "p")
    for i in range(5):
        prim.index(str(i), {"body": f"d{i}", "n": i})
    prim.delete("3")
    rep = new_engine(tmp_path / "r")
    for op in prim.ops_since(-1):
        rep.apply_replica_op(op)
    dp, dr = prim.replication_digest(), rep.replication_digest()
    assert dp["digest"] == dr["digest"]
    assert dp["seq_digest"] == dr["seq_digest"]
    assert dp["doc_count"] == dr["doc_count"] == 4
    prim.close()
    rep.close()


# -- the durability audit (testing/history.py) -----------------------------

def _acked_index(hist, doc_id, src, seq, term=1, version=1):
    op_id = hist.invoke("index", doc_id, src)
    hist.ok(op_id, {"_seq_no": seq, "_primary_term": term,
                    "_version": version})
    return op_id


def test_history_green_path_passes():
    hist = HistoryRecorder()
    _acked_index(hist, "a", {"n": 1}, seq=0)
    _acked_index(hist, "a", {"n": 2}, seq=1, version=2)
    op = hist.invoke("delete", "b")
    hist.ok(op, {"_seq_no": 2, "_primary_term": 1})
    op = hist.invoke("index", "c", {"n": 3})
    hist.unknown(op, "timeout")            # either final state is legal
    report = DurabilityChecker(hist).check({"a": {"n": 2}})
    assert report["ok"], report
    assert report["checked_ops"] == 4
    assert report["outcomes"]["ok"] == 3


def test_checker_catches_lost_acked_write():
    hist = HistoryRecorder()
    _acked_index(hist, "a", {"n": 1}, seq=0)
    report = DurabilityChecker(hist).check({})     # acked doc vanished
    assert not report["ok"]
    assert report["lost_acked_writes"][0]["doc_id"] == "a"
    assert report["lost_acked_writes"][0]["acked"] == \
        canonical({"n": 1})
    # ...but a LATER unknown-outcome op un-pins the final state: the
    # lost-write claim must not fire when a racing op may have deleted it
    hist2 = HistoryRecorder()
    _acked_index(hist2, "a", {"n": 1}, seq=0)
    op = hist2.invoke("delete", "a")
    hist2.unknown(op, "partition")
    assert DurabilityChecker(hist2).check({})["ok"]


def test_checker_catches_stale_ack():
    """Content only ever written by DEFINITE failures (the fenced
    deposed-primary writes) becoming visible is the stale-ack bug."""
    hist = HistoryRecorder()
    op = hist.invoke("index", "a", {"n": 666})
    hist.fail(op, "fenced")
    report = DurabilityChecker(hist).check({"a": {"n": 666}})
    assert not report["ok"]
    assert report["stale_acks"][0]["doc_id"] == "a"


def test_checker_catches_term_seq_regression():
    hist = HistoryRecorder()
    _acked_index(hist, "a", {"n": 1}, seq=5, term=2)
    # settled strictly before the next invoke, yet acked BEHIND it
    _acked_index(hist, "a", {"n": 2}, seq=3, term=1, version=2)
    report = DurabilityChecker(hist).check({"a": {"n": 2}})
    assert report["monotonicity_violations"], report
    assert not report["ok"]


def test_checker_catches_cross_copy_conflict():
    """Two copies serving the same (seq, term) with different bytes is
    the split-brain divergence signature fencing exists to prevent."""
    hist = HistoryRecorder()
    _acked_index(hist, "a", {"n": 1}, seq=0)
    report = DurabilityChecker(hist).check(
        {"a": {"n": 1}},
        copy_digests=[("n0/s0", {"a": [0, 1, 1, 111]}),
                      ("n1/s0", {"a": [0, 1, 1, 222]})])
    assert report["copy_conflicts"][0]["doc_id"] == "a"
    assert not report["ok"]


def test_open_intervals_settle_unknown_never_dropped():
    hist = HistoryRecorder()
    hist.invoke("index", "a", {"n": 1})    # worker died mid-flight
    hist.settle_open_as_unknown("drain")
    counts = hist.counts()
    assert counts == {"ok": 0, "fail": 0, "unknown": 1, "total": 1}
    assert DurabilityChecker(hist).check({})["ok"]


# -- cluster-level fencing + deposed-primary failover ----------------------

def test_deposed_primary_promotes_and_fences_old_lineage(cluster):
    """The deposed-primary flow end to end: a ``deposed`` fail-copy
    promotes an in-sync replica under a bumped term (old copy keeps an
    OUT-of-sync slot), replication ops stamped with the old term are
    fenced with a counted rejection, and a non-primary asked to execute
    a primary write refuses with the retryable 503 instead of acking."""
    hub, ids, nodes = cluster
    nodes["n0"].create_index("fence", {"settings": {
        "number_of_shards": 3, "number_of_replicas": 1}})
    wait_until(lambda: all("fence" in nodes[i].indices for i in ids))
    for i in range(12):
        nodes[ids[i % 3]].index_doc("fence", str(i),
                                    {"body": f"doc {i}", "n": i})

    def entry(shard):
        return nodes["n0"].coordinator.state().routing["fence"][shard]

    assert wait_until(lambda: all(
        set([entry(s)["primary"]] + entry(s)["replicas"])
        == set(entry(s)["in_sync"]) for s in range(3)))
    old_primary = entry(0)["primary"]
    old_term = int(entry(0).get("primary_term", 1))

    stale_before = metrics().counter(
        "replication.stale_primary_rejections").value
    nodes["n0"]._h_fail_copy({"index": "fence", "shard": 0,
                              "node": old_primary, "deposed": True})
    assert wait_until(lambda: entry(0)["primary"] != old_primary)
    e = entry(0)
    new_primary = e["primary"]
    assert int(e["primary_term"]) == old_term + 1
    assert old_primary in e["replicas"]    # deposed copy keeps a slot

    # a late replication op from the old lineage is fenced — rejected
    # loudly, counted, and never applied
    with pytest.raises(VersionConflictError):
        nodes[new_primary]._h_replicate_op({
            "index": "fence", "shard": 0,
            "rep_op": {"op": "index", "id": "stale-doc",
                       "source": {"body": "stale", "n": -1},
                       "seq_no": 999, "version": 1,
                       "primary_term": old_term}})
    assert metrics().counter(
        "replication.stale_primary_rejections").value > stale_before
    assert nodes[new_primary].get_doc("fence", "stale-doc") is None

    # a primary write landing on a copy that does NOT hold the primary
    # slot refuses before touching the engine — no false ack
    fenced_before = metrics().counter("replication.fenced_ops").value
    bystander = next(n for n in e["replicas"]
                     if "fence" in nodes[n].indices
                     and 0 in nodes[n].indices["fence"].local_shards)
    with pytest.raises(PrimaryFencedError):
        nodes[bystander]._h_write_shard({
            "index": "fence", "shard": 0, "op": "index",
            "id": "misrouted", "source": {"body": "x", "n": 0}})
    assert metrics().counter("replication.fenced_ops").value \
        == fenced_before + 1

    # the promoted lineage resyncs: the new primary's ENGINE term
    # catches up to the routing term and writes flow again
    def row():
        st = nodes[new_primary].replication_stats()
        return next(r for r in st["shards"]
                    if r["index"] == "fence" and r["shard"] == 0)
    assert wait_until(
        lambda: row()["engine_primary_term"] == old_term + 1)
    assert row()["role"] == "primary"
    r = nodes[new_primary].index_doc("fence", "post-failover",
                                     {"body": "alive", "n": 100})
    assert r["result"] == "created"
    assert r["_primary_term"] >= 1
    # the deposed copy recovers back into sync under the new term
    assert wait_until(lambda: old_primary in entry(0)["in_sync"],
                      timeout=20.0)


def test_nodes_stats_exposes_replication_block(tmp_path):
    """Single-node observability face: ``_nodes/stats`` carries the
    per-shard term/checkpoint positions and the replication.* counter
    family (same names the cluster nodes' ``replication_stats`` uses)."""
    node = Node(str(tmp_path / "node"), port=0)
    try:
        node.rest.dispatch("PUT", "/rsafe", {}, json.dumps(
            {"settings": {"number_of_shards": 1}}).encode())
        for i in range(3):
            node.rest.dispatch("PUT", f"/rsafe/_doc/{i}", {},
                               json.dumps({"n": i}).encode())
        status, resp = node.rest.dispatch("GET", "/_nodes/stats", {},
                                          None)
        assert status == 200
        block = resp["nodes"][node.node_id]["replication"]
        row = next(s for s in block["shards"] if s["index"] == "rsafe")
        assert row["primary_term"] >= 1
        assert row["max_seq_no"] == 2
        assert row["local_checkpoint"] == 2
        assert set(block["counters"]) == {
            "fenced_ops", "stale_primary_rejections", "rollbacks",
            "resyncs", "resync_failures", "durability_checked_ops"}
    finally:
        node.stop()


# -- the acceptance bar: deterministic split-brain under chaos -------------

def test_split_brain_directive_fences_and_loses_nothing(tmp_path):
    """The PR's acceptance test: a seeded ``isolate_primary_with_writes``
    directive manufactures split brain (partition the primary → writes
    into the cut → eviction + promotion under a bumped term → heal →
    writes through the deposed node's stale state).  Deterministic
    across two runs; the stale lineage is fenced (counters move, the
    old primary stops acking), and the durability audit proves zero
    lost acked writes and zero stale acks after the heal."""
    def cfg():
        return SoakConfig(seed=77, n_ops=24, schedule=[
            {"step": 6, "fault": "isolate_primary_with_writes",
             "writes": 2}])

    r1 = SoakRunner(str(tmp_path / "a"), cfg()).run()
    r2 = SoakRunner(str(tmp_path / "b"), cfg()).run()
    v1 = [(v["slo"], v["ok"]) for v in r1["verdicts"]]
    v2 = [(v["slo"], v["ok"]) for v in r2["verdicts"]]
    assert v1 == v2                        # seed-pure, replayable

    d = next(a for a in r1["chaos"]["applied"]
             if a["fault"] == "isolate_primary_with_writes")
    assert "skipped" not in d, d
    # the old primary STOPPED ACKING: its post-heal writes fenced into
    # definite failures instead of false acks
    assert d["fenced_writes"] > 0, d
    assert r1["chaos"]["fenced_ops"] > 0
    assert r1["chaos"]["stale_primary_rejections"] > 0

    dur = r1["chaos"]["durability"]
    assert dur["checked_ops"] > 0
    assert dur["lost_acked_writes"] == []
    assert dur["stale_acks"] == []
    assert dur["monotonicity_violations"] == []
    assert dur["copy_conflicts"] == []
    assert dur["ok"]
    # per-copy parity: primary/replica digests identical per shard
    assert r1["chaos"]["copy_parity"]["ok"], r1["chaos"]["copy_parity"]
    for slo in ("no_lost_acked_writes", "no_stale_acks", "copy_parity"):
        v = next(x for x in r1["verdicts"] if x["slo"] == slo)
        assert v["ok"], v
    assert r1["slo_ok"], r1["verdicts"]


# -- REST + client optimistic concurrency (if_seq_no/if_primary_term) ------

def test_occ_conflicts_over_rest_and_client(tmp_path):
    """End-to-end 409 surface: a stale ``if_seq_no``/``if_primary_term``
    on index AND delete returns ``version_conflict_engine_exception``
    over REST, the matching pair succeeds, and the bundled client maps
    the 409 to ``ConflictError`` with params passed through."""
    node = Node(str(tmp_path / "node"), port=0).start()
    client = OpenSearch(hosts=[{"host": "127.0.0.1",
                                "port": node.port}])
    try:
        r = client.index("occ", {"n": 1}, id="1")
        seq, term = r["_seq_no"], r["_primary_term"]
        with pytest.raises(ConflictError) as ei:
            client.index("occ", {"n": 2}, id="1",
                         params={"if_seq_no": 999,
                                 "if_primary_term": term})
        assert ei.value.status_code == 409
        assert ei.value.info["error"]["type"] == \
            "version_conflict_engine_exception"
        with pytest.raises(ConflictError):
            client.delete("occ", "1", params={"if_seq_no": seq,
                                              "if_primary_term": 99})
        assert client.get("occ", "1")["_source"] == {"n": 1}
        r2 = client.index("occ", {"n": 2}, id="1",
                          params={"if_seq_no": seq,
                                  "if_primary_term": term})
        assert r2["result"] == "updated" and r2["_seq_no"] > seq
        r3 = client.delete("occ", "1",
                           params={"if_seq_no": r2["_seq_no"],
                                   "if_primary_term":
                                       r2["_primary_term"]})
        assert r3["result"] == "deleted"
        # garbage OCC params are a typed 400, never a ValueError 500
        status, body = node.rest.dispatch(
            "PUT", "/occ/_doc/1", {"if_seq_no": "banana"},
            json.dumps({"n": 9}).encode())
        assert status == 400
        assert body["error"]["type"] == "illegal_argument_exception"
    finally:
        node.stop()


# -- tier-1 lint: every write handler must fence ---------------------------

def _run_lint(repo):
    return subprocess.run(
        [sys.executable, TOOLS + "/check_term_fencing.py", str(repo)],
        capture_output=True, text=True)


def test_term_fencing_lint_is_clean():
    r = _run_lint(REPO)
    assert r.returncode == 0, r.stdout + r.stderr


def test_term_fencing_lint_catches_unfenced_handler(tmp_path):
    """A write-action handler with no primary_term validation and no
    waiver must fail the lint; the explicit ``# fencing-ok (<why>)``
    annotation silences it."""
    pkg = tmp_path / "opensearch_tpu" / "cluster"
    pkg.mkdir(parents=True)
    unfenced = '''A_X = "indices:data/write/x"
WRITE_ACTIONS = (A_X,)


class N:
    def _register_write_handlers(self, t):
        write_handlers = {A_X: self._h_x}
        for a, h in write_handlers.items():
            t.register_handler(a, h)

    def _h_x(self, payload):
        return {"acknowledged": True}
'''
    (pkg / "node.py").write_text(unfenced)
    r = _run_lint(tmp_path)
    assert r.returncode == 1
    assert "_h_x" in r.stdout and "primary_term" in r.stdout

    (pkg / "node.py").write_text(unfenced.replace(
        "    def _h_x(self, payload):",
        "    # fencing-ok (test fixture: replies only, never applies)\n"
        "    def _h_x(self, payload):"))
    r = _run_lint(tmp_path)
    assert r.returncode == 0, r.stdout
