"""Python client (opensearch-py-compatible surface) against a live node."""

import pytest

from opensearch_tpu.client import (ConflictError, NotFoundError,
                                   OpenSearch, RequestError, helpers)
from opensearch_tpu.node import Node


@pytest.fixture()
def client(tmp_path):
    node = Node(str(tmp_path / "node"), port=0, path_repo=[str(tmp_path)]).start()
    yield OpenSearch(hosts=[{"host": "127.0.0.1", "port": node.port}])
    node.stop()


def test_crud_and_search(client):
    assert client.ping() and "version" in client.info()
    client.indices.create("idx", {"mappings": {"properties": {
        "t": {"type": "text"}, "n": {"type": "long"}}}})
    assert client.indices.exists("idx")
    r = client.index("idx", {"t": "hello world", "n": 1}, id="1")
    assert r["result"] == "created"
    client.index("idx", {"t": "goodbye world", "n": 2}, id="2",
                 params={"refresh": True})
    assert client.get("idx", "1")["_source"]["n"] == 1
    assert client.exists("idx", "1") and not client.exists("idx", "9")
    resp = client.search(index="idx", body={
        "query": {"match": {"t": "world"}}})
    assert resp["hits"]["total"]["value"] == 2
    assert client.count(index="idx")["count"] == 2
    client.delete("idx", "1")
    with pytest.raises(NotFoundError):
        client.get("idx", "1")


def test_exception_mapping(client):
    with pytest.raises(NotFoundError) as e:
        client.search(index="nope", body={})
    assert e.value.status_code == 404 and e.value.info["status"] == 404
    client.indices.create("e1")
    with pytest.raises(RequestError):
        client.search(index="e1", body={"query": {"bogus": {}}})
    client.index("e1", {"a": 1}, id="1")
    with pytest.raises(ConflictError):
        client.create("e1", "1", {"a": 2})


def test_bulk_helper_and_msearch(client):
    client.indices.create("b", {"mappings": {"properties": {
        "n": {"type": "long"}}}})
    ok, errors = helpers.bulk(client, [
        {"_index": "b", "_id": str(i), "n": i} for i in range(10)])
    assert ok == 10 and not errors
    client.indices.refresh("b")
    resp = client.msearch([
        {"index": "b"}, {"query": {"range": {"n": {"gte": 5}}}},
        {"index": "b"}, {"query": {"match_all": {}}, "size": 0}])
    assert resp["responses"][0]["hits"]["total"]["value"] == 5
    assert resp["responses"][1]["hits"]["total"]["value"] == 10
    # scroll through everything
    first = client.search(index="b", body={"size": 4},
                          params={"scroll": "1m"})
    seen = len(first["hits"]["hits"])
    sid = first["_scroll_id"]
    while True:
        page = client.scroll(sid, body={"scroll": "1m"})
        if not page["hits"]["hits"]:
            break
        seen += len(page["hits"]["hits"])
        sid = page["_scroll_id"]
    assert seen == 10
    client.clear_scroll(sid)


def test_namespaced_clients(client):
    assert client.cluster.health()["status"] in ("green", "yellow")
    client.indices.create("ns", {})
    client.index("ns", {"x": 1}, id="1", params={"refresh": True})
    assert any(r["index"] == "ns" for r in client.cat.indices())
    client.indices.update_aliases({"actions": [
        {"add": {"index": "ns", "alias": "ns-alias"}}]})
    assert client.search(index="ns-alias",
                         body={})["hits"]["total"]["value"] == 1
    client.cluster.put_settings({"persistent": {
        "search.max_buckets": 5000}})
    flat = str(client.cluster.get_settings())
    assert "5000" in flat
    stats = client.nodes.stats()
    assert "file_cache" in str(stats)


def test_snapshot_roundtrip_via_client(client, tmp_path):
    client.indices.create("s", {})
    client.index("s", {"v": 1}, id="1", params={"refresh": True})
    client.snapshot.create_repository("r", {
        "type": "fs",
        "settings": {"location": str(tmp_path / "repo")}})
    client.snapshot.create("r", "snap")
    client.indices.delete("s")
    client.snapshot.restore("r", "snap", {"indices": "s"})
    assert client.get("s", "1")["_source"]["v"] == 1
    client.indices.delete("s")
    client.snapshot.delete("r", "snap")


def test_connection_failover():
    from opensearch_tpu.client import ConnectionError as CErr
    c = OpenSearch(hosts=[{"host": "127.0.0.1", "port": 1}],
                   timeout=2)
    assert c.ping() is False
    with pytest.raises(CErr):
        c.info()
