"""Deterministic coordination simulation (VERDICT r4 item 9; the
DisruptableMockTransport / CoordinatorTests technique, SURVEY §4.3).

One seeded RNG drives the whole cluster from a single thread: every step
picks an action (election attempt, state update, partition, heal,
failure-detection round) and the in-process transport delivers messages
synchronously, so a seed fully determines the execution.  After every
step the Raft-style safety invariants are checked:

  S1  election safety — at most one leader per term;
  S2  state-machine safety — two nodes that committed the same
      (term, version) hold byte-identical states;
  S3  monotonicity — a node's committed (term, version) never goes
      backwards;
  S4  committed-state durability — a state committed by a quorum is
      never superseded by a lineage that drops it (the newest committed
      state across nodes descends from every older committed version).

Hundreds of seeds explore different partition/election interleavings —
races are found by enumeration, not wall-clock luck.
"""

import os
import random

import pytest

from opensearch_tpu.cluster.coordination import (Coordinator,
                                                 CoordinationError, Mode)
from opensearch_tpu.transport.service import (LocalTransport,
                                              NodeDisconnectedError,
                                              TransportService)

N_SEEDS = int(os.environ.get("OSTPU_SIM_SEEDS", 1000))
N_STEPS = 15


class Sim:
    def __init__(self, seed: int, n=3):
        self.rng = random.Random(seed)
        self.hub = LocalTransport.Hub()
        self.ids = [f"n{i}" for i in range(n)]
        self.cut: set = set()            # currently partitioned nodes

        def rule(src, dst, frame):
            if src in self.cut or dst in self.cut:
                raise NodeDisconnectedError(f"{src}->{dst} partitioned")
        self.hub.add_rule(rule)
        self.coords = {}
        for nid in self.ids:
            svc = TransportService(nid, LocalTransport(self.hub))
            self.coords[nid] = Coordinator(nid, svc, voting_nodes=self.ids,
                                           node_info={"name": nid},
                                           check_retries=1)
        # invariant bookkeeping
        self.leaders_by_term: dict = {}
        self.committed_payloads: dict = {}   # (term, version) -> payload
        self.last_committed: dict = {nid: (0, 0) for nid in self.ids}
        self.quorum_committed: set = set()   # (term, version) with quorum

    def close(self):
        for c in self.coords.values():
            c.stop()
            c.transport.close()

    # -- actions ----------------------------------------------------------

    def step(self):
        action = self.rng.choice(
            ["election", "election", "update", "update", "partition",
             "heal", "checks"])
        nid = self.rng.choice(self.ids)
        c = self.coords[nid]
        try:
            if action == "election" and nid not in self.cut:
                c.start_election()
            elif action == "update" and c.mode == Mode.LEADER \
                    and nid not in self.cut:
                marker = f"i{self.rng.randrange(1000)}"
                c.submit_state_update(lambda s: s.with_(
                    indices={**s.indices, marker: {"settings": {},
                                                   "mappings": {}}}))
            elif action == "partition" and len(self.cut) == 0:
                self.cut.add(nid)        # isolate one node at a time
            elif action == "heal":
                self.cut.clear()
            elif action == "checks" and nid not in self.cut:
                c.run_checks_once()
        except (CoordinationError, NodeDisconnectedError):
            pass                          # failures are part of the game

    # -- invariants --------------------------------------------------------

    def check(self, seed, step):
        leaders = [(c.current_term, nid) for nid, c in self.coords.items()
                   if c.mode == Mode.LEADER]
        for term, nid in leaders:
            prev = self.leaders_by_term.get(term)
            assert prev is None or prev == nid, (
                f"seed {seed} step {step}: TWO leaders in term {term}: "
                f"{prev} and {nid}")
            self.leaders_by_term[term] = nid
        committed_now = {}
        for nid, c in self.coords.items():
            st = c.state()
            key = (st.term, st.version)
            payload = st.to_payload()
            prev = self.committed_payloads.get(key)
            assert prev is None or prev == payload, (
                f"seed {seed} step {step}: divergent committed state "
                f"{key} on {nid}")
            self.committed_payloads[key] = payload
            assert key >= self.last_committed[nid], (
                f"seed {seed} step {step}: committed state went "
                f"backwards on {nid}: {self.last_committed[nid]} -> {key}")
            self.last_committed[nid] = key
            committed_now.setdefault(key, []).append(nid)
        majority = len(self.ids) // 2 + 1
        for key, holders in committed_now.items():
            if len(holders) >= majority and key > (0, 0):
                self.quorum_committed.add(key)

    def check_final(self, seed):
        """S4: the newest committed state's index set contains every
        marker that was in any quorum-committed predecessor (no silent
        rollback of committed data)."""
        newest_key = max((c.state().term, c.state().version)
                         for c in self.coords.values())
        newest = self.committed_payloads[newest_key]
        for key in self.quorum_committed:
            if key == newest_key:
                continue
            older = self.committed_payloads[key]
            missing = set(older["indices"]) - set(newest["indices"])
            assert not missing, (
                f"seed {seed}: quorum-committed indices {missing} from "
                f"{key} lost by {newest_key}")


@pytest.mark.parametrize("chunk", range(10))
def test_simulation_safety(chunk):
    per = N_SEEDS // 10
    for seed in range(chunk * per, (chunk + 1) * per):
        sim = Sim(seed)
        try:
            for step in range(N_STEPS):
                sim.step()
                sim.check(seed, step)
            sim.check_final(seed)
        finally:
            sim.close()
