"""Cross-cluster search: two live nodes, remote registered via affix
settings, 'alias:index' expressions fan out over HTTP and merge (ref
transport/RemoteClusterService.java, TransportSearchAction.java:440)."""

import json
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.node import Node


@pytest.fixture()
def two_nodes(tmp_path):
    a = Node(str(tmp_path / "a"), name="node-a", port=0).start()
    b = Node(str(tmp_path / "b"), name="node-b", port=0).start()
    yield a, b
    a.stop()
    b.stop()


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


def test_ccs_merges_local_and_remote(two_nodes):
    a, b = two_nodes
    call(a, "PUT", "/logs", {"mappings": {"properties": {
        "m": {"type": "text"}}}})
    call(a, "PUT", "/logs/_doc/a1", {"m": "common local event"})
    call(a, "POST", "/_refresh")
    call(b, "PUT", "/logs", {"mappings": {"properties": {
        "m": {"type": "text"}}}})
    call(b, "PUT", "/logs/_doc/b1", {"m": "common remote event"})
    call(b, "PUT", "/logs/_doc/b2", {"m": "unrelated words"})
    call(b, "POST", "/_refresh")

    code, _ = call(a, "PUT", "/_cluster/settings", {"persistent": {
        "cluster.remote": {"west": {
            "seeds": [f"127.0.0.1:{b.port}"]}}}})
    assert code == 200

    code, resp = call(a, "POST", "/logs,west:logs/_search",
                      {"query": {"match": {"m": "common"}}, "size": 10})
    assert code == 200
    assert resp["_clusters"]["total"] == 2
    got = {h["_index"]: h["_id"] for h in resp["hits"]["hits"]}
    assert got == {"logs": "a1", "west:logs": "b1"}
    assert resp["hits"]["total"]["value"] == 2

    # remote-only expression
    code, resp = call(a, "POST", "/west:logs/_search",
                      {"query": {"match_all": {}}, "size": 10})
    assert resp["hits"]["total"]["value"] == 2
    assert all(h["_index"].startswith("west:")
               for h in resp["hits"]["hits"])

    # remote index errors surface as 502-family errors, not hangs
    code, resp = call(a, "POST", "/west:nope/_search",
                      {"query": {"match_all": {}}})
    assert code == 502
    # unknown alias
    code, resp = call(a, "POST", "/east:logs/_search",
                      {"query": {"match_all": {}}})
    assert code == 400
    # aggs across clusters rejected loudly
    code, resp = call(a, "POST", "/logs,west:logs/_search",
                      {"size": 0, "aggs": {"x": {"terms": {
                          "field": "m"}}}})
    assert code == 400


def test_ccs_unreachable_seed_fails_over_then_errors(two_nodes):
    a, b = two_nodes
    call(b, "PUT", "/idx", {})
    call(b, "PUT", "/idx/_doc/1", {"x": 1})
    call(b, "POST", "/_refresh")
    # first seed dead, second alive -> fail over
    call(a, "PUT", "/_cluster/settings", {"persistent": {
        "cluster.remote": {"west": {"seeds": [
            "127.0.0.1:1", f"127.0.0.1:{b.port}"]}}}})
    code, resp = call(a, "POST", "/west:idx/_search",
                      {"query": {"match_all": {}}})
    assert code == 200 and resp["hits"]["total"]["value"] == 1
    # all seeds dead -> 502
    call(a, "PUT", "/_cluster/settings", {"persistent": {
        "cluster.remote": {"gone": {"seeds": ["127.0.0.1:1"]}}}})
    code, resp = call(a, "POST", "/gone:idx/_search",
                      {"query": {"match_all": {}}})
    assert code == 502
