"""Randomized property tests: device results vs an independent numpy
oracle on random corpora/queries (the randomized-testing harness SURVEY
§4 calls for; reproduce any failure with the printed OSTPU_TEST_SEED)."""

import math

import numpy as np
import pytest

from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher

K1, B = 1.2, 0.75
VOCAB = [f"w{i}" for i in range(40)]


def random_corpus(rng, n_docs, n_segments):
    mapper = DocumentMapper({"properties": {
        "t": {"type": "text"}, "n": {"type": "long"},
        "k": {"type": "keyword"}}})
    writer = SegmentWriter()
    docs = []
    for i in range(n_docs):
        words = rng.choice(VOCAB, size=rng.integers(1, 12)).tolist()
        docs.append({"t": " ".join(words),
                     "n": int(rng.integers(-50, 50)),
                     "k": str(rng.choice(["a", "b", "c", "d"]))})
    segs = []
    cuts = sorted(rng.choice(np.arange(1, n_docs),
                             size=n_segments - 1, replace=False).tolist()) \
        if n_segments > 1 else []
    bounds = [0, *cuts, n_docs]
    for si in range(n_segments):
        chunk = docs[bounds[si]: bounds[si + 1]]
        parsed = [mapper.parse(str(bounds[si] + j), d)
                  for j, d in enumerate(chunk)]
        segs.append(writer.build(parsed, f"r{si}"))
    return ShardSearcher(segs, mapper), docs


def oracle_bm25(docs, terms, k1=K1, b=B):
    """Scalar BM25 oracle (Lucene formula)."""
    N = sum(1 for d in docs if d["t"])
    avgdl = sum(len(d["t"].split()) for d in docs) / max(N, 1)
    scores = {}
    for term in terms:
        df = sum(1 for d in docs if term in d["t"].split())
        if df == 0:
            continue
        idf = math.log(1 + (N - df + 0.5) / (df + 0.5))
        for i, d in enumerate(docs):
            tf = d["t"].split().count(term)
            if tf == 0:
                continue
            dl = len(d["t"].split())
            scores[i] = scores.get(i, 0.0) + \
                idf * tf / (tf + k1 * (1 - b + b * dl / avgdl))
    return scores


@pytest.mark.parametrize("trial", range(3))
def test_random_match_queries_vs_oracle(random_rng, trial):
    rng = random_rng
    n_docs = int(rng.integers(20, 120))
    searcher, docs = random_corpus(rng, n_docs,
                                   int(rng.integers(1, 4)))
    for _ in range(5):
        terms = rng.choice(VOCAB,
                           size=rng.integers(1, 4), replace=False)
        resp = searcher.search({"query": {"match": {
            "t": " ".join(terms)}}, "size": n_docs})
        expected = oracle_bm25(docs, set(terms))
        got = {int(h["_id"]): h["_score"]
               for h in resp["hits"]["hits"]}
        assert set(got) == set(expected), terms
        for i, s in expected.items():
            assert got[i] == pytest.approx(s, rel=1e-4), (terms, i)


@pytest.mark.parametrize("trial", range(3))
def test_random_bool_filters_vs_oracle(random_rng, trial):
    rng = random_rng
    n_docs = int(rng.integers(20, 120))
    searcher, docs = random_corpus(rng, n_docs,
                                   int(rng.integers(1, 4)))
    for _ in range(5):
        lo = int(rng.integers(-50, 40))
        hi = lo + int(rng.integers(1, 40))
        kw = str(rng.choice(["a", "b", "c", "d"]))
        resp = searcher.search({"query": {"bool": {"filter": [
            {"range": {"n": {"gte": lo, "lt": hi}}},
            {"term": {"k": kw}}]}}, "size": n_docs})
        expected = {i for i, d in enumerate(docs)
                    if lo <= d["n"] < hi and d["k"] == kw}
        got = {int(h["_id"]) for h in resp["hits"]["hits"]}
        assert got == expected, (lo, hi, kw)


@pytest.mark.parametrize("trial", range(2))
def test_random_agg_sums_vs_oracle(random_rng, trial):
    rng = random_rng
    n_docs = int(rng.integers(20, 100))
    searcher, docs = random_corpus(rng, n_docs,
                                   int(rng.integers(1, 4)))
    resp = searcher.search({"size": 0, "aggs": {
        "by_k": {"terms": {"field": "k", "size": 10},
                 "aggs": {"s": {"sum": {"field": "n"}}}}}})
    buckets = {b["key"]: b for b in
               resp["aggregations"]["by_k"]["buckets"]}
    for kw in ("a", "b", "c", "d"):
        members = [d for d in docs if d["k"] == kw]
        if not members:
            assert kw not in buckets
            continue
        assert buckets[kw]["doc_count"] == len(members)
        assert buckets[kw]["s"]["value"] == pytest.approx(
            sum(d["n"] for d in members))
