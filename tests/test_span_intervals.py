"""Span family + intervals queries against a positional python oracle
(ref SpanNearQueryBuilder.java:51, SpanFirstQueryBuilder.java:47,
IntervalQueryBuilder.java:43)."""

import pytest

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher

DOCS = [
    "quick brown fox jumps over the lazy dog",        # 0
    "quick fox",                                      # 1
    "fox quick",                                      # 2
    "quick red sly brown fox",                        # 3
    "the brown quick fox",                            # 4
    "dog jumps",                                      # 5
    "quick brown cat and a slow fox",                 # 6
]


@pytest.fixture(scope="module")
def searcher():
    mapper = DocumentMapper({"properties": {"t": {"type": "text"}}})
    writer = SegmentWriter()
    half = [mapper.parse(str(i), {"t": t}) for i, t in enumerate(DOCS[:4])]
    rest = [mapper.parse(str(i + 4), {"t": t})
            for i, t in enumerate(DOCS[4:])]
    return ShardSearcher([writer.build(half, "sp0"),
                          writer.build(rest, "sp1")], mapper)


def ids(resp):
    return sorted(int(h["_id"]) for h in resp["hits"]["hits"])


def oracle_near(terms, slop, in_order):
    out = []
    for i, d in enumerate(DOCS):
        toks = d.split()
        pos = {t: [p for p, w in enumerate(toks) if w == t]
               for t in terms}
        if any(not pos[t] for t in terms):
            continue
        ok = False
        if in_order:
            for p0 in pos[terms[0]]:
                prev, good = p0, True
                for t in terms[1:]:
                    nxt = [p for p in pos[t] if p > prev]
                    if not nxt:
                        good = False
                        break
                    prev = min(nxt)
                if good and prev - p0 - (len(terms) - 1) <= slop:
                    ok = True
                    break
        else:
            assert len(terms) == 2
            ok = any(abs(p1 - p0) - 1 <= slop
                     for p0 in pos[terms[0]] for p1 in pos[terms[1]])
        if ok:
            out.append(i)
    return out


def test_span_term(searcher):
    resp = searcher.search({"query": {"span_term": {"t": "fox"}}})
    assert ids(resp) == [0, 1, 2, 3, 4, 6]


@pytest.mark.parametrize("slop,in_order", [(0, True), (1, True),
                                           (3, True), (0, False),
                                           (2, False)])
def test_span_near_vs_oracle(searcher, slop, in_order):
    body = {"query": {"span_near": {
        "clauses": [{"span_term": {"t": "quick"}},
                    {"span_term": {"t": "fox"}}],
        "slop": slop, "in_order": in_order}}, "size": 10}
    assert ids(searcher.search(body)) == \
        oracle_near(["quick", "fox"], slop, in_order), (slop, in_order)


def test_span_near_three_clauses_ordered(searcher):
    body = {"query": {"span_near": {
        "clauses": [{"span_term": {"t": "quick"}},
                    {"span_term": {"t": "brown"}},
                    {"span_term": {"t": "fox"}}],
        "slop": 2, "in_order": True}}, "size": 10}
    assert ids(searcher.search(body)) == \
        oracle_near(["quick", "brown", "fox"], 2, True)


def test_span_near_validation(searcher):
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"span_near": {
            "clauses": [{"span_term": {"t": "a"}},
                        {"span_term": {"t": "b"}},
                        {"span_term": {"t": "c"}}],
            "in_order": False}}})
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"span_near": {
            "clauses": [{"term": {"t": "a"}}]}}})
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"span_near": {"clauses": []}}})


def test_span_first(searcher):
    # 'fox' within the first 2 positions
    resp = searcher.search({"query": {"span_first": {
        "match": {"span_term": {"t": "fox"}}, "end": 2}}, "size": 10})
    assert ids(resp) == [i for i, d in enumerate(DOCS)
                         if "fox" in d.split()[:2]]


def test_span_or(searcher):
    resp = searcher.search({"query": {"span_or": {
        "clauses": [{"span_term": {"t": "dog"}},
                    {"span_term": {"t": "cat"}}]}}, "size": 10})
    assert ids(resp) == [0, 5, 6]


def test_intervals_match_ordered_gaps(searcher):
    body = {"query": {"intervals": {"t": {"match": {
        "query": "quick fox", "ordered": True, "max_gaps": 0}}}},
        "size": 10}
    assert ids(searcher.search(body)) == oracle_near(
        ["quick", "fox"], 0, True)
    body["query"]["intervals"]["t"]["match"]["max_gaps"] = 3
    assert ids(searcher.search(body)) == oracle_near(
        ["quick", "fox"], 3, True)


def test_intervals_match_unordered_unbounded_is_and(searcher):
    body = {"query": {"intervals": {"t": {"match": {
        "query": "fox quick"}}}}, "size": 10}
    assert ids(searcher.search(body)) == [
        i for i, d in enumerate(DOCS)
        if {"fox", "quick"} <= set(d.split())]


def test_intervals_any_of_all_of(searcher):
    body = {"query": {"intervals": {"t": {"any_of": {"intervals": [
        {"match": {"query": "dog"}},
        {"match": {"query": "cat"}}]}}}}, "size": 10}
    assert ids(searcher.search(body)) == [0, 5, 6]
    body = {"query": {"intervals": {"t": {"all_of": {
        "ordered": True, "max_gaps": 0, "intervals": [
            {"match": {"query": "brown"}},
            {"match": {"query": "fox"}}]}}}}, "size": 10}
    assert ids(searcher.search(body)) == oracle_near(
        ["brown", "fox"], 0, True)


def test_intervals_validation(searcher):
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"intervals": {"t": {
            "fuzzy": {"term": "qick"}}}}})
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"intervals": {"t": {}}}})


def test_span_scores_positive_and_slop_dynamic(searcher):
    """slop is a dynamic input: widening it must not change plan
    structure (same compiled program), and scores stay BM25-positive."""
    base = {"query": {"span_near": {
        "clauses": [{"span_term": {"t": "quick"}},
                    {"span_term": {"t": "fox"}}],
        "slop": 0, "in_order": True}}, "size": 10}
    r0 = searcher.search(base)
    assert all(h["_score"] > 0 for h in r0["hits"]["hits"])
    base["query"]["span_near"]["slop"] = 3
    r3 = searcher.search(base)
    assert set(ids(r0)) <= set(ids(r3))


def test_ordered_full_bucket_no_false_match(searcher):
    """Review regression: an anchor past the last occurrence of the next
    clause must not clamp-match the final key (out-of-order false
    positive when a clause's positions exactly fill the pad bucket).
    Exercised logically here: 'fox quick' (doc 2) must NEVER match
    ordered quick->fox regardless of slop."""
    for slop in (0, 5, 100):
        resp = searcher.search({"query": {"span_near": {
            "clauses": [{"span_term": {"t": "quick"}},
                        {"span_term": {"t": "fox"}}],
            "slop": slop, "in_order": True}}, "size": 10})
        assert 2 not in ids(resp), slop


def test_full_bucket_boundary_ordered():
    """Force the bucket-exactly-full layout (1024 positions = the
    minimum bucket, no KEY_PAD slot) and check the trailing anchor."""
    import numpy as np

    from opensearch_tpu.mapping.mapper import DocumentMapper

    mapper = DocumentMapper({"properties": {"t": {"type": "text"}}})
    writer = SegmentWriter()
    docs = []
    # 1023 'b' occurrences spread over filler docs, then the trap doc
    # 'b a' where 'a' follows every 'b' — an ordered a->b anchor in the
    # trap doc has NO following b
    for i in range(341):
        docs.append(mapper.parse(str(i), {"t": "b b b"}))
    docs.append(mapper.parse("999", {"t": "b a"}))
    seg = writer.build(docs, "fb")
    pf = seg.postings["t"]
    tid = pf.term_id("b")
    e0, e1 = int(pf.offsets[tid]), int(pf.offsets[tid + 1])
    assert int(pf.pos_offsets[e1] - pf.pos_offsets[e0]) == 1024
    s = ShardSearcher([seg], mapper)
    resp = s.search({"query": {"span_near": {
        "clauses": [{"span_term": {"t": "a"}},
                    {"span_term": {"t": "b"}}],
        "slop": 1000, "in_order": True}}, "size": 400})
    assert ids(resp) == []     # no b after any a anywhere


def test_unordered_same_term_needs_two_occurrences():
    """Review regression: [fox, fox] unordered must not let a single
    occurrence match itself."""
    from opensearch_tpu.mapping.mapper import DocumentMapper

    mapper = DocumentMapper({"properties": {"t": {"type": "text"}}})
    docs = [("0", "one fox here"), ("1", "fox and fox"),
            ("2", "fox then later a fox"), ("3", "no animals")]
    seg = SegmentWriter().build(
        [mapper.parse(i, {"t": t}) for i, t in docs], "st")
    s = ShardSearcher([seg], mapper)
    body = {"query": {"span_near": {
        "clauses": [{"span_term": {"t": "fox"}},
                    {"span_term": {"t": "fox"}}],
        "slop": 1, "in_order": False}}, "size": 10}
    assert ids(s.search(body)) == [1]
    body["query"]["span_near"]["slop"] = 10
    assert ids(s.search(body)) == [1, 2]


def test_intervals_rejects_unsupported_options(searcher):
    bad1 = {"query": {"intervals": {"t": {"match": {
        "query": "quick fox", "max_gaps": 1,
        "filter": {"not_containing": {"match": {"query": "x"}}}}}}}}
    with pytest.raises(OpenSearchTpuError, match="not supported"):
        searcher.search(bad1)
    bad2 = {"query": {"intervals": {"t": {"match": {
        "query": "quick", "use_field": "other"}}}}}
    with pytest.raises(OpenSearchTpuError, match="not supported"):
        searcher.search(bad2)
