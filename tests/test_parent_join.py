"""Parent-join field + has_child / has_parent / parent_id vs host
oracles (VERDICT r4 item 7; ref modules/parent-join/
ParentJoinFieldMapper.java, HasChildQueryBuilder.java).  Children and
parents are spread across segments to exercise the cross-segment
host-side ordinal join."""

import numpy as np
import pytest

from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher

MAPPING = {"properties": {
    "my_join": {"type": "join",
                "relations": {"question": "answer"}},
    "body": {"type": "text"},
    "votes": {"type": "long"},
}}

# 3 questions; answers reference them, spread over segments
PARENTS = [
    {"_id": "q1", "body": "how do tpus work", "my_join": "question"},
    {"_id": "q2", "body": "why is the sky blue", "my_join": "question"},
    {"_id": "q3", "body": "unanswered question", "my_join": "question"},
]
CHILDREN = [
    {"_id": "a1", "body": "systolic arrays", "votes": 3,
     "my_join": {"name": "answer", "parent": "q1"}},
    {"_id": "a2", "body": "matrix units work fast", "votes": 7,
     "my_join": {"name": "answer", "parent": "q1"}},
    {"_id": "a3", "body": "rayleigh scattering", "votes": 5,
     "my_join": {"name": "answer", "parent": "q2"}},
    {"_id": "a4", "body": "it just is", "votes": 1,
     "my_join": {"name": "answer", "parent": "q2"}},
]


@pytest.fixture(scope="module")
def searcher():
    mapper = DocumentMapper(MAPPING)
    w = SegmentWriter()
    docs = PARENTS + CHILDREN
    # interleave across 3 segments so parents/children split
    segs = []
    for si in range(3):
        chunk = docs[si::3]
        parsed = [mapper.parse(d["_id"],
                               {k: v for k, v in d.items() if k != "_id"})
                  for d in chunk]
        segs.append(w.build(parsed, f"s{si}"))
    return ShardSearcher(segs, mapper)


def ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


def test_has_child_basic(searcher):
    resp = searcher.search({"query": {"has_child": {
        "type": "answer", "query": {"match": {"body": "work"}}}}})
    # 'work' matches a1? no — a2 ("matrix units work fast") -> q1 only
    assert ids(resp) == ["q1"]
    # match_all children -> every question with any answer
    resp = searcher.search({"query": {"has_child": {
        "type": "answer", "query": {"match_all": {}}}}})
    assert ids(resp) == ["q1", "q2"]


def test_has_child_score_modes(searcher):
    for mode, expect in [("sum", {"q1": 3 + 7, "q2": 5 + 1}),
                         ("max", {"q1": 7, "q2": 5}),
                         ("min", {"q1": 3, "q2": 1}),
                         ("avg", {"q1": 5.0, "q2": 3.0})]:
        resp = searcher.search({"query": {"has_child": {
            "type": "answer", "score_mode": mode,
            "query": {"function_score": {
                "query": {"match_all": {}},
                "functions": [{"field_value_factor":
                               {"field": "votes"}}],
                "boost_mode": "replace"}}}}})
        got = {h["_id"]: h["_score"] for h in resp["hits"]["hits"]}
        assert got == pytest.approx(expect), mode


def test_has_child_min_max_children(searcher):
    resp = searcher.search({"query": {"has_child": {
        "type": "answer", "query": {"match_all": {}},
        "min_children": 2}}})
    assert ids(resp) == ["q1", "q2"]
    resp = searcher.search({"query": {"has_child": {
        "type": "answer", "query": {"match": {"body": "scattering"}},
        "min_children": 2}}})
    assert ids(resp) == []                      # q2 has only 1 match


def test_has_parent(searcher):
    resp = searcher.search({"query": {"has_parent": {
        "parent_type": "question", "query": {"match": {"body": "sky"}}}}})
    assert ids(resp) == ["a3", "a4"]            # q2's answers
    # score=false -> constant 1.0
    assert all(h["_score"] == pytest.approx(1.0)
               for h in resp["hits"]["hits"])


def test_parent_id(searcher):
    resp = searcher.search({"query": {"parent_id": {
        "type": "answer", "id": "q1"}}})
    assert ids(resp) == ["a1", "a2"]


def test_join_in_bool_composition(searcher):
    """Join queries compose inside bool like any plan node."""
    resp = searcher.search({"query": {"bool": {
        "must": [{"has_child": {"type": "answer",
                                "query": {"match_all": {}}}}],
        "must_not": [{"term": {"_id": "q2"}}]}}})
    assert ids(resp) == ["q1"]


def test_join_validation(searcher):
    from opensearch_tpu.common.errors import (IllegalArgumentError,
                                              MapperParsingError)

    with pytest.raises(IllegalArgumentError):
        searcher.search({"query": {"has_child": {
            "type": "nope", "query": {"match_all": {}}}}})
    with pytest.raises(IllegalArgumentError):
        searcher.search({"query": {"has_parent": {
            "parent_type": "nope", "query": {"match_all": {}}}}})
    mapper = DocumentMapper(MAPPING)
    with pytest.raises(MapperParsingError):
        mapper.parse("x", {"my_join": {"name": "answer"}})  # no parent
    with pytest.raises(MapperParsingError):
        mapper.parse("x", {"my_join": "not_a_relation"})


def test_join_oracle_randomized():
    """Random parent/child graph vs a plain-Python oracle."""
    rng = np.random.default_rng(17)
    mapper = DocumentMapper(MAPPING)
    w = SegmentWriter()
    parents = [f"p{i}" for i in range(12)]
    docs = [{"_id": p, "my_join": "question",
             "body": f"topic{i % 4}"} for i, p in enumerate(parents)]
    children = []
    for i in range(40):
        par = parents[rng.integers(0, len(parents))]
        children.append({"_id": f"c{i}",
                         "my_join": {"name": "answer", "parent": par},
                         "body": f"term{i % 5}",
                         "votes": int(rng.integers(1, 10))})
    alldocs = docs + children
    segs = []
    for si in range(4):
        chunk = alldocs[si::4]
        parsed = [mapper.parse(d["_id"],
                               {k: v for k, v in d.items() if k != "_id"})
                  for d in chunk]
        segs.append(w.build(parsed, f"s{si}"))
    s = ShardSearcher(segs, mapper)

    for t in range(5):
        term = f"term{t}"
        resp = s.search({"query": {"has_child": {
            "type": "answer", "query": {"match": {"body": term}}}},
            "size": 20})
        oracle = sorted({c["my_join"]["parent"] for c in children
                         if c["body"] == term})
        assert ids(resp) == oracle, term
    for t in range(4):
        topic = f"topic{t}"
        resp = s.search({"query": {"has_parent": {
            "parent_type": "question",
            "query": {"match": {"body": topic}}}}, "size": 50})
        matched_parents = {d["_id"] for d in docs if d["body"] == topic}
        oracle = sorted(c["_id"] for c in children
                        if c["my_join"]["parent"] in matched_parents)
        assert ids(resp) == oracle, topic
