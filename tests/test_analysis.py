import pytest

from opensearch_tpu.analysis import AnalysisRegistry
from opensearch_tpu.analysis.porter import stem
from opensearch_tpu.common.errors import IllegalArgumentError


@pytest.fixture
def registry():
    return AnalysisRegistry()


def test_standard_analyzer_lowercases_and_splits(registry):
    assert registry.get("standard").terms("The Quick-Brown FOX, 42 jumps!") == [
        "the", "quick", "brown", "fox", "42", "jumps",
    ]


def test_standard_positions_and_offsets(registry):
    tokens = registry.get("standard").analyze("hello brave world")
    assert [(t.term, t.position) for t in tokens] == [
        ("hello", 0), ("brave", 1), ("world", 2),
    ]
    assert tokens[1].start_offset == 6 and tokens[1].end_offset == 11


def test_whitespace_keeps_punctuation(registry):
    assert registry.get("whitespace").terms("Hello, world!") == ["Hello,", "world!"]


def test_keyword_analyzer_single_token(registry):
    assert registry.get("keyword").terms("New York City") == ["New York City"]


def test_simple_analyzer_drops_digits(registry):
    assert registry.get("simple").terms("abc 123 def") == ["abc", "def"]


def test_english_analyzer_stems_and_stops(registry):
    terms = registry.get("english").terms("The running dogs are jumping quickly")
    assert "the" not in terms and "are" not in terms
    assert "run" in terms and "dog" in terms and "jump" in terms


def test_porter_stemmer_classic_cases():
    cases = {
        "caresses": "caress", "ponies": "poni", "ties": "ti", "caress": "caress",
        "cats": "cat", "feed": "feed", "agreed": "agre", "plastered": "plaster",
        "motoring": "motor", "sing": "sing", "conflated": "conflat",
        "troubled": "troubl", "sized": "size", "hopping": "hop", "falling": "fall",
        "happy": "happi", "relational": "relat", "conditional": "condit",
        "vietnamization": "vietnam", "predication": "predic",
        "electrical": "electr", "hopefulness": "hope", "goodness": "good",
        "formalize": "formal", "triplicate": "triplic", "formative": "form",
        "revival": "reviv", "allowance": "allow", "inference": "infer",
        "adjustment": "adjust", "probate": "probat", "cease": "ceas",
        "controll": "control", "roll": "roll",
    }
    for word, expected in cases.items():
        assert stem(word) == expected, f"{word} -> {stem(word)} != {expected}"


def test_custom_analyzer_from_settings():
    reg = AnalysisRegistry(
        {
            "filter": {"my_stop": {"type": "stop", "stopwords": ["foo"]}},
            "analyzer": {
                "my_analyzer": {
                    "type": "custom",
                    "tokenizer": "whitespace",
                    "filter": ["lowercase", "my_stop"],
                }
            },
        }
    )
    assert reg.get("my_analyzer").terms("FOO Bar baz") == ["bar", "baz"]


def test_html_strip_char_filter():
    reg = AnalysisRegistry(
        {
            "analyzer": {
                "html": {
                    "type": "custom",
                    "tokenizer": "standard",
                    "filter": ["lowercase"],
                    "char_filter": ["html_strip"],
                }
            }
        }
    )
    assert reg.get("html").terms("<p>Hello <b>World</b></p>") == ["hello", "world"]


def test_unknown_analyzer_raises(registry):
    with pytest.raises(IllegalArgumentError):
        registry.get("nope")


def test_shingle_filter():
    reg = AnalysisRegistry(
        {
            "analyzer": {
                "sh": {"type": "custom", "tokenizer": "whitespace", "filter": ["shingle"]}
            }
        }
    )
    assert set(reg.get("sh").terms("a b c")) == {"a", "b", "c", "a b", "b c"}


def test_custom_tokenizer_section():
    # ADVICE: settings.analysis.tokenizer must be honoured
    from opensearch_tpu.analysis import AnalysisRegistry

    reg = AnalysisRegistry({
        "tokenizer": {"my_ngram": {"type": "ngram", "min_gram": 2, "max_gram": 2}},
        "analyzer": {"a": {"type": "custom", "tokenizer": "my_ngram"}},
    })
    assert reg.get("a").terms("abc") == ["ab", "bc"]


def test_edge_ngram_and_pattern_tokenizers():
    from opensearch_tpu.analysis import AnalysisRegistry

    reg = AnalysisRegistry({
        "tokenizer": {
            "edge": {"type": "edge_ngram", "min_gram": 1, "max_gram": 3},
            "csv": {"type": "pattern", "pattern": ","},
        },
        "analyzer": {
            "e": {"type": "custom", "tokenizer": "edge"},
            "c": {"type": "custom", "tokenizer": "csv"},
        },
    })
    assert reg.get("e").terms("abcd") == ["a", "ab", "abc"]
    assert reg.get("c").terms("x,y,z") == ["x", "y", "z"]


def test_edge_ngram_short_input_no_duplicates():
    from opensearch_tpu.analysis import AnalysisRegistry

    reg = AnalysisRegistry({
        "tokenizer": {"edge": {"type": "edge_ngram", "min_gram": 1, "max_gram": 3}},
        "analyzer": {"e": {"type": "custom", "tokenizer": "edge"}},
    })
    assert reg.get("e").terms("ab") == ["a", "ab"]
