"""Query insights (PR 10): always-on top-N query attribution,
per-plan-signature workload stats, coalescability reporting, cluster
fan-in, the recovery observability surfaces, the mesh-path fallback,
and the Prometheus label-cardinality lint.

Pinned invariants:
- responses are byte-identical with insights enabled vs disabled (the
  recorder never mutates a response);
- the plan signature recorded by a data node equals the one the
  coordinator computes from the same body (fan-in aggregates correctly);
- every Prometheus label value flows through the bounded signature /
  top-N path (tools/check_prom_labels.py, tier-1 via this file).
"""

import json
import subprocess
import sys
import time

import pytest

from opensearch_tpu.common.breakers import (CircuitBreakerService,
                                            breaker_service, install)
from opensearch_tpu.common.telemetry import (flight_recorder, metrics,
                                             tracer)
from opensearch_tpu.node import Node
from opensearch_tpu.search import insights as insights_mod
from opensearch_tpu.search.insights import (QueryInsightsService,
                                            canonical_query,
                                            merge_sections,
                                            scored_for_body,
                                            signature_hash)

TOOLS = __file__.rsplit("/tests/", 1)[0] + "/tools"


@pytest.fixture(autouse=True)
def _clean_telemetry():
    tracer().reset()
    flight_recorder().reset()
    yield
    tracer().reset()
    flight_recorder().reset()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _svc(clock=None, **kw):
    return QueryInsightsService(node_id="test-node",
                                clock=clock or FakeClock(), **kw)


def _rec(sig="q1", took=5.0, **kw):
    rec = {"signature": sig, "scored": True, "took_ms": took,
           "execution_path": "host", "plan_cache": "miss"}
    rec.update(kw)
    return rec


# -- unit: record / rollup / coalescability ---------------------------------

def test_rollup_counts_percentiles_and_interarrival():
    clock = FakeClock()
    svc = _svc(clock)
    for took in (1.0, 2.0, 100.0):
        svc.record(_rec(took=took))
        clock.advance(0.5)                     # 500ms apart
    sec = svc.section()
    sig = signature_hash("q1", True)
    roll = sec["signatures"][sig]
    assert roll["count"] == 3
    assert roll["latency_ms"]["max"] == 100.0
    assert roll["latency_ms"]["p99"] <= 100.0
    assert roll["interarrival_ms"]["mean"] == pytest.approx(500.0)
    assert roll["interarrival_ms"]["min"] == pytest.approx(500.0)
    # 500ms apart with a 10ms window: nothing coalesces
    assert roll["coalescable_fraction"] == 0.0
    assert sec["coalescability"]["coalescable_fraction"] == 0.0


def test_coalescability_fraction_counts_close_arrivals():
    clock = FakeClock()
    svc = _svc(clock, coalesce_window_ms=10.0)
    svc.record(_rec())                        # first arrival never counts
    for _ in range(3):
        clock.advance(0.005)                  # 5ms < 10ms window
        svc.record(_rec())
    clock.advance(5.0)                        # way outside the window
    svc.record(_rec())
    # a DIFFERENT signature arriving nearby does not coalesce with q1
    clock.advance(0.001)
    svc.record(_rec(sig="q2"))
    rep = svc.coalescability()
    assert rep["arrivals"] == 6
    assert rep["coalesced"] == 3
    assert rep["coalescable_fraction"] == pytest.approx(3 / 6)
    assert rep["top_signatures"][0]["signature"] == \
        signature_hash("q1", True)


def test_top_rings_rank_by_latency_cpu_and_heap():
    svc = _svc()
    svc.record(_rec(sig="slow", took=50.0), cpu_nanos=10, heap_bytes=10)
    svc.record(_rec(sig="cpu", took=1.0), cpu_nanos=9_000_000,
               heap_bytes=20)
    svc.record(_rec(sig="heap", took=2.0), cpu_nanos=20,
               heap_bytes=1 << 20)
    assert svc.top(by="latency")[0]["signature"] == \
        signature_hash("slow", True)
    assert svc.top(by="cpu")[0]["signature"] == \
        signature_hash("cpu", True)
    assert svc.top(by="heap")[0]["signature"] == \
        signature_hash("heap", True)
    from opensearch_tpu.common.errors import IllegalArgumentError
    with pytest.raises(IllegalArgumentError):
        svc.top(by="vibes")


def test_sliding_window_expires_ring_entries():
    clock = FakeClock()
    svc = _svc(clock, window_s=60.0)
    svc.record(_rec(sig="old"))
    clock.advance(120.0)
    svc.record(_rec(sig="new"))
    sigs = {r["signature"] for r in svc.top(n=10)}
    assert sigs == {signature_hash("new", True)}
    st = svc.stats()
    assert st["records"] == 2            # lifetime totals keep counting
    assert st["ring_size"] == 1


def test_signature_table_bounded_with_lru_eviction():
    clock = FakeClock()
    svc = _svc(clock, max_signatures=4)
    for i in range(10):
        svc.record(_rec(sig=f"q{i}"))
        clock.advance(1.0)
    st = svc.stats()
    assert st["signatures"] <= 4
    # the most recent signatures survive
    assert signature_hash("q9", True) in svc.section()["signatures"]
    assert signature_hash("q0", True) not in svc.section()["signatures"]


def test_breaker_pressure_evicts_rings_then_drops():
    prev = breaker_service()
    tiny = CircuitBreakerService({"breaker.request.limit": 3000,
                                  "breaker.total.limit": 3000})
    install(tiny)
    try:
        svc = _svc()
        for i in range(50):
            svc.record(_rec(sig=f"q{i}", took=float(i)))
        st = svc.stats()
        # bounded: the ring shrank under pressure instead of growing
        # past the breaker, and the overflow is accounted, not silent
        assert st["ring_bytes"] <= 3000
        assert st["evictions"] > 0 or st["dropped"] > 0
        assert tiny.request.used <= 3000
        svc.reset()
        assert tiny.request.used == 0      # every reservation released
    finally:
        install(prev)


def test_disabled_service_records_nothing():
    svc = _svc()
    svc.set_enabled(False)
    svc.record(_rec())
    assert svc.stats()["records"] == 0
    svc.set_enabled(True)
    svc.record(_rec())
    assert svc.stats()["records"] == 1


# -- unit: signatures -------------------------------------------------------

def test_signature_canonicalization_ignores_key_order():
    a = canonical_query({"bool": {"must": [{"match": {"t": "x"}}],
                                  "filter": []}})
    b = canonical_query({"bool": {"filter": [],
                                  "must": [{"match": {"t": "x"}}]}})
    assert a == b
    assert signature_hash(a, True) == signature_hash(b, True)
    assert signature_hash(a, True) != signature_hash(a, False)
    assert signature_hash(None) == "_unsigned"


def test_scored_for_body_mirrors_executor():
    assert scored_for_body({}) is True
    assert scored_for_body({"sort": [{"n": "asc"}]}) is False
    assert scored_for_body({"sort": ["_score"]}) is True
    assert scored_for_body({"sort": [{"n": "asc"}],
                            "min_score": 0.5}) is True


# -- unit: fan-in merge -----------------------------------------------------

def _section(node, sig_counts, top=()):
    return {
        "node": node,
        "top_queries": [dict(t, node=node) for t in top],
        "signatures": {s: {"count": c, "coalesced": c // 2,
                           "source": s}
                       for s, c in sig_counts.items()},
        "coalescability": {},
        "totals": {"records": sum(sig_counts.values()),
                   "coalesced": sum(c // 2
                                    for c in sig_counts.values())},
    }


def test_merge_sections_is_deterministic_and_provenance_annotated():
    sections = {
        "n1": _section("n1", {"sigA": 4, "sigB": 2},
                       top=[{"signature": "sigA", "took_ms": 9.0}]),
        "n0": _section("n0", {"sigA": 6},
                       top=[{"signature": "sigA", "took_ms": 12.0}]),
        "n2": {"error": "ReceiveTimeoutError: boom"},
    }
    out1 = merge_sections(sections, by="latency", n=5)
    out2 = merge_sections(dict(reversed(list(sections.items()))),
                          by="latency", n=5)
    assert out1 == out2                     # input order never matters
    assert out1["failed_nodes"] == {"n2": "ReceiveTimeoutError: boom"}
    assert out1["top_queries"][0]["node"] == "n0"     # 12ms beats 9ms
    merged_a = out1["signatures"]["sigA"]
    assert merged_a["count"] == 10
    assert set(merged_a["nodes"]) == {"n0", "n1"}     # provenance kept
    assert out1["coalescability"]["arrivals"] == 12


# -- REST integration -------------------------------------------------------

@pytest.fixture(scope="module")
def node(tmp_path_factory):
    n = Node(str(tmp_path_factory.mktemp("insights-node")), port=0)
    yield n
    n.stop()


def call(node, method, path, body=None, params=None, headers=None,
         ndjson=None):
    if ndjson is not None:
        raw = ("\n".join(json.dumps(l) for l in ndjson) + "\n").encode()
        ctype = "application/x-ndjson"
    else:
        raw = json.dumps(body).encode() if body is not None else None
        ctype = "application/json"
    return node.rest.dispatch(method, path, params or {}, raw, ctype,
                              headers=headers or {})


def _seed(node, index, docs=24):
    s, r = call(node, "PUT", f"/{index}", {
        "mappings": {"properties": {"t": {"type": "text"},
                                    "n": {"type": "long"}}}})
    assert s == 200, r
    lines = []
    for i in range(docs):
        lines.append({"index": {"_index": index, "_id": str(i)}})
        lines.append({"t": f"w{i % 5} common", "n": i})
    s, r = call(node, "POST", "/_bulk", params={"refresh": "true"},
                ndjson=lines)
    assert s == 200 and not r["errors"], r


def test_rest_records_and_top_queries_endpoint(node):
    _seed(node, "insix")
    node.insights.reset()
    body = {"query": {"match": {"t": "common"}}, "size": 5}
    for _ in range(3):
        s, r = call(node, "POST", "/insix/_search", body,
                    headers={"X-Opaque-Id": "dashboards-7"})
        assert s == 200 and "_insight" not in r
    s, out = call(node, "GET", "/_insights/top_queries")
    assert s == 200
    sig = signature_hash(canonical_query(body["query"]), True)
    assert [e for e in out["top_queries"] if e["signature"] == sig]
    roll = out["signatures"][sig]
    assert roll["count"] == 3
    # plan-cache attribution: the first run misses, repeats hit
    assert roll["nodes"][node.node_id]["plan_cache_hits"] == 2
    # X-Opaque-Id threads into the rollup's client attribution
    assert roll["nodes"][node.node_id]["clients"] == {"dashboards-7": 3}
    top = out["top_queries"][0]
    assert top["x_opaque_id"] == "dashboards-7"
    assert top["node"] == node.node_id
    assert top["execution_path"] in ("host", "device")
    assert top["cpu_nanos"] >= 0 and "took_ms" in top
    # ranked-by-cpu variant answers too
    s, out = call(node, "GET", "/_insights/top_queries",
                  params={"by": "cpu", "size": "2"})
    assert s == 200 and len(out["top_queries"]) <= 2


def test_responses_byte_identical_with_insights_on_and_off(node):
    _seed(node, "insbyte")
    body = {"query": {"match": {"t": "common"}}, "size": 4}

    def run():
        s, r = call(node, "POST", "/insbyte/_search", body)
        assert s == 200
        r = dict(r)
        r.pop("took")          # wall-clock, varies run to run regardless
        return json.dumps(r, sort_keys=True)

    warm = run()               # plan cache warm for both measurements
    on = run()
    s, _ = call(node, "PUT", "/_cluster/settings", {
        "transient": {"search.insights.enabled": False}})
    assert s == 200
    try:
        off = run()
        assert warm == on == off
        before = node.insights.stats()["records"]
        run()
        assert node.insights.stats()["records"] == before  # truly off
    finally:
        call(node, "PUT", "/_cluster/settings", {
            "transient": {"search.insights.enabled": None}})
    assert node.insights.enabled


def test_msearch_members_recorded_with_batch_attribution(node):
    _seed(node, "insms")
    node.insights.reset()
    lines = []
    for i in range(4):
        lines.append({"index": "insms"})
        lines.append({"query": {"match": {"t": f"w{i}"}}, "size": 3})
    s, r = call(node, "POST", "/_msearch", ndjson=lines)
    assert s == 200
    assert all(m.get("status") == 200 and "_insight" not in m
               for m in r["responses"])
    sec = node.insights.section()
    assert sec["totals"]["records"] == 4       # one record per member
    batched = [e for e in sec["top_queries"] if e.get("batched")]
    assert batched and batched[0]["batched"] == 4   # coalesced group of 4
    assert batched[0]["execution_path"].endswith("_batched")
    # four distinct term sets -> four distinct plan signatures
    assert len(sec["signatures"]) == 4


def test_request_cache_hit_attribution(node):
    _seed(node, "inscache")
    node.insights.reset()
    body = {"query": {"term": {"t": "common"}}, "size": 0}
    for _ in range(2):
        s, _r = call(node, "POST", "/inscache/_search", body)
        assert s == 200
    recs = node.insights.top(n=10)
    states = sorted(r["request_cache"] for r in recs)
    assert states == ["hit", "miss"]
    hit = next(r for r in recs if r["request_cache"] == "hit")
    assert hit["execution_path"] == "cached"
    assert hit["plan_cache"] == "hit"
    # both runs map to the SAME signature (scored=False on both)
    assert len({r["signature"] for r in recs}) == 1


def test_nodes_stats_query_insights_block(node):
    _seed(node, "insstats")
    node.insights.reset()
    call(node, "POST", "/insstats/_search",
         {"query": {"match": {"t": "common"}}})
    s, r = call(node, "GET", "/_nodes/stats")
    assert s == 200
    qi = r["nodes"][node.node_id]["query_insights"]
    assert qi["enabled"] is True
    assert qi["records"] >= 1
    assert qi["signatures"] >= 1
    assert 0.0 <= qi["coalescable_fraction"] <= 1.0
    assert {"rejected", "dropped", "evictions"} <= set(qi)


_PROM_LINE = (r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
              r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
              r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? '
              r'[0-9eE.+-]+(ns|inf|an)?$')


def test_metrics_exposition_carries_bounded_signature_labels(node):
    import re
    _seed(node, "insprom")
    node.insights.reset()
    for _ in range(2):
        call(node, "POST", "/insprom/_search",
             {"query": {"match": {"t": "common"}}})
    s, payload = call(node, "GET", "/_metrics")
    assert s == 200
    text = payload.text
    ins = [l for l in text.splitlines() if "insights" in l]
    series = [l for l in ins if not l.startswith("#")]
    assert series, "no insights series in /_metrics"
    rx = re.compile(_PROM_LINE)
    for line in series:
        assert rx.match(line), f"invalid prometheus line: {line!r}"
        # the signature (or PR-14 tenant) is a LABEL drawn from a
        # bounded path — never part of the metric name
        assert re.search(r'\{signature="[0-9a-f_]{1,12}"', line) \
            or re.search(r'\{tenant="[^"]{1,64}"', line), line
        assert "node=" in line
    counts = [l for l in series
              if l.startswith(
                  "opensearch_tpu_insights_signature_queries_total")]
    assert counts and counts[0].rstrip().endswith("2")


def test_rejected_searches_counted_without_ring_entries(node):
    from opensearch_tpu.search.backpressure import SearchRejectedError
    node.insights.reset()
    orig = node.search_backpressure.admission.acquire

    def rejecting(_name, tenant=None):
        raise SearchRejectedError("saturated", retry_after_seconds=1)
    node.search_backpressure.admission.acquire = rejecting
    try:
        s, _ = call(node, "POST", "/insix/_search",
                    {"query": {"match_all": {}}})
        assert s == 429
    finally:
        node.search_backpressure.admission.acquire = orig
    st = node.insights.stats()
    assert st["rejected"] == 1
    assert st["ring_size"] == 0


# -- dynamic settings -------------------------------------------------------

def test_insights_settings_reach_live_service(node):
    s, _ = call(node, "PUT", "/_cluster/settings", {"transient": {
        "search.insights.top_n": 3,
        "search.insights.coalesce_window_ms": 25.0}})
    assert s == 200
    try:
        assert node.insights.top_n == 3
        assert node.insights.coalesce_window_ms == 25.0
    finally:
        call(node, "PUT", "/_cluster/settings", {"transient": {
            "search.insights.top_n": None,
            "search.insights.coalesce_window_ms": None}})
    assert node.insights.top_n == 10


# -- recovery observability -------------------------------------------------

def test_cat_recovery_and_nodes_stats_recovery_section(node):
    _seed(node, "insrec")
    metrics().counter("recovery.corrupt_blobs").inc(2)
    s, rows = call(node, "GET", "/_cat/recovery/insrec")
    assert s == 200 and rows
    row = rows[0]
    assert row["index"] == "insrec" and row["stage"] == "done"
    assert int(row["corrupt_blobs"]) >= 2
    assert "retries" in row
    s, r = call(node, "GET", "/_nodes/stats")
    rec = r["nodes"][node.node_id]["recovery"]
    assert rec["corrupt_blobs"] >= 2
    assert set(rec["retries"]) == {"start", "report", "fetch"}
    assert {"attempts", "retries", "exhausted"} <= \
        set(rec["retries"]["start"])
    shards = [s_ for s_ in rec["shards"] if s_["index"] == "insrec"]
    assert shards and shards[0]["stage"] == "done"


# -- mesh fallback (satellite: the pre-existing 500) ------------------------

def test_mesh_unavailable_degrades_to_host_scatter(node, monkeypatch):
    """With no shard_map in jax, index.search.mesh must not 500: the
    host scatter serves the request with mesh semantics (per-shard
    scoring stats, coordinator merge order) and the fallback is counted
    in search.mesh.fallback."""
    from opensearch_tpu.parallel import dist_search
    from opensearch_tpu.search.executor import merge_hit_rows
    s, _ = call(node, "PUT", "/meshfall", {
        "settings": {"number_of_shards": 4, "search.mesh": True},
        "mappings": {"properties": {"t": {"type": "text"},
                                    "n": {"type": "long"}}}})
    assert s == 200
    lines = []
    for i in range(40):
        lines.append({"index": {"_index": "meshfall", "_id": str(i)}})
        lines.append({"t": f"w{i % 7} common", "n": i})
    s, r = call(node, "POST", "/_bulk", params={"refresh": "true"},
                ndjson=lines)
    assert s == 200 and not r["errors"]

    monkeypatch.setattr(dist_search, "MESH_AVAILABLE", False)
    node.insights.reset()
    before = metrics().counter("search.mesh.fallback").value
    body = {"query": {"match": {"t": "common"}}, "size": 8}
    svc = node.indices.get("meshfall")
    assert svc._use_mesh(body)          # the request still opts in
    s, resp = call(node, "POST", "/meshfall/_search", body)
    assert s == 200, resp               # no 500
    assert metrics().counter("search.mesh.fallback").value == before + 1
    assert resp["hits"]["total"]["value"] == 40
    # parity with the per-shard host oracle (the mesh merge semantics)
    rows, total = [], 0
    for si, sh in enumerate(sorted(svc.local_shards)):
        r2 = svc.local_shards[sh].acquire_searcher().search(
            dict(body, size=8))
        total += r2["hits"]["total"]["value"]
        rows.extend((h, si, pos)
                    for pos, h in enumerate(r2["hits"]["hits"]))
    want = [(h["_id"], h["_score"])
            for h in merge_hit_rows(rows, None)[:8]]
    got = [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]]
    assert got == want and total == 40
    # the fallback is attributed in insights, not just a counter
    paths = {e["execution_path"] for e in node.insights.top(n=5)}
    assert "mesh_fallback" in paths


def test_mesh_shim_still_serves_mesh_when_available(node):
    """Regression guard for the shard_map compat shim itself: when the
    mesh IS available the request takes it (no fallback count)."""
    from opensearch_tpu.parallel import dist_search
    if not dist_search.MESH_AVAILABLE:
        pytest.skip("no shard_map in this jax")
    before = metrics().counter("search.mesh.fallback").value
    body = {"query": {"match": {"t": "common"}}, "size": 5}
    s, resp = call(node, "POST", "/meshfall/_search", body)
    assert s == 200 and resp["hits"]["hits"]
    assert metrics().counter("search.mesh.fallback").value == before


# -- cluster fan-in ---------------------------------------------------------

def wait_until(pred, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:   # deadline-bounded poll
        if pred():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def cluster(tmp_path):
    from opensearch_tpu.cluster.node import ClusterNode
    from opensearch_tpu.transport.service import (LocalTransport,
                                                  TransportService)
    hub = LocalTransport.Hub()
    ids = ["n0", "n1", "n2"]
    nodes = {}
    for nid in ids:
        svc = TransportService(nid, LocalTransport(hub))
        n = ClusterNode(nid, str(tmp_path / nid), svc, ids)
        n.search_backpressure.trackers["cpu_usage"].probe = lambda: 0.0
        nodes[nid] = n
    assert nodes["n0"].start_election()
    assert wait_until(lambda: all(
        nodes[i].coordinator.state().master_node == "n0" for i in ids))
    yield hub, ids, nodes
    for n in nodes.values():
        n.stop()


def test_three_node_fanin_merge_deterministic(cluster):
    from opensearch_tpu.common import tasks as taskmod
    hub, ids, nodes = cluster
    nodes["n0"].create_index("fan", {
        "settings": {"number_of_shards": 3, "number_of_replicas": 1},
        "mappings": {"properties": {"t": {"type": "text"}}}})

    def in_sync():
        routing = nodes["n0"].coordinator.state().routing.get("fan", [])
        return routing and all(
            set(e["in_sync"]) == {e["primary"], *e["replicas"]}
            for e in routing)
    assert wait_until(in_sync)
    for i in range(30):
        nodes["n0"].index_doc("fan", str(i), {"t": f"w{i % 4} common"})
    nodes["n0"].refresh("fan")

    body = {"query": {"match": {"t": "common"}}}
    # X-Opaque-Id rides the ambient task into the scatter payloads
    tm = nodes["n2"].task_manager
    outer = tm.register("rest:test",
                        headers={"X-Opaque-Id": "tenant-42"})
    token = taskmod.set_current(outer)
    try:
        for _ in range(3):
            r = nodes["n2"].search("fan", dict(body))
            assert r["hits"]["total"]["value"] == 30
    finally:
        taskmod.reset_current(token)
        tm.unregister(outer)

    out1 = nodes["n2"].top_queries(by="latency", n=8)
    assert out1["coordinator"] == "n2"
    assert "failed_nodes" not in out1
    sig = signature_hash(canonical_query(body["query"]), True)
    merged = out1["signatures"][sig]
    # coordinator scatter + shard query phases all fold into ONE
    # signature: the coordinator's computed key matches the data nodes'
    # plan-cache stamps (parity), and provenance names every recorder
    assert merged["count"] >= 6
    assert len(merged["nodes"]) == 3
    paths = set()
    for entry in out1["top_queries"]:
        assert entry["node"] in ids            # provenance annotated
        paths.add(entry["execution_path"])
    assert "scatter" in paths                  # coordinator records
    assert paths & {"host", "device"}          # data nodes record
    # X-Opaque-Id reached the DATA nodes' records, not just n2's
    data_entries = [e for e in out1["top_queries"]
                    if e["node"] != "n2"]
    assert data_entries
    assert all(e.get("x_opaque_id") == "tenant-42"
               for e in data_entries)
    # deterministic: a second merge of the same state is identical
    out2 = nodes["n2"].top_queries(by="latency", n=8)
    assert out1 == out2


def test_fanin_reports_unreachable_node(cluster):
    hub, ids, nodes = cluster
    nodes["n1"].stop()
    hub.unregister("n1") if hasattr(hub, "unregister") else None
    out = nodes["n0"].top_queries()
    # n1 may answer from its (stopped) local transport or fail; either
    # way the merge never throws and every live node reports
    assert "n0" in out["nodes"] or out.get("failed_nodes")


# -- SLO breach snapshot ----------------------------------------------------

def test_soak_breach_capture_includes_top_queries_snapshot(tmp_path):
    from opensearch_tpu.testing.workload import SoakConfig, SoakRunner
    cfg = SoakConfig.smoke(
        n_ops=8, n_docs=8, faults_enabled=False, control_run=False,
        slos={"p99_ms": {"search": -1.0},
              "max_rejection_rate": 1.0,
              "max_unexpected_errors": 1000,
              "require_convergence": False})
    report = SoakRunner(str(tmp_path), cfg).run()
    breached = [v for v in report["verdicts"] if not v["ok"]]
    assert breached, "forced breach did not breach"
    qi = report["chaos"]["query_insights"]
    assert qi["totals"]["records"] > 0
    assert qi["top_queries"], "no workload evidence in the snapshot"
    for v in breached:
        snap = v["flight_recorder"]["detail"]["query_insights"]
        assert snap["totals"]["records"] > 0
        assert 0.0 <= snap["coalescability"]["coalescable_fraction"] <= 1


# -- lint: prometheus label cardinality -------------------------------------

def test_prom_label_lint_repo_clean():
    proc = subprocess.run(
        [sys.executable, f"{TOOLS}/check_prom_labels.py"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_prom_label_lint_catches_unannotated_site(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        'def emit(term):\n'
        '    return f\'my_metric{{query="{term}"}} 1\'\n')
    proc = subprocess.run(
        [sys.executable, f"{TOOLS}/check_prom_labels.py", str(bad)],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "label" in proc.stdout
    ok = tmp_path / "ok.py"
    ok.write_text(
        'def emit(sig):\n'
        '    # label-ok: sig is a bounded top-N signature hash\n'
        '    return f\'my_metric{{signature="{sig}"}} 1\'\n')
    proc = subprocess.run(
        [sys.executable, f"{TOOLS}/check_prom_labels.py", str(ok)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout
