"""k-NN correctness: exact brute-force must have recall@k == 1.0 vs a
numpy oracle for every space type, including filtered knn and hybrid
bool composition (VERDICT round-1 item 7's 'done' bar)."""

import numpy as np
import pytest

from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher

DIM = 16


def build(space, n_docs=120, n_segments=3, seed=21):
    rng = np.random.default_rng(seed)
    mapper = DocumentMapper({"properties": {
        "vec": {"type": "knn_vector", "dimension": DIM, "space_type": space},
        "group": {"type": "keyword"},
        "body": {"type": "text"},
    }})
    writer = SegmentWriter()
    segments, vectors, groups = [], [], []
    per = n_docs // n_segments
    doc_no = 0
    for si in range(n_segments):
        parsed = []
        for _ in range(per):
            v = rng.normal(size=DIM).astype(np.float32)
            g = ["even", "odd"][doc_no % 2]
            vectors.append(v)
            groups.append(g)
            parsed.append(mapper.parse(str(doc_no), {
                "vec": v.tolist(), "group": g, "body": "common text"}))
            doc_no += 1
        segments.append(writer.build(parsed, f"s{si}"))
    return ShardSearcher(segments, mapper), np.stack(vectors), groups


def oracle_scores(vectors, q, space):
    dots = vectors @ q
    if space == "l2":
        d2 = ((vectors - q) ** 2).sum(axis=1)
        return 1.0 / (1.0 + d2)
    if space == "cosinesimil":
        cos = dots / (np.linalg.norm(vectors, axis=1) * np.linalg.norm(q))
        return (1.0 + cos) / 2.0
    return np.where(dots >= 0, dots + 1.0, 1.0 / (1.0 - dots))


@pytest.mark.parametrize("space", ["l2", "cosinesimil", "innerproduct"])
def test_knn_exact_recall(space):
    searcher, vectors, _ = build(space)
    rng = np.random.default_rng(1)
    for _ in range(3):
        q = rng.normal(size=DIM).astype(np.float32)
        resp = searcher.search({"query": {"knn": {"vec": {
            "vector": q.tolist(), "k": 10}}}, "size": 10})
        exp = oracle_scores(vectors.astype(np.float64), q.astype(np.float64),
                            space)
        order = np.argsort(-exp, kind="stable")[:10]
        got_ids = [h["_id"] for h in resp["hits"]["hits"]]
        assert got_ids == [str(i) for i in order]       # recall@10 == 1.0
        for h, i in zip(resp["hits"]["hits"], order):
            assert h["_score"] == pytest.approx(exp[i], rel=1e-4)


def test_knn_filtered():
    searcher, vectors, groups = build("l2")
    q = np.zeros(DIM, np.float32)
    resp = searcher.search({"query": {"knn": {"vec": {
        "vector": q.tolist(), "k": 5,
        "filter": {"term": {"group": "even"}}}}}, "size": 5})
    exp = oracle_scores(vectors, q, "l2")
    even = [i for i, g in enumerate(groups) if g == "even"]
    order = sorted(even, key=lambda i: -exp[i])[:5]
    assert [h["_id"] for h in resp["hits"]["hits"]] == [str(i) for i in order]


def test_knn_k_limits_matches():
    searcher, vectors, _ = build("l2")
    resp = searcher.search({"query": {"knn": {"vec": {
        "vector": np.zeros(DIM).tolist(), "k": 7}}}, "size": 50})
    assert resp["hits"]["total"]["value"] == 7


def test_knn_hybrid_bool():
    """BM25 + knn in one bool: scores sum for docs matching both."""
    searcher, vectors, _ = build("l2")
    q = np.zeros(DIM, np.float32)
    resp = searcher.search({"query": {"bool": {
        "should": [
            {"match": {"body": "common"}},
            {"knn": {"vec": {"vector": q.tolist(), "k": 3}}},
        ]}}, "size": 120})
    exp = oracle_scores(vectors, q, "l2")
    top3 = set(np.argsort(-exp, kind="stable")[:3])
    base = {h["_id"]: h["_score"] for h in resp["hits"]["hits"]}
    assert resp["hits"]["total"]["value"] == 120
    some_plain = next(h for h in resp["hits"]["hits"]
                      if int(h["_id"]) not in top3)
    for i in top3:
        assert base[str(i)] == pytest.approx(
            some_plain["_score"] + exp[i], rel=1e-4)


def test_knn_dim_mismatch_rejected():
    from opensearch_tpu.common.errors import IllegalArgumentError
    searcher, _, _ = build("l2")
    with pytest.raises(IllegalArgumentError):
        searcher.search({"query": {"knn": {"vec": {
            "vector": [1.0, 2.0], "k": 3}}}})
