"""k-NN correctness: exact brute-force must have recall@k == 1.0 vs a
numpy oracle for every space type, including filtered knn and hybrid
bool composition (VERDICT round-1 item 7's 'done' bar)."""

import numpy as np
import pytest

from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher

DIM = 16


def build(space, n_docs=120, n_segments=3, seed=21):
    rng = np.random.default_rng(seed)
    mapper = DocumentMapper({"properties": {
        "vec": {"type": "knn_vector", "dimension": DIM, "space_type": space},
        "group": {"type": "keyword"},
        "body": {"type": "text"},
    }})
    writer = SegmentWriter()
    segments, vectors, groups = [], [], []
    per = n_docs // n_segments
    doc_no = 0
    for si in range(n_segments):
        parsed = []
        for _ in range(per):
            v = rng.normal(size=DIM).astype(np.float32)
            g = ["even", "odd"][doc_no % 2]
            vectors.append(v)
            groups.append(g)
            parsed.append(mapper.parse(str(doc_no), {
                "vec": v.tolist(), "group": g, "body": "common text"}))
            doc_no += 1
        segments.append(writer.build(parsed, f"s{si}"))
    return ShardSearcher(segments, mapper), np.stack(vectors), groups


def oracle_scores(vectors, q, space):
    dots = vectors @ q
    if space == "l2":
        d2 = ((vectors - q) ** 2).sum(axis=1)
        return 1.0 / (1.0 + d2)
    if space == "cosinesimil":
        cos = dots / (np.linalg.norm(vectors, axis=1) * np.linalg.norm(q))
        return (1.0 + cos) / 2.0
    return np.where(dots >= 0, dots + 1.0, 1.0 / (1.0 - dots))


@pytest.mark.parametrize("space", ["l2", "cosinesimil", "innerproduct"])
def test_knn_exact_recall(space):
    searcher, vectors, _ = build(space)
    rng = np.random.default_rng(1)
    for _ in range(3):
        q = rng.normal(size=DIM).astype(np.float32)
        resp = searcher.search({"query": {"knn": {"vec": {
            "vector": q.tolist(), "k": 10}}}, "size": 10})
        exp = oracle_scores(vectors.astype(np.float64), q.astype(np.float64),
                            space)
        order = np.argsort(-exp, kind="stable")[:10]
        got_ids = [h["_id"] for h in resp["hits"]["hits"]]
        assert got_ids == [str(i) for i in order]       # recall@10 == 1.0
        for h, i in zip(resp["hits"]["hits"], order):
            assert h["_score"] == pytest.approx(exp[i], rel=1e-4)


def test_knn_filtered():
    searcher, vectors, groups = build("l2")
    q = np.zeros(DIM, np.float32)
    resp = searcher.search({"query": {"knn": {"vec": {
        "vector": q.tolist(), "k": 5,
        "filter": {"term": {"group": "even"}}}}}, "size": 5})
    exp = oracle_scores(vectors, q, "l2")
    even = [i for i, g in enumerate(groups) if g == "even"]
    order = sorted(even, key=lambda i: -exp[i])[:5]
    assert [h["_id"] for h in resp["hits"]["hits"]] == [str(i) for i in order]


def test_knn_k_limits_matches():
    searcher, vectors, _ = build("l2")
    resp = searcher.search({"query": {"knn": {"vec": {
        "vector": np.zeros(DIM).tolist(), "k": 7}}}, "size": 50})
    assert resp["hits"]["total"]["value"] == 7


def test_knn_hybrid_bool():
    """BM25 + knn in one bool: scores sum for docs matching both."""
    searcher, vectors, _ = build("l2")
    q = np.zeros(DIM, np.float32)
    resp = searcher.search({"query": {"bool": {
        "should": [
            {"match": {"body": "common"}},
            {"knn": {"vec": {"vector": q.tolist(), "k": 3}}},
        ]}}, "size": 120})
    exp = oracle_scores(vectors, q, "l2")
    top3 = set(np.argsort(-exp, kind="stable")[:3])
    base = {h["_id"]: h["_score"] for h in resp["hits"]["hits"]}
    assert resp["hits"]["total"]["value"] == 120
    some_plain = next(h for h in resp["hits"]["hits"]
                      if int(h["_id"]) not in top3)
    for i in top3:
        assert base[str(i)] == pytest.approx(
            some_plain["_score"] + exp[i], rel=1e-4)


def test_knn_dim_mismatch_rejected():
    from opensearch_tpu.common.errors import IllegalArgumentError
    searcher, _, _ = build("l2")
    with pytest.raises(IllegalArgumentError):
        searcher.search({"query": {"knn": {"vec": {
            "vector": [1.0, 2.0], "k": 3}}}})


# ---------------------------------------------------------------------------
# ANN (IVF / IVF-PQ) wired through the knn query path (VERDICT r3 item 2)
# ---------------------------------------------------------------------------


def build_ann(method, n_docs=600, n_segments=2, dim=32, seed=5,
              space="l2"):
    """Clustered synthetic corpus (GloVe-like: gaussian blobs) mapped with
    an ANN method — recall against brute force is meaningful only when the
    data actually has cluster structure."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(12, dim))
    mapper = DocumentMapper({"properties": {
        "vec": {"type": "knn_vector", "dimension": dim,
                "space_type": space, "method": method},
    }})
    writer = SegmentWriter()
    segments, vectors = [], []
    per = n_docs // n_segments
    doc_no = 0
    for si in range(n_segments):
        parsed = []
        for _ in range(per):
            c = centers[rng.integers(len(centers))]
            v = (c + rng.normal(scale=0.6, size=dim)).astype(np.float32)
            vectors.append(v)
            parsed.append(mapper.parse(str(doc_no), {"vec": v.tolist()}))
            doc_no += 1
        segments.append(writer.build(parsed, f"a{si}"))
    return ShardSearcher(segments, mapper), np.stack(vectors)


@pytest.mark.parametrize("method", [
    {"name": "ivf", "parameters": {"nlist": 16, "nprobe": 8}},
    {"name": "ivf_pq", "parameters": {"nlist": 16, "nprobe": 8, "m": 8}},
])
def test_knn_ann_recall(method):
    searcher, vectors = build_ann(method)
    rng = np.random.default_rng(3)
    hits_sum = total = 0
    for _ in range(10):
        qv = vectors[rng.integers(len(vectors))] + \
            rng.normal(scale=0.1, size=vectors.shape[1]).astype(np.float32)
        resp = searcher.search({"query": {"knn": {"vec": {
            "vector": qv.tolist(), "k": 10}}}, "size": 10})
        exp = oracle_scores(vectors.astype(np.float64),
                            qv.astype(np.float64), "l2")
        truth = {str(i) for i in np.argsort(-exp, kind="stable")[:10]}
        got = {h["_id"] for h in resp["hits"]["hits"]}
        hits_sum += len(truth & got)
        total += 10
    assert hits_sum / total >= 0.9          # recall@10 over 10 queries


def test_knn_ann_nprobe_full_is_exact():
    """nprobe == nlist probes every cluster -> identical to brute force."""
    method = {"name": "ivf", "parameters": {"nlist": 8, "nprobe": 8}}
    searcher, vectors = build_ann(method, n_docs=300, n_segments=1)
    q = vectors[7] * 0.9
    resp = searcher.search({"query": {"knn": {"vec": {
        "vector": q.tolist(), "k": 10}}}, "size": 10})
    exp = oracle_scores(vectors.astype(np.float64), q.astype(np.float64),
                        "l2")
    order = np.argsort(-exp, kind="stable")[:10]
    assert [h["_id"] for h in resp["hits"]["hits"]] == [str(i) for i in order]


def test_knn_ann_request_override_and_deletes():
    """method_parameters overrides nprobe per request; deleted docs never
    surface from the probed clusters (live mask applied post-gather)."""
    method = {"name": "ivf", "parameters": {"nlist": 8, "nprobe": 8}}
    searcher, vectors = build_ann(method, n_docs=200, n_segments=1)
    q = vectors[11]
    resp = searcher.search({"query": {"knn": {"vec": {
        "vector": q.tolist(), "k": 3,
        "method_parameters": {"nprobe": 1}}}}, "size": 3})
    assert len(resp["hits"]["hits"]) == 3
    top = resp["hits"]["hits"][0]["_id"]
    seg = searcher.segments[0]
    seg.delete_local(seg.id_to_local[top])
    # searchers are point-in-time (Lucene reader semantics): reopen to see
    # the delete; the trained IVF structure is reused, not rebuilt
    reopened = ShardSearcher(searcher.segments, searcher.mapper)
    assert seg._ann                       # cache survived the reopen
    resp2 = reopened.search({"query": {"knn": {"vec": {
        "vector": q.tolist(), "k": 3}}}, "size": 3})
    assert top not in {h["_id"] for h in resp2["hits"]["hits"]}
