"""script_score: expression subset compiled to jnp, the k-NN plugin's
knn_score script (BASELINE config #2's exact shape), clean 400s for
unsupported scripts (VERDICT r3 item 8; ref script/ScriptService.java:438,
modules/lang-painless)."""

import numpy as np
import pytest

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher
from opensearch_tpu.search.scripting import ScriptException

DIM = 8


def build(n=20, seed=3):
    rng = np.random.default_rng(seed)
    mapper = DocumentMapper({"properties": {
        "title": {"type": "text"},
        "rank": {"type": "long"},
        "weight": {"type": "double"},
        "vec": {"type": "knn_vector", "dimension": DIM,
                "space_type": "l2"},
    }})
    writer = SegmentWriter()
    vecs = rng.normal(size=(n, DIM)).astype(np.float32)
    segs, parsed = [], []
    for i in range(n):
        doc = {"title": "common words here", "rank": i,
               "weight": float(i) / 2.0, "vec": vecs[i].tolist()}
        if i == n - 1:
            doc.pop("weight")                # one missing value
        parsed.append(mapper.parse(str(i), doc))
        if i == n // 2:
            segs.append(writer.build(parsed, "sc0"))
            parsed = []
    segs.append(writer.build(parsed, "sc1"))
    return ShardSearcher(segs, mapper), vecs


def search_scores(searcher, script, query=None, size=30, **kw):
    body = {"query": {"script_score": {
        "query": query or {"match_all": {}}, "script": script, **kw}},
        "size": size}
    resp = searcher.search(body)
    return {h["_id"]: h["_score"] for h in resp["hits"]["hits"]}


def test_field_arithmetic_and_score():
    searcher, _ = build()
    got = search_scores(searcher, {
        "source": "_score * 2 + doc['rank'].value"},
        query={"match": {"title": "common"}})
    base = searcher.search({"query": {"match": {"title": "common"}},
                            "size": 30})
    base_scores = {h["_id"]: h["_score"] for h in base["hits"]["hits"]}
    for did, s in got.items():
        assert s == pytest.approx(base_scores[did] * 2 + int(did), rel=1e-5)


def test_math_functions_and_params():
    searcher, _ = build()
    got = search_scores(searcher, {
        "source": "Math.log(doc['rank'].value + params.offset)",
        "params": {"offset": 2}})
    for did, s in got.items():
        assert s == pytest.approx(np.log(int(did) + 2), rel=1e-5)


def test_missing_value_reads_zero_and_size():
    searcher, _ = build()
    got = search_scores(searcher, {
        "source": "doc['weight'].size() > 0 ? doc['weight'].value : -1"})
    assert got["19"] == pytest.approx(-1.0)
    assert got["4"] == pytest.approx(2.0)


def test_knn_score_script_matches_exact_knn():
    """BASELINE config #2: knn via script_score must rank identically to
    the knn query's exact brute-force kernel."""
    searcher, vecs = build()
    q = vecs[7] + 0.05
    got = searcher.search({"query": {"script_score": {
        "query": {"match_all": {}},
        "script": {"lang": "knn", "source": "knn_score",
                   "params": {"field": "vec",
                              "query_value": q.tolist(),
                              "space_type": "l2"}}}}, "size": 5})
    knn = searcher.search({"query": {"knn": {"vec": {
        "vector": q.tolist(), "k": 5}}}, "size": 5})
    assert [h["_id"] for h in got["hits"]["hits"]] == \
        [h["_id"] for h in knn["hits"]["hits"]]
    for a, b in zip(got["hits"]["hits"], knn["hits"]["hits"]):
        assert a["_score"] == pytest.approx(b["_score"], rel=1e-5)


def test_cosine_similarity_function():
    searcher, vecs = build()
    q = np.ones(DIM, np.float32)
    got = search_scores(searcher, {
        "source": "cosineSimilarity(params.qv, doc['vec']) + 1.0",
        "params": {"qv": q.tolist()}})
    for did, s in got.items():
        v = vecs[int(did)]
        cos = float(v @ q / (np.linalg.norm(v) * np.linalg.norm(q)))
        assert s == pytest.approx(cos + 1.0, rel=1e-4)


def test_min_score_filters_docs():
    searcher, _ = build()
    got = search_scores(searcher, {"source": "doc['rank'].value"},
                        min_score=10)
    assert set(got) == {str(i) for i in range(10, 20)}


def test_unknown_constructs_are_400_not_crash():
    searcher, _ = build()
    for bad in [
        {"source": "__import__('os').system('x')"},
        {"source": "doc['rank'].value; 1"},
        {"source": "while True: 1"},
        {"source": "unknownvar + 1"},
        {"source": "doc['rank'].values"},
        {"source": "params.qv.unknown()"},
        {"lang": "mustache", "source": "1"},
        {"source": ""},
    ]:
        with pytest.raises(OpenSearchTpuError) as ei:
            search_scores(searcher, bad)
        assert getattr(ei.value, "status", 500) == 400, bad


def test_script_over_text_field_rejected():
    searcher, _ = build()
    with pytest.raises(ScriptException):
        search_scores(searcher, {"source": "doc['title'].value"})


def test_same_script_shares_program_across_param_values():
    """Changing a param value must NOT be a new compiled program — params
    are dynamic inputs (plan equality ignores values)."""
    from opensearch_tpu.search.compiler import compile_query
    from opensearch_tpu.search.query_dsl import parse_query

    searcher, _ = build()
    q1 = parse_query({"script_score": {
        "query": {"match_all": {}},
        "script": {"source": "doc['rank'].value * params.f",
                   "params": {"f": 2.0}}}})
    q2 = parse_query({"script_score": {
        "query": {"match_all": {}},
        "script": {"source": "doc['rank'].value * params.f",
                   "params": {"f": 5.0}}}})
    p1, _b1 = compile_query(q1, searcher.ctx)
    p2, _b2 = compile_query(q2, searcher.ctx)
    assert p1 == p2 and hash(p1) == hash(p2)


def test_painless_syntax_translation_preserves_quoted_fields():
    """&&/||/true inside doc['...'] quotes must survive; outside they
    translate (round-4 review finding)."""
    from opensearch_tpu.search.scripting import _painless_to_python

    assert _painless_to_python("a && b || !c") == "a  and  b  or   not c"
    assert "doc['true']" in _painless_to_python("doc['true'].value * 2")
    assert _painless_to_python("x != 1") == "x != 1"
    out = _painless_to_python(
        "doc['w'].size() > 0 && true ? doc['w'].value : 0")
    assert "doc['w']" in out and "if" in out
