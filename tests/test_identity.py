"""Identity: internal users, basic auth, role enforcement (ref
identity/IdentityService.java:23)."""

import base64
import json
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.node import Node


def call(node, method, path, body=None, auth=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    headers = {"Content-Type": "application/json"} if data else {}
    if auth:
        headers["Authorization"] = "Basic " + base64.b64encode(
            f"{auth[0]}:{auth[1]}".encode()).decode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


@pytest.fixture()
def node(tmp_path):
    n = Node(str(tmp_path / "node"), port=0).start()
    yield n
    n.stop()


def test_disabled_by_default(node):
    assert call(node, "GET", "/_cluster/health")[0] == 200
    assert call(node, "GET", "/_security/user")[0] == 200


def test_auth_flow_and_roles(node):
    # bootstrap: create users, then enable
    assert call(node, "PUT", "/_security/user/admin",
                {"password": "s3cret1", "roles": ["admin"]})[0] == 200
    assert call(node, "PUT", "/_security/user/viewer",
                {"password": "v13wer1", "roles": ["readonly"]})[0] == 200
    assert call(node, "PUT", "/_cluster/settings", {
        "persistent": {"identity.enabled": True}},
        auth=("admin", "s3cret1"))[0] == 200
    # anonymous: 401 everywhere except the liveness root
    assert call(node, "GET", "/")[0] == 200
    assert call(node, "GET", "/_cluster/health")[0] == 401
    assert call(node, "PUT", "/idx", {})[0] == 401
    # wrong password: 401
    assert call(node, "GET", "/_cluster/health",
                auth=("admin", "nope000"))[0] == 401
    # admin: full access
    assert call(node, "PUT", "/idx", {}, auth=("admin", "s3cret1"))[0] == 200
    assert call(node, "PUT", "/idx/_doc/1?refresh=true", {"a": 1},
                auth=("admin", "s3cret1"))[0] in (200, 201)
    # readonly: reads + search-shaped POSTs pass, writes 403
    ro = ("viewer", "v13wer1")
    assert call(node, "GET", "/idx/_doc/1", auth=ro)[0] == 200
    assert call(node, "POST", "/idx/_search", {}, auth=ro)[0] == 200
    assert call(node, "POST", "/idx/_count", {}, auth=ro)[0] == 200
    code, body = call(node, "PUT", "/idx/_doc/2", {"a": 2}, auth=ro)
    assert code == 403 and "no permissions" in json.dumps(body)
    assert call(node, "POST", "/_bulk", None, auth=ro)[0] == 403
    # readonly cannot manage users either
    assert call(node, "PUT", "/_security/user/evil",
                {"password": "evil123", "roles": ["admin"]},
                auth=ro)[0] == 403


def test_users_survive_restart(tmp_path):
    n = Node(str(tmp_path / "node"), port=0).start()
    call(n, "PUT", "/_security/user/admin",
         {"password": "s3cret1", "roles": ["admin"]})
    call(n, "PUT", "/_cluster/settings",
         {"persistent": {"identity.enabled": True}})
    n.stop()
    n2 = Node(str(tmp_path / "node"), port=0).start()
    try:
        assert call(n2, "GET", "/_cluster/health")[0] == 401
        assert call(n2, "GET", "/_cluster/health",
                    auth=("admin", "s3cret1"))[0] == 200
    finally:
        n2.stop()


def test_user_validation(node):
    assert call(node, "PUT", "/_security/user/x",
                {"password": "short"})[0] == 400
    assert call(node, "PUT", "/_security/user/x",
                {"password": "longenough",
                 "roles": ["superuser"]})[0] == 400
    assert call(node, "PUT", "/_security/user/a:b",
                {"password": "longenough", "roles": ["admin"]})[0] == 400
    assert call(node, "DELETE", "/_security/user/ghost")[0] == 404


def test_enabled_with_no_users_does_not_lock_out(node):
    assert call(node, "PUT", "/_cluster/settings", {
        "persistent": {"identity.enabled": True}})[0] == 200
    # no users yet: enforcement deferred so the operator can bootstrap
    assert call(node, "GET", "/_cluster/health")[0] == 200
    call(node, "PUT", "/_security/user/admin",
         {"password": "s3cret1", "roles": ["admin"]})
    assert call(node, "GET", "/_cluster/health")[0] == 401


def test_readonly_cannot_write_via_crafted_ids(node):
    """Review regression (reproduced live pre-fix): authorization keys
    on the matched route, so POST /idx/_doc/_search must not let a
    readonly user create a document whose id merely LOOKS like a read
    action."""
    call(node, "PUT", "/_security/user/admin",
         {"password": "s3cret1", "roles": ["admin"]})
    call(node, "PUT", "/_security/user/viewer",
         {"password": "v13wer1", "roles": ["readonly"]})
    call(node, "PUT", "/_cluster/settings",
         {"persistent": {"identity.enabled": True}},
         auth=("admin", "s3cret1"))
    call(node, "PUT", "/idx", {}, auth=("admin", "s3cret1"))
    ro = ("viewer", "v13wer1")
    for path in ("/idx/_doc/_search", "/idx/_doc/_count",
                 "/idx/_update/_msearch"):
        code, _ = call(node, "POST", path, {"a": 1}, auth=ro)
        assert code == 403, path
    # readonly CAN release its own contexts (DELETE scroll/PIT)
    code, body = call(node, "POST", "/idx/_search?scroll=1m",
                      {"size": 1}, auth=ro)
    assert code == 200
    sid = body["_scroll_id"]
    assert call(node, "DELETE", "/_search/scroll",
                {"scroll_id": sid}, auth=ro)[0] == 200
    # but security APIs are admin-only, even GET
    assert call(node, "GET", "/_security/user", auth=ro)[0] == 403
    assert call(node, "GET", "/_security/user",
                auth=("admin", "s3cret1"))[0] == 200


def test_put_user_reports_update_vs_create(node):
    code, body = call(node, "PUT", "/_security/user/u1",
                      {"password": "abcdef1", "roles": ["admin"]})
    assert code == 200 and body["created"] is True
    code, body = call(node, "PUT", "/_security/user/u1",
                      {"password": "newpass1", "roles": ["admin"]})
    assert code == 200 and body["created"] is False


def test_credential_cache_invalidated_on_password_change(node):
    from opensearch_tpu.security.identity import AuthenticationError

    node.identity.put_user("u", "firstpw", ["admin"])
    node.identity.enabled = True
    hdr = "Basic " + base64.b64encode(b"u:firstpw").decode()
    assert node.identity.authenticate(hdr)["name"] == "u"
    assert node.identity.authenticate(hdr)["name"] == "u"  # cached path
    node.identity.put_user("u", "secondpw", ["admin"])
    with pytest.raises(AuthenticationError):
        node.identity.authenticate(hdr)
    hdr2 = "Basic " + base64.b64encode(b"u:secondpw").decode()
    assert node.identity.authenticate(hdr2)["name"] == "u"


def test_client_http_auth(node):
    from opensearch_tpu.client import (AuthorizationException,
                                       OpenSearch, TransportError)

    call(node, "PUT", "/_security/user/admin",
         {"password": "s3cret1", "roles": ["admin"]})
    call(node, "PUT", "/_security/user/viewer",
         {"password": "v13wer1", "roles": ["readonly"]})
    call(node, "PUT", "/_cluster/settings",
         {"persistent": {"identity.enabled": True}},
         auth=("admin", "s3cret1"))
    host = f"http://127.0.0.1:{node.port}"
    anon = OpenSearch(hosts=[host])
    with pytest.raises(TransportError) as e:
        anon.cluster.health()
    assert e.value.status_code == 401
    admin = OpenSearch(hosts=[host], http_auth=("admin", "s3cret1"))
    assert admin.cluster.health()["status"] in ("green", "yellow")
    admin.indices.create("ci", {})
    ro = OpenSearch(hosts=[host], http_auth=("viewer", "v13wer1"))
    assert ro.search(index="ci", body={})["hits"]["total"]["value"] == 0
    with pytest.raises(AuthorizationException):
        ro.index("ci", {"a": 1}, id="1")


def test_password_rotation_preserves_roles(node):
    """Review regression: PUT without [roles] must not demote — the
    sole admin rotating their password would lock out user management
    permanently."""
    call(node, "PUT", "/_security/user/boss",
         {"password": "firstpw", "roles": ["admin"]})
    call(node, "PUT", "/_cluster/settings",
         {"persistent": {"identity.enabled": True}})
    code, body = call(node, "PUT", "/_security/user/boss",
                      {"password": "secondpw"},
                      auth=("boss", "firstpw"))
    assert code == 200 and body["created"] is False
    # still admin: can manage users with the NEW password
    assert call(node, "PUT", "/_security/user/other",
                {"password": "otherpw", "roles": ["readonly"]},
                auth=("boss", "secondpw"))[0] == 200
    # query param cannot retarget the path's username
    code, _ = call(node, "DELETE", "/_security/user/other?username=boss",
                   auth=("boss", "secondpw"))
    assert code == 200
    users = call(node, "GET", "/_security/user",
                 auth=("boss", "secondpw"))[1]
    assert "boss" in users and "other" not in users
