"""Searchable snapshots: mount a snapshot as a read-only index whose
segment files stream from the repository through the node-level LRU file
cache (ref RestoreService.java remote_snapshot storage type,
index/store/remote/filecache/FileCache.java)."""

import json
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.index.filecache import FileCache
from opensearch_tpu.node import Node


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


# -- FileCache unit behavior -------------------------------------------------

def test_file_cache_lru_eviction(tmp_path):
    fc = FileCache(str(tmp_path / "fc"), max_bytes=100)
    fc.get("a", lambda: b"x" * 40)
    fc.get("b", lambda: b"x" * 40)
    fc.get("a", lambda: 1 / 0)          # hit: fetch not called
    fc.get("c", lambda: b"x" * 40)      # evicts b (LRU), not a
    stats = fc.stats()
    assert stats["evictions"] == 1 and stats["entries"] == 2
    assert (tmp_path / "fc" / "a").exists()
    assert not (tmp_path / "fc" / "b").exists()
    # evicted entries re-fetch at the same stable path
    p = fc.get("b", lambda: b"y" * 10)
    assert p == str(tmp_path / "fc" / "b")


def test_file_cache_oversized_entry_and_warm_restart(tmp_path):
    fc = FileCache(str(tmp_path / "fc"), max_bytes=10)
    p = fc.get("big", lambda: b"z" * 50)   # larger than the whole budget
    assert (tmp_path / "fc" / "big").read_bytes() == b"z" * 50
    fc2 = FileCache(str(tmp_path / "fc"), max_bytes=10)
    assert fc2.stats()["entries"] == 1     # index rebuilt from disk
    fc2.get("big", lambda: 1 / 0)          # still a hit, no refetch


# -- end-to-end mount --------------------------------------------------------

@pytest.fixture()
def mounted(tmp_path):
    node = Node(str(tmp_path / "node"), port=0, path_repo=[str(tmp_path)]).start()
    call(node, "PUT", "/_snapshot/repo", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo")}})
    call(node, "PUT", "/src", {
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {"t": {"type": "text"},
                                    "n": {"type": "long"}}}})
    for i in range(20):
        call(node, "PUT", f"/src/_doc/{i}", {"t": f"event {i}", "n": i})
    call(node, "POST", "/src/_refresh")
    assert call(node, "PUT", "/_snapshot/repo/snap1",
                {"indices": "src"})[0] == 200
    call(node, "DELETE", "/src")
    code, body = call(node, "POST", "/_snapshot/repo/snap1/_restore", {
        "indices": "src", "rename_pattern": "src",
        "rename_replacement": "mounted",
        "storage_type": "remote_snapshot"})
    assert code == 200, body
    yield node, tmp_path
    node.stop()


def test_mount_searches_without_local_copy(mounted):
    node, tmp_path = mounted
    code, body = call(node, "GET", "/mounted/_search",
                      body={"query": {"match": {"t": "event"}},
                            "size": 25})
    assert code == 200 and body["hits"]["total"]["value"] == 20
    # no segment data was copied into the index dir: every segment file
    # is a symlink into the node file cache
    import os
    idx = tmp_path / "node" / "indices" / "mounted"
    seg_files = [os.path.join(r, f) for r, _, fs in os.walk(idx)
                 for f in fs if "/segments" in r or r.endswith("segments")]
    assert seg_files and all(os.path.islink(p) for p in seg_files)
    # aggs + get work too
    code, body = call(node, "GET", "/mounted/_search", body={
        "size": 0, "aggs": {"s": {"sum": {"field": "n"}}}})
    assert body["aggregations"]["s"]["value"] == sum(range(20))
    code, doc = call(node, "GET", "/mounted/_doc/7")
    assert code == 200 and doc["_source"]["n"] == 7


def test_mount_is_read_only(mounted):
    node, _ = mounted
    code, body = call(node, "PUT", "/mounted/_doc/99", {"n": 99})
    assert code == 403, body
    assert "read-only" in json.dumps(body)
    code, _ = call(node, "DELETE", "/mounted/_doc/3")
    assert code == 403
    code, body = call(node, "POST", "/_bulk", {})  # smoke other routes
    code, _ = call(node, "POST", "/mounted/_forcemerge")
    assert code == 403
    # flush is a no-op, not an error (the reference accepts it)
    assert call(node, "POST", "/mounted/_flush")[0] == 200


def test_backing_snapshot_protected_until_unmount(mounted):
    node, _ = mounted
    code, body = call(node, "DELETE", "/_snapshot/repo/snap1")
    assert code == 400 and "mounted" in json.dumps(body)
    assert call(node, "DELETE", "/mounted")[0] == 200
    assert call(node, "DELETE", "/_snapshot/repo/snap1")[0] == 200


def test_mount_survives_restart_and_eviction(mounted):
    node, tmp_path = mounted
    # shrink the cache to force every blob out, then restart: the
    # deferred boot-time mount re-fetches through the cache
    code, _ = call(node, "PUT", "/_cluster/settings", {
        "persistent": {"node.searchable_snapshot.cache.size": 1}})
    assert code == 200
    node.stop()
    import shutil
    shutil.rmtree(tmp_path / "node" / "filecache")
    node2 = Node(str(tmp_path / "node"), port=0, path_repo=[str(tmp_path)]).start()
    try:
        code, body = call(node2, "GET", "/mounted/_search",
                          body={"size": 25})
        assert code == 200 and body["hits"]["total"]["value"] == 20
        code, stats = call(node2, "GET", "/_nodes/stats")
        fc = stats["nodes"][node2.node_id]["file_cache"]
        assert fc["misses"] > 0
    finally:
        node2.stop()


def test_mount_missing_repo_does_not_block_boot(mounted):
    node, tmp_path = mounted
    node.stop()
    # repository contents vanish: node must still boot, mount stays
    # closed (404) instead of crashing startup
    import shutil
    shutil.rmtree(tmp_path / "repo")
    shutil.rmtree(tmp_path / "node" / "filecache")
    node2 = Node(str(tmp_path / "node"), port=0, path_repo=[str(tmp_path)]).start()
    try:
        assert call(node2, "GET", "/_cluster/health")[0] == 200
        assert call(node2, "GET", "/mounted/_search", body={})[0] == 404
    finally:
        node2.stop()


def test_file_cache_pin_and_shrink(tmp_path):
    """Review regressions: (a) materializing a shard bigger than the
    whole budget must pin its file set (fetching file N previously
    evicted file 1's blob from under its symlink); (b) shrinking
    max_bytes dynamically reclaims disk immediately."""
    fc = FileCache(str(tmp_path / "fc"), max_bytes=50)
    with fc.pin({"a", "b", "c"}):
        fc.get("a", lambda: b"x" * 40)
        fc.get("b", lambda: b"x" * 40)
        fc.get("c", lambda: b"x" * 40)
        assert fc.stats()["entries"] == 3   # pinned set exceeds budget
    # pins released: next accounting evicts back toward the budget
    assert fc.stats()["size_in_bytes"] <= 50
    fc2 = FileCache(str(tmp_path / "fc2"), max_bytes=1000)
    for i in range(5):
        fc2.get(f"s{i}", lambda: b"y" * 100)
    fc2.set_max_bytes(250)
    st = fc2.stats()
    assert st["size_in_bytes"] <= 250 and st["evictions"] >= 3
    import os
    assert len(os.listdir(tmp_path / "fc2")) == st["entries"]


def test_mount_blocks_mapping_updates(mounted):
    node, _ = mounted
    code, body = call(node, "PUT", "/mounted/_mapping",
                      {"properties": {"extra": {"type": "keyword"}}})
    assert code == 403, body


def test_mount_larger_than_cache_budget(tmp_path):
    """A mount whose file set exceeds the cache budget still opens (over
    budget while pinned) and searches correctly."""
    node = Node(str(tmp_path / "node"), port=0, path_repo=[str(tmp_path)]).start()
    try:
        call(node, "PUT", "/_snapshot/r", {
            "type": "fs", "settings": {"location": str(tmp_path / "r")}})
        call(node, "PUT", "/_cluster/settings", {
            "persistent": {"node.searchable_snapshot.cache.size": 1}})
        call(node, "PUT", "/big", {"mappings": {"properties": {
            "t": {"type": "text"}}}})
        for i in range(30):
            call(node, "PUT", f"/big/_doc/{i}", {"t": f"payload {i}"})
        call(node, "POST", "/big/_refresh")
        call(node, "PUT", "/_snapshot/r/s", {"indices": "big"})
        call(node, "DELETE", "/big")
        code, body = call(node, "POST", "/_snapshot/r/s/_restore", {
            "indices": "big", "rename_pattern": "big",
            "rename_replacement": "bigm",
            "storage_type": "remote_snapshot"})
        assert code == 200, body
        code, body = call(node, "GET", "/bigm/_search", body={"size": 0})
        assert code == 200 and body["hits"]["total"]["value"] == 30
    finally:
        node.stop()
