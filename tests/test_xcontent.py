"""x-content multi-format: CBOR codec + YAML, request/response
negotiation over REST (ref libs/x-content XContentType.java:38)."""

import json
import struct
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.common.errors import OpenSearchTpuError, ParsingError
from opensearch_tpu.common.xcontent import (cbor_dumps, cbor_loads,
                                            from_bytes, to_bytes)
from opensearch_tpu.node import Node


@pytest.mark.parametrize("value", [
    None, True, False, 0, 23, 24, 255, 256, 65536, 2**32, -1, -25,
    1.5, -2.75, "", "héllo ✓", b"\x00\xff", [], [1, [2, 3], "x"],
    {}, {"a": 1, "nested": {"b": [True, None, 3.14]}},
])
def test_cbor_roundtrip(value):
    assert cbor_loads(cbor_dumps(value)) == value


def test_cbor_half_float_and_tag_decode():
    # 0xF9 0x3C00 = half-precision 1.0; tag 0 wrapping a string
    assert cbor_loads(bytes([0xF9, 0x3C, 0x00])) == 1.0
    tagged = bytes([0xC0]) + cbor_dumps("2026-01-01")
    assert cbor_loads(tagged) == "2026-01-01"


def test_cbor_malformed():
    with pytest.raises(ParsingError):
        cbor_loads(cbor_dumps({"a": 1})[:-1])      # truncated
    with pytest.raises(ParsingError):
        cbor_loads(cbor_dumps(1) + b"\x00")        # trailing bytes
    with pytest.raises(ParsingError):
        cbor_loads(bytes([0x5F]))                  # indefinite length


def test_from_bytes_negotiation():
    assert from_bytes(b'{"a": 1}') == {"a": 1}
    assert from_bytes(b"a: 1\nb: [x, y]\n",
                      "application/yaml") == {"a": 1, "b": ["x", "y"]}
    assert from_bytes(cbor_dumps({"a": 1}),
                      "application/cbor; charset=x") == {"a": 1}
    with pytest.raises(OpenSearchTpuError) as e:
        from_bytes(b"x", "application/smile")
    assert e.value.status == 406
    with pytest.raises(ParsingError):
        from_bytes(b"{bad", "application/json")
    with pytest.raises(ParsingError):
        from_bytes(b"a: [unclosed", "application/yaml")


def test_to_bytes_negotiation():
    data, ct = to_bytes({"a": 1})
    assert json.loads(data) == {"a": 1} and "json" in ct
    data, ct = to_bytes({"a": 1}, format_param="yaml")
    assert b"a: 1" in data and "yaml" in ct
    data, ct = to_bytes({"a": 1}, accept="application/cbor")
    assert cbor_loads(data) == {"a": 1} and ct == "application/cbor"


def _raw(node, method, path, data=None, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{node.port}{path}", data=data,
        method=method, headers=headers or {})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, r.headers.get("Content-Type"), r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type"), e.read()


def test_rest_yaml_and_cbor(tmp_path):
    node = Node(str(tmp_path / "n"), port=0).start()
    try:
        # YAML request body
        c, _, _ = _raw(node, "PUT", "/y",
                       data=b"mappings:\n  properties:\n    n:\n"
                            b"      type: long\n",
                       headers={"Content-Type": "application/yaml"})
        assert c == 200
        c, _, _ = _raw(node, "PUT", "/y/_doc/1?refresh=true",
                       data=b"n: 7\n",
                       headers={"Content-Type": "application/yaml"})
        assert c in (200, 201)
        # YAML response via format param
        c, ct, body = _raw(node, "GET", "/y/_doc/1?format=yaml")
        assert c == 200 and "yaml" in ct and b"n: 7" in body
        # CBOR request + response via Accept
        c, ct, body = _raw(
            node, "POST", "/y/_search",
            data=cbor_dumps({"query": {"term": {"n": 7}}}),
            headers={"Content-Type": "application/cbor",
                     "Accept": "application/cbor"})
        assert c == 200 and ct == "application/cbor"
        assert cbor_loads(body)["hits"]["total"]["value"] == 1
        # SMILE is a clear 406 both ways
        c, _, _ = _raw(node, "POST", "/y/_search", data=b"x",
                       headers={"Content-Type": "application/smile"})
        assert c == 406
        c, _, _ = _raw(node, "GET", "/y/_doc/1?format=smile")
        assert c == 406
        # _cat stays tabular/json regardless of format param
        c, ct, body = _raw(node, "GET", "/_cat/indices?format=json")
        assert c == 200 and "json" in ct
    finally:
        node.stop()


def test_cbor_malformed_inputs_are_parsing_errors():
    """Review regression: malformed CBOR must surface as 400 parsing
    errors, never as raw TypeError/UnicodeDecodeError/RecursionError
    (500s)."""
    # map with an array key {[1]: 2}
    with pytest.raises(ParsingError, match="map keys"):
        cbor_loads(bytes([0xA1, 0x81, 0x01, 0x02]))
    # invalid UTF-8 text string
    with pytest.raises(ParsingError, match="UTF-8"):
        cbor_loads(bytes([0x62, 0xFF, 0xFE]))
    # deep nesting: 3000 x array-of-one
    with pytest.raises(ParsingError, match="nested too deeply"):
        cbor_loads(bytes([0x81] * 3000) + bytes([0x01]))
    # declared container length far beyond the input
    with pytest.raises(ParsingError, match="exceeds input"):
        cbor_loads(bytes([0x9B]) + struct.pack(">Q", 2**40))


def test_cat_format_json_wins_over_accept(tmp_path):
    node = Node(str(tmp_path / "n"), port=0).start()
    try:
        c, ct, body = _raw(node, "GET", "/_cat/indices?format=json",
                           headers={"Accept": "application/yaml"})
        assert c == 200 and "json" in ct
        json.loads(body)
    finally:
        node.stop()
