"""IVF / IVF-PQ approximate k-NN: recall + semantics vs the exact oracle
(rank-eval style verification, SURVEY §2 rank-eval module note)."""

import numpy as np
import pytest

import jax.numpy as jnp

from opensearch_tpu.ops.ivf import (IvfIndex, IvfPqIndex, ivf_search,
                                    ivf_search_batch, ivfpq_search_l2,
                                    train_kmeans)


def _corpus(n=2000, d=32, seed=5, clusters=30):
    """Clustered synthetic corpus (GloVe-like local structure)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(clusters, d)).astype(np.float32) * 4
    assign = rng.integers(0, clusters, size=n)
    x = centers[assign] + rng.normal(size=(n, d)).astype(np.float32)
    return x.astype(np.float32)


def _exact_top10(x, q):
    d2 = ((x - q) ** 2).sum(axis=1)
    return set(np.argsort(d2, kind="stable")[:10])


def test_kmeans_converges():
    x = _corpus(n=500, d=8, clusters=5)
    valid = np.ones(len(x), bool)
    cents, assign = train_kmeans(x, valid, 5, iters=15)
    assert cents.shape == (5, 8)
    # every point assigned to its nearest centroid
    d2 = ((x[:, None, :] - cents[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(assign, d2.argmin(axis=1))


def test_ivf_recall_at_10():
    x = _corpus()
    valid = np.ones(len(x), bool)
    idx = IvfIndex.build(x, valid, nlist=64, iters=10)
    cents, grouped, gids, gvalid = idx.device()
    live = jnp.ones(len(x), bool)
    rng = np.random.default_rng(9)
    recalls = []
    for _ in range(20):
        q = x[rng.integers(len(x))] + rng.normal(size=x.shape[1]).astype(
            np.float32) * 0.1
        vals, ids = ivf_search(cents, grouped, gids, gvalid,
                               jnp.asarray(q), live, space="l2", k=10,
                               nprobe=8)
        got = set(int(i) for i in np.asarray(ids) if i >= 0)
        recalls.append(len(got & _exact_top10(x, q)) / 10)
    assert np.mean(recalls) >= 0.9, np.mean(recalls)


def test_ivf_respects_live_mask():
    x = _corpus(n=300, d=8)
    valid = np.ones(len(x), bool)
    idx = IvfIndex.build(x, valid, nlist=8)
    cents, grouped, gids, gvalid = idx.device()
    q = jnp.asarray(x[0])
    live = np.ones(len(x), bool)
    vals, ids = ivf_search(cents, grouped, gids, gvalid, q,
                           jnp.asarray(live), space="l2", k=5, nprobe=8)
    top1 = int(ids[0])
    assert top1 == 0                     # the query IS doc 0
    live[top1] = False                   # delete it
    vals2, ids2 = ivf_search(cents, grouped, gids, gvalid, q,
                             jnp.asarray(live), space="l2", k=5, nprobe=8)
    assert top1 not in set(int(i) for i in np.asarray(ids2))


def test_ivf_batch_matches_single():
    x = _corpus(n=400, d=16)
    valid = np.ones(len(x), bool)
    idx = IvfIndex.build(x, valid, nlist=16)
    dev = idx.device()
    live = jnp.ones(len(x), bool)
    qs = jnp.asarray(x[:5])
    bv, bi = ivf_search_batch(*dev, qs, live, space="l2", k=5, nprobe=4)
    for i in range(5):
        sv, si = ivf_search(*dev, qs[i], live, space="l2", k=5, nprobe=4)
        np.testing.assert_array_equal(np.asarray(bi[i]), np.asarray(si))


@pytest.mark.parametrize("space", ["l2", "cosinesimil", "innerproduct"])
def test_ivf_spaces_score_translation(space):
    """nprobe == nlist makes IVF exhaustive: scores must equal the exact
    kernel's for the same winners."""
    from opensearch_tpu.ops.knn import knn_topk

    x = _corpus(n=200, d=8)
    valid = np.ones(len(x), bool)
    idx = IvfIndex.build(x, valid, nlist=4)
    cents, grouped, gids, gvalid = idx.device()
    live = jnp.ones(len(x), bool)
    q = jnp.asarray(x[3])
    vals, ids = ivf_search(cents, grouped, gids, gvalid, q, live,
                           space=space, k=5, nprobe=idx.nlist)
    ev, ei = knn_topk(jnp.asarray(x), live, q, space=space, k=5)
    # summation order differs between the gathered and flat kernels:
    # allow a few ulp on the squared-distance clamp
    np.testing.assert_allclose(np.asarray(vals), np.asarray(ev),
                               rtol=1e-4, atol=1e-4)
    assert set(np.asarray(ids).tolist()) == set(np.asarray(ei).tolist())


def test_ivfpq_recall_at_10():
    x = _corpus(n=1500, d=32)
    valid = np.ones(len(x), bool)
    idx = IvfPqIndex.build(x, valid, nlist=32, m=8)
    cents, cbs, codes, gids, gvalid = idx.device()
    live = jnp.ones(len(x), bool)
    rng = np.random.default_rng(11)
    recalls = []
    for _ in range(15):
        q = x[rng.integers(len(x))] + rng.normal(size=32).astype(
            np.float32) * 0.05
        vals, ids = ivfpq_search_l2(cents, cbs, codes, gids, gvalid,
                                    jnp.asarray(q), live, k=10, nprobe=8)
        got = set(int(i) for i in np.asarray(ids) if i >= 0)
        recalls.append(len(got & _exact_top10(x, q)) / 10)
    # PQ is lossy: the standard bar is recall@10 >= 0.7 at these params
    assert np.mean(recalls) >= 0.7, np.mean(recalls)
