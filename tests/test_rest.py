"""End-to-end REST slice over real HTTP: index lifecycle, _bulk, CRUD,
_search (+aggs, sort), _count, _cluster/health, _cat (the reference's
rest-api-spec YAML-test shapes, VERDICT round-1 item 5)."""

import json
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.node import Node


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    n = Node(str(tmp_path_factory.mktemp("node")), port=0).start()
    yield n
    n.stop()


def call(node, method, path, body=None, ndjson=None, raw=False):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = None
    headers = {}
    if ndjson is not None:
        data = ("\n".join(json.dumps(l) for l in ndjson) + "\n").encode()
        headers["Content-Type"] = "application/x-ndjson"
    elif body is not None:
        data = json.dumps(body).encode()
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, (payload if raw else
                                 json.loads(payload) if payload else {})
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, (payload if raw else
                        json.loads(payload) if payload else {})


def test_root_and_health(node):
    status, body = call(node, "GET", "/")
    assert status == 200 and body["version"]["distribution"] == "opensearch-tpu"
    status, body = call(node, "GET", "/_cluster/health")
    assert status == 200 and body["status"] in ("green", "yellow")


def test_index_lifecycle(node):
    status, body = call(node, "PUT", "/books", {
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {
            "title": {"type": "text"},
            "year": {"type": "integer"},
            "genre": {"type": "keyword"}}}})
    assert status == 200 and body["acknowledged"]
    status, _ = call(node, "HEAD", "/books")
    assert status == 200
    status, body = call(node, "PUT", "/books", {})
    assert status == 400 and "exists" in json.dumps(body)
    status, body = call(node, "GET", "/books/_mapping")
    assert body["books"]["mappings"]["properties"]["title"]["type"] == "text"
    status, body = call(node, "GET", "/books/_settings")
    assert body["books"]["settings"]["index"]["number_of_shards"] == "2"


def test_doc_crud(node):
    call(node, "PUT", "/crud", {})
    status, body = call(node, "PUT", "/crud/_doc/1", {"x": 1})
    assert status == 201 and body["result"] == "created"
    status, body = call(node, "PUT", "/crud/_doc/1", {"x": 2})
    assert status == 200 and body["result"] == "updated" and body["_version"] == 2
    status, body = call(node, "GET", "/crud/_doc/1")
    assert status == 200 and body["_source"] == {"x": 2}
    status, body = call(node, "GET", "/crud/_source/1")
    assert body == {"x": 2}
    # op_type=create conflicts on existing
    status, body = call(node, "PUT", "/crud/_create/1", {"x": 3})
    assert status == 409
    # optimistic concurrency
    status, body = call(node, "PUT", "/crud/_doc/1?if_seq_no=999&if_primary_term=1",
                        {"x": 9})
    assert status == 409
    # update API
    status, body = call(node, "POST", "/crud/_update/1", {"doc": {"y": 5}})
    assert status == 200
    _, body = call(node, "GET", "/crud/_doc/1")
    assert body["_source"] == {"x": 2, "y": 5}
    status, body = call(node, "DELETE", "/crud/_doc/1")
    assert status == 200 and body["result"] == "deleted"
    status, body = call(node, "GET", "/crud/_doc/1")
    assert status == 404 and body["found"] is False
    status, body = call(node, "GET", "/crud/_doc/nope")
    assert status == 404


def test_bulk_and_search(node):
    call(node, "PUT", "/library", {"mappings": {"properties": {
        "title": {"type": "text"}, "year": {"type": "integer"},
        "genre": {"type": "keyword"}}}})
    lines = []
    docs = [
        {"title": "the old man and the sea", "year": 1952, "genre": "fiction"},
        {"title": "war and peace", "year": 1869, "genre": "fiction"},
        {"title": "a brief history of time", "year": 1988, "genre": "science"},
        {"title": "the selfish gene", "year": 1976, "genre": "science"},
        {"title": "sea of tranquility", "year": 2022, "genre": "fiction"},
    ]
    for i, d in enumerate(docs):
        lines.append({"index": {"_index": "library", "_id": str(i)}})
        lines.append(d)
    lines.append({"delete": {"_index": "library", "_id": "99"}})
    status, body = call(node, "POST", "/_bulk?refresh=true", ndjson=lines)
    assert status == 200
    assert body["errors"] is False or body["items"][-1]["delete"]["status"] == 404
    assert [it["index"]["status"] for it in body["items"][:5]] == [201] * 5

    status, body = call(node, "POST", "/library/_search", {
        "query": {"match": {"title": "sea"}}})
    assert status == 200
    ids = {h["_id"] for h in body["hits"]["hits"]}
    assert ids == {"0", "4"}

    status, body = call(node, "POST", "/library/_search", {
        "size": 0,
        "aggs": {"genres": {"terms": {"field": "genre"}},
                 "years": {"stats": {"field": "year"}}}})
    genres = {b["key"]: b["doc_count"]
              for b in body["aggregations"]["genres"]["buckets"]}
    assert genres == {"fiction": 3, "science": 2}
    assert body["aggregations"]["years"]["min"] == 1869

    status, body = call(node, "GET", "/library/_search?q=title:gene")
    assert body["hits"]["total"]["value"] == 1

    status, body = call(node, "POST", "/library/_search", {
        "sort": [{"year": "asc"}], "size": 2})
    assert [h["_id"] for h in body["hits"]["hits"]] == ["1", "0"]

    status, body = call(node, "POST", "/library/_count",
                        {"query": {"term": {"genre": "science"}}})
    assert body["count"] == 2


def test_bulk_partial_errors(node):
    lines = [
        {"index": {"_index": "mixed", "_id": "1"}},
        {"n": 1},
        {"index": {"_index": "mixed", "_id": "2"}},
        {"n": "not-a-number-for-long-field"},
    ]
    call(node, "PUT", "/mixed",
         {"mappings": {"properties": {"n": {"type": "long"}}}})
    status, body = call(node, "POST", "/_bulk?refresh=true", ndjson=lines)
    assert status == 200
    assert body["errors"] is True
    assert body["items"][0]["index"]["status"] == 201
    assert body["items"][1]["index"]["status"] == 400
    assert "error" in body["items"][1]["index"]


def test_multi_index_search(node):
    call(node, "PUT", "/multi_a", {})
    call(node, "PUT", "/multi_b", {})
    call(node, "PUT", "/multi_a/_doc/1?refresh=true", {"t": "apple pie"})
    call(node, "PUT", "/multi_b/_doc/2?refresh=true", {"t": "apple juice"})
    status, body = call(node, "POST", "/multi_a,multi_b/_search",
                        {"query": {"match": {"t": "apple"}}})
    assert body["hits"]["total"]["value"] == 2
    idx = {h["_index"] for h in body["hits"]["hits"]}
    assert idx == {"multi_a", "multi_b"}
    status, body = call(node, "POST", "/multi_*/_search",
                        {"query": {"match_all": {}}})
    assert body["hits"]["total"]["value"] == 2


def test_mget(node):
    call(node, "PUT", "/mg", {})
    call(node, "PUT", "/mg/_doc/a", {"v": 1})
    call(node, "PUT", "/mg/_doc/b", {"v": 2})
    status, body = call(node, "POST", "/_mget", {"docs": [
        {"_index": "mg", "_id": "a"}, {"_index": "mg", "_id": "zz"}]})
    assert body["docs"][0]["_source"] == {"v": 1}
    assert body["docs"][1]["found"] is False


def test_cat_and_stats(node):
    status, text = call(node, "GET", "/_cat/indices?v", raw=True)
    assert status == 200
    assert b"health" in text and b"library" in text
    status, body = call(node, "GET", "/_cat/indices?format=json")
    assert isinstance(body, list) and any(r["index"] == "library" for r in body)
    status, body = call(node, "GET", "/library/_stats")
    assert body["_all"]["primaries"]["docs"]["count"] == 5
    status, body = call(node, "GET", "/_nodes/stats")
    assert status == 200


def test_error_shapes(node):
    status, body = call(node, "GET", "/missing_index/_search", {})
    assert status == 404
    assert body["error"]["type"] == "index_not_found_exception"
    status, body = call(node, "POST", "/library/_search",
                        {"query": {"bogus": {}}})
    assert status == 400
    assert body["error"]["type"] == "parsing_exception"
    status, body = call(node, "DELETE", "/")
    assert status in (400, 405)


def test_forcemerge_and_flush(node):
    for i in range(6):
        call(node, "PUT", f"/fm/_doc/{i}?refresh=true", {"n": i})
    status, body = call(node, "POST", "/fm/_forcemerge?max_num_segments=1")
    assert status == 200
    status, body = call(node, "POST", "/fm/_flush")
    assert status == 200
    status, body = call(node, "GET", "/fm/_count")
    assert body["count"] == 6


def test_persistence_across_restart(tmp_path):
    n1 = Node(str(tmp_path), port=0).start()
    call(n1, "PUT", "/persist",
         {"mappings": {"properties": {"k": {"type": "keyword"}}}})
    call(n1, "PUT", "/persist/_doc/1?refresh=true", {"k": "v"})
    call(n1, "POST", "/persist/_flush")
    call(n1, "PUT", "/persist/_doc/2", {"k": "w"})   # translog only
    n1.stop()

    n2 = Node(str(tmp_path), port=0).start()
    status, body = call(n2, "GET", "/persist/_doc/1")
    assert status == 200 and body["_source"] == {"k": "v"}
    status, body = call(n2, "GET", "/persist/_doc/2")
    assert status == 200 and body["_source"] == {"k": "w"}
    call(n2, "POST", "/persist/_refresh")
    status, body = call(n2, "GET", "/persist/_count")
    assert body["count"] == 2
    n2.stop()


def test_dynamic_mapping_survives_flush_and_restart(tmp_path):
    """Dynamically-added fields must be queryable after flush + restart
    (the translog can no longer re-derive them once trimmed)."""
    n1 = Node(str(tmp_path), port=0).start()
    call(n1, "PUT", "/dyn", {})
    call(n1, "PUT", "/dyn/_doc/1?refresh=true", {"price": 42, "tag": "x"})
    call(n1, "POST", "/dyn/_flush")
    n1.stop()

    n2 = Node(str(tmp_path), port=0).start()
    status, body = call(n2, "GET", "/dyn/_mapping")
    props = body["dyn"]["mappings"]["properties"]
    assert props["price"]["type"] == "long"
    status, body = call(n2, "POST", "/dyn/_search",
                        {"query": {"range": {"price": {"gte": 40}}}})
    assert body["hits"]["total"]["value"] == 1
    status, body = call(n2, "POST", "/dyn/_search",
                        {"query": {"term": {"tag.keyword": "x"}}})
    assert body["hits"]["total"]["value"] == 1
    n2.stop()


def test_search_empty_node_and_no_match_wildcard(tmp_path):
    n = Node(str(tmp_path), port=0).start()
    status, body = call(n, "POST", "/_search", {"query": {"match_all": {}}})
    assert status == 200 and body["hits"]["total"]["value"] == 0
    status, body = call(n, "POST", "/nomatch-*/_search", {})
    assert status == 200 and body["hits"]["hits"] == []
    n.stop()


def test_multi_index_search_with_sort_merges_globally(node):
    """Explicit sort across indices must merge by sort key, not
    concatenate per-index sorted lists (round-2 advisor finding)."""
    call(node, "PUT", "/msort_a",
         {"mappings": {"properties": {"k": {"type": "long"}}}})
    call(node, "PUT", "/msort_b",
         {"mappings": {"properties": {"k": {"type": "long"}}}})
    call(node, "PUT", "/msort_a/_doc/a3?refresh=true", {"k": 3})
    call(node, "PUT", "/msort_a/_doc/a5?refresh=true", {"k": 5})
    call(node, "PUT", "/msort_b/_doc/b1?refresh=true", {"k": 1})
    call(node, "PUT", "/msort_b/_doc/b2?refresh=true", {"k": 2})
    status, body = call(node, "POST", "/msort_a,msort_b/_search",
                        {"query": {"match_all": {}},
                         "sort": [{"k": "asc"}]})
    assert status == 200
    ks = [h["sort"][0] for h in body["hits"]["hits"]]
    assert ks == [1, 2, 3, 5]
    status, body = call(node, "POST", "/msort_a,msort_b/_search",
                        {"query": {"match_all": {}},
                         "sort": [{"k": "desc"}], "size": 2})
    assert [h["sort"][0] for h in body["hits"]["hits"]] == [5, 3]


def test_mesh_search_path_matches_host_merge(node):
    """index.search.mesh routes REST _search through the device-collective
    merge; results must match a host scatter-gather over the same
    per-shard searchers bit-for-bit."""
    call(node, "PUT", "/meshidx", {
        "settings": {"number_of_shards": 4, "search.mesh": True},
        "mappings": {"properties": {"t": {"type": "text"},
                                    "n": {"type": "long"}}}})
    lines = []
    for i in range(60):
        lines.append({"index": {"_index": "meshidx", "_id": str(i)}})
        lines.append({"t": f"word{i % 7} common", "n": i})
    call(node, "POST", "/_bulk?refresh=true", ndjson=lines)

    body = {"query": {"bool": {
        "must": [{"match": {"t": "common"}}],
        "filter": [{"range": {"n": {"gte": 10, "lt": 50}}}]}},
        "size": 12}
    status, resp = call(node, "POST", "/meshidx/_search", body)
    assert status == 200
    assert resp["hits"]["total"]["value"] == 40

    # host-side oracle over the same per-shard searchers
    from opensearch_tpu.search.executor import merge_hit_rows
    svc = node.indices.get("meshidx")
    assert svc._use_mesh(body)        # the request really takes the mesh path
    rows, total = [], 0
    for si, s in enumerate(sorted(svc.local_shards)):
        r = svc.local_shards[s].acquire_searcher().search(dict(body, size=12))
        total += r["hits"]["total"]["value"]
        rows.extend((h, si, pos)
                    for pos, h in enumerate(r["hits"]["hits"]))
    want = [(h["_id"], h["_score"]) for h in merge_hit_rows(rows, None)[:12]]
    got = [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]]
    assert got == want
    assert total == 40


def test_msearch_rest_per_request_errors(node):
    """_msearch: one bad body yields an error entry for THAT position only;
    a multi-index pattern target works like _search."""
    call(node, "PUT", "/ms1", {"mappings": {"properties": {
        "t": {"type": "text"}, "n": {"type": "long"}}}})
    call(node, "PUT", "/ms2", {"mappings": {"properties": {
        "t": {"type": "text"}, "n": {"type": "long"}}}})
    for i in range(4):
        call(node, "PUT", f"/ms1/_doc/a{i}", {"t": "hello world", "n": i})
        call(node, "PUT", f"/ms2/_doc/b{i}", {"t": "hello there", "n": 10 + i})
    call(node, "POST", "/ms1/_refresh")
    call(node, "POST", "/ms2/_refresh")
    code, resp = call(node, "POST", "/_msearch", ndjson=[
        {"index": "ms1"},
        {"query": {"match": {"t": "hello"}}, "size": 10},
        {"index": "ms1"},
        {"query": {"definitely_not_a_query": {}}},
        {"index": "ms*"},
        {"query": {"match": {"t": "hello"}}, "size": 10},
        {"index": "nope"},
        {"query": {"match_all": {}}},
    ])
    assert code == 200
    r = resp["responses"]
    assert r[0]["status"] == 200
    assert r[0]["hits"]["total"]["value"] == 4
    assert r[1]["status"] == 400 and "error" in r[1]
    assert r[2]["status"] == 200
    assert r[2]["hits"]["total"]["value"] == 8      # ms1 + ms2 via pattern
    assert r[3]["status"] == 404 and "error" in r[3]
