"""PR 17: the QoS-driven searcher autoscaler.

Unit tests pin the decision logic (dwell, hysteresis, cooldown, bounds,
evidence weighting) against a stub coordinator on an injectable clock;
integration tests actuate a real in-process fleet (scale-up serves,
drain-safe retirement, leader-failover abandon/resume — the crash-
safety contract); the soak acceptance asserts audited scale events with
SLOs green across both transitions and two-run verdict determinism; and
the elasticity sweep shows ``max_sustainable_qps`` strictly higher with
the autoscaler closing the loop than with the fleet pinned at min.
"""

import contextlib
import subprocess
import sys
import time

from opensearch_tpu.cluster.autoscaler import (SearcherAutoscaler,
                                               retire_searcher)
from opensearch_tpu.cluster.coordination import FailedToCommitError
from opensearch_tpu.cluster.state import ClusterState, allocate_shards
from opensearch_tpu.testing.loadgen import (_elastic_fleet,
                                            run_autoscale_sweep)
from opensearch_tpu.testing.workload import run_autoscale_soak

REPO = __file__.rsplit("/tests/", 1)[0]
TOOLS = REPO + "/tools"


# -- unit scaffolding --------------------------------------------------------

class FakeAdmission:
    """Just enough of SearchAdmissionController.stats() for evidence."""

    def __init__(self):
        self.max_concurrent = 8
        self.occupancy = 0.0
        self.retry_after_s = 1.0
        self.tenants = {}

    def stats(self):
        return {"occupancy": self.occupancy,
                "retry_after_s": self.retry_after_s,
                "tenants": dict(self.tenants),
                "max_concurrent": self.max_concurrent}


class FakeCoordinator:
    """Single-node leader: state updates apply synchronously."""

    def __init__(self, state):
        self._state = state
        self.leader = True
        self.rank_fn = None
        self.publish_error = None

    def is_leader(self):
        return self.leader

    def state(self):
        return self._state

    def submit_state_update(self, fn):
        new = fn(self._state)
        if new is self._state:
            return self._state
        if self.publish_error is not None:
            raise self.publish_error
        self._state = new.with_(version=self._state.version + 1)
        return self._state

    # actuator-ok (test stub mirrors the audited-by-caller primitive)
    def remove_node(self, nid):
        nodes = dict(self._state.nodes)
        nodes.pop(nid, None)
        self._state = allocate_shards(self._state.with_(nodes=nodes))

    def _reconfigure(self, nodes):
        return tuple(sorted(
            n for n, info in nodes.items()
            if (info or {}).get("master_eligible", True)))


class FakeQos:
    def __init__(self):
        self.records = []

    def record_adaptation(self, knob, old, new, evidence, tenant=None):
        rec = {"knob": knob, "old": old, "new": new,
               "evidence": evidence, "tenant": tenant}
        self.records.append(rec)
        return rec


class FakeCollector:
    def __init__(self):
        self.outstanding_by = {}
        self.removed = []

    def remove_node(self, nid):
        self.removed.append(nid)

    def outstanding(self, nid):
        return self.outstanding_by.get(nid, 0)


class FakeNode:
    def __init__(self):
        self.stopped = False
        self.file_cache = None

    def stop(self):
        self.stopped = True


def base_state(searchers=("s0",)):
    nodes = {"n0": {"name": "n0", "roles": ["master", "data"],
                    "master_eligible": True}}
    for sid in searchers:
        nodes[sid] = {"name": sid, "roles": ["search"],
                      "master_eligible": False}
    indices = {"tier": {"settings": {
        "number_of_shards": 1, "number_of_replicas": 0,
        "number_of_search_replicas": 1}, "mappings": {}}}
    return allocate_shards(ClusterState(
        master_node="n0", nodes=nodes, indices=indices, voting=("n0",)))


def make_asc(coord, adm, clock, **kw):
    asc = SearcherAutoscaler(coord, admission=adm,
                             clock=lambda: clock["t"], interval_s=0.0,
                             **kw)
    asc.enabled = True
    asc.min_searchers = 1
    asc.max_searchers = 3
    asc.dwell_s = 1.0
    asc.cooldown_s = 5.0
    asc.drain_timeout_s = 0.2
    return asc


# -- unit: gates and evidence ------------------------------------------------

def test_disabled_and_not_leader_are_noops():
    coord = FakeCoordinator(base_state())
    adm = FakeAdmission()
    clock = {"t": 0.0}
    asc = make_asc(coord, adm, clock)
    asc.enabled = False
    assert asc.run_once()["reason"] == "disabled"
    asc.enabled = True
    coord.leader = False
    assert asc.run_once()["reason"] == "not_leader"
    # losing leadership resets the dwell timer: regaining it must
    # re-earn the full window
    coord.leader = True
    adm.occupancy = 1.0
    assert asc.run_once()["reason"] == "dwell_up"
    coord.leader = False
    asc.run_once()
    coord.leader = True
    clock["t"] += 5.0
    assert asc.run_once()["reason"] == "dwell_up"


def test_evidence_tenant_weighted_occupancy_and_retry_hot():
    coord = FakeCoordinator(base_state())
    adm = FakeAdmission()
    asc = make_asc(coord, adm, {"t": 0.0})
    # a tenant pinned at its carve is hot even when the global pool
    # looks idle (the noisy-neighbor signature)
    adm.occupancy = 0.1
    adm.tenants = {"t-hot": {"inflight": 9, "max_concurrent": 10}}
    ev = asc._evidence()
    assert ev["weighted_occupancy"] == 0.9 and ev["hot"]
    adm.tenants = {}
    adm.occupancy = 0.2
    ev = asc._evidence()
    assert not ev["hot"] and not ev["cold"]  # the hysteresis band
    adm.occupancy = 0.05
    assert asc._evidence()["cold"]
    # a hot measured Retry-After EWMA alone marks hot (and masks cold)
    adm.retry_after_s = 2.5
    ev = asc._evidence()
    assert ev["hot"] and not ev["cold"]


def test_scale_up_waits_out_dwell_then_commits_atomically():
    coord = FakeCoordinator(base_state())
    adm = FakeAdmission()
    qos = FakeQos()
    clock = {"t": 0.0}
    provisioned = []
    asc = make_asc(coord, adm, clock, qos=qos,
                   provision=lambda nid: provisioned.append(nid) or None)
    adm.occupancy = 1.0
    assert asc.run_once()["reason"] == "dwell_up"
    clock["t"] += 0.5
    assert asc.run_once()["reason"] == "dwell_up"
    assert not provisioned
    clock["t"] += 0.51
    dec = asc.run_once()
    assert dec["action"] == "scale_up" and dec["node"] == "as0"
    assert provisioned == ["as0"]
    st = coord.state()
    assert "as0" in st.nodes
    # the SAME commit bumped the tier's search slots and re-allocated,
    # so the new searcher holds a slot immediately
    assert st.indices["tier"]["settings"][
        "number_of_search_replicas"] == 2
    assert any("as0" in (e.get("search_replicas") or [])
               for e in st.routing["tier"])
    # a searcher node must never become master-eligible via autoscale
    assert "as0" not in st.voting
    assert [r["knob"] for r in qos.records] == ["autoscale.searchers"]
    assert qos.records[0]["evidence"]["decision"] == "scale_up"


def test_cooldown_gates_consecutive_scales_and_max_bounds():
    coord = FakeCoordinator(base_state())
    adm = FakeAdmission()
    clock = {"t": 0.0}
    asc = make_asc(coord, adm, clock, provision=lambda nid: None)
    adm.occupancy = 1.0
    clock["t"] = 10.0
    asc.run_once()                      # arm dwell
    clock["t"] += 1.0
    assert asc.run_once()["action"] == "scale_up"      # -> as0
    clock["t"] += 1.5                   # dwell satisfied, cooldown not
    asc.run_once()
    clock["t"] += 1.5
    assert asc.run_once()["reason"] == "dwell_up"
    assert len(asc._searchers(coord.state())) == 2
    clock["t"] += 5.0                   # past cooldown
    assert asc.run_once()["action"] == "scale_up"      # -> as1 (max=3)
    clock["t"] += 10.0
    asc.run_once()                      # arm dwell again
    clock["t"] += 1.0
    # at max_searchers hot evidence is steady, not a fourth node
    assert asc.run_once()["reason"] == "steady"
    assert asc.scale_ups == 2


def test_scale_down_drains_lifo_victim_and_min_bound_holds():
    coord = FakeCoordinator(base_state(searchers=("s0", "as0")))
    adm = FakeAdmission()
    qos = FakeQos()
    col = FakeCollector()
    clock = {"t": 0.0}
    victim_node = FakeNode()
    asc = make_asc(coord, adm, clock, qos=qos, collector=col,
                   resolve=lambda nid: victim_node)
    adm.occupancy = 0.0
    asc.run_once()
    clock["t"] += 1.0
    dec = asc.run_once()
    assert dec["action"] == "scale_down" and dec["node"] == "as0"
    assert dec["drain"]["drained"] and not dec["drain"]["hard_kill"]
    assert victim_node.stopped and col.removed == ["as0"]
    assert "as0" not in coord.state().nodes
    # decisions audited: the drain record AND the fleet change
    assert [r["knob"] for r in qos.records] == [
        "autoscale.drain", "autoscale.searchers"]
    # at min_searchers cold evidence never retires the last searcher
    clock["t"] += 10.0
    asc.run_once()
    clock["t"] += 1.0
    assert asc.run_once()["reason"] == "steady"
    assert "s0" in coord.state().nodes


def test_drain_timeout_escalates_to_hard_kill():
    coord = FakeCoordinator(base_state(searchers=("s0", "as0")))
    col = FakeCollector()
    col.outstanding_by["as0"] = 3       # straggler RPCs never complete
    node = FakeNode()
    t0 = time.monotonic()
    res = retire_searcher(coord, "as0", collector=col, node=node,
                          drain_timeout_s=0.05)
    assert res["hard_kill"] and not res["drained"]
    assert res["drain_s"] >= 0.05
    assert time.monotonic() - t0 < 2.0  # bounded, not wedged
    # the victim is still stopped and fully removed from state
    assert node.stopped and "as0" not in coord.state().nodes


def test_retire_marks_draining_and_vacates_slots_in_one_commit():
    """Step-1 atomicity: the drain marker and the slot vacation land in
    the SAME committed update, so there is no window where scatters
    still route to a draining searcher."""
    coord = FakeCoordinator(base_state(searchers=("s0", "as0")))
    assert any("as0" in (e.get("search_replicas") or [])
               for e in coord.state().routing["tier"])
    states = []
    inner = coord.submit_state_update

    def spy(fn):
        out = inner(fn)
        states.append(out)
        return out
    coord.submit_state_update = spy
    retire_searcher(coord, "as0", drain_timeout_s=0.05)
    assert states, "drain must go through submit_state_update"
    first = states[0]
    assert first.nodes["as0"]["draining"]
    assert all("as0" not in (e.get("search_replicas") or [])
               for e in first.routing["tier"])


def test_no_provisioner_records_skip_without_half_acting():
    coord = FakeCoordinator(base_state())
    adm = FakeAdmission()
    clock = {"t": 0.0}
    asc = make_asc(coord, adm, clock)
    adm.occupancy = 1.0
    asc.run_once()
    clock["t"] += 1.0
    assert asc.run_once()["reason"] == "no_provisioner"
    assert set(coord.state().nodes) == {"n0", "s0"}


def test_maybe_tick_self_paces_on_injected_clock():
    coord = FakeCoordinator(base_state())
    adm = FakeAdmission()
    clock = {"t": 0.0}
    asc = make_asc(coord, adm, clock)
    asc.interval_s = 1.0
    assert asc.maybe_tick() is not None
    assert asc.maybe_tick() is None     # same instant: paced out
    clock["t"] += 1.0
    assert asc.maybe_tick() is not None
    asc.stop()
    clock["t"] += 1.0
    assert asc.maybe_tick() is None


def test_concurrency_link_tracks_fleet_and_is_audited():
    coord = FakeCoordinator(base_state())
    adm = FakeAdmission()
    qos = FakeQos()
    clock = {"t": 0.0}
    asc = make_asc(coord, adm, clock, qos=qos,
                   provision=lambda nid: None)
    asc.concurrency_per_searcher = 4
    adm.max_concurrent = 4
    adm.occupancy = 1.0
    asc.run_once()
    clock["t"] += 1.0
    assert asc.run_once()["action"] == "scale_up"
    assert adm.max_concurrent == 8
    assert [r["knob"] for r in qos.records] == [
        "autoscale.max_concurrent", "autoscale.searchers"]


# -- unit: crash safety (satellite 3) ---------------------------------------

def test_failed_publish_abandons_provisioned_node_without_orphan():
    """Leader loses quorum mid-scale: the admit publish raises, the
    provisioned-but-never-committed node is stopped, and the cluster
    state carries no half-added member."""
    coord = FakeCoordinator(base_state())
    adm = FakeAdmission()
    qos = FakeQos()
    clock = {"t": 0.0}
    built = {}

    def provision(nid):
        built[nid] = FakeNode()
        return None
    retired = []
    asc = make_asc(coord, adm, clock, qos=qos, provision=provision,
                   resolve=built.get, on_retired=retired.append)
    coord.publish_error = FailedToCommitError("publish quorum lost")
    adm.occupancy = 1.0
    asc.run_once()
    clock["t"] += 1.0
    dec = asc.run_once()
    assert dec["action"] == "abandoned"
    assert built["as0"].stopped
    assert retired == ["as0"]
    assert "as0" not in coord.state().nodes
    assert asc.abandoned == 1 and asc.scale_ups == 0
    rec = qos.records[-1]
    assert (rec["knob"], rec["old"], rec["new"]) == (
        "autoscale.searchers", "provisioned", "abandoned")
    # quorum back: the still-armed hot window retries cleanly on the
    # next tick, reusing the never-committed id
    coord.publish_error = None
    clock["t"] += 10.0
    assert asc.run_once()["action"] == "scale_up"
    assert "as0" in coord.state().nodes


def test_new_leader_resumes_interrupted_drain_from_state():
    """A leader that died after committing ``draining`` leaves a
    durable marker; a FRESH controller (the new leader — zero inherited
    decision state) finds it on its first tick and completes the
    retirement."""
    coord = FakeCoordinator(base_state(searchers=("s0", "as0")))

    def mark(st):
        nodes = dict(st.nodes)
        nodes["as0"] = dict(nodes["as0"], draining=True)
        return allocate_shards(st.with_(nodes=nodes))
    coord.submit_state_update(mark)

    adm = FakeAdmission()
    qos = FakeQos()
    node = FakeNode()
    retired = []
    asc = make_asc(coord, adm, {"t": 0.0}, qos=qos,
                   resolve=lambda nid: node,
                   on_retired=retired.append)
    dec = asc.run_once()
    assert dec["action"] == "resume_drain" and dec["node"] == "as0"
    assert node.stopped and retired == ["as0"]
    assert "as0" not in coord.state().nodes
    assert asc.scale_downs == 1
    assert any(r["knob"] == "autoscale.searchers"
               and r["old"] == "draining" and r["new"] == "retired"
               for r in qos.records)


# -- integration: real fleet ------------------------------------------------

def _wire(ctx, *, max_searchers=2, dwell=0.5, cooldown=1.0):
    """Deterministic autoscaler over the loadgen fleet: injected clock,
    provision through the fleet's own node builder."""
    leader, nodes = ctx["leader"], ctx["nodes"]
    clock = {"t": 0.0}
    asc = leader.autoscaler
    asc.clock = lambda: clock["t"]
    asc.interval_s = 0.0
    asc.enabled = True
    asc.min_searchers = 1
    asc.max_searchers = max_searchers
    asc.dwell_s = dwell
    asc.cooldown_s = cooldown
    asc.drain_timeout_s = 2.0

    def provision(nid):
        node = ctx["build"](nid, ("search",))
        nodes[nid] = node
        return {"name": nid, "roles": ["search"],
                "master_eligible": False}
    asc.provision = provision
    asc.resolve = nodes.get
    asc.on_retired = lambda nid: nodes.pop(nid, None)
    return asc, clock


def _tier_ready(leader, want):
    routing = leader.coordinator.state().routing.get("tier", [])
    return bool(routing) and all(
        len(e.get("search_replicas") or []) >= want
        and set(e.get("search_replicas") or [])
        == set(e.get("search_in_sync") or []) for e in routing)


def _wait(pred, what, timeout=10.0):
    deadline = time.monotonic() + timeout
    while not pred():                    # deadline
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.02)                 # deadline


def test_integration_scale_up_serves_then_drain_retires(tmp_path):
    ctx = _elastic_fleet(str(tmp_path), service_delay_s=0.0)
    leader, nodes = ctx["leader"], ctx["nodes"]
    try:
        asc, clock = _wire(ctx)
        adm = leader.search_backpressure.admission
        adm.max_concurrent = 2
        with contextlib.ExitStack() as held:
            held.enter_context(adm.acquire("search"))
            held.enter_context(adm.acquire("search"))   # occupancy 1.0
            asc.run_once()
            clock["t"] += 0.51
            dec = asc.run_once()
        assert dec["action"] == "scale_up" and dec["node"] == "as0"
        # the provisioned searcher recovers its slot and SERVES
        _wait(lambda: _tier_ready(leader, 2), "as0 in sync")
        hits = leader.search("tier", {"query": {
            "match": {"body": "hello"}}, "size": 3})
        assert hits["hits"]["total"]["value"] > 0
        # audited with numeric evidence
        scale_audit = [r for r in leader.qos.audit(16)
                       if r["knob"] == "autoscale.searchers"]
        assert scale_audit and "weighted_occupancy" in \
            scale_audit[0]["evidence"]
        # idle fleet: cold evidence past dwell + cooldown drains as0
        # (the serving search above already ticked the loop and may
        # have armed the cold dwell — accept whichever tick lands it)
        clock["t"] += 1.01                     # cooldown over
        dec = asc.run_once()
        if dec["action"] != "scale_down":
            clock["t"] += 0.51
            dec = asc.run_once()
        assert dec["action"] == "scale_down" and dec["node"] == "as0"
        assert dec["drain"]["drained"]
        assert "as0" not in leader.coordinator.state().nodes
        assert "as0" not in nodes
        _wait(lambda: _tier_ready(leader, 1), "post-drain refill")
        hits = leader.search("tier", {"query": {
            "match": {"body": "hello"}}, "size": 3})
        assert hits["hits"]["total"]["value"] > 0
    finally:
        for n in list(nodes.values()):
            n.stop()


def test_integration_failover_mid_scale_abandons(tmp_path):
    """The real coordinator's publish fails mid-admit: no orphaned node
    in state, the provisioned node is stopped, and the fleet keeps
    serving."""
    ctx = _elastic_fleet(str(tmp_path), service_delay_s=0.0)
    leader, nodes = ctx["leader"], ctx["nodes"]
    try:
        asc, clock = _wire(ctx)
        adm = leader.search_backpressure.admission
        adm.max_concurrent = 2
        real_publish = leader.coordinator.publish

        def failing_publish(state):
            raise FailedToCommitError("injected: quorum lost mid-scale")
        leader.coordinator.publish = failing_publish
        with contextlib.ExitStack() as held:
            held.enter_context(adm.acquire("search"))
            held.enter_context(adm.acquire("search"))
            asc.run_once()
            clock["t"] += 0.51
            dec = asc.run_once()
        assert dec["action"] == "abandoned"
        assert "as0" not in leader.coordinator.state().nodes
        assert "as0" not in nodes
        leader.coordinator.publish = real_publish
        hits = leader.search("tier", {"query": {
            "match": {"body": "hello"}}, "size": 3})
        assert hits["hits"]["total"]["value"] > 0
    finally:
        for n in list(nodes.values()):
            n.stop()


def test_integration_new_leader_object_resumes_drain(tmp_path):
    """Controller state is rebuilt from cluster state: a brand-new
    autoscaler instance (the failed-over leader) completes a drain its
    predecessor only started."""
    ctx = _elastic_fleet(str(tmp_path), service_delay_s=0.0)
    leader, nodes = ctx["leader"], ctx["nodes"]
    try:
        def mark(st):
            marked = dict(st.nodes)
            marked["s0"] = dict(marked["s0"], draining=True)
            return allocate_shards(st.with_(nodes=marked),
                                   rank=leader.response_collector.rank)
        leader.coordinator.submit_state_update(mark)
        successor = SearcherAutoscaler(
            leader.coordinator,
            admission=leader.search_backpressure.admission,
            collector=leader.response_collector, qos=leader.qos,
            resolve=nodes.get,
            on_retired=lambda nid: nodes.pop(nid, None))
        successor.enabled = True
        successor.drain_timeout_s = 2.0
        dec = successor.run_once()
        assert dec["action"] == "resume_drain" and dec["node"] == "s0"
        assert "s0" not in leader.coordinator.state().nodes
        assert any(r["knob"] == "autoscale.drain"
                   for r in leader.qos.audit(16))
    finally:
        for n in list(nodes.values()):
            n.stop()


# -- acceptance: the autoscale churn soak -----------------------------------

def test_autoscale_soak_holds_slos_across_transitions(tmp_path):
    report = run_autoscale_soak(str(tmp_path))
    assert report["slo_ok"], report["verdicts"]
    chaos = report["chaos"]
    asr = chaos["autoscale"]
    assert asr["scale_ups"] >= 1
    assert asr["drains_completed"] >= 1
    assert asr["hard_kills"] == 0
    assert asr["decisions_audited"] >= 2
    assert chaos["unexpected_errors"] == []
    by_slo = {v["slo"]: v for v in report["verdicts"]}
    assert by_slo["autoscale_scale_up_audited"]["ok"]
    assert by_slo["autoscale_drain_complete"]["ok"]
    # both transitions carry their measured numbers
    applied = {d.get("fault"): d for d in chaos["applied"]}
    assert applied["scale_up_pressure"]["time_to_scale_up_s"] >= 0.0
    assert applied["scale_down_idle"]["drain_s"] >= 0.0


def test_autoscale_soak_two_run_verdict_determinism(tmp_path):
    a = run_autoscale_soak(str(tmp_path / "a"))
    b = run_autoscale_soak(str(tmp_path / "b"))
    assert a["chaos"]["schedule"] == b["chaos"]["schedule"]
    # verdict KEY SET and outcomes are pinned; observed latencies vary
    assert [(v["slo"], v["limit"], v["ok"]) for v in a["verdicts"]] == \
        [(v["slo"], v["limit"], v["ok"]) for v in b["verdicts"]]
    assert a["slo_ok"] and b["slo_ok"]
    assert a["chaos"]["final_state"] == b["chaos"]["final_state"]
    ca, cb = a["chaos"]["autoscale"], b["chaos"]["autoscale"]
    for k in ("scale_ups", "scale_downs", "hard_kills",
              "searchers_final"):
        assert ca[k] == cb[k], k


# -- acceptance: the elasticity sweep ---------------------------------------

def test_autoscale_sweep_raises_max_sustainable_qps(tmp_path):
    """Same seeded offered-load ramp, pinned fleet vs autoscaled: the
    closed loop must move the capacity ceiling, not just add nodes."""
    report = run_autoscale_sweep(str(tmp_path))
    assert report["slo_ok"], report["verdicts"]
    ms = report["max_sustainable_qps"]
    assert ms["autoscaled"] > ms["pinned"], ms
    assert report["autoscaled"]["autoscale"]["scale_ups"] >= 1
    assert report["autoscaled"]["audit"]


# -- satellite: audited-actuators lint --------------------------------------

def test_check_audited_actuators_lint_passes_repo():
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_audited_actuators.py"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_check_audited_actuators_lint_catches_violations(tmp_path):
    (tmp_path / "bad.py").write_text(
        "class Controller:\n"
        "    def grow(self):\n"
        "        self.coordinator.add_node('n9', {})\n"
        "    def adapt(self):\n"
        "        qosmod.SHED_OCCUPANCY = 0.5\n"
        "    # actuator-ok (membership primitive; callers audit)\n"
        "    def primitive(self):\n"
        "        self.coordinator.remove_node('n9')\n"
        "    def audited(self):\n"
        "        self.coordinator.submit_state_update(lambda s: s)\n"
        "        self.qos.record_adaptation('k', 0, 1, {})\n")
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_audited_actuators.py",
         str(tmp_path / "bad.py")],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert "bad.py:2" in out.stdout and "[grow]" in out.stdout
    assert "bad.py:4" in out.stdout and "SHED_OCCUPANCY" in out.stdout
    assert "[primitive]" not in out.stdout   # annotated escape
    assert "[audited]" not in out.stdout     # appends to the ring
