"""Aggregation-tail correctness vs plain-Python oracles (VERDICT r4
item 5): composite (+after pagination), significant_terms, top_hits,
extended_stats, percentile_ranks, weighted_avg, multi_terms, rare_terms,
median_absolute_deviation — each also checked 1-shard vs 3-shard
partial-merge (reduce_aggs over wire partials)."""

import json
import math

import numpy as np
import pytest

from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.aggs import reduce_aggs
from opensearch_tpu.search.executor import ShardSearcher

MAPPING = {"properties": {
    "cat": {"type": "keyword"},
    "tag": {"type": "keyword"},
    "n": {"type": "long"},
    "price": {"type": "double"},
    "w": {"type": "double"},
    "body": {"type": "text"},
    "day": {"type": "date"},
}}

rng = np.random.default_rng(11)
CATS = ["a", "b", "c"]
DOCS = []
for i in range(90):
    cat = CATS[i % 3]
    DOCS.append({
        "cat": cat,
        "tag": f"t{i % 7}" if i % 9 else f"rare{i}",   # rare{0,9,...} once
        "n": int(i % 5),
        "price": float(i),
        "w": float(1 + i % 3),
        # 'sig' appears mostly in cat a docs -> significant for query sig
        "body": ("sig special" if (cat == "a" and i % 2 == 0)
                 else "common filler"),
        "day": f"2023-0{(i % 3) + 1}-15",
    })


def searcher(n_segments=1):
    mapper = DocumentMapper(MAPPING)
    w = SegmentWriter()
    segs = []
    per = math.ceil(len(DOCS) / n_segments)
    for si in range(n_segments):
        chunk = DOCS[si * per: (si + 1) * per]
        if chunk:
            parsed = [mapper.parse(f"{si}-{i}", d)
                      for i, d in enumerate(chunk)]
            segs.append(w.build(parsed, f"s{si}"))
    return ShardSearcher(segs, mapper)


def run(aggs, query=None, n_shards=1):
    body = {"size": 0, "query": query or {"match_all": {}}, "aggs": aggs}
    if n_shards == 1:
        return searcher(1).search(body)["aggregations"]
    partials = []
    split = searcher(n_shards)
    for si in range(len(split.segments)):
        sub = ShardSearcher([split.segments[si]], split.mapper)
        # round-trip through JSON: partials must be wire-safe
        partials.append(json.loads(json.dumps(
            sub.search(body, agg_partials=True)["aggregation_partials"])))
    return reduce_aggs(aggs, partials)


@pytest.mark.parametrize("n_shards", [1, 3])
def test_extended_stats(n_shards):
    out = run({"es": {"extended_stats": {"field": "price"}}}, n_shards=n_shards)
    v = np.asarray([d["price"] for d in DOCS])
    es = out["es"]
    assert es["count"] == len(v)
    assert es["avg"] == pytest.approx(v.mean())
    assert es["sum_of_squares"] == pytest.approx((v ** 2).sum())
    assert es["variance"] == pytest.approx(v.var())
    assert es["std_deviation"] == pytest.approx(v.std())
    assert es["std_deviation_bounds"]["upper"] == pytest.approx(
        v.mean() + 2 * v.std())


@pytest.mark.parametrize("n_shards", [1, 3])
def test_weighted_avg(n_shards):
    out = run({"wa": {"weighted_avg": {"value": {"field": "price"},
                                       "weight": {"field": "w"}}}},
              n_shards=n_shards)
    v = np.asarray([d["price"] for d in DOCS])
    w = np.asarray([d["w"] for d in DOCS])
    assert out["wa"]["value"] == pytest.approx((v * w).sum() / w.sum())


@pytest.mark.parametrize("n_shards", [1, 3])
def test_percentile_ranks(n_shards):
    out = run({"pr": {"percentile_ranks": {"field": "price",
                                           "values": [10, 50, 89]}}},
              n_shards=n_shards)
    v = np.asarray([d["price"] for d in DOCS])
    for x in (10, 50, 89):
        assert out["pr"]["values"][f"{float(x)}"] == pytest.approx(
            100.0 * (v <= x).sum() / len(v))


@pytest.mark.parametrize("n_shards", [1, 3])
def test_median_absolute_deviation(n_shards):
    out = run({"mad": {"median_absolute_deviation": {"field": "price"}}},
              n_shards=n_shards)
    v = np.asarray([d["price"] for d in DOCS], np.float64)
    med = np.median(v)
    assert out["mad"]["value"] == pytest.approx(
        np.median(np.abs(v - med)), rel=0.02)


@pytest.mark.parametrize("n_shards", [1, 3])
def test_significant_terms_jlh(n_shards):
    out = run({"sig": {"significant_terms": {"field": "cat",
                                             "min_doc_count": 1}}},
              query={"match": {"body": "sig"}}, n_shards=n_shards)
    # 'sig' only occurs in cat=a docs: a is the only significant bucket
    assert out["sig"]["doc_count"] == 15          # fg size
    keys = [b["key"] for b in out["sig"]["buckets"]]
    assert keys == ["a"]
    b = out["sig"]["buckets"][0]
    assert b["doc_count"] == 15 and b["bg_count"] == 30
    fg_rate, bg_rate = 15 / 15, 30 / 90
    assert b["score"] == pytest.approx(
        (fg_rate - bg_rate) * (fg_rate / bg_rate))


@pytest.mark.parametrize("n_shards", [1, 3])
def test_rare_terms(n_shards):
    out = run({"rare": {"rare_terms": {"field": "tag"}}}, n_shards=n_shards)
    # oracle: tags occurring exactly once across the WHOLE corpus
    from collections import Counter
    c = Counter(d["tag"] for d in DOCS)
    expect = sorted(t for t, n in c.items() if n == 1)
    assert [b["key"] for b in out["rare"]["buckets"]] == expect
    assert all(b["doc_count"] == 1 for b in out["rare"]["buckets"])


def test_rare_terms_cross_shard_exclusion():
    """A term under max_doc_count on EVERY shard but over it in total
    must not be reported (the over-list / CuckooFilter role)."""
    out = run({"rare": {"rare_terms": {"field": "cat",
                                       "max_doc_count": 40}}}, n_shards=3)
    # each cat has 30 docs: <=40 per merged sum? 30 <= 40 -> all rare.
    assert len(out["rare"]["buckets"]) == 3
    out = run({"rare": {"rare_terms": {"field": "cat",
                                       "max_doc_count": 20}}}, n_shards=3)
    # per 30-doc shard each cat has ~10 (<=20) but totals 30 > 20
    assert out["rare"]["buckets"] == []


@pytest.mark.parametrize("n_shards", [1, 3])
def test_multi_terms_with_metric_sub(n_shards):
    out = run({"mt": {"multi_terms": {"terms": [{"field": "cat"},
                                                {"field": "n"}],
                                      "size": 50},
                      "aggs": {"p": {"sum": {"field": "price"}}}}},
              n_shards=n_shards)
    from collections import Counter, defaultdict
    c = Counter((d["cat"], d["n"]) for d in DOCS)
    sums = defaultdict(float)
    for d in DOCS:
        sums[(d["cat"], d["n"])] += d["price"]
    got = {tuple(b["key"]): (b["doc_count"], b["p"]["value"])
           for b in out["mt"]["buckets"]}
    assert len(got) == len(c)
    for k, n in c.items():
        assert got[k][0] == n
        assert got[k][1] == pytest.approx(sums[k])
    # count-desc order with key tiebreak
    counts = [b["doc_count"] for b in out["mt"]["buckets"]]
    assert counts == sorted(counts, reverse=True)


@pytest.mark.parametrize("n_shards", [1, 3])
def test_top_hits_top_level_and_under_terms(n_shards):
    aggs = {"cats": {"terms": {"field": "cat"},
                     "aggs": {"best": {"top_hits": {
                         "size": 2, "sort": [{"price": {"order": "desc"}}],
                         "_source": ["price", "cat"]}}}},
            "overall": {"top_hits": {"size": 3,
                                     "sort": [{"price": {"order": "desc"}}]}}}
    out = run(aggs, n_shards=n_shards)
    top = out["overall"]["hits"]
    assert top["total"]["value"] == 90
    assert [h["sort"][0] for h in top["hits"]] == [89.0, 88.0, 87.0]
    for b in out["cats"]["buckets"]:
        cat = b["key"]
        oracle = sorted((d["price"] for d in DOCS if d["cat"] == cat),
                        reverse=True)[:2]
        hits = b["best"]["hits"]["hits"]
        assert [h["sort"][0] for h in hits] == oracle
        assert hits[0]["_source"]["cat"] == cat
        assert set(hits[0]["_source"]) == {"price", "cat"}


def test_top_hits_by_score():
    out = run({"th": {"top_hits": {"size": 2}}},
              query={"match": {"body": "sig"}})
    hits = out["th"]["hits"]
    assert hits["total"]["value"] == 15
    assert hits["max_score"] is not None
    assert hits["hits"][0]["_score"] == pytest.approx(hits["max_score"])


@pytest.mark.parametrize("n_shards", [1, 3])
def test_composite_terms_pagination(n_shards):
    from collections import Counter
    c = Counter((d["cat"], d["n"]) for d in DOCS)
    expect = sorted(c.items())
    aggs = {"comp": {"composite": {
        "size": 4, "sources": [{"c": {"terms": {"field": "cat"}}},
                               {"num": {"terms": {"field": "n"}}}]}}}
    seen = []
    after = None
    for _page in range(10):
        a = {"comp": {"composite": {**aggs["comp"]["composite"]}}}
        if after is not None:
            a["comp"]["composite"]["after"] = after
        out = run(a, n_shards=n_shards)["comp"]
        if not out["buckets"]:
            break
        for b in out["buckets"]:
            seen.append(((b["key"]["c"], b["key"]["num"]), b["doc_count"]))
        after = out.get("after_key")
        if after is None:
            break
    assert seen == expect


@pytest.mark.parametrize("n_shards", [1, 3])
def test_composite_date_histogram_source_with_sub(n_shards):
    aggs = {"comp": {"composite": {
        "size": 10,
        "sources": [{"month": {"date_histogram":
                               {"field": "day",
                                "calendar_interval": "month"}}}]},
        "aggs": {"p": {"avg": {"field": "price"}}}}}
    out = run(aggs, n_shards=n_shards)["comp"]
    assert len(out["buckets"]) == 3
    from collections import defaultdict
    per_month = defaultdict(list)
    for d in DOCS:
        per_month[d["day"][:7]].append(d["price"])
    months = sorted(per_month)
    for b, m in zip(out["buckets"], months):
        import datetime as dt
        got = dt.datetime.fromtimestamp(
            b["key"]["month"] / 1000, tz=dt.timezone.utc).strftime("%Y-%m")
        assert got == m
        assert b["doc_count"] == len(per_month[m])
        assert b["p"]["value"] == pytest.approx(np.mean(per_month[m]))


def test_composite_desc_order():
    aggs = {"comp": {"composite": {
        "size": 2, "sources": [{"c": {"terms": {"field": "cat",
                                                "order": "desc"}}}]}}}
    out = run(aggs)["comp"]
    assert [b["key"]["c"] for b in out["buckets"]] == ["c", "b"]
    # paginate past the end
    aggs["comp"]["composite"]["after"] = out["after_key"]
    out2 = run(aggs)["comp"]
    assert [b["key"]["c"] for b in out2["buckets"]] == ["a"]


def test_unsupported_sub_agg_is_400():
    from opensearch_tpu.common.errors import IllegalArgumentError

    with pytest.raises(IllegalArgumentError):
        run({"t": {"terms": {"field": "cat"},
                   "aggs": {"c": {"cardinality": {"field": "tag"}}}}})
    with pytest.raises(IllegalArgumentError):
        run({"h": {"histogram": {"field": "price", "interval": 10},
                   "aggs": {"th": {"top_hits": {}}}}})
