"""Quantized, paged device index (index/codec.py, ops/quantized.py,
the DevicePager in common/device_ledger.py).

The tentpole invariants: (1) quantized top-k is RANK-IDENTICAL to the
f32 path — the per-term exact-rank-parity guard stores any term whose
quantized order would diverge at full precision; (2) the host fallback
on quantized segments is byte-identical to the device kernels (same
dequantized f32 column, same op order); (3) pager eviction and restage
never change a result bit; (4) ``.quant`` sidecars are crash-safe —
corruption degrades to recompute-and-rewrite, never a failed search.

Also covers the bit-packed doc-id codec (host/device decode parity),
the block-max prefetch oracle, demand-staged full postings for
filter-context/phrase plans on quantized segments, the `_nodes/stats`
``device.pager`` section, and the tools/check_quantized_staging.py
tier-1 lint.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from opensearch_tpu.common.device_ledger import device_ledger, device_pager
from opensearch_tpu.common.telemetry import metrics
from opensearch_tpu.index import codec
from opensearch_tpu.index import store
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.ops import bm25 as bm25_ops
from opensearch_tpu.search.executor import ShardSearcher

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


@pytest.fixture(autouse=True)
def _clean_pager_state():
    led = device_ledger()
    led.reset()
    yield
    led.reset()


@pytest.fixture(params=["host", "device"])
def scoring_path(request, monkeypatch):
    monkeypatch.setattr(bm25_ops, "HOST_SCORING",
                        request.param == "host")
    return request.param


def zipf_corpus(rng, n_docs, vocab=120, avg_len=24):
    docs = []
    for _ in range(n_docs):
        n = int(rng.integers(avg_len // 2, avg_len * 2))
        terms = (rng.zipf(1.4, size=n) - 1).clip(0, vocab - 1)
        docs.append({"body": " ".join(f"w{t}" for t in terms)})
    return docs


def build_searcher(docs, seg_sizes, prefix="qz"):
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    writer = SegmentWriter()
    segs, i = [], 0
    for si, size in enumerate(seg_sizes):
        batch = [mapper.parse(str(i + j), d)
                 for j, d in enumerate(docs[i: i + size])]
        segs.append(writer.build(batch, f"{prefix}{si}"))
        i += size
    return ShardSearcher(segs, mapper), mapper


def ranked_hits(resp):
    return [(h["_id"], np.float32(h["_score"]))
            for h in resp["hits"]["hits"]]


def assert_rank_parity_mod_ties(got, ref, tol=0.03):
    """The quantized ranking must equal the f32 ranking up to
    permutations WITHIN near-tie groups of the reference: per-segment
    scale factors put each doc's dequantized score inside a small error
    band around its f32 score, so docs whose f32 scores are closer than
    the band may swap — any reordering across a larger gap is a bug
    (the per-term exact-rank-parity guard rules it out within a
    segment; across segments the bands themselves bound it)."""
    assert sorted(i for i, _ in got) == sorted(i for i, _ in ref)
    groups, cur = [], []
    for _id, sc in ref:
        if cur and abs(cur[-1][1] - sc) > tol * max(abs(sc), 1e-6):
            groups.append(cur)
            cur = []
        cur.append((_id, sc))
    if cur:
        groups.append(cur)
    pos = 0
    for g in groups:
        want = {i for i, _ in g}
        have = {i for i, _ in got[pos:pos + len(g)]}
        assert have == want, (pos, have, want)
        pos += len(g)


# -- codec: quantization + parity guard -------------------------------------

def test_quantize_postings_bound_safe_and_nonzero():
    """Floor-of-1 quantization: every dequantized impact stays at or
    below the term's block max (the pruning bound stays an upper
    bound), and no matched posting quantizes to zero (score > 0 iff
    matched is preserved)."""
    rng = np.random.default_rng(7)
    _, mapper = build_searcher(zipf_corpus(rng, 50), [50], prefix="cb")
    writer = SegmentWriter()
    batch = [mapper.parse(str(i), d)
             for i, d in enumerate(zipf_corpus(rng, 120))]
    seg = writer.build(batch, "codecseg")
    pf = seg.postings["body"]
    avgdl = float(np.float32(pf.doc_lens.mean()))
    imp, mx = seg.impact_table("body", avgdl)
    qt = codec.quantize_postings(pf, imp, mx, avgdl)

    deq = qt.dequantized()
    assert deq.shape == imp.shape and deq.dtype == np.float32
    per_term_max = mx[np.searchsorted(pf.offsets, np.arange(len(imp)),
                                      side="right") - 1]
    assert np.all(deq <= per_term_max * np.float32(1.0001))
    assert np.all(deq[imp > 0] > 0)
    assert qt.stats["quant_bytes"] < qt.stats["f32_bytes"]
    assert qt.stats["postings"] == len(imp)
    assert qt.nbytes == qt.stats["quant_bytes"]


def test_parity_guard_stores_misranked_terms_exact():
    """A term whose int8 buckets would reorder its postings relative to
    the f32 sort (ties break by doc id) is stored exact-f32 — rank
    parity is guaranteed per construction, not per corpus."""
    # term 0: docs 3 and 5 collapse into the same bucket but doc 5
    # outranks doc 3 at f32 — the quantized tie would invert them
    # term 1: well-separated values, quantizes cleanly
    offsets = np.array([0, 3, 6], dtype=np.int64)
    doc_ids = np.array([3, 5, 9, 1, 2, 4], dtype=np.int32)
    imp = np.array([0.5, 0.5001, 1.0, 0.25, 0.5, 1.0], dtype=np.float32)
    mx = np.array([1.0, 1.0], dtype=np.float32)
    qvals, scales, exact_vals, exact_offsets, stats = \
        codec.quantize_impacts(imp, mx, offsets, doc_ids)
    assert stats["exact_terms"] == 1
    assert stats["exact_postings"] == 3
    assert exact_offsets[1] - exact_offsets[0] == 3
    np.testing.assert_array_equal(exact_vals[:3], imp[:3])
    # clean term stays quantized-only
    assert exact_offsets[2] == exact_offsets[1]


def test_pack_unpack_doc_ids_roundtrip():
    offsets = np.array([0, 3, 3, 7], dtype=np.int64)
    doc_ids = np.array([100, 101, 4096, 5, 6, 1000, 1 << 20],
                       dtype=np.int32)
    packed, base, width = codec.pack_doc_ids(doc_ids, offsets)
    assert packed.dtype == np.uint32
    out = codec.unpack_doc_ids(packed, base, offsets, width)
    np.testing.assert_array_equal(out, doc_ids)
    np.testing.assert_array_equal(base, [100, 0, 5])


def test_gather_postings_packed_matches_unpacked():
    """The device bit-decode gather returns the same doc ids / slots /
    valid lanes as the plain CSR gather it replaces."""
    rng = np.random.default_rng(11)
    _, mapper = build_searcher(zipf_corpus(rng, 40), [40], prefix="gp")
    writer = SegmentWriter()
    batch = [mapper.parse(str(i), d)
             for i, d in enumerate(zipf_corpus(rng, 150))]
    seg = writer.build(batch, "gatherseg")
    pf = seg.postings["body"]
    packed, base, width = codec.pack_doc_ids(pf.doc_ids, pf.offsets)

    T = len(pf.offsets) - 1
    term_ids = jnp.asarray(               # staging-ok: test inputs
        np.array([0, 1, 2, min(3, T - 1)], dtype=np.int32))
    active = jnp.asarray(                 # staging-ok: test inputs
        np.array([True, True, True, True]))
    budget = 1 << int(np.ceil(np.log2(len(pf.doc_ids) + 1)))
    d0, _tf, s0, v0 = bm25_ops.gather_postings(
        jnp.asarray(pf.offsets),          # staging-ok: test inputs
        jnp.asarray(pf.doc_ids),          # staging-ok: test inputs
        jnp.asarray(pf.tfs),              # staging-ok: test inputs
        term_ids, active, budget=budget, pad_doc=seg.n_docs)
    d1, _idx, s1, v1 = bm25_ops.gather_postings_packed(
        jnp.asarray(pf.offsets),          # staging-ok: test inputs
        jnp.asarray(packed),              # staging-ok: test inputs
        jnp.asarray(base),                # staging-ok: test inputs
        term_ids, active, width=width, budget=budget,
        pad_doc=seg.n_docs)
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(s0)[np.asarray(v0)],
                                  np.asarray(s1)[np.asarray(v1)])


# -- engine parity: quantized vs f32, host vs device -------------------------

def test_quantized_single_term_single_segment_exact_rank(
        scoring_path, monkeypatch):
    """The pinned parity suite: within one segment a single term's
    quantized ranking is IDENTICAL to f32 — the exact-rank-parity guard
    stores any term whose quantized order would diverge, so this holds
    per construction, not per corpus (and on both lowerings)."""
    rng = np.random.default_rng(71)
    docs = zipf_corpus(rng, 280)
    monkeypatch.setattr(codec, "QUANTIZED_MODE", "off")
    s_f, _ = build_searcher(docs, [280], prefix="pf")
    refs = {}
    for t in ("w0", "w1", "w2", "w5", "w9", "w17"):
        refs[t] = s_f.search(
            {"query": {"match": {"body": t}}, "size": 280})

    device_ledger().reset()
    monkeypatch.setattr(codec, "QUANTIZED_MODE", "on")
    s_q, _ = build_searcher(docs, [280], prefix="pq")
    for t, r in refs.items():
        got = s_q.search({"query": {"match": {"body": t}}, "size": 280})
        assert [h[0] for h in ranked_hits(got)] == \
            [h[0] for h in ranked_hits(r)], t
        assert got["hits"]["total"]["value"] == \
            r["hits"]["total"]["value"]


@pytest.mark.parametrize("seed", [5, 23])
def test_quantized_rank_parity_vs_f32(seed, scoring_path, monkeypatch):
    """General engine parity: under QUANTIZED_MODE=on multi-term,
    multi-segment rankings match the f32 path up to near-tie
    permutations, with matched-doc sets and totals identical and scores
    within the dequantization tolerance — across the sequential and
    batched msearch paths and both lowerings."""
    rng = np.random.default_rng(seed)
    docs = zipf_corpus(rng, 300)
    queries = []
    for _ in range(5):
        a, b = (rng.zipf(1.4, size=2) - 1).clip(0, 119)
        terms = [f"w{a}"] if a == b else [f"w{a}", f"w{b}"]
        queries.append({"query": {"match": {"body": " ".join(terms)}},
                        "size": 300})

    monkeypatch.setattr(codec, "QUANTIZED_MODE", "off")
    s_f32, _ = build_searcher(docs, [120, 100, 80], prefix=f"f{seed}_")
    ref = [s_f32.search(dict(q)) for q in queries]

    device_ledger().reset()
    monkeypatch.setattr(codec, "QUANTIZED_MODE", "on")
    s_q, _ = build_searcher(docs, [120, 100, 80], prefix=f"q{seed}_")
    for q, r in zip(queries, ref):
        got = s_q.search(dict(q))
        assert_rank_parity_mod_ties(ranked_hits(got), ranked_hits(r))
        assert got["hits"]["total"]["value"] == \
            r["hits"]["total"]["value"]
        for (_, sq), (_, sf) in zip(ranked_hits(got), ranked_hits(r)):
            assert abs(sq - sf) <= 3e-2 * max(abs(sf), 1e-6)
    # batched msearch path: the device union lowering demand-stages the
    # exact f32 impacts while the host fallback scores off the
    # dequantized tables — either way the ranking parity must hold
    mresp = s_q.msearch([dict(q) for q in queries])
    for m, r in zip(mresp, ref):
        assert_rank_parity_mod_ties(ranked_hits(m), ranked_hits(r))


def test_quantized_mesh_search_rank_parity(monkeypatch):
    """The mesh scatter-gather path over quantized shards returns the
    same ranked ids and totals as over f32 shards."""
    from opensearch_tpu.parallel.dist_search import MeshSearcher
    rng = np.random.default_rng(9)
    docs = zipf_corpus(rng, 240)
    body = {"query": {"match": {"body": "w0 w4"}}, "size": 240}
    monkeypatch.setattr(bm25_ops, "HOST_SCORING", False)

    monkeypatch.setattr(codec, "QUANTIZED_MODE", "off")
    shards_f = [build_searcher(docs[i * 60:(i + 1) * 60], [60],
                               prefix=f"mf{i}_")[0] for i in range(4)]
    ref = MeshSearcher(shards_f).search(dict(body))

    device_ledger().reset()
    monkeypatch.setattr(codec, "QUANTIZED_MODE", "on")
    shards_q = [build_searcher(docs[i * 60:(i + 1) * 60], [60],
                               prefix=f"mq{i}_")[0] for i in range(4)]
    got = MeshSearcher(shards_q).search(dict(body))
    assert_rank_parity_mod_ties(ranked_hits(got), ranked_hits(ref))
    assert got["hits"]["total"]["value"] == ref["hits"]["total"]["value"]


def test_quantized_host_device_byte_identical(monkeypatch):
    """On a quantized segment the host fallback computes scores from
    the SAME dequantized f32 column in the same op order as the device
    kernel — byte-identical, like the f32 path's host/device parity."""
    rng = np.random.default_rng(31)
    docs = zipf_corpus(rng, 260)
    monkeypatch.setattr(codec, "QUANTIZED_MODE", "on")
    body = {"query": {"match": {"body": "w0 w3"}}, "size": 260}

    monkeypatch.setattr(bm25_ops, "HOST_SCORING", True)
    s_host, _ = build_searcher(docs, [130, 130], prefix="hb")
    host = ranked_hits(s_host.search(dict(body)))

    device_ledger().reset()
    monkeypatch.setattr(bm25_ops, "HOST_SCORING", False)
    s_dev, _ = build_searcher(docs, [130, 130], prefix="db")
    dev = ranked_hits(s_dev.search(dict(body)))
    assert host == dev    # ids AND float32 scores, bit-for-bit


def test_filter_phrase_on_quantized_segments(monkeypatch):
    """Plans that need raw postings (filter context, phrase) demand-
    stage them via ensure_postings on quantized segments and match the
    f32 path exactly — and the staging is counted."""
    rng = np.random.default_rng(17)
    docs = zipf_corpus(rng, 200)
    bodies = [
        {"query": {"bool": {"filter": [{"term": {"body": "w0"}}]}},
         "size": 200},
        {"query": {"bool": {"must": [{"term": {"body": "w0"}},
                                     {"term": {"body": "w1"}}]}},
         "size": 200},
        {"query": {"match_phrase": {"body": "w0 w1"}}, "size": 200},
    ]
    monkeypatch.setattr(bm25_ops, "HOST_SCORING", False)

    monkeypatch.setattr(codec, "QUANTIZED_MODE", "off")
    s_f32, _ = build_searcher(docs, [100, 100], prefix="ff")
    ref = [s_f32.search(dict(b)) for b in bodies]

    device_ledger().reset()
    monkeypatch.setattr(codec, "QUANTIZED_MODE", "on")
    c0 = metrics().counter("device.quantized.full_postings").value
    s_q, _ = build_searcher(docs, [100, 100], prefix="qf")
    for b, r in zip(bodies, ref):
        got = s_q.search(dict(b))
        assert got["hits"]["total"]["value"] == \
            r["hits"]["total"]["value"]
        assert ranked_hits(got) == ranked_hits(r)
    assert metrics().counter("device.quantized.full_postings").value > c0


# -- pager: LRU eviction, restage identity, prefetch -------------------------

def _mk_loader(i):
    def loader():
        return [("a", "impacts_q", np.full(32, i, dtype=np.int8)),
                ("b", "postings_q",
                 (np.arange(8, dtype=np.uint32) + i))]
    return loader


def test_pager_lru_eviction_and_restage():
    led = device_ledger()
    pager = device_pager()
    pager.set_page_bytes(256)
    led.set_budget(512)                      # capacity: 2 pages
    assert pager.capacity_pages() == 2

    keys = [("ix", 0, f"s{i}", "body", 0.0) for i in range(3)]
    a1 = pager.acquire(keys[0], _mk_loader(1))
    assert pager.stats()["misses"] == 1
    again = pager.acquire(keys[0], _mk_loader(1))
    assert pager.stats()["hits"] == 1 and again is a1
    pager.acquire(keys[1], _mk_loader(2))
    pager.acquire(keys[2], _mk_loader(3))    # evicts LRU (keys[0])
    st = pager.stats()
    assert st["resident_entries"] == 2 and st["evictions"] == 1

    # restage of the evicted entry is byte-identical and evicts anew
    a1b = pager.acquire(keys[0], _mk_loader(1))
    np.testing.assert_array_equal(np.asarray(a1b["a"]),
                                  np.full(32, 1, dtype=np.int8))
    st = pager.stats()
    assert st["misses"] == 4 and st["evictions"] == 2
    assert st["resident_pages"] <= 2


def test_pager_prefetch_never_evicts():
    led = device_ledger()
    pager = device_pager()
    pager.set_page_bytes(256)
    led.set_budget(512)                      # capacity: 2 pages
    keys = [("ix", 0, f"p{i}", "body", 0.0) for i in range(3)]
    pager.acquire(keys[0], _mk_loader(1))
    pager.acquire(keys[1], _mk_loader(2))
    # full: prefetch refuses rather than evicting a resident entry
    assert pager.prefetch(keys[2], _mk_loader(3), 64) is False
    assert pager.stats()["resident_entries"] == 2
    assert pager.stats()["prefetches"] == 0
    led.set_budget(2048)                     # room opens up
    assert pager.prefetch(keys[2], _mk_loader(3), 64) is True
    assert pager.stats()["prefetches"] == 1
    hits0 = pager.stats()["hits"]
    pager.acquire(keys[2], _mk_loader(3))    # prefetched: a hit
    assert pager.stats()["hits"] == hits0 + 1
    # already resident: prefetch is a no-op
    assert pager.prefetch(keys[2], _mk_loader(3), 64) is False


def test_pager_eviction_is_invisible_to_results(monkeypatch):
    """Crush the device budget under the quantized working set: the
    pager thrashes (evictions > 0) but every score bit is unchanged."""
    rng = np.random.default_rng(41)
    docs = zipf_corpus(rng, 240)
    monkeypatch.setattr(codec, "QUANTIZED_MODE", "on")
    monkeypatch.setattr(bm25_ops, "HOST_SCORING", False)
    s, _ = build_searcher(docs, [80, 80, 80], prefix="ev")
    body = {"query": {"match": {"body": "w0 w2"}}, "size": 240}
    ref = ranked_hits(s.search(dict(body)))
    assert device_pager().stats()["resident_entries"] > 0

    device_ledger().set_budget(1)            # evict everything staged
    got = ranked_hits(s.search(dict(body)))
    assert got == ref                        # bit-for-bit
    assert device_pager().stats()["evictions"] > 0


def test_prefetch_oracle_runs_ahead_of_dispatch(monkeypatch):
    """The block-max prefetch oracle stages every segment's quantized
    tables before the dispatch loop asks — a cold scored query sees
    pager hits, not misses."""
    rng = np.random.default_rng(53)
    docs = zipf_corpus(rng, 210)
    monkeypatch.setattr(codec, "QUANTIZED_MODE", "on")
    monkeypatch.setattr(bm25_ops, "HOST_SCORING", False)
    s, _ = build_searcher(docs, [70, 70, 70], prefix="po")
    s.search({"query": {"match": {"body": "w1"}}, "size": 10})
    st = device_pager().stats()
    assert st["prefetches"] == 3
    assert st["misses"] == 0
    assert st["hits"] >= 3


def test_pager_stats_in_ledger_and_metrics(monkeypatch):
    monkeypatch.setattr(codec, "QUANTIZED_MODE", "on")
    monkeypatch.setattr(bm25_ops, "HOST_SCORING", False)
    rng = np.random.default_rng(61)
    s, _ = build_searcher(zipf_corpus(rng, 90), [90], prefix="st")
    s.search({"query": {"match": {"body": "w0"}}, "size": 5})
    led = device_ledger()
    pstats = led.stats()["pager"]
    for key in ("page_bytes", "capacity_pages", "resident_pages",
                "resident_entries", "resident_bytes", "hits", "misses",
                "evictions", "evicted_pages", "prefetches"):
        assert key in pstats
    assert pstats["resident_entries"] >= 1
    text = led.prometheus_text()
    assert "opensearch_tpu_device_pager_resident_pages" in text
    assert "opensearch_tpu_device_pager_capacity_pages" in text


# -- .quant sidecars: durability + corruption matrix -------------------------

def _seg_on_disk(tmp_path, n_docs=70):
    rng = np.random.default_rng(19)
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    writer = SegmentWriter()
    batch = [mapper.parse(str(i), d)
             for i, d in enumerate(zipf_corpus(rng, n_docs))]
    seg = writer.build(batch, "qsc0")
    store.save_segment(seg, str(tmp_path))
    loaded = store.load_segment(str(tmp_path), "qsc0")
    avgdl = float(np.float32(loaded.postings["body"].doc_lens.mean()))
    return loaded, avgdl


def test_quant_sidecar_roundtrip_and_staleness(tmp_path):
    loaded, avgdl = _seg_on_disk(tmp_path)
    qt = loaded.quantized_table("body", avgdl)
    path = os.path.join(str(tmp_path),
                        store.quant_sidecar_name("qsc0", "body"))
    assert os.path.exists(path)

    back = store.load_quantized_tables(str(tmp_path), "qsc0", "body",
                                       avgdl=avgdl)
    np.testing.assert_array_equal(back.qvals, qt.qvals)
    np.testing.assert_array_equal(back.scales, qt.scales)
    np.testing.assert_array_equal(back.packed, qt.packed)
    np.testing.assert_array_equal(back.base, qt.base)
    assert back.width == qt.width and back.dtype == qt.dtype

    # avgdl moved under a refresh/merge: the sidecar is stale, not wrong
    assert store.load_quantized_tables(str(tmp_path), "qsc0", "body",
                                       avgdl=avgdl + 1.0) is None
    # absent file is absent, not an error
    assert store.load_quantized_tables(str(tmp_path), "qsc0",
                                       "nosuch") is None
    # the sidecar participates in fsck and teardown
    assert store.verify_segment(str(tmp_path), "qsc0") is True
    store.delete_segment_files(str(tmp_path), "qsc0")
    assert not os.path.exists(path)


@pytest.mark.parametrize("corruption", [
    "truncate", "bitflip", "bad_header", "garbage_payload"])
def test_quant_sidecar_corruption_matrix(tmp_path, corruption):
    loaded, avgdl = _seg_on_disk(tmp_path)
    loaded.quantized_table("body", avgdl)
    path = os.path.join(str(tmp_path),
                        store.quant_sidecar_name("qsc0", "body"))
    data = open(path, "rb").read()
    if corruption == "truncate":
        bad = data[:6]
    elif corruption == "bitflip":
        flip = bytearray(data)
        flip[20] ^= 0xFF
        bad = bytes(flip)
    elif corruption == "bad_header":
        bad = b"zzzzzzzz" + data[8:]
    else:                                   # valid CRC over garbage
        import zlib
        payload = b"not an npz at all"
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        bad = f"{crc:08x}".encode() + payload
    with open(path, "wb") as f:
        f.write(bad)

    with pytest.raises(store.CorruptIndexError) as ei:
        store.load_quantized_tables(str(tmp_path), "qsc0", "body")
    assert "qsc0.body.quant" in str(ei.value)
    # fsck surfaces the bad sidecar (verify_segment raises on the
    # first corrupt file, per its contract)
    with pytest.raises(store.CorruptIndexError):
        store.verify_segment(str(tmp_path), "qsc0")

    # the search path degrades: a fresh reader recomputes AND rewrites
    again = store.load_segment(str(tmp_path), "qsc0")
    qt = again.quantized_table("body", avgdl)
    assert qt is not None
    assert store.load_quantized_tables(
        str(tmp_path), "qsc0", "body", avgdl=avgdl) is not None
    assert store.verify_segment(str(tmp_path), "qsc0") is True


# -- tools/check_quantized_staging.py lint -----------------------------------

def test_check_quantized_staging_lint_passes():
    r = subprocess.run(
        [sys.executable,
         os.path.join(TOOLS, "check_quantized_staging.py")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_check_quantized_staging_lint_catches_violations(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "imp = dseg.impacts('body')\n"
        "led.stage(g, a, kind=\"impacts\", field='body')\n"
        "ok = dseg.impacts('body')  # quantize-ok: test annotation\n"
        "# quantize-ok: above-line annotation\n"
        "ok2 = led.stage(g, a, kind='impacts')\n"
        "fine = led.stage(g, a, kind='impacts_q')\n")
    exempt = tmp_path / "codec.py"
    exempt.write_text("imp = dseg.impacts('body')\n")
    r = subprocess.run(
        [sys.executable,
         os.path.join(TOOLS, "check_quantized_staging.py"),
         str(tmp_path)],
        capture_output=True, text=True)
    assert r.returncode == 1
    assert "bad.py:1" in r.stdout and "bad.py:2" in r.stdout
    assert "bad.py:3" not in r.stdout and "bad.py:5" not in r.stdout
    assert "bad.py:6" not in r.stdout
    assert f"{exempt}:" not in r.stdout    # codec.py is exempt wholesale
