"""Search backpressure & overload protection (PR 4; ref
search/backpressure/SearchBackpressureService.java,
tasks/TaskResourceTrackingService.java,
tasks/TaskCancellationService.java): per-task resource tracking,
duress-driven cancellation, admission control, and coordinator→data-node
cancellation propagation.  Everything here is deterministic — injectable
clocks, forced-duress fault injection, event-gated blocking — no
wall-clock sleeps drive any assertion.
"""

import json
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.common.breakers import breaker_service
from opensearch_tpu.common.tasks import (TaskCancelledException,
                                         TaskManager, charge_current,
                                         check_current, reset_current,
                                         set_current)
from opensearch_tpu.search.backpressure import (SearchBackpressureService,
                                                SearchRejectedError,
                                                TokenBucket)
from opensearch_tpu.node import Node
from opensearch_tpu.testing.fault_injection import FaultInjector
from opensearch_tpu.transport.service import (LocalTransport,
                                              TransportService)

TOOLS = __file__.rsplit("/tests/", 1)[0] + "/tools"


@pytest.fixture()
def node(tmp_path):
    n = Node(str(tmp_path / "node"), port=0).start()
    yield n
    n.stop()


def call(node, method, path, body=None, headers=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    if isinstance(body, (dict, list)):
        data = json.dumps(body).encode()
    else:
        data = body
    hdrs = dict(headers or {})
    if isinstance(body, (dict, list)):
        hdrs.setdefault("Content-Type", "application/json")
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=hdrs)
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return (resp.status,
                    json.loads(payload) if payload else {},
                    dict(resp.headers))
    except urllib.error.HTTPError as e:
        payload = e.read()
        return (e.code, json.loads(payload) if payload else {},
                dict(e.headers))


def wait_until(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:    # deadline
        if pred():
            return True
        time.sleep(0.02)                  # deadline
    return pred()


def make_service(tm, **kw):
    """Backpressure service on a fake clock with quiet probes (tests
    force duress explicitly)."""
    clock = kw.pop("clock", None) or FakeClock()
    kw.setdefault("cpu_load_fn", lambda: 0.0)
    kw.setdefault("num_successive_breaches", 1)
    kw.setdefault("task_cpu_nanos_threshold", 1_000_000)
    kw.setdefault("task_heap_bytes_threshold", 1 << 40)
    kw.setdefault("task_elapsed_nanos_threshold", 1 << 62)
    svc = SearchBackpressureService(tm, clock=clock, **kw)
    svc._test_clock = clock
    return svc


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


# -- task resource tracking -------------------------------------------------


def test_task_cpu_tracking_at_checkpoints():
    tm = TaskManager()
    t = tm.register("indices:data/read/search", "q")
    token = set_current(t)
    try:
        acc = 0
        for i in range(20_000):
            acc += i * i                 # burn some real CPU
            if i % 1000 == 0:
                check_current()          # checkpoint folds the delta in
    finally:
        reset_current(token)
    stats = t.resource_stats()
    assert stats["cpu_time_in_nanos"] > 0
    assert stats["checkpoints"] >= 20
    assert stats["elapsed_time_in_nanos"] > 0
    tm.unregister(t)


def test_task_heap_charged_to_breaker_and_released():
    tm = TaskManager()
    t = tm.register("indices:data/read/search", "q")
    base = breaker_service().request.used
    token = set_current(t)
    try:
        charge_current(4096, "test buffers")
        charge_current({"rows": ["x"] * 10}, "structured")
    finally:
        reset_current(token)
    assert t.heap_bytes > 4096
    assert breaker_service().request.used >= base + 4096
    stats = t.resource_stats()
    assert stats["peak_heap_size_in_bytes"] == t.heap_bytes
    tm.unregister(t)                     # unregister releases the bytes
    assert breaker_service().request.used == base
    assert t.heap_bytes == 0


def test_search_merge_charges_heap_to_owning_task():
    from opensearch_tpu.index.segment import SegmentWriter
    from opensearch_tpu.mapping.mapper import DocumentMapper
    from opensearch_tpu.search.executor import ShardSearcher

    mapper = DocumentMapper({"properties": {"t": {"type": "text"}}})
    writer = SegmentWriter()
    segs = [writer.build([mapper.parse(f"{i}", {"t": "common word"})
                          for i in range(8)], "c0")]
    searcher = ShardSearcher(segs, mapper)
    tm = TaskManager()
    t = tm.register("indices:data/read/search", "q")
    token = set_current(t)
    try:
        r = searcher.search({"query": {"match": {"t": "common"}}})
        assert r["hits"]["total"]["value"] == 8
        assert t.resource_stats()["peak_heap_size_in_bytes"] > 0
    finally:
        reset_current(token)
        tm.unregister(t)


def test_tasks_rest_surface_resource_stats(node):
    code, resp, _ = call(node, "GET", "/_tasks")
    assert code == 200
    tasks = resp["nodes"][node.node_id]["tasks"]
    t = next(t for t in tasks.values()
             if t["action"] == "rest:h_tasks_list")
    rs = t["resource_stats"]
    assert {"cpu_time_in_nanos", "elapsed_time_in_nanos",
            "heap_size_in_bytes",
            "peak_heap_size_in_bytes"} <= set(rs)


# -- duress-driven cancellation ---------------------------------------------


def _search_task(tm, cpu_nanos):
    t = tm.register("indices:data/read/search", f"q-{cpu_nanos}")
    t.add_cpu_nanos(cpu_nanos)
    return t


def test_enforced_cancels_exactly_the_top_consumer():
    tm = TaskManager()
    svc = make_service(tm, mode="enforced", num_successive_breaches=3)
    mid = _search_task(tm, 3_000_000)
    top = _search_task(tm, 5_000_000)
    low = _search_task(tm, 2_000_000)
    faults = FaultInjector(LocalTransport.Hub(), seed=7)
    faults.induce_search_duress(svc, ticks=3)
    assert svc.run_once()["duress"] is False    # streak 1 of 3
    assert svc.run_once()["duress"] is False    # streak 2 of 3
    out = svc.run_once()                        # streak reached: act
    assert out["duress"] is True
    assert out["cancelled"] == [top]
    assert top.cancelled and not mid.cancelled and not low.cancelled
    assert "search backpressure" in top.cancel_reason
    st = svc.stats()
    assert st["cancellation_count"] == 1
    assert st["search_task"]["resource_tracker_cancellations"][
        "cpu_usage"] == 1
    assert st["node_duress"]["in_duress"] is True
    # duress lifted -> streak resets, nothing else is cancelled
    assert svc.run_once()["duress"] is False
    assert not mid.cancelled


def test_monitor_only_counts_without_cancelling():
    tm = TaskManager()
    svc = make_service(tm, mode="monitor_only")
    top = _search_task(tm, 9_000_000)
    svc.force_duress(1)
    out = svc.run_once()
    assert out["duress"] is True and out["cancelled"] == []
    assert not top.cancelled
    st = svc.stats()
    assert st["cancellation_count"] == 0
    assert st["monitor_only_count"] == 1


def test_disabled_mode_is_inert():
    tm = TaskManager()
    svc = make_service(tm, mode="disabled")
    top = _search_task(tm, 9_000_000)
    svc.force_duress(5)
    for _ in range(5):
        assert svc.run_once() == {"duress": False, "cancelled": []}
    assert not top.cancelled


def test_cancellation_rate_limited_by_token_bucket():
    tm = TaskManager()
    svc = make_service(tm, mode="enforced", cancellation_rate=1.0,
                       cancellation_burst=1.0,
                       max_cancellations_per_tick=10)
    a = _search_task(tm, 9_000_000)
    b = _search_task(tm, 8_000_000)
    svc.force_duress(1)
    out = svc.run_once()
    # one token: the top consumer goes, the second hits the limit
    assert out["cancelled"] == [a]
    assert not b.cancelled
    assert svc.stats()["limit_reached_count"] == 1
    # refill on the fake clock -> the next duress tick takes b
    svc._test_clock.advance(2.0)
    svc.force_duress(1)
    assert svc.run_once()["cancelled"] == [b]


def test_non_search_tasks_are_never_sacrificed():
    tm = TaskManager()
    svc = make_service(tm, mode="enforced")
    bulk = tm.register("indices:data/write/bulk", "heavy write")
    bulk.add_cpu_nanos(10_000_000_000)
    svc.force_duress(1)
    assert svc.run_once()["cancelled"] == []
    assert not bulk.cancelled


def test_token_bucket_deterministic_refill():
    clock = FakeClock()
    tb = TokenBucket(rate=2.0, burst=2.0, clock=clock)
    assert tb.request() and tb.request() and not tb.request()
    clock.advance(0.5)                    # +1 token
    assert tb.request() and not tb.request()


def test_real_duress_trackers_breach_on_thresholds():
    tm = TaskManager()
    load = [0.0]
    svc = SearchBackpressureService(tm, cpu_load_fn=lambda: load[0],
                                    cpu_threshold=0.9,
                                    num_successive_breaches=1)
    assert svc.run_once()["duress"] is False
    load[0] = 0.95
    assert svc.run_once()["duress"] is True
    st = svc.stats()["node_duress"]["trackers"]["cpu_usage"]
    assert st["current"] == 0.95 and st["breach_count"] >= 1


# -- dynamic settings (the formerly-dead search_backpressure.mode) ---------


def test_mode_setting_flip_takes_effect_immediately(node):
    assert node.search_backpressure.mode == "monitor_only"
    code, _, _ = call(node, "PUT", "/_cluster/settings", {
        "persistent": {"search_backpressure.mode": "enforced"}})
    assert code == 200
    assert node.search_backpressure.mode == "enforced"
    code, resp, _ = call(node, "GET", "/_nodes/stats")
    assert resp["nodes"][node.node_id]["search_backpressure"][
        "mode"] == "enforced"
    code, _, _ = call(node, "PUT", "/_cluster/settings", {
        "persistent": {"search_backpressure.mode": "bogus"}})
    assert code == 400
    assert node.search_backpressure.mode == "enforced"   # unchanged


def test_node_duress_settings_consumers(node):
    code, _, _ = call(node, "PUT", "/_cluster/settings", {"transient": {
        "search_backpressure.node_duress.cpu_threshold": 0.5,
        "search_backpressure.node_duress.search_queue_threshold": 7,
        "search_backpressure.node_duress.num_successive_breaches": 2,
        "search_backpressure.max_concurrent_searches": 9}})
    assert code == 200
    bp = node.search_backpressure
    assert bp.trackers["cpu_usage"].threshold == 0.5
    assert bp.trackers["search_queue"].threshold == 7
    assert bp.num_successive_breaches == 2
    assert bp.admission.max_concurrent == 9


# -- admission control ------------------------------------------------------


def test_admission_gate_rejects_429_with_retry_after(node):
    call(node, "PUT", "/idx", {"mappings": {"properties": {
        "t": {"type": "text"}}}})
    call(node, "PUT", "/idx/_doc/1", {"t": "hello"})
    call(node, "POST", "/idx/_refresh")
    node.search_backpressure.set_max_concurrent_searches(1)
    base = node.search_backpressure.admission.stats()["rejected_count"]
    with node.search_backpressure.admission.acquire():
        code, resp, headers = call(node, "POST", "/idx/_search",
                                   {"query": {"match": {"t": "hello"}}})
        assert code == 429
        assert resp["error"]["type"] == "search_rejected_exception"
        assert headers.get("Retry-After") == "1"
    # permit released: the same request succeeds
    code, resp, _ = call(node, "POST", "/idx/_search",
                         {"query": {"match": {"t": "hello"}}})
    assert code == 200 and resp["hits"]["total"]["value"] == 1
    # accounting: admission stats + the search.rejected metric
    code, stats, _ = call(node, "GET", "/_nodes/stats")
    nstats = stats["nodes"][node.node_id]
    assert nstats["search_backpressure"]["admission_control"][
        "rejected_count"] == base + 1
    assert nstats["telemetry"]["counters"]["search.rejected"] >= 1


def test_enforced_duress_rejects_new_searches_at_admission():
    tm = TaskManager()
    svc = make_service(tm, mode="enforced", num_successive_breaches=2)
    svc.force_duress(10)     # covers the admission path's own tick too
    svc.run_once()
    svc.run_once()
    assert svc.in_duress()
    with pytest.raises(SearchRejectedError):
        with svc.admission.acquire():
            pass
    assert svc.admission.stats()["rejected_count"] == 1
    # monitor_only observes duress but never sheds load at the gate
    svc.set_mode("monitor_only")
    with svc.admission.acquire():
        pass


def test_rejected_execution_maps_retry_after_and_metric(node):
    from opensearch_tpu.common.threadpool import RejectedExecutionError

    def h_always_rejected(req):
        raise RejectedExecutionError(
            "rejected execution on [search]: queue capacity reached")
    node.rest.register("GET", "/_test/rejected", h_always_rejected)
    code, resp, headers = call(node, "GET", "/_test/rejected")
    assert code == 429
    assert resp["error"]["type"] == "rejected_execution_exception"
    assert headers.get("Retry-After") == "1"
    code, stats, _ = call(node, "GET", "/_nodes/stats")
    assert stats["nodes"][node.node_id]["telemetry"]["counters"][
        "search.rejected"] >= 1


# -- scroll/PIT context cleanup on cancellation -----------------------------


def test_cancelling_scroll_task_closes_context_and_releases_breaker(node):
    from opensearch_tpu.rest.controller import RestRequest

    call(node, "PUT", "/s", {"mappings": {"properties": {
        "t": {"type": "text"}}}})
    for i in range(20):
        call(node, "PUT", f"/s/_doc/{i}", {"t": "common filler"})
    call(node, "POST", "/s/_refresh")
    base = breaker_service().request.used
    code, resp, _ = call(node, "POST", "/s/_search?scroll=1m",
                         {"size": 2, "query": {"match": {"t": "common"}}})
    assert code == 200
    sid = resp["_scroll_id"]
    assert breaker_service().request.used > base   # cursor reserved
    assert node.contexts.count() == 1
    # fetch a page as a registered task, then cancel that task: the
    # live context must close NOW, not at keep-alive expiry
    task = node.task_manager.register("indices:data/read/scroll",
                                      "scroll page")
    token = set_current(task)
    try:
        req = RestRequest("POST", "/_search/scroll", {},
                          json.dumps({"scroll_id": sid}).encode(),
                          "application/json")
        status, page = node.rest.h_scroll_next(req)
        assert status == 200 and len(page["hits"]["hits"]) == 2
        task.cancel("user gave up")
    finally:
        reset_current(token)
        node.task_manager.unregister(task)
    assert node.contexts.count() == 0
    assert breaker_service().request.used == base  # reservation freed
    code, resp, _ = call(node, "POST", "/_search/scroll",
                         {"scroll_id": sid})
    assert code == 404                              # context is gone


def test_cancelling_pit_task_closes_context(node):
    from opensearch_tpu.rest.controller import RestRequest

    call(node, "PUT", "/p", {"mappings": {"properties": {
        "t": {"type": "text"}}}})
    call(node, "PUT", "/p/_doc/1", {"t": "hello"})
    call(node, "POST", "/p/_refresh")
    code, resp, _ = call(node, "POST", "/p/_search/point_in_time"
                                       "?keep_alive=1m")
    assert code == 200
    pid = resp["pit_id"]
    task = node.task_manager.register("indices:data/read/search", "pit")
    token = set_current(task)
    try:
        req = RestRequest("POST", "/_search", {}, json.dumps({
            "pit": {"id": pid}, "query": {"match_all": {}}}).encode(),
            "application/json")
        status, page = node.rest.h_search(req)
        assert status == 200
        task.cancel("pit abandoned")
    finally:
        reset_current(token)
        node.task_manager.unregister(task)
    assert node.contexts.count() == 0


# -- parent bans + remote cancellation propagation --------------------------


def test_ban_cancels_running_and_late_children():
    tm = TaskManager()
    child = tm.register("indices:data/read/search[shards]", "running",
                        parent_task_id="n1:7")
    other = tm.register("indices:data/read/search[shards]", "other",
                        parent_task_id="n1:8")
    cancelled = tm.ban_parent("n1:7", "parent cancelled")
    assert cancelled == [child] and child.cancelled and not other.cancelled
    # a child registering AFTER the ban arrives pre-cancelled
    late = tm.register("indices:data/read/search[shards]", "late",
                       parent_task_id="n1:7")
    assert late.cancelled
    tm.unban_parent("n1:7")
    fresh = tm.register("indices:data/read/search[shards]", "fresh",
                        parent_task_id="n1:7")
    assert not fresh.cancelled


@pytest.fixture
def cluster(tmp_path):
    hub = LocalTransport.Hub()
    ids = ["n0", "n1", "n2"]
    nodes = {}
    for nid in ids:
        svc = TransportService(nid, LocalTransport(hub))
        from opensearch_tpu.cluster.node import ClusterNode
        nodes[nid] = ClusterNode(nid, str(tmp_path / nid), svc, ids)
    assert nodes["n0"].start_election()
    assert wait_until(lambda: all(
        nodes[i].coordinator.state().master_node == "n0" for i in ids))
    yield hub, ids, nodes
    for n in nodes.values():
        n.stop()


def test_coordinator_cancel_propagates_to_remote_shard_tasks(cluster):
    """The PR's acceptance path: a coordinator-side cancel stops remote
    shard tasks (the data node's task list drains) and the search
    returns PARTIAL results (counted _shards.failures) instead of
    hanging — all event-driven, no timing assumptions."""
    from opensearch_tpu.search.executor import ShardSearcher

    hub, ids, nodes = cluster
    nodes["n0"].create_index("logs", {
        "settings": {"number_of_shards": 1},
        "mappings": {"properties": {"msg": {"type": "text"}}}})
    assert wait_until(lambda: all(
        "logs" in nodes[i].coordinator.state().indices for i in ids))
    routing = nodes["n0"].coordinator.state().routing["logs"]
    owner = routing[0]["primary"]
    coord = next(i for i in ids if i != owner)
    nodes[coord].index_doc("logs", "1", {"msg": "hello world"})
    nodes[coord].refresh("logs")

    started, release = threading.Event(), threading.Event()
    orig = ShardSearcher.search

    def blocked(self, body=None, **kw):
        started.set()
        deadline = time.monotonic() + 20
        while not release.is_set() and time.monotonic() < deadline:  # deadline
            check_current()              # raises once the ban lands
            release.wait(0.01)
        return orig(self, body, **kw)

    ShardSearcher.search = blocked
    result = {}

    def run():
        try:
            result["resp"] = nodes[coord].search(
                "logs", {"query": {"match": {"msg": "hello"}}})
        except Exception as e:  # noqa: BLE001 — surfaced in asserts
            result["exc"] = e

    th = threading.Thread(target=run, name="test-coordinator-search",
                          daemon=True)
    try:
        th.start()
        assert started.wait(10), "shard-side search never started"
        # the data node is running a child task tied to the coordinator
        assert wait_until(lambda: any(
            t.parent_task_id for t in nodes[owner].task_manager.list(
                "indices:data/read/search*")))
        cancelled = nodes[coord].task_manager.cancel(
            actions="indices:data/read/search", reason="test cancel")
        assert len(cancelled) == 1
        th.join(15)
        assert not th.is_alive(), "cancelled search hung"
    finally:
        release.set()
        ShardSearcher.search = orig
    assert "resp" in result, f"search raised: {result.get('exc')!r}"
    shards = result["resp"]["_shards"]
    assert shards["failed"] >= 1
    assert shards["failures"][0]["reason"]["type"] == \
        "task_cancelled_exception"
    # remote shard tasks drained — nothing left running on the data node
    assert wait_until(lambda: nodes[owner].task_manager.list(
        "indices:data/read/search*") == [])
    # coordinator side cleaned up too
    assert nodes[coord].task_manager.list(
        "indices:data/read/search*") == []


def test_cluster_search_registers_and_drains_tasks(cluster):
    hub, ids, nodes = cluster
    nodes["n0"].create_index("d", {
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {"msg": {"type": "text"}}}})
    assert wait_until(lambda: all(
        "d" in nodes[i].coordinator.state().indices for i in ids))
    for i in range(6):
        nodes["n1"].index_doc("d", str(i), {"msg": "hello"})
    nodes["n1"].refresh("d")
    r = nodes["n1"].search("d", {"query": {"match": {"msg": "hello"}}})
    assert r["hits"]["total"]["value"] == 6
    assert r["_shards"]["failed"] == 0
    for nid in ids:
        assert nodes[nid].task_manager.list(
            "indices:data/read/search*") == []


def test_fault_injector_stall_holds_frames_until_release(cluster):
    """The event-gated stall primitive: a held frame is NOT delivered
    until release(), then arrives immediately (no wall-clock delay)."""
    hub, ids, nodes = cluster
    faults = FaultInjector(hub, seed=3)
    rule = faults.stall(action="indices:data/read/get",
                        target="n0", times=1)
    fut = nodes["n1"].transport.submit_request(
        "n0", "indices:data/read/get",
        {"index": "missing", "shard": 0, "id": "1"})
    assert not fut.done()
    rule.release()
    with pytest.raises(Exception):
        fut.result(timeout=10)           # delivered: shard-not-found
    faults.clear()


# -- lint: thread hygiene ---------------------------------------------------


def test_thread_hygiene_lint_clean():
    proc = subprocess.run(
        [sys.executable, f"{TOOLS}/check_thread_hygiene.py"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_thread_hygiene_lint_catches_violations(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import threading\n"
        "t = threading.Thread(target=print)\n"           # missing both
        "u = threading.Thread(target=print, daemon=True)\n"  # missing name
        "ok = threading.Thread(target=print, name='x', daemon=True)\n"
        "ann = threading.Thread(target=print)  # thread-ok\n")
    proc = subprocess.run(
        [sys.executable, f"{TOOLS}/check_thread_hygiene.py",
         str(tmp_path)],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert proc.stdout.count("bad.py") == 2
