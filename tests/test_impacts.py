"""Impact-ordered scoring: byte-exact score parity, plan-cache
zero-recompile hot path, block-max segment pruning, and the hot-path
sync lint.

The tentpole invariant: precomputing per-posting impacts
(``DeviceSegment.impacts``) must not change a single score bit relative
to the impact formula evaluated in numpy float32 — across the
sequential path, the batched msearch path, the pruned path, and after a
refresh rebuilds the searcher with a different avgdl.

The references here mirror the kernels' float32 operation order
(ops/bm25.py ``compute_impacts`` / ``impact_scores``) and accumulate
with ``np.add.at`` in gather order (term-major), which XLA:CPU's
in-order scatter-add reproduces exactly.  Queries use <=2 distinct
terms so per-doc accumulation order is commutativity-safe across the
sequential and batched layouts.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from opensearch_tpu.common.telemetry import metrics
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search import plan as P
from opensearch_tpu.search.executor import ShardSearcher

K1, B = 1.2, 0.75
TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def zipf_corpus(rng, n_docs, vocab=120, avg_len=24):
    docs = []
    for _ in range(n_docs):
        n = int(rng.integers(avg_len // 2, avg_len * 2))
        terms = (rng.zipf(1.4, size=n) - 1).clip(0, vocab - 1)
        docs.append({"body": " ".join(f"w{t}" for t in terms)})
    return docs


def build_searcher(docs, seg_sizes):
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    writer = SegmentWriter()
    segs, i = [], 0
    for si, size in enumerate(seg_sizes):
        batch = [mapper.parse(str(i + j), d)
                 for j, d in enumerate(docs[i: i + size])]
        segs.append(writer.build(batch, f"imp{si}"))
        i += size
    return ShardSearcher(segs, mapper), mapper


def reference_scores(searcher, terms, weights=None):
    """float32 impact-formula scores per (seg, local), mirroring the
    kernel op-for-op: imp = tf/(tf + k1*(1-b + b*dl/avgdl)), contrib =
    w * (idf * imp), accumulated term-major per segment."""
    field = "body"
    stats = searcher.ctx.field_stats(field)
    avgdl = np.float32(stats.avgdl)
    weights = weights or [1.0] * len(terms)
    out = {}
    for si, seg in enumerate(searcher.segments):
        pf = seg.postings[field]
        dl = pf.doc_lens[pf.doc_ids]
        norm = np.float32(K1) * (np.float32(1.0 - B)
                                 + np.float32(B) * dl / avgdl)
        imp = (pf.tfs / (pf.tfs + norm)).astype(np.float32)
        scores = np.zeros(seg.n_docs, np.float32)
        for t, w in zip(terms, weights):
            tid = pf.term_id(t)
            if tid < 0:
                continue
            idf = np.float32(P.bm25_ops.idf(searcher.ctx.df(field, t),
                                            stats.doc_count))
            e0, e1 = int(pf.offsets[tid]), int(pf.offsets[tid + 1])
            base = idf * imp[e0:e1]
            np.add.at(scores, pf.doc_ids[e0:e1], np.float32(w) * base)
        for local in range(seg.n_docs):
            out[(si, local)] = scores[local]
    return out


def hit_scores(searcher, resp):
    """{(seg, local): float32 score} out of a search response."""
    id_of = {}
    for si, seg in enumerate(searcher.segments):
        for local, did in enumerate(seg.doc_ids):
            id_of[did] = (si, local)
    return {id_of[h["_id"]]: np.float32(h["_score"])
            for h in resp["hits"]["hits"]}


@pytest.fixture(params=["host", "device"])
def scoring_path(request, monkeypatch):
    """Run the parity suite over BOTH lowerings of the term-bag hot
    path: the CPU-backend host fast path and the XLA kernels (what an
    accelerator backend executes).  They must be byte-identical."""
    from opensearch_tpu.ops import bm25 as bm25_ops
    monkeypatch.setattr(bm25_ops, "HOST_SCORING",
                        request.param == "host")
    return request.param


@pytest.mark.parametrize("seed", [3, 17, 92])
def test_sequential_batched_pruned_scores_byte_exact(seed, scoring_path):
    rng = np.random.default_rng(seed)
    docs = zipf_corpus(rng, 220)
    searcher, _ = build_searcher(docs, [90, 70, 60])
    for _ in range(6):
        a, b = (rng.zipf(1.4, size=2) - 1).clip(0, 119)
        terms = [f"w{a}"] if a == b else [f"w{a}", f"w{b}"]
        query = {"match": {"body": " ".join(terms)}}
        ref = reference_scores(searcher, terms)
        n = sum(s.n_docs for s in searcher.segments)

        # sequential path: every hit byte-equal to the reference formula
        resp = searcher.search({"query": query, "size": n})
        got = hit_scores(searcher, resp)
        assert got, "query matched nothing — bad corpus seed"
        for key, s in got.items():
            assert s == np.float32(ref[key]), (key, s, ref[key])
        assert resp["hits"]["total"]["value"] == \
            sum(1 for v in ref.values() if v > 0)

        # batched msearch path: byte-equal to the sequential path
        [mresp] = searcher.msearch([{"query": query, "size": n}])
        mgot = hit_scores(searcher, mresp)
        assert mgot == got

        # pruned path (min_score): the skip must only drop segments
        # that contribute nothing, never change a surviving score
        cutoff = float(np.median([v for v in ref.values() if v > 0]))
        presp = searcher.search({"query": query, "size": n,
                                 "min_score": cutoff})
        pgot = hit_scores(searcher, presp)
        for key, s in pgot.items():
            assert s == np.float32(ref[key])
        assert set(pgot) == {k for k, s in got.items()
                             if s >= np.float32(cutoff)}


def test_and_semantics_and_weights_byte_exact():
    rng = np.random.default_rng(5)
    docs = zipf_corpus(rng, 150)
    searcher, _ = build_searcher(docs, [80, 70])
    terms = ["w0", "w3"]
    ref = reference_scores(searcher, terms, weights=[2.5, 2.5])
    q = {"match": {"body": {"query": "w0 w3", "operator": "and",
                            "boost": 2.5}}}
    n = sum(s.n_docs for s in searcher.segments)
    resp = searcher.search({"query": q, "size": n})
    got = hit_scores(searcher, resp)
    assert got
    for key, s in got.items():
        assert s == np.float32(ref[key])
    [mresp] = searcher.msearch([{"query": q, "size": n}])
    assert hit_scores(searcher, mresp) == got


def test_refresh_invalidates_staged_impacts(tmp_path):
    """A refresh that changes avgdl must re-derive impacts: scores after
    the refresh must match the reference recomputed against the NEW
    shard stats, exactly."""
    from opensearch_tpu.indices.service import IndexService

    svc = IndexService("imp", str(tmp_path / "imp"), {},
                       {"properties": {"body": {"type": "text"}}})
    rng = np.random.default_rng(11)
    docs = zipf_corpus(rng, 60)
    for i, d in enumerate(docs):
        svc.index_doc(str(i), d)
    svc.refresh()
    q = {"match": {"body": "w0 w2"}}
    s1 = svc.searcher()
    ref1 = reference_scores(s1, ["w0", "w2"])
    got1 = hit_scores(s1, svc.search({"query": q, "size": 100}))
    assert got1
    for key, s in got1.items():
        assert s == np.float32(ref1[key])
    # second wave with much longer docs shifts avgdl
    more = zipf_corpus(rng, 40, avg_len=80)
    for i, d in enumerate(more):
        svc.index_doc(f"n{i}", d)
    svc.refresh()
    s2 = svc.searcher()
    assert s2 is not s1           # reader generation bumped
    ref2 = reference_scores(s2, ["w0", "w2"])
    got2 = hit_scores(s2, svc.search({"query": q, "size": 200}))
    assert got2
    for key, s in got2.items():
        assert s == np.float32(ref2[key])
    # the old searcher's avgdl keys must actually differ (stats moved)
    assert s1.ctx.field_stats("body").avgdl != \
        s2.ctx.field_stats("body").avgdl


def test_repeated_query_zero_compile_zero_retrace(monkeypatch):
    """The zero-recompile hot path: a repeated identical-shape query
    must hit the plan cache (no compile_query), reuse prepared bindings,
    and add no XLA trace cache entries."""
    import opensearch_tpu.search.executor as ex

    rng = np.random.default_rng(7)
    searcher, _ = build_searcher(zipf_corpus(rng, 120), [60, 60])
    body = {"query": {"match": {"body": "w1 w4"}}, "size": 5}
    hits_c = metrics().counter("search.plan_cache.hits")
    miss_c = metrics().counter("search.plan_cache.misses")
    m0h, m0m = hits_c.value, miss_c.value
    first = searcher.search(body)
    assert miss_c.value > m0m          # cold: compiled once
    calls = []
    real = ex.compile_query
    monkeypatch.setattr(ex, "compile_query",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    traces_before = P.run_topk._cache_size()
    h1 = hits_c.value
    second = searcher.search(body)
    assert calls == []                 # zero compile_query calls
    assert hits_c.value > h1           # served from the plan cache
    assert P.run_topk._cache_size() == traces_before   # zero retraces
    assert [h["_id"] for h in second["hits"]["hits"]] == \
        [h["_id"] for h in first["hits"]["hits"]]
    assert [h["_score"] for h in second["hits"]["hits"]] == \
        [h["_score"] for h in first["hits"]["hits"]]
    # key order in the body must not miss (canonicalized keys)
    h2 = hits_c.value
    searcher.search({"size": 5, "query": {"match": {"body": "w1 w4"}}})
    assert hits_c.value > h2


def test_min_score_pruning_skips_segments_exactly():
    """Segments whose block-max bound can't reach min_score are skipped
    without dispatch, and results are identical to the unpruned path."""
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    writer = SegmentWriter()
    # seg 0: the term occurs once in a LONG doc (low impact);
    # seg 1: high-tf short docs (high impact)
    low = [mapper.parse("L0", {"body": "alpha " + "pad " * 200})]
    high = [mapper.parse(f"H{i}", {"body": "alpha alpha alpha"})
            for i in range(3)]
    segs = [writer.build(low, "low"), writer.build(high, "high")]
    searcher = ShardSearcher(segs, mapper)
    q = {"match": {"body": "alpha"}}
    all_scores = sorted(
        (h["_score"] for h in
         searcher.search({"query": q, "size": 10})["hits"]["hits"]),
        reverse=True)
    assert len(all_scores) == 4
    cutoff = (all_scores[2] + all_scores[3]) / 2  # between high and low
    plan, bind = searcher.compiled(q, scored=True)
    bounds = [plan.max_score_bound(bind, seg)
              for seg in searcher.segments]
    assert bounds[0] < cutoff <= bounds[1]
    pruned_c = metrics().counter("search.segments_pruned")
    p0 = pruned_c.value
    resp = searcher.search({"query": q, "size": 10, "min_score": cutoff})
    assert pruned_c.value == p0 + 1         # the low segment skipped
    assert resp["hits"]["total"]["value"] == 3
    assert {h["_id"] for h in resp["hits"]["hits"]} == {"H0", "H1", "H2"}
    assert resp["hits"]["total"]["relation"] == "eq"


def test_kth_score_pruning_with_waived_totals():
    """track_total_hits=false lets block-max pruning skip segments that
    can't beat the running k-th score; totals degrade to a lower bound
    flagged with relation gte, top-k hits stay identical."""
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    writer = SegmentWriter()
    high = [mapper.parse(f"H{i}", {"body": "alpha alpha alpha"})
            for i in range(4)]
    low = [mapper.parse(f"L{i}", {"body": "alpha " + "pad " * 200})
           for i in range(3)]
    searcher = ShardSearcher(
        [writer.build(high, "high"), writer.build(low, "low")], mapper)
    body = {"query": {"match": {"body": "alpha"}}, "size": 3,
            "track_total_hits": False}
    exact = searcher.search({"query": body["query"], "size": 3})
    resp = searcher.search(body)
    assert [h["_id"] for h in resp["hits"]["hits"]] == \
        [h["_id"] for h in exact["hits"]["hits"]]
    if resp["hits"]["total"]["relation"] == "gte":
        assert resp["hits"]["total"]["value"] <= \
            exact["hits"]["total"]["value"]
    else:   # harvest raced slower than dispatch: exact answer is fine
        assert resp["hits"]["total"] == exact["hits"]["total"]


def test_count_skips_unmatchable_segments():
    """ShardSearcher.count() can-match-skips segments the plan provably
    can't match, with identical counts."""
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    writer = SegmentWriter()
    segs = [writer.build([mapper.parse(f"{si}-{i}",
                                       {"body": f"seg{si} common"})
                          for i in range(4)], f"c{si}")
            for si in range(3)]
    searcher = ShardSearcher(segs, mapper)
    pruned_c = metrics().counter("search.segments_pruned")
    p0 = pruned_c.value
    assert searcher.count({"match": {"body": "seg1"}}) == 4
    assert pruned_c.value == p0 + 2      # two segments never dispatched
    assert searcher.count({"match": {"body": "common"}}) == 12


# -- tools/check_hot_path_sync.py lint --------------------------------------

def test_check_hot_path_sync_lint_passes():
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_hot_path_sync.py")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_check_hot_path_sync_lint_catches_violations(tmp_path):
    bad = tmp_path / "search"
    bad.mkdir()
    (bad / "executor.py").write_text(
        "import numpy as np\n"
        "class ShardSearcher:\n"
        "    def _topk(self, plan):\n"
        "        out = []\n"
        "        for seg in self.segments:\n"
        "            vals = self.run(seg)\n"
        "            out.append(np.asarray(vals))\n"
        "            score = float(vals[0])\n"
        "            ok = np.asarray(vals)  # sync-ok\n"
        "        return out\n")
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_hot_path_sync.py"),
         str(tmp_path)],
        capture_output=True, text=True)
    assert r.returncode == 1
    assert "asarray" in r.stdout and "float" in r.stdout
    # the annotated line is not reported
    assert r.stdout.count("asarray") == 1
