"""index.codec: default vs best_compression segment formats (ref
index/codec/CodecService.java:46)."""

import json
import os
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.node import Node


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


DOC = {"msg": "the quick brown fox " * 40, "n": 1}


def _src_bytes(tmp_path, index):
    total = 0
    root = tmp_path / "node" / "indices" / index
    for r, _, files in os.walk(root):
        for f in files:
            if f.endswith(".src"):
                total += os.path.getsize(os.path.join(r, f))
    assert total > 0
    return total


def test_best_compression_shrinks_and_survives_restart(tmp_path):
    node = Node(str(tmp_path / "node"), port=0).start()
    call(node, "PUT", "/plain", {"settings": {"codec": "default"}})
    call(node, "PUT", "/packed",
         {"settings": {"index": {"codec": "best_compression"}}})
    for idx in ("plain", "packed"):
        for i in range(50):
            call(node, "PUT", f"/{idx}/_doc/{i}", DOC)
        call(node, "POST", f"/{idx}/_refresh")
        assert call(node, "POST", f"/{idx}/_flush")[0] == 200
    plain, packed = (_src_bytes(tmp_path, "plain"),
                     _src_bytes(tmp_path, "packed"))
    assert packed < plain / 5, (plain, packed)   # repetitive text deflates
    node.stop()
    # compressed segments reload transparently (meta is self-describing)
    node2 = Node(str(tmp_path / "node"), port=0).start()
    try:
        code, body = call(node2, "GET", "/packed/_search",
                          body={"query": {"term": {"n": 1}}, "size": 1})
        assert code == 200 and body["hits"]["total"]["value"] == 50
        assert body["hits"]["hits"][0]["_source"]["msg"] == DOC["msg"]
    finally:
        node2.stop()


def test_unknown_codec_rejected(tmp_path):
    node = Node(str(tmp_path / "node"), port=0).start()
    try:
        code, body = call(node, "PUT", "/bad",
                          {"settings": {"codec": "zstd_turbo"}})
        assert code == 400 and "index.codec" in json.dumps(body)
    finally:
        node.stop()
