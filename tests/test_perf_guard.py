"""Perf-regression guard (VERDICT r4 item 3: r03->r04 silently lost 22%
and batched fell below sequential with no gate).  Absolute QPS is
machine-dependent, so the guard checks the INVARIANT that regressed: a
64-query msearch batch must not be slower than the same queries run
sequentially — the union-of-terms kernel amortizes every per-query cost,
so an inversion means a recompile/staging bug crept back in."""

import time

import numpy as np
import pytest

import bench
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher


@pytest.mark.slow
def test_batched_not_slower_than_sequential():
    raw = bench.build_raw_corpus(20_000)
    seg = bench.make_segment(raw)
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    s = ShardSearcher([seg], mapper, index_name="bench")
    pairs = bench.gen_query_terms(128)
    queries = [{"query": {"match": {"body": f"t{a} t{b}"}}, "size": 10}
               for a, b in pairs]
    # warm both paths (compiles out of the measurement)
    for i in range(0, 128, 64):
        s.msearch(queries[i: i + 64])
    for q in queries[:16]:
        s.search(q)

    t0 = time.monotonic()
    for _ in range(2):
        for i in range(0, 128, 64):
            s.msearch(queries[i: i + 64])
    batched_qps = 256 / (time.monotonic() - t0)

    t0 = time.monotonic()
    for q in queries[:64]:
        s.search(q)
    seq_qps = 64 / (time.monotonic() - t0)

    # generous 0.8x floor absorbs machine noise while still catching the
    # r4-style inversion (batched was 2.7x SLOWER then)
    assert batched_qps >= 0.8 * seq_qps, (
        f"batched msearch regressed below sequential: "
        f"{batched_qps:.1f} vs {seq_qps:.1f} qps")


def test_batched_single_program_per_batch():
    """The union kernel must stay ONE compile per (q_pad, t_pad, budget)
    — per-query budget bucketing (the r4 compile explosion) would show
    up as many cache entries."""
    from opensearch_tpu.search import batch as batch_mod

    raw = bench.build_raw_corpus(5_000)
    seg = bench.make_segment(raw)
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    s = ShardSearcher([seg], mapper, index_name="bench")
    pairs = bench.gen_query_terms(64)
    queries = [{"query": {"match": {"body": f"t{a} t{b}"}}, "size": 10}
               for a, b in pairs]
    before = batch_mod.batch_impact_union_topk._cache_size()
    s.msearch(queries)
    s.msearch(queries)          # identical batch: no new programs
    after = batch_mod.batch_impact_union_topk._cache_size()
    assert after - before <= 1, (
        f"one 64-query batch compiled {after - before} programs "
        "(per-query budget bucketing is back?)")
