"""Remote store: flush-time segment mirroring to a blob repository and
restore after total local loss (ref RemoteStoreRefreshListener.java:56,
RemoteSegmentStoreDirectory.java:77)."""

import json
import shutil
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.node import Node


@pytest.fixture()
def node(tmp_path):
    n = Node(str(tmp_path / "node"), port=0, path_repo=[str(tmp_path)]).start()
    yield n
    n.stop()


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


def test_remote_store_mirror_and_restore(tmp_path):
    node = Node(str(tmp_path / "node"), port=0, path_repo=[str(tmp_path)]).start()
    call(node, "PUT", "/_snapshot/mirror", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo")}})
    code, _ = call(node, "PUT", "/rsidx", {
        "settings": {"number_of_shards": 2,
                     "remote_store": {"enabled": True,
                                      "repository": "mirror"}},
        "mappings": {"properties": {"m": {"type": "text"},
                                    "n": {"type": "long"}}}})
    assert code == 200
    for i in range(12):
        call(node, "PUT", f"/rsidx/_doc/{i}", {"m": f"event {i}", "n": i})
    call(node, "POST", "/rsidx/_refresh")
    code, _ = call(node, "POST", "/rsidx/_flush")
    assert code == 200
    # remote manifests exist for both shards + index meta
    repo = tmp_path / "repo"
    assert (repo / "remote" / "rsidx" / "0" / "manifest.json").exists()
    assert (repo / "remote" / "rsidx" / "1" / "manifest.json").exists()
    assert (repo / "remote" / "rsidx" / "_meta.json").exists()

    # total local loss: kill the node and wipe the index's local disk
    # (DELETE would also drop the mirror — remote store answers NODE
    # loss, not intentional deletion)
    node.stop()
    shutil.rmtree(tmp_path / "node" / "indices" / "rsidx")
    node = Node(str(tmp_path / "node"), port=0, path_repo=[str(tmp_path)]).start()
    code, _ = call(node, "POST", "/rsidx/_count")
    assert code == 404

    code, resp = call(node, "POST", "/_remotestore/_restore",
                      {"indices": ["rsidx"]})
    assert code == 200 and resp["remote_store"]["indices"] == ["rsidx"]
    code, resp = call(node, "POST", "/rsidx/_search",
                      {"query": {"match_all": {}}, "size": 50})
    assert resp["hits"]["total"]["value"] == 12
    code, resp = call(node, "GET", "/rsidx/_doc/7")
    assert code == 200 and resp["_source"]["n"] == 7
    # settings round-trip: still remote-store enabled, 2 shards
    code, resp = call(node, "GET", "/rsidx/_settings")
    assert resp["rsidx"]["settings"]["index"]["number_of_shards"] == "2"
    # restored index keeps mirroring on the next flush
    call(node, "PUT", "/rsidx/_doc/new", {"m": "after restore", "n": 99})
    code, _ = call(node, "POST", "/rsidx/_flush")
    assert code == 200
    # DELETE drops the mirror too (and snapshot-shared blobs survive GC
    # only while referenced)
    call(node, "DELETE", "/rsidx")
    import pathlib
    assert not (tmp_path / "repo" / "remote" / "rsidx").exists()
    node.stop()


def test_remote_store_errors(node, tmp_path):
    code, resp = call(node, "POST", "/_remotestore/_restore", {})
    assert code == 400
    code, resp = call(node, "POST", "/_remotestore/_restore",
                      {"indices": ["ghost"]})
    assert code == 404
    call(node, "PUT", "/plain", {})
    code, resp = call(node, "POST", "/_remotestore/_restore",
                      {"indices": ["plain"]})
    assert code == 400                      # open index


def test_remote_store_incremental(node, tmp_path):
    call(node, "PUT", "/_snapshot/mirror2", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo2")}})
    call(node, "PUT", "/inc", {"settings": {
        "remote_store": {"enabled": True, "repository": "mirror2"}}})
    call(node, "PUT", "/inc/_doc/1?refresh=true", {"a": 1})
    call(node, "POST", "/inc/_flush")
    blobs = tmp_path / "repo2" / "blobs"
    n1 = len(list(blobs.iterdir()))
    # flush again with no changes: nothing new uploads
    call(node, "POST", "/inc/_flush")
    assert len(list(blobs.iterdir())) == n1


def test_gc_spares_remote_blobs_and_flush_survives_missing_repo(
        node, tmp_path):
    """Review regressions: snapshot deletion must not GC remote-store
    blobs; a vanished repository never blocks local flush."""
    call(node, "PUT", "/_snapshot/shared", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo3")}})
    call(node, "PUT", "/rsx", {"settings": {
        "remote_store": {"enabled": True, "repository": "shared"}},
        "mappings": {"properties": {"a": {"type": "long"}}}})
    call(node, "PUT", "/rsx/_doc/1?refresh=true", {"a": 1})
    call(node, "POST", "/rsx/_flush")
    # snapshot an unrelated index, then delete the snapshot: GC must
    # keep the remote-store blobs
    call(node, "PUT", "/other", {})
    call(node, "PUT", "/other/_doc/1?refresh=true", {"b": 2})
    call(node, "PUT", "/_snapshot/shared/s1", {"indices": "other"})
    call(node, "DELETE", "/_snapshot/shared/s1")
    import json as _json
    manifest = _json.loads(
        (tmp_path / "repo3" / "remote" / "rsx" / "0" /
         "manifest.json").read_text())
    for f in manifest["files"]:
        assert (tmp_path / "repo3" / "blobs" / f["blob"]).exists(), \
            f["name"]
    # repository vanishes: flush still succeeds locally
    call(node, "DELETE", "/_snapshot/shared")
    call(node, "PUT", "/rsx/_doc/2", {"a": 2})
    code, _ = call(node, "POST", "/rsx/_flush")
    assert code == 200


def test_meta_only_advances_from_latest_complete_flush(
        node, tmp_path, monkeypatch):
    """Review regressions: (a) a flush that is no longer the newest must
    not write _meta.json (stale flush beside mixed-generation manifests
    would restore under the wrong schema); (b) partial shard-upload
    failure holds meta back until a later complete flush; (c) a failing
    meta write is best-effort like the shard uploads."""
    import opensearch_tpu.index.remote_store as rs

    call(node, "PUT", "/_snapshot/m4", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo4")}})
    call(node, "PUT", "/rsm", {
        "settings": {"number_of_shards": 2,
                     "remote_store": {"enabled": True,
                                      "repository": "m4"}},
        "mappings": {"properties": {"a": {"type": "long"}}}})
    for i in range(6):
        call(node, "PUT", f"/rsm/_doc/{i}", {"a": i})
    call(node, "POST", "/rsm/_refresh")
    assert call(node, "POST", "/rsm/_flush")[0] == 200
    svc = node.indices.indices["rsm"]
    assert svc._meta_gen == svc._flush_gen

    # (b) one shard's upload fails: meta stays at the old generation
    gen_before = svc._meta_gen
    real_upload = rs.upload_shard

    def fail_shard1(repo, index, shard_id, engine, commit):
        if shard_id == 1:
            raise OSError("blob store hiccup")
        return real_upload(repo, index, shard_id, engine, commit)

    monkeypatch.setattr(rs, "upload_shard", fail_shard1)
    call(node, "PUT", "/rsm/_doc/10?refresh=true", {"a": 10})
    assert call(node, "POST", "/rsm/_flush")[0] == 200
    assert svc._meta_gen == gen_before

    # (a) a newer flush starts while this one holds the mutex (simulated
    # by bumping _flush_gen from inside the upload): no meta write
    def bump_gen(repo, index, shard_id, engine, commit):
        out = real_upload(repo, index, shard_id, engine, commit)
        svc._flush_gen += 1
        return out

    monkeypatch.setattr(rs, "upload_shard", bump_gen)
    call(node, "PUT", "/rsm/_doc/11?refresh=true", {"a": 11})
    assert call(node, "POST", "/rsm/_flush")[0] == 200
    assert svc._meta_gen == gen_before
    svc._flush_gen -= 2          # undo the simulated newer flushes

    # (c) meta write failure is best-effort: flush still returns 200
    monkeypatch.setattr(rs, "upload_shard", real_upload)
    repo_obj = node.snapshots._repo("m4")
    real_container = repo_obj.store.container

    class MetaFailing:
        def __init__(self, inner):
            self._inner = inner

        def write_blob(self, name, data):
            if name == "_meta.json":
                raise OSError("meta write refused")
            return self._inner.write_blob(name, data)

        def __getattr__(self, item):
            return getattr(self._inner, item)

    monkeypatch.setattr(repo_obj.store, "container",
                        lambda path: MetaFailing(real_container(path)))
    call(node, "PUT", "/rsm/_doc/12?refresh=true", {"a": 12})
    assert call(node, "POST", "/rsm/_flush")[0] == 200
    assert svc._meta_gen == gen_before

    # finally a clean complete flush advances meta to the latest gen
    monkeypatch.setattr(repo_obj.store, "container", real_container)
    call(node, "PUT", "/rsm/_doc/13?refresh=true", {"a": 13})
    assert call(node, "POST", "/rsm/_flush")[0] == 200
    assert svc._meta_gen == svc._flush_gen
