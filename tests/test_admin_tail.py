"""Admin-API tail: rollover, shrink/split/clone, recovery API, data
streams, reroute (VERDICT r4 item 8; ref action/admin/indices/rollover/,
shrink/, datastream/)."""

import json
import urllib.request

import pytest

from opensearch_tpu.node import Node


@pytest.fixture()
def node(tmp_path):
    n = Node(str(tmp_path / "node"), port=0,
             path_repo=[str(tmp_path)]).start()
    yield n
    n.stop()


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type":
                                          "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_rollover_write_alias(node):
    call(node, "PUT", "/logs-000001",
         {"aliases": {"logs": {"is_write_index": True}}})
    for i in range(3):
        call(node, "PUT", f"/logs/_doc/{i}?refresh=true", {"n": i})
    # unmet condition -> not rolled
    code, resp = call(node, "POST", "/logs/_rollover",
                      {"conditions": {"max_docs": 100}})
    assert code == 200 and resp["rolled_over"] is False
    assert resp["new_index"] == "logs-000002"
    # met condition -> rolled; writes flip to the new index
    code, resp = call(node, "POST", "/logs/_rollover",
                      {"conditions": {"max_docs": 3}})
    assert resp["rolled_over"] is True
    assert resp["old_index"] == "logs-000001"
    code, resp = call(node, "PUT", "/logs/_doc/new?refresh=true",
                      {"n": 9})
    assert resp["_index"] == "logs-000002"
    # the alias still searches BOTH indices
    code, resp = call(node, "POST", "/logs/_search",
                      {"query": {"match_all": {}}})
    assert resp["hits"]["total"]["value"] == 4


def test_rollover_requires_alias(node):
    call(node, "PUT", "/plain", {})
    code, resp = call(node, "POST", "/plain/_rollover", {})
    assert code == 400


@pytest.mark.parametrize("mode,src,tgt", [("shrink", 4, 2),
                                          ("split", 2, 4),
                                          ("clone", 3, 3)])
def test_resize(node, mode, src, tgt):
    call(node, "PUT", f"/src_{mode}",
         {"settings": {"number_of_shards": src}})
    for i in range(20):
        call(node, "PUT", f"/src_{mode}/_doc/{i}", {"n": i})
    call(node, "POST", f"/src_{mode}/_refresh")
    # resize requires a write block
    code, resp = call(node, "PUT",
                      f"/src_{mode}/_{mode}/dst_{mode}",
                      {"settings": {"number_of_shards": tgt}})
    assert code == 400 and "blocks.write" in resp["error"]["reason"]
    call(node, "PUT", f"/src_{mode}/_settings",
         {"index.blocks.write": True})
    code, resp = call(node, "PUT",
                      f"/src_{mode}/_{mode}/dst_{mode}",
                      {"settings": {"number_of_shards": tgt}})
    assert code == 200, resp
    code, resp = call(node, "GET", f"/dst_{mode}/_count")
    assert resp["count"] == 20
    assert resp["_shards"]["total"] == tgt
    # every doc fetches by id from the re-routed target
    code, resp = call(node, "GET", f"/dst_{mode}/_doc/7")
    assert resp["_source"] == {"n": 7}


def test_resize_invalid_factor(node):
    call(node, "PUT", "/s3", {"settings": {"number_of_shards": 3}})
    call(node, "PUT", "/s3/_settings", {"index.blocks.write": True})
    code, resp = call(node, "PUT", "/s3/_shrink/s3small",
                      {"settings": {"number_of_shards": 2}})
    assert code == 400


def test_recovery_api(node):
    call(node, "PUT", "/r1", {"settings": {"number_of_shards": 2}})
    code, resp = call(node, "GET", "/r1/_recovery")
    assert code == 200
    shards = resp["r1"]["shards"]
    assert len(shards) == 2
    assert all(s["stage"] == "DONE" for s in shards)


def test_data_stream_lifecycle(node):
    # needs a matching template with a data_stream section
    code, resp = call(node, "PUT", "/_data_stream/metrics")
    assert code == 400
    call(node, "PUT", "/_index_template/metrics_t", {
        "index_patterns": ["metrics*"], "data_stream": {}})
    code, resp = call(node, "PUT", "/_data_stream/metrics")
    assert code == 200
    # writes land in the newest backing index
    code, resp = call(node, "POST", "/metrics/_doc?refresh=true",
                      {"@timestamp": "2023-05-01T00:00:00Z", "v": 1})
    assert resp["_index"] == ".ds-metrics-000001"
    # rollover creates generation 2; writes flip
    code, resp = call(node, "POST", "/metrics/_rollover", {})
    assert resp["new_index"] == ".ds-metrics-000002"
    code, resp = call(node, "POST", "/metrics/_doc?refresh=true",
                      {"@timestamp": "2023-05-02T00:00:00Z", "v": 2})
    assert resp["_index"] == ".ds-metrics-000002"
    # search spans all generations
    code, resp = call(node, "POST", "/metrics/_search",
                      {"query": {"match_all": {}}})
    assert resp["hits"]["total"]["value"] == 2
    code, resp = call(node, "GET", "/_data_stream/metrics")
    ds = resp["data_streams"][0]
    assert ds["generation"] == 2 and len(ds["indices"]) == 2
    # delete removes backing indices
    code, resp = call(node, "DELETE", "/_data_stream/metrics")
    assert code == 200
    assert call(node, "GET", "/.ds-metrics-000001")[0] == 404


def test_reroute_validates_commands(node):
    code, _ = call(node, "POST", "/_cluster/reroute",
                   {"commands": [{"move": {"index": "x", "shard": 0}}]})
    assert code == 200
    code, _ = call(node, "POST", "/_cluster/reroute",
                   {"commands": [{"explode": {}}]})
    assert code == 400
