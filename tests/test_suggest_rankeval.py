"""Suggesters (term/phrase) and rank evaluation (ref search/suggest/,
modules/rank-eval — SURVEY's recall@10 verification harness)."""

import json
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.node import Node


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    n = Node(str(tmp_path_factory.mktemp("node")), port=0).start()
    call(n, "PUT", "/books", {"mappings": {"properties": {
        "title": {"type": "text"}}}})
    titles = ["the quick brown fox", "quickly running foxes",
              "brown bears fishing", "quantum computing basics",
              "fox hunting history"]
    for i, t in enumerate(titles):
        call(n, "PUT", f"/books/_doc/{i}", {"title": t})
    call(n, "POST", "/books/_refresh")
    yield n
    n.stop()


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


def test_term_suggester(node):
    code, resp = call(node, "POST", "/books/_search", {
        "size": 0,
        "suggest": {"fix": {"text": "quik browm",
                            "term": {"field": "title"}}}})
    assert code == 200
    sug = resp["suggest"]["fix"]
    assert len(sug) == 2
    assert sug[0]["text"] == "quik"
    assert sug[0]["options"][0]["text"] == "quick"
    assert sug[1]["options"][0]["text"] == "brown"
    assert sug[0]["options"][0]["freq"] >= 1
    # a correctly spelled term yields no options in missing mode
    code, resp = call(node, "POST", "/books/_search", {
        "size": 0, "suggest": {"s": {"text": "fox",
                                     "term": {"field": "title"}}}})
    assert resp["suggest"]["s"][0]["options"] == []


def test_phrase_suggester_with_highlight(node):
    code, resp = call(node, "POST", "/books/_search", {
        "size": 0,
        "suggest": {"fix": {"text": "quik brown fix",
                            "phrase": {"field": "title", "max_errors": 2,
                                       "highlight": {
                                           "pre_tag": "<em>",
                                           "post_tag": "</em>"}}}}})
    opts = resp["suggest"]["fix"][0]["options"]
    assert opts
    assert opts[0]["text"] == "quick brown fox"
    assert "<em>quick</em>" in opts[0]["highlighted"]
    assert "brown" in opts[0]["highlighted"]
    assert "<em>brown</em>" not in opts[0]["highlighted"]


def test_suggest_errors(node):
    code, _ = call(node, "POST", "/books/_search", {
        "suggest": {"s": {"text": "x", "term": {}}}})
    assert code == 400
    code, _ = call(node, "POST", "/books/_search", {
        "suggest": {"s": {"term": {"field": "title"}}}})
    assert code == 400


def test_rank_eval_metrics(node):
    reqs = {"requests": [
        {"id": "fox_q",
         "request": {"query": {"match": {"title": "fox"}}},
         "ratings": [
             {"_index": "books", "_id": "0", "rating": 1},
             {"_index": "books", "_id": "4", "rating": 1},
             {"_index": "books", "_id": "3", "rating": 0}]},
        {"id": "bears_q",
         "request": {"query": {"match": {"title": "bears"}}},
         "ratings": [{"_index": "books", "_id": "2", "rating": 1}]},
    ]}
    code, resp = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"precision": {"k": 2}}})
    assert code == 200
    assert resp["metric_score"] == pytest.approx(1.0)
    assert resp["details"]["fox_q"]["metric_score"] == pytest.approx(1.0)
    code, resp = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"recall": {"k": 10}}})
    assert resp["metric_score"] == pytest.approx(1.0)
    code, resp = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"mean_reciprocal_rank": {"k": 5}}})
    assert resp["metric_score"] == pytest.approx(1.0)
    code, resp = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"dcg": {"k": 5}}})
    assert resp["metric_score"] > 0.0               # raw DCG (default)
    code, resp = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"dcg": {"k": 5, "normalize": True}}})
    assert 0.0 < resp["metric_score"] <= 1.0        # nDCG
    # a failing request lands in failures; the rest still score
    code, resp = call(node, "POST", "/books/_rank_eval", {
        "requests": [
            {"id": "good", "request": {"query": {"match": {
                "title": "fox"}}},
             "ratings": [{"_index": "books", "_id": "0", "rating": 1}]},
            {"id": "broken", "request": {"query": {
                "definitely_not": {}}}, "ratings": []}],
        "metric": {"precision": {"k": 5}}})
    assert code == 200
    assert "broken" in resp["failures"]
    assert resp["details"]["good"]["metric_score"] > 0
    # unrated docs surface for triage
    code, resp = call(node, "POST", "/books/_rank_eval", {
        "requests": [{"id": "q", "request": {
            "query": {"match": {"title": "quick"}}},
            "ratings": []}],
        "metric": {"precision": {"k": 5}}})
    assert resp["details"]["q"]["unrated_docs"]
    code, _ = call(node, "POST", "/books/_rank_eval", {
        "requests": [], "metric": {"precision": {}}})
    assert code == 400
    code, _ = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"made_up": {}}})
    assert code == 400


def test_completion_suggester():
    """completion field + prefix suggest vs a plain oracle
    (CompletionSuggester / CompletionFieldMapper analog)."""
    from opensearch_tpu.index.segment import SegmentWriter
    from opensearch_tpu.mapping.mapper import DocumentMapper
    from opensearch_tpu.search.executor import ShardSearcher

    mapper = DocumentMapper({"properties": {
        "sug": {"type": "completion"}, "title": {"type": "keyword"}}})
    w = SegmentWriter()
    docs = [
        ("1", {"sug": {"input": ["trial", "trying"], "weight": 10},
               "title": "a"}),
        ("2", {"sug": {"input": ["tried"], "weight": 5}, "title": "b"}),
        ("3", {"sug": "trick", "title": "c"}),
        ("4", {"sug": {"input": ["other"], "weight": 99}, "title": "d"}),
    ]
    segs = []
    for si in range(2):
        parsed = [mapper.parse(i, s) for i, s in docs[si::2]]
        segs.append(w.build(parsed, f"s{si}"))
    s = ShardSearcher(segs, mapper)
    resp = s.search({"suggest": {
        "c": {"prefix": "tri", "completion": {"field": "sug"}}}})
    entry = resp["suggest"]["c"][0]
    assert entry["text"] == "tri" and entry["length"] == 3
    opts = entry["options"]
    # weight-desc, prefix-only ("trying" starts with "try", not "tri"):
    # trial (10) > tried (5) > trick (1)
    assert [o["text"] for o in opts] == ["trial", "tried", "trick"]
    assert opts[0]["_score"] == 10.0 and opts[0]["_id"] == "1"
    # skip_duplicates collapses per-doc
    resp = s.search({"suggest": {
        "c": {"prefix": "tri", "completion": {
            "field": "sug", "skip_duplicates": True}}}})
    opts = resp["suggest"]["c"][0]["options"]
    assert [o["_id"] for o in opts] == ["1", "2", "3"]
    # size truncation
    resp = s.search({"suggest": {
        "c": {"prefix": "tri", "completion": {"field": "sug",
                                              "size": 2}}}})
    assert len(resp["suggest"]["c"][0]["options"]) == 2


def test_completion_per_input_weights_and_persistence(tmp_path):
    """Each input keeps ITS OWN weight (not the doc max), and weights
    survive the segment save/load round trip."""
    from opensearch_tpu.index.segment import SegmentWriter
    from opensearch_tpu.index.store import load_segment, save_segment
    from opensearch_tpu.mapping.mapper import DocumentMapper
    from opensearch_tpu.search.executor import ShardSearcher

    mapper = DocumentMapper({"properties": {"sug": {"type": "completion"}}})
    parsed = [
        mapper.parse("1", {"sug": [{"input": ["apple"], "weight": 100},
                                   {"input": ["apricot"], "weight": 1}]}),
        mapper.parse("2", {"sug": {"input": ["applause"], "weight": 50}}),
    ]
    seg = SegmentWriter().build(parsed, "sw")
    save_segment(seg, str(tmp_path))
    seg2 = load_segment(str(tmp_path), "sw")
    for s in (seg, seg2):
        searcher = ShardSearcher([s], mapper)
        resp = searcher.search({"suggest": {
            "c": {"prefix": "ap", "completion": {"field": "sug"}}}})
        opts = resp["suggest"]["c"][0]["options"]
        # apricot must rank by ITS weight (1), below applause (50)
        assert [(o["text"], o["_score"]) for o in opts] == [
            ("apple", 100.0), ("applause", 50.0), ("apricot", 1.0)]


def test_completion_merge_and_zero_weight():
    """Cross-shard completion merge keeps weight order (_score vs score
    key mismatch regression) and an explicit weight 0 round-trips."""
    from opensearch_tpu.index.segment import SegmentWriter
    from opensearch_tpu.mapping.mapper import DocumentMapper
    from opensearch_tpu.search.executor import ShardSearcher
    from opensearch_tpu.search.suggest import merge_suggest

    mapper = DocumentMapper({"properties": {"sug": {"type": "completion"}}})
    w = SegmentWriter()
    s1 = ShardSearcher([w.build([mapper.parse(
        "1", {"sug": {"input": ["trial"], "weight": 10}})], "a")], mapper)
    s2 = ShardSearcher([w.build([
        mapper.parse("2", {"sug": {"input": ["tried"], "weight": 5}}),
        mapper.parse("3", {"sug": {"input": ["trill"], "weight": 0}}),
    ], "b")], mapper)
    body = {"suggest": {"c": {"prefix": "tri",
                              "completion": {"field": "sug"}}}}
    merged = merge_suggest([s1.search(body)["suggest"],
                            s2.search(body)["suggest"]])
    opts = merged["c"][0]["options"]
    assert [(o["text"], o["_score"]) for o in opts] == [
        ("trial", 10.0), ("tried", 5.0), ("trill", 0.0)]
