"""Suggesters (term/phrase) and rank evaluation (ref search/suggest/,
modules/rank-eval — SURVEY's recall@10 verification harness)."""

import json
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.node import Node


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    n = Node(str(tmp_path_factory.mktemp("node")), port=0).start()
    call(n, "PUT", "/books", {"mappings": {"properties": {
        "title": {"type": "text"}}}})
    titles = ["the quick brown fox", "quickly running foxes",
              "brown bears fishing", "quantum computing basics",
              "fox hunting history"]
    for i, t in enumerate(titles):
        call(n, "PUT", f"/books/_doc/{i}", {"title": t})
    call(n, "POST", "/books/_refresh")
    yield n
    n.stop()


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


def test_term_suggester(node):
    code, resp = call(node, "POST", "/books/_search", {
        "size": 0,
        "suggest": {"fix": {"text": "quik browm",
                            "term": {"field": "title"}}}})
    assert code == 200
    sug = resp["suggest"]["fix"]
    assert len(sug) == 2
    assert sug[0]["text"] == "quik"
    assert sug[0]["options"][0]["text"] == "quick"
    assert sug[1]["options"][0]["text"] == "brown"
    assert sug[0]["options"][0]["freq"] >= 1
    # a correctly spelled term yields no options in missing mode
    code, resp = call(node, "POST", "/books/_search", {
        "size": 0, "suggest": {"s": {"text": "fox",
                                     "term": {"field": "title"}}}})
    assert resp["suggest"]["s"][0]["options"] == []


def test_phrase_suggester_with_highlight(node):
    code, resp = call(node, "POST", "/books/_search", {
        "size": 0,
        "suggest": {"fix": {"text": "quik brown fix",
                            "phrase": {"field": "title", "max_errors": 2,
                                       "highlight": {
                                           "pre_tag": "<em>",
                                           "post_tag": "</em>"}}}}})
    opts = resp["suggest"]["fix"][0]["options"]
    assert opts
    assert opts[0]["text"] == "quick brown fox"
    assert "<em>quick</em>" in opts[0]["highlighted"]
    assert "brown" in opts[0]["highlighted"]
    assert "<em>brown</em>" not in opts[0]["highlighted"]


def test_suggest_errors(node):
    code, _ = call(node, "POST", "/books/_search", {
        "suggest": {"s": {"text": "x", "term": {}}}})
    assert code == 400
    code, _ = call(node, "POST", "/books/_search", {
        "suggest": {"s": {"term": {"field": "title"}}}})
    assert code == 400


def test_rank_eval_metrics(node):
    reqs = {"requests": [
        {"id": "fox_q",
         "request": {"query": {"match": {"title": "fox"}}},
         "ratings": [
             {"_index": "books", "_id": "0", "rating": 1},
             {"_index": "books", "_id": "4", "rating": 1},
             {"_index": "books", "_id": "3", "rating": 0}]},
        {"id": "bears_q",
         "request": {"query": {"match": {"title": "bears"}}},
         "ratings": [{"_index": "books", "_id": "2", "rating": 1}]},
    ]}
    code, resp = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"precision": {"k": 2}}})
    assert code == 200
    assert resp["metric_score"] == pytest.approx(1.0)
    assert resp["details"]["fox_q"]["metric_score"] == pytest.approx(1.0)
    code, resp = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"recall": {"k": 10}}})
    assert resp["metric_score"] == pytest.approx(1.0)
    code, resp = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"mean_reciprocal_rank": {"k": 5}}})
    assert resp["metric_score"] == pytest.approx(1.0)
    code, resp = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"dcg": {"k": 5}}})
    assert resp["metric_score"] > 0.0               # raw DCG (default)
    code, resp = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"dcg": {"k": 5, "normalize": True}}})
    assert 0.0 < resp["metric_score"] <= 1.0        # nDCG
    # a failing request lands in failures; the rest still score
    code, resp = call(node, "POST", "/books/_rank_eval", {
        "requests": [
            {"id": "good", "request": {"query": {"match": {
                "title": "fox"}}},
             "ratings": [{"_index": "books", "_id": "0", "rating": 1}]},
            {"id": "broken", "request": {"query": {
                "definitely_not": {}}}, "ratings": []}],
        "metric": {"precision": {"k": 5}}})
    assert code == 200
    assert "broken" in resp["failures"]
    assert resp["details"]["good"]["metric_score"] > 0
    # unrated docs surface for triage
    code, resp = call(node, "POST", "/books/_rank_eval", {
        "requests": [{"id": "q", "request": {
            "query": {"match": {"title": "quick"}}},
            "ratings": []}],
        "metric": {"precision": {"k": 5}}})
    assert resp["details"]["q"]["unrated_docs"]
    code, _ = call(node, "POST", "/books/_rank_eval", {
        "requests": [], "metric": {"precision": {}}})
    assert code == 400
    code, _ = call(node, "POST", "/books/_rank_eval", {
        **reqs, "metric": {"made_up": {}}})
    assert code == 400
