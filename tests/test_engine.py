"""Engine lifecycle: index→refresh→search→delete cycles, realtime GET,
versioning/optimistic concurrency, translog crash recovery, flush/commit,
force-merge (InternalEngine + Translog analogs, ref
index/engine/InternalEngine.java:845, index/translog/Translog.java:541)."""

import json
import os

import pytest

from opensearch_tpu.common.errors import VersionConflictError
from opensearch_tpu.index.engine import InternalEngine
from opensearch_tpu.mapping.mapper import DocumentMapper

MAPPING = {"properties": {
    "title": {"type": "text"},
    "n": {"type": "long"},
    "tag": {"type": "keyword"},
}}


def new_engine(path, durability="request"):
    return InternalEngine(str(path), DocumentMapper(MAPPING),
                          index_name="idx", durability=durability)


def search_ids(engine, query=None):
    s = engine.acquire_searcher()
    resp = s.search({"query": query or {"match_all": {}}, "size": 100})
    return sorted(h["_id"] for h in resp["hits"]["hits"])


def test_index_refresh_search_cycle(tmp_path):
    eng = new_engine(tmp_path)
    r = eng.index("1", {"title": "hello world", "n": 1})
    assert (r.result, r.version, r.seq_no) == ("created", 1, 0)
    # NRT semantics: invisible to search before refresh, visible to GET
    assert search_ids(eng) == []
    assert eng.get("1")["_source"]["title"] == "hello world"
    assert eng.get("1", realtime=False) is None
    eng.refresh()
    assert search_ids(eng) == ["1"]
    assert eng.get("1", realtime=False)["found"]
    eng.close()


def test_update_and_delete_cycle(tmp_path):
    eng = new_engine(tmp_path)
    eng.index("1", {"title": "old text", "n": 1})
    eng.refresh()
    r = eng.index("1", {"title": "new text", "n": 2})
    assert (r.result, r.version) == ("updated", 2)
    # pre-refresh: search still sees the old doc, GET sees the new one
    assert search_ids(eng, {"match": {"title": "old"}}) == ["1"]
    assert eng.get("1")["_source"]["title"] == "new text"
    eng.refresh()
    assert search_ids(eng, {"match": {"title": "old"}}) == []
    assert search_ids(eng, {"match": {"title": "new"}}) == ["1"]

    r = eng.delete("1")
    assert (r.result, r.version) == ("deleted", 3)
    assert eng.get("1") is None
    assert search_ids(eng) == ["1"]     # unrefreshed delete still visible
    eng.refresh()
    assert search_ids(eng) == []
    assert eng.delete("1").result == "not_found"
    assert eng.doc_count() == 0
    eng.close()


def test_versioning_conflicts(tmp_path):
    eng = new_engine(tmp_path)
    r = eng.index("1", {"n": 1})
    with pytest.raises(VersionConflictError):
        eng.index("1", {"n": 2}, if_seq_no=99, if_primary_term=1)
    r2 = eng.index("1", {"n": 2}, if_seq_no=r.seq_no, if_primary_term=1)
    assert r2.version == 2
    with pytest.raises(VersionConflictError):
        eng.index("1", {"n": 3}, version=1)       # internal: must match current
    # external versioning: must strictly increase
    eng.index("2", {"n": 1}, version=10, version_type="external")
    with pytest.raises(VersionConflictError):
        eng.index("2", {"n": 2}, version=10, version_type="external")
    r3 = eng.index("2", {"n": 2}, version=20, version_type="external")
    assert r3.version == 20
    with pytest.raises(VersionConflictError):
        eng.delete("2", if_seq_no=0, if_primary_term=1)
    eng.close()


def test_kill9_recovery_from_translog(tmp_path):
    eng = new_engine(tmp_path)
    for i in range(20):
        eng.index(str(i), {"title": f"doc number {i}", "n": i})
    eng.delete("5")
    eng.index("7", {"title": "updated doc", "n": 700})
    eng.ensure_synced()
    # kill -9: drop the engine without close/flush
    del eng

    eng2 = new_engine(tmp_path)
    assert eng2.doc_count() == 19
    assert eng2.get("5") is None
    assert eng2.get("7")["_source"]["n"] == 700
    assert eng2.get("7")["_version"] == 2
    assert eng2.max_seq_no == 21
    eng2.refresh()
    assert len(search_ids(eng2)) == 19
    # new writes continue from the recovered seq_no
    r = eng2.index("new", {"n": 1})
    assert r.seq_no == 22
    eng2.close()


def test_torn_translog_tail_discarded(tmp_path):
    eng = new_engine(tmp_path)
    eng.index("1", {"n": 1})
    eng.index("2", {"n": 2})
    eng.ensure_synced()
    gen = eng.translog.generation
    del eng
    # simulate a torn final write (kill -9 mid-append)
    log = tmp_path / "translog" / f"translog-{gen}.log"
    with open(log, "ab") as f:
        f.write(b'deadbeef{"op":"index","id":"3"')   # no newline, bad crc
    eng2 = new_engine(tmp_path)
    assert eng2.doc_count() == 2
    assert eng2.get("3") is None
    eng2.close()


def test_flush_commit_and_reopen(tmp_path):
    eng = new_engine(tmp_path)
    for i in range(10):
        eng.index(str(i), {"title": "flushed doc", "n": i})
    commit = eng.flush()
    assert commit["max_seq_no"] == 9
    assert len(commit["segments"]) == 1
    # translog trimmed: no ops to replay
    assert eng.translog.ops_count() == 0
    eng.index("10", {"title": "post flush", "n": 10})
    eng.ensure_synced()
    del eng

    eng2 = new_engine(tmp_path)
    assert eng2.doc_count() == 11            # 10 from segments + 1 replayed
    eng2.refresh()
    assert len(search_ids(eng2)) == 11
    eng2.close()


def test_delete_survives_flush_cycle(tmp_path):
    eng = new_engine(tmp_path)
    eng.index("a", {"n": 1})
    eng.index("b", {"n": 2})
    eng.flush()
    eng.delete("a")
    eng.flush()                               # persists the live bitmap
    del eng
    eng2 = new_engine(tmp_path)
    assert eng2.doc_count() == 1
    assert eng2.get("a") is None
    assert eng2.get("b")["found"]
    eng2.close()


def test_force_merge(tmp_path):
    eng = new_engine(tmp_path)
    for i in range(30):
        eng.index(str(i), {"title": f"merge doc {i}", "n": i, "tag": "t"})
        if i % 10 == 9:
            eng.refresh()
    eng.delete("3")
    eng.refresh()
    assert len(eng.segments) == 3
    before = search_ids(eng, {"term": {"tag": "t"}})
    n = eng.force_merge(1)
    assert n == 1
    after = search_ids(eng, {"term": {"tag": "t"}})
    assert before == after
    assert eng.doc_count() == 29
    eng.close()


def test_merge_cleans_persisted_files(tmp_path):
    eng = new_engine(tmp_path)
    for i in range(10):
        eng.index(str(i), {"n": i})
        if i % 5 == 4:
            eng.flush()
    assert len(os.listdir(tmp_path / "segments")) > 3
    eng.force_merge(1)
    eng.flush()
    del eng
    eng2 = new_engine(tmp_path)
    assert eng2.doc_count() == 10
    eng2.close()


def test_force_merge_crash_before_flush_keeps_data(tmp_path):
    """Merged-away segment files must survive until the NEXT commit —
    a crash right after force_merge recovers the pre-merge state."""
    eng = new_engine(tmp_path)
    for i in range(10):
        eng.index(str(i), {"n": i})
    eng.flush()
    eng.force_merge(1)
    del eng                                   # crash: no flush after merge
    eng2 = new_engine(tmp_path)
    assert eng2.doc_count() == 10
    eng2.refresh()
    assert len(search_ids(eng2)) == 10
    eng2.flush()                              # now the old files may go
    eng2.close()


def test_torn_tail_truncated_before_reopen_append(tmp_path):
    """A torn tail must be truncated at open, or the next append merges
    with the garbage and an acked op is lost on the following recovery."""
    eng = new_engine(tmp_path)
    eng.index("1", {"n": 1})
    eng.ensure_synced()
    gen = eng.translog.generation
    del eng
    log = tmp_path / "translog" / f"translog-{gen}.log"
    with open(log, "ab") as f:
        f.write(b'deadbeef{"op":"index","id":"torn"')
    eng2 = new_engine(tmp_path)
    eng2.index("2", {"n": 2})                 # appended after truncation
    eng2.ensure_synced()
    del eng2
    eng3 = new_engine(tmp_path)
    assert eng3.doc_count() == 2
    assert eng3.get("2")["found"]
    eng3.close()


def test_searcher_is_point_in_time(tmp_path):
    """An acquired searcher must not see deletes applied by a later
    refresh (Lucene reader snapshot semantics)."""
    eng = new_engine(tmp_path)
    for i in range(5):
        eng.index(str(i), {"n": i})
    eng.refresh()
    old = eng.acquire_searcher()
    assert len(old.search({"size": 10})["hits"]["hits"]) == 5
    eng.delete("2")
    eng.refresh()
    # old snapshot unchanged; new searcher sees the delete
    assert len(old.search({"size": 10})["hits"]["hits"]) == 5
    new = eng.acquire_searcher()
    assert len(new.search({"size": 10})["hits"]["hits"]) == 4
    eng.close()


def test_sequence_numbers_monotonic(tmp_path):
    eng = new_engine(tmp_path)
    seqs = [eng.index(str(i), {"n": i}).seq_no for i in range(5)]
    seqs.append(eng.delete("0").seq_no)
    assert seqs == list(range(6))
    assert eng.stats()["seq_no"]["max_seq_no"] == 5
    eng.close()


def test_mid_file_translog_corruption_raises(tmp_path):
    """Corruption BEFORE valid, fsynced records must raise at open — never
    silently truncate acked ops (reference: TranslogCorruptedException)."""
    import pytest

    from opensearch_tpu.index.translog import TranslogCorruptedError

    eng = new_engine(tmp_path)
    eng.index("1", {"n": 1})
    eng.index("2", {"n": 2})
    eng.ensure_synced()
    gen = eng.translog.generation
    del eng
    log = tmp_path / "translog" / f"translog-{gen}.log"
    data = log.read_bytes()
    lines = data.split(b"\n")
    assert len(lines) >= 3          # two records + trailing empty
    # flip a byte inside the FIRST record's payload: corruption followed
    # by a valid record is mid-file, not a torn tail
    first = bytearray(lines[0])
    first[-1] ^= 0xFF
    lines[0] = bytes(first)
    log.write_bytes(b"\n".join(lines))
    with pytest.raises(TranslogCorruptedError):
        new_engine(tmp_path)


def test_delete_tombstones_pruned_on_flush(tmp_path):
    """Delete tombstones must not outlive the commit that made the
    deletes durable (GC-deletes analog) or delete-heavy workloads grow
    the version map without bound."""
    eng = new_engine(tmp_path)
    for i in range(20):
        eng.index(str(i), {"n": i})
    for i in range(15):
        eng.delete(str(i))
    eng.refresh()
    tombstones = sum(1 for v in eng._version_map.values() if v.deleted)
    assert tombstones == 15         # retained until the flush commit
    eng.flush()
    tombstones = sum(1 for v in eng._version_map.values() if v.deleted)
    assert tombstones == 0
    # deleted docs stay deleted after the prune + reopen
    assert eng.get("3") is None or eng.get("3").get("found") is False
    eng.close()
    eng2 = new_engine(tmp_path)
    eng2.refresh()
    assert len(search_ids(eng2)) == 5
    eng2.close()


def test_unacked_garbage_then_valid_record_truncated(tmp_path):
    """Out-of-order page writeback can persist a later UNACKED op but not
    an earlier one.  Corruption at/past the fsync high-water mark is
    unacked garbage — truncate it (and any unacked valid ops after it),
    never raise."""
    import zlib

    from opensearch_tpu.index.translog import Translog

    tl = Translog(str(tmp_path / "tl"))
    tl.add({"op": "index", "id": "1", "seq_no": 0})
    tl.sync()                               # high-water mark: op 1 acked
    path = tl._gen_path(tl.generation)
    tl._file.close()
    # simulate: two unacked appends, the first lost to a torn page, the
    # second (with a VALID crc) persisted
    payload = b'{"op":"index","id":"3","seq_no":2}'
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    with open(path, "ab") as f:
        f.write(b"deadbeefGARBAGE\n")
        f.write(f"{crc:08x}".encode() + payload + b"\n")
    tl2 = Translog(str(tmp_path / "tl"))    # must truncate, not raise
    ops = list(tl2.read_ops())
    assert [o["id"] for o in ops] == ["1"]
    tl2.close()


def test_replica_op_stale_primary_term_fenced(tmp_path):
    """Ops from a deposed primary (lower term) must be rejected — the
    operation-permit/primary-term fencing analog."""
    import pytest

    from opensearch_tpu.common.errors import VersionConflictError

    eng = new_engine(tmp_path)
    eng.apply_replica_op({"op": "index", "id": "a", "source": {"n": 1},
                          "routing": None, "seq_no": 0, "version": 1,
                          "primary_term": 2})
    with pytest.raises(VersionConflictError):
        eng.apply_replica_op({"op": "index", "id": "b", "source": {"n": 2},
                              "routing": None, "seq_no": 1, "version": 1,
                              "primary_term": 1})
    # realtime GET from the replica op buffer
    doc = eng.get("a")
    assert doc["found"] and doc["_source"] == {"n": 1}
    # promotion replays the buffered op into the indexing path
    eng.promote_to_primary(term=3)
    eng.refresh()
    assert len(search_ids(eng)) == 1
    assert eng.primary_term == 3
    eng.close()


def test_corrupt_last_acked_record_raises(tmp_path):
    """Even with NO valid record after it, corruption below the fsync
    high-water mark is acked-data loss and must raise, not truncate."""
    import pytest

    from opensearch_tpu.index.translog import (Translog,
                                               TranslogCorruptedError)

    tl = Translog(str(tmp_path / "tl"))
    tl.add({"op": "index", "id": "1", "seq_no": 0})
    tl.sync()
    path = tl._gen_path(tl.generation)
    tl._file.close()
    data = bytearray(open(path, "rb").read())
    data[10] ^= 0xFF                       # corrupt the acked record
    open(path, "wb").write(bytes(data))
    with pytest.raises(TranslogCorruptedError):
        Translog(str(tmp_path / "tl"))


def test_retention_leases_pin_translog_and_serve_ops(tmp_path):
    """A lease keeps op history through flush so ops_since() can serve a
    partitioned replica; removing it lets the translog trim again
    (ref index/seqno/RetentionLease.java, VERDICT r4 item 9)."""
    from opensearch_tpu.index.engine import InternalEngine
    from opensearch_tpu.mapping.mapper import DocumentMapper

    mapper = DocumentMapper({"properties": {"n": {"type": "long"}}})
    e = InternalEngine(str(tmp_path / "sh"), mapper)
    for i in range(5):
        e.index(f"d{i}", {"n": i})
    e.add_retention_lease("replica-1", 2)
    e.flush()                        # leases pin history past the commit
    ops = e.ops_since(2)
    assert [op["seq_no"] for op in ops] == [3, 4]
    assert all(op["op"] == "index" for op in ops)
    # no lease + flush -> history trimmed -> ops-based recovery refused
    e.remove_retention_lease("replica-1")
    e.index("d9", {"n": 9})
    e.flush()
    assert e.ops_since(2) is None
    e.close()
