"""Nested field type + nested query: per-object matching semantics (the
whole point — cross-object combinations must NOT match; ref
index/mapper/ nested objects + join/ToParentBlockJoinQuery)."""

import numpy as np
import pytest

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher

MAPPING = {"properties": {
    "title": {"type": "text"},
    "comments": {"type": "nested", "properties": {
        "author": {"type": "keyword"},
        "stars": {"type": "integer"},
        "text": {"type": "text"},
        "at": {"type": "date"},
    }},
}}

DOCS = [
    {"title": "post one", "comments": [
        {"author": "alice", "stars": 5, "text": "great work",
         "at": "2024-01-01T00:00:00Z"},
        {"author": "bob", "stars": 1, "text": "terrible mess",
         "at": "2024-02-01T00:00:00Z"},
    ]},
    {"title": "post two", "comments": [
        {"author": "alice", "stars": 1, "text": "not my thing",
         "at": "2024-03-01T00:00:00Z"},
        {"author": "bob", "stars": 5, "text": "great stuff",
         "at": "2024-04-01T00:00:00Z"},
    ]},
    {"title": "post three", "comments": [
        {"author": "carol", "stars": 3, "text": "average"},
    ]},
    {"title": "post four no comments"},
]


@pytest.fixture(scope="module")
def searcher():
    mapper = DocumentMapper(MAPPING)
    writer = SegmentWriter()
    half = 2
    segs = [writer.build([mapper.parse(str(i), d)
                          for i, d in enumerate(DOCS[:half])], "n0"),
            writer.build([mapper.parse(str(half + i), d)
                          for i, d in enumerate(DOCS[half:])], "n1")]
    return ShardSearcher(segs, mapper)


def ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


def test_same_object_semantics(searcher):
    """THE nested property: alice AND stars=5 must hold within ONE
    comment.  Doc0 has (alice,5); doc1 has alice(1) and bob(5) — a
    flattened index would wrongly match doc1."""
    q = {"nested": {"path": "comments", "query": {"bool": {"must": [
        {"term": {"comments.author": "alice"}},
        {"term": {"comments.stars": 5}}]}}}}
    resp = searcher.search({"query": q, "size": 10})
    assert ids(resp) == ["0"]


def test_nested_single_condition_and_ranges(searcher):
    resp = searcher.search({"query": {"nested": {
        "path": "comments",
        "query": {"term": {"comments.author": "alice"}}}}, "size": 10})
    assert ids(resp) == ["0", "1"]
    resp = searcher.search({"query": {"nested": {
        "path": "comments",
        "query": {"range": {"comments.stars": {"gte": 4}}}}},
        "size": 10})
    assert ids(resp) == ["0", "1"]
    # range + author in the same object again
    resp = searcher.search({"query": {"nested": {
        "path": "comments", "query": {"bool": {"must": [
            {"term": {"comments.author": "bob"}},
            {"range": {"comments.stars": {"lte": 2}}}]}}}},
        "size": 10})
    assert ids(resp) == ["0"]
    # date range inside the object
    resp = searcher.search({"query": {"nested": {
        "path": "comments", "query": {"range": {"comments.at": {
            "gte": "2024-03-15T00:00:00Z"}}}}}, "size": 10})
    assert ids(resp) == ["1"]


def test_nested_text_match_and_exists(searcher):
    resp = searcher.search({"query": {"nested": {
        "path": "comments",
        "query": {"match": {"comments.text": "great"}}}}, "size": 10})
    assert ids(resp) == ["0", "1"]
    # match + author must co-occur in one object
    resp = searcher.search({"query": {"nested": {
        "path": "comments", "query": {"bool": {"must": [
            {"match": {"comments.text": "great"}},
            {"term": {"comments.author": "alice"}}]}}}}, "size": 10})
    assert ids(resp) == ["0"]
    resp = searcher.search({"query": {"nested": {
        "path": "comments",
        "query": {"exists": {"field": "comments.at"}}}}, "size": 10})
    assert ids(resp) == ["0", "1"]          # carol's comment has no date


def test_nested_composition_with_outer_query(searcher):
    resp = searcher.search({"query": {"bool": {
        "must": [{"match": {"title": "post"}}],
        "filter": [{"nested": {"path": "comments", "query": {
            "term": {"comments.author": "carol"}}}}]}}, "size": 10})
    assert ids(resp) == ["2"]
    # must_not nested: docs with NO terrible comment
    resp = searcher.search({"query": {"bool": {
        "must": [{"match": {"title": "post"}}],
        "must_not": [{"nested": {"path": "comments", "query": {
            "match": {"comments.text": "terrible"}}}}]}}, "size": 10})
    assert ids(resp) == ["1", "2", "3"]


def test_nested_errors_and_unmapped(searcher):
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"nested": {
            "path": "title", "query": {"match_all": {}}}}})
    resp = searcher.search({"query": {"nested": {
        "path": "nope", "ignore_unmapped": True,
        "query": {"match_all": {}}}}, "size": 10})
    assert resp["hits"]["total"]["value"] == 0
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"nested": {
            "path": "comments",
            "query": {"wildcard": {"comments.author": "a*"}}}}})


def test_nested_survives_persistence(tmp_path):
    """Flush -> reopen: nested blocks round-trip through the store."""
    from opensearch_tpu.index.engine import InternalEngine

    mapper = DocumentMapper(MAPPING)
    eng = InternalEngine(str(tmp_path / "nst"), mapper, index_name="nst")
    for i, d in enumerate(DOCS):
        eng.index(str(i), d)
    eng.refresh()
    eng.flush()
    eng.close()
    eng2 = InternalEngine(str(tmp_path / "nst"), mapper,
                          index_name="nst")
    s = eng2.acquire_searcher()
    resp = s.search({"query": {"nested": {"path": "comments",
                                          "query": {"bool": {"must": [
                                              {"term": {"comments.author":
                                                        "alice"}},
                                              {"term": {"comments.stars":
                                                        5}}]}}}},
                     "size": 10})
    assert sorted(h["_id"] for h in resp["hits"]["hits"]) == ["0"]


def test_nested_should_optional_with_must(searcher):
    """should beside must is OPTIONAL (round-4 review finding)."""
    resp = searcher.search({"query": {"nested": {
        "path": "comments", "query": {"bool": {
            "must": [{"term": {"comments.author": "alice"}}],
            "should": [{"term": {"comments.stars": 5}}]}}}},
        "size": 10})
    assert ids(resp) == ["0", "1"]          # both alice comments
    # explicit minimum_should_match=1 makes it required again
    resp = searcher.search({"query": {"nested": {
        "path": "comments", "query": {"bool": {
            "must": [{"term": {"comments.author": "alice"}}],
            "should": [{"term": {"comments.stars": 5}}],
            "minimum_should_match": 1}}}}, "size": 10})
    assert ids(resp) == ["0"]


def test_nested_date_match_parses(searcher):
    resp = searcher.search({"query": {"nested": {
        "path": "comments",
        "query": {"match": {"comments.at": "2024-02-01T00:00:00Z"}}}},
        "size": 10})
    assert ids(resp) == ["0"]
