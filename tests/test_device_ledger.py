"""Device-resident memory & transfer observability (PR 11).

Covers the residency ledger (``common/device_ledger.py``): accounting
parity with the actually staged arrays, LRU-dispatch budget eviction
with byte-identical host-fallback results, the `_nodes/stats` ``device``
section / `_cat/segments` footprint columns / `/_metrics` gauges, the
version-tolerant compile registry, the insights transfer attribution,
the bench ``device`` phase, the client additions, and the
``tools/check_device_staging.py`` tier-1 lint.
"""

import gc
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from opensearch_tpu.common.device_ledger import (GroupCloser,
                                                 KernelCompileRegistry,
                                                 device_ledger,
                                                 host_footprint,
                                                 kernel_registry)
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.node import Node
from opensearch_tpu.ops import bm25 as bm25_ops
from opensearch_tpu.search.executor import ShardSearcher

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


@pytest.fixture(autouse=True)
def _clean_ledger():
    """The ledger is process-global (like breakers/metrics): reset it
    and the host-scoring override around every test."""
    led = device_ledger()
    led.reset()
    prev = bm25_ops.HOST_SCORING
    yield
    bm25_ops.HOST_SCORING = prev
    led.reset()


MAPPING = {"properties": {"t": {"type": "text"},
                          "k": {"type": "keyword"},
                          "n": {"type": "long"}}}


def _mapper():
    return DocumentMapper(MAPPING)


def _segment(mapper, docs, seg_id, base=0):
    parsed = [mapper.parse(str(base + i),
                           {"t": t, "k": f"g{i % 2}", "n": base + i})
              for i, t in enumerate(docs)]
    return SegmentWriter().build(parsed, seg_id)


def _searcher(n_segs=2):
    mapper = _mapper()
    texts = [["alpha beta", "beta gamma", "alpha alpha gamma"],
             ["beta beta delta", "alpha gamma", "gamma delta"],
             ["alpha delta", "beta", "alpha beta gamma delta"]]
    segs = [_segment(mapper, texts[i % len(texts)], f"s{i}", base=i * 3)
            for i in range(n_segs)]
    return ShardSearcher(segs, mapper, index_name="ledgerix")


# -- accounting parity ------------------------------------------------------

def _staged_nbytes(dseg):
    """Walk the ACTUAL staged arrays of one DeviceSegment."""
    total = 0
    for fam in (dseg.postings, dseg.numeric, dseg.ordinal, dseg.vector,
                dseg.geo):
        for arrs in fam.values():
            total += sum(int(v.nbytes) for k, v in arrs.items()
                         if k != "n_ords")
    for _live_np, staged in dseg._live_cache.values():
        total += int(staged.nbytes)
    return total


def test_ledger_matches_staged_nbytes_exactly():
    s = _searcher(n_segs=2)
    led = device_ledger()
    for seg in s.segments:
        dseg = seg.device()
        assert led.device_footprint(seg) == _staged_nbytes(dseg)
    assert led.resident_bytes() == sum(
        _staged_nbytes(seg.device()) for seg in s.segments)


def test_ledger_tracks_lazy_impacts_and_live_snapshots():
    s = _searcher(n_segs=1)
    seg = s.segments[0]
    dseg = seg.device()
    led = device_ledger()
    before = led.device_footprint(seg)
    imp = dseg.impacts("t", 2.0)
    assert led.device_footprint(seg) == before + int(imp.nbytes)
    # a deletes-invalidated live bitmap stages a NEW snapshot entry
    seg.apply_deletes([0])
    live2 = dseg.live_jnp(seg.live)
    assert led.device_footprint(seg) == (
        before + int(imp.nbytes) + int(live2.nbytes))
    assert led.device_footprint(seg) == _staged_nbytes(dseg) + int(
        imp.nbytes)


def test_refresh_away_releases_ledger_groups():
    s = _searcher(n_segs=2)
    for seg in s.segments:
        seg.device()
    led = device_ledger()
    assert led.resident_bytes() > 0
    assert led.stats()["resident_segments"] == 2
    for seg in s.segments:
        seg._device = None
    del s
    gc.collect()
    assert led.stats()["resident_segments"] == 0
    assert led.resident_bytes() == 0


def test_host_footprint_is_the_single_size_source():
    s = _searcher(n_segs=1)
    seg = s.segments[0]
    total = host_footprint(seg)
    per = host_footprint(seg, per_field=True)
    assert total == sum(per.values()) > 0
    # every host array family is covered (postings + the doc values)
    assert ("postings", "t") in per and ("ordinal", "k") in per \
        and ("numeric", "n") in per
    # the DeviceSegment breaker estimate derives from the same number
    assert seg.device()._breaker_bytes == total * 2


# -- budget eviction --------------------------------------------------------

def test_budget_eviction_is_byte_identical_via_host_fallback():
    bm25_ops.HOST_SCORING = False          # force the device kernels
    s = _searcher(n_segs=2)
    led = device_ledger()
    body = {"query": {"match": {"t": "alpha beta"}}, "size": 5}
    r1 = s.search(body)
    assert led.resident_bytes() > 0
    led.set_budget(1)                       # far below the footprint
    st = led.stats()["budget"]
    assert st["evictions"] == 2 and st["evicted_bytes"] > 0
    assert all(seg._device is None and seg._device_evicted
               for seg in s.segments)
    r2 = s.search(body)                     # host impact-table fallback
    assert json.dumps(r1["hits"], sort_keys=True) == \
        json.dumps(r2["hits"], sort_keys=True)
    assert led.stats()["budget"]["host_fallbacks"] == 2
    # the fallback did NOT restage anything
    assert led.stats()["budget"]["restages"] == 0


def test_budget_eviction_releases_breaker_charge():
    from opensearch_tpu.common.breakers import breaker_service
    bm25_ops.HOST_SCORING = False
    s = _searcher(n_segs=1)
    breaker = breaker_service().fielddata
    used0 = breaker.used
    dseg = s.segments[0].device()
    charged = dseg._breaker_bytes
    assert charged > 0 and breaker.used >= used0 + charged
    used_staged = breaker.used
    device_ledger().set_budget(1)
    # eviction released the staging charge exactly once (the GC
    # finalizer on the dead DeviceSegment must not double-release)
    assert breaker.used == used_staged - charged
    del dseg
    gc.collect()
    assert breaker.used == used_staged - charged


def test_eviction_order_is_least_recently_dispatched():
    bm25_ops.HOST_SCORING = False
    s = _searcher(n_segs=2)
    led = device_ledger()
    for seg in s.segments:
        seg.device()
    g0 = s.segments[0].device()._ledger_group
    g1 = s.segments[1].device()._ledger_group
    led.record_dispatch(g0)
    led.record_dispatch(g1)
    led.record_dispatch(g0)                 # seg0 dispatched most recently
    budget = led.resident_bytes() - 1       # must evict exactly one
    led.set_budget(budget)
    assert s.segments[1]._device is None    # LRU-dispatch victim
    assert s.segments[0]._device is not None


def test_restage_counted_when_no_host_fallback_exists():
    bm25_ops.HOST_SCORING = False
    s = _searcher(n_segs=1)
    led = device_ledger()
    body = {"query": {"match": {"t": "alpha"}}, "size": 2,
            "aggs": {"m": {"max": {"field": "n"}}}}
    r1 = s.search(body)
    led.set_budget(1)                       # evict; aggs path must restage
    r2 = s.search(body)
    assert json.dumps(r1["aggregations"]) == json.dumps(
        r2["aggregations"])
    assert json.dumps(r1["hits"], sort_keys=True) == \
        json.dumps(r2["hits"], sort_keys=True)
    assert led.stats()["budget"]["restages"] >= 1


def test_msearch_batched_path_survives_budget():
    bm25_ops.HOST_SCORING = False
    s = _searcher(n_segs=2)
    bodies = [{"query": {"match": {"t": "alpha"}}, "size": 3},
              {"query": {"match": {"t": "beta"}}, "size": 3}]
    r1 = s.msearch(bodies)
    device_ledger().set_budget(1)
    r2 = s.msearch(bodies)
    assert json.dumps([r["hits"] for r in r1], sort_keys=True) == \
        json.dumps([r["hits"] for r in r2], sort_keys=True)


def test_transfer_counters_split_stage_and_fetch():
    bm25_ops.HOST_SCORING = False
    s = _searcher(n_segs=1)
    led = device_ledger()
    s.search({"query": {"match": {"t": "alpha"}}, "size": 3})
    t = led.stats()["transfers"]
    assert t["stage"]["bytes"] > 0 and t["stage"]["ops"] > 0
    assert t["fetch"]["bytes"] > 0 and t["fetch"]["ops"] > 0
    snap = led.transfer_snapshot()
    assert snap == (t["stage"]["bytes"], t["fetch"]["bytes"])


# -- compile registry -------------------------------------------------------

def test_compile_registry_counts_query_kernels():
    bm25_ops.HOST_SCORING = False
    s = _searcher(n_segs=1)
    s.search({"query": {"match": {"t": "alpha"}}, "size": 3})
    counts = kernel_registry().counts()
    assert counts["kernels"].get("plan.run_topk", 0) >= 1
    assert counts["total"] >= 1
    assert counts["unavailable"] == 0


def test_compile_registry_unavailable_fallback():
    reg = KernelCompileRegistry()
    reg._defaults_loaded = True             # isolate from the real kernels

    def plain_fn():
        pass

    class Broken:
        def _cache_size(self):
            raise RuntimeError("moved in this jax")

    reg.register("no_introspection", plain_fn)
    reg.register("raises", Broken())

    def good():
        pass
    good._cache_size = lambda: 3
    reg.register("good", good)
    counts = reg.counts()
    assert counts["unavailable"] == 2       # counted, never raising
    assert counts["kernels"] == {"good": 3}
    assert counts["total"] == 3


def test_profiler_xla_compiles_survives_missing_introspection(
        monkeypatch):
    from opensearch_tpu.search import profile as profile_mod
    broken = KernelCompileRegistry()
    broken._defaults_loaded = True          # zero kernels registered
    monkeypatch.setattr(
        "opensearch_tpu.common.device_ledger._registry", broken)
    assert profile_mod.xla_program_count() == 0
    prof = profile_mod.QueryProfiler()
    section = prof.shard_section("ix", 0, plan_type="T",
                                 description="d", total_segments=0)
    assert section["engine"]["xla_compiles"] == 0


# -- insights attribution ---------------------------------------------------

def test_insights_rollups_carry_transfer_bytes():
    from opensearch_tpu.search import insights as insights_mod
    from opensearch_tpu.search.insights import QueryInsightsService
    bm25_ops.HOST_SCORING = False
    s = _searcher(n_segs=1)
    svc = QueryInsightsService(node_id="t")
    body = {"query": {"match": {"t": "alpha"}}, "size": 3}
    with insights_mod.collecting() as sink:
        s.search(body)
    for rec in sink:
        assert rec.get("transfer_bytes", 0) > 0   # first run stages
        svc.record(rec)
    sig = insights_mod.signature_hash(
        insights_mod.canonical_query(body["query"]), True)
    roll = svc.section()["signatures"][sig]
    assert roll["device_transfer_bytes"] > 0


# -- REST surfaces ----------------------------------------------------------

@pytest.fixture()
def node(tmp_path):
    n = Node(str(tmp_path / "node"), port=0)
    yield n
    n.stop()


def call(node, method, path, body=None, params=None, ndjson=None):
    if ndjson is not None:
        raw = ("\n".join(json.dumps(l) for l in ndjson) + "\n").encode()
        ctype = "application/x-ndjson"
    else:
        raw = json.dumps(body).encode() if body is not None else None
        ctype = "application/json"
    return node.rest.dispatch(method, path, params or {}, raw, ctype,
                              headers={})


def _seed(node, index="devix", docs=12):
    s, r = call(node, "PUT", f"/{index}", {"mappings": MAPPING})
    assert s == 200, r
    lines = []
    for i in range(docs):
        lines.append({"index": {"_index": index, "_id": str(i)}})
        lines.append({"t": f"alpha w{i % 3}", "k": f"g{i % 2}", "n": i})
    s, r = call(node, "POST", "/_bulk", params={"refresh": "true"},
                ndjson=lines)
    assert s == 200 and not r["errors"], r


def test_nodes_stats_device_section_and_budget_setting(node):
    bm25_ops.HOST_SCORING = False
    _seed(node)
    body = {"query": {"match": {"t": "alpha"}}, "size": 5}
    s, r1 = call(node, "POST", "/devix/_search", body)
    assert s == 200
    s, stats = call(node, "GET", "/_nodes/stats")
    dev = stats["nodes"][node.node_id]["device"]
    assert dev["resident_bytes"] > 0
    assert dev["resident_segments"] >= 1
    assert dev["indices"]["devix"]["bytes"] > 0
    assert dev["indices"]["devix"]["dispatches"] >= 1
    assert dev["transfers"]["stage"]["bytes"] > 0
    assert dev["transfers"]["fetch"]["bytes"] > 0
    assert dev["compile_registry"]["total"] >= 1
    assert "backend" in dev
    # dynamic budget below the footprint -> counted eviction, and the
    # SAME query answers byte-identically off the host tables
    s, _ = call(node, "PUT", "/_cluster/settings", {
        "transient": {"device.memory.budget_bytes": 1}})
    assert s == 200
    s, r2 = call(node, "POST", "/devix/_search", body)
    assert s == 200
    assert json.dumps(r1["hits"], sort_keys=True) == \
        json.dumps(r2["hits"], sort_keys=True)
    s, stats = call(node, "GET", "/_nodes/stats")
    dev = stats["nodes"][node.node_id]["device"]
    assert dev["budget"]["budget_bytes"] == 1
    assert dev["budget"]["evictions"] >= 1
    assert dev["budget"]["host_fallbacks"] >= 1
    s, _ = call(node, "PUT", "/_cluster/settings", {
        "transient": {"device.memory.budget_bytes": None}})
    assert s == 200
    assert device_ledger().budget_bytes is None


def test_cat_segments_footprint_columns(node):
    bm25_ops.HOST_SCORING = False
    _seed(node)
    s, _ = call(node, "POST", "/devix/_search",
                {"query": {"match": {"t": "alpha"}}, "size": 3})
    assert s == 200
    s, rows = call(node, "GET", "/_cat/segments",
                   params={"format": "json"})
    assert s == 200 and rows
    row = next(r for r in rows if r["index"] == "devix")
    assert int(row["size"]) > 0              # host footprint
    assert int(row["size.device"]) > 0       # staged footprint
    # budget eviction empties the device column, host stays
    device_ledger().set_budget(1)
    s, rows = call(node, "GET", "/_cat/segments",
                   params={"format": "json"})
    row = next(r for r in rows if r["index"] == "devix")
    assert int(row["size"]) > 0 and int(row["size.device"]) == 0


def test_cat_fielddata_uses_host_footprint(node):
    _seed(node)
    s, rows = call(node, "GET", "/_cat/fielddata",
                   params={"format": "json"})
    assert s == 200
    krow = next(r for r in rows if r["field"] == "k")
    seg = next(iter(
        node.indices.indices["devix"].local_shards.values())).segments[0]
    per = host_footprint(seg, per_field=True)
    assert int(krow["size"]) == per[("ordinal", "k")]


def test_metrics_exposition_has_device_series(node):
    bm25_ops.HOST_SCORING = False
    _seed(node)
    s, _ = call(node, "POST", "/devix/_search",
                {"query": {"match": {"t": "alpha"}}, "size": 3})
    assert s == 200
    s, payload = call(node, "GET", "/_metrics")
    text = payload.text if hasattr(payload, "text") else str(payload)
    assert "opensearch_tpu_device_resident_bytes " in text
    assert "opensearch_tpu_device_budget_bytes 0" in text
    assert 'opensearch_tpu_device_index_resident_bytes{index="devix"}' \
        in text
    # ledger counters flow through the MetricsRegistry exposition
    assert "device_transfer_stage_bytes_total" in text
    assert "device_transfer_fetch_bytes_total" in text


# -- bench phase ------------------------------------------------------------

def test_bench_device_phase_reports_nonzero_line():
    sys.path.insert(0, os.path.dirname(TOOLS))
    try:
        import bench
    finally:
        sys.path.pop(0)
    s = _searcher(n_segs=2)
    queries = [{"query": {"match": {"t": t}}, "size": 5}
               for t in ("alpha", "beta", "alpha beta", "gamma")]
    data = bench.run_device_phase(s, queries, seq_n=4, platform="cpu")
    assert data["resident_bytes"] > 0
    assert data["transfer_stage_bytes"] > 0
    assert data["transfer_fetch_bytes"] > 0
    assert data["evictions"] >= 1
    assert data["budget_bytes"] < data["resident_bytes"]
    assert data["qps_unconstrained"] > 0
    assert data["qps_budget_constrained"] > 0
    # the phase restores global state
    assert device_ledger().budget_bytes is None
    assert bm25_ops.HOST_SCORING is None


# -- client -----------------------------------------------------------------

def test_client_cat_segments_and_device_stats(tmp_path):
    from opensearch_tpu.client import OpenSearch
    bm25_ops.HOST_SCORING = False
    node = Node(str(tmp_path / "cnode"), port=0).start()
    try:
        client = OpenSearch(hosts=[{"host": "127.0.0.1",
                                    "port": node.port}])
        client.indices.create("cix", {"mappings": MAPPING})
        for i in range(6):
            client.index("cix", {"t": f"alpha w{i}", "n": i}, id=str(i))
        client.indices.refresh("cix")
        client.search(index="cix",
                      body={"query": {"match": {"t": "alpha"}}})
        rows = client.cat.segments()
        row = next(r for r in rows if r["index"] == "cix")
        assert int(row["size"]) > 0 and int(row["size.device"]) > 0
        dev = client.nodes.device()
        assert dev[node.node_id]["resident_bytes"] > 0
        assert dev[node.node_id]["transfers"]["stage"]["bytes"] > 0
    finally:
        node.stop()


# -- GroupCloser ------------------------------------------------------------

def test_group_closer_releases_entries_on_cache_drop():
    led = device_ledger()
    group = led.open_group(index="ix", shard=0, segment="batchy")
    led.stage(group, np.zeros(16, np.float32), kind="batch_group",
              name="x")
    led.seal(group)
    assert led.resident_bytes() == 64
    holder = {"_ledger": GroupCloser(led, group)}
    del group
    del holder
    gc.collect()
    assert led.resident_bytes() == 0


# -- tools/check_device_staging.py lint -------------------------------------

def test_check_device_staging_lint_passes():
    r = subprocess.run(
        [sys.executable,
         os.path.join(TOOLS, "check_device_staging.py")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_check_device_staging_lint_catches_violations(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\nimport jax.numpy as jnp\n"
        "x = jnp.asarray([1, 2, 3])\n"
        "y = jax.device_put(x)\n"
        "ok = jnp.asarray([1])  # staging-ok: test annotation\n"
        "# staging-ok: above-line annotation\n"
        "ok2 = jnp.asarray([2])\n")
    r = subprocess.run(
        [sys.executable,
         os.path.join(TOOLS, "check_device_staging.py"),
         str(tmp_path)],
        capture_output=True, text=True)
    assert r.returncode == 1
    assert "bad.py:3" in r.stdout and "bad.py:4" in r.stdout
    assert "bad.py:5" not in r.stdout and "bad.py:7" not in r.stdout
