"""Tiered caching subsystem: the weighted-LRU primitive
(common/cache.py), the shard request cache (indices/request_cache.py)
end-to-end over REST and in cluster mode, and the ad-hoc-cache lint.

Acceptance bar (ISSUE 3): a repeated identical ``_search`` with
``request_cache=true`` is served from IndicesRequestCache (hit counter
increments, response byte-identical), a refresh+write invalidates it
(miss, fresh results), and ``_nodes/stats`` + ``POST
/<index>/_cache/clear`` report/reset the stats.
"""

import gc
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.common.breakers import CircuitBreakerService
from opensearch_tpu.common.cache import (EVICTED, EXPIRED, EXPLICIT,
                                         REPLACED, Cache, attached_cache,
                                         estimate_weight)
from opensearch_tpu.indices.request_cache import request_cache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- common/cache.py: the weighted-LRU primitive ---------------------------

def test_cache_hit_miss_and_stats():
    c = Cache("t.basic")
    assert c.get("k") is None
    c.put("k", "v")
    assert c.get("k") == "v"
    s = c.stats()
    assert s["hit_count"] == 1 and s["miss_count"] == 1
    assert s["entries"] == 1 and s["memory_size_in_bytes"] > 0


def test_cache_lru_eviction_by_weight():
    c = Cache("t.lru", max_weight=30, weigher=lambda k, v: 10)
    for k in ("a", "b", "c"):
        c.put(k, k)
    c.get("a")                       # a becomes most-recent
    c.put("d", "d")                  # evicts b (LRU)
    assert c.get("b") is None
    assert c.get("a") == "a" and c.get("c") == "c" and c.get("d") == "d"
    assert c.stats()["evictions"] == 1
    assert c.weight <= 30


def test_cache_oversized_entry_rejected():
    c = Cache("t.oversize", max_weight=10, weigher=lambda k, v: 100)
    assert c.put("k", "v") is False
    assert len(c) == 0 and c.stats()["rejections"] == 1


def test_cache_ttl_expiry_with_injected_clock():
    now = [0.0]
    c = Cache("t.ttl", ttl_s=5.0, clock=lambda: now[0])
    c.put("k", "v")
    assert c.get("k") == "v"
    now[0] = 5.1
    assert c.get("k") is None        # expired counts as a miss
    assert len(c) == 0


def test_cache_removal_listener_reasons():
    seen = []
    c = Cache("t.listener", max_weight=20, weigher=lambda k, v: 10,
              removal_listener=lambda k, v, r: seen.append((k, r)))
    c.put("a", 1)
    c.put("a", 2)                    # REPLACED
    c.put("b", 1)
    c.put("c", 1)                    # evicts a
    c.invalidate("b")                # EXPLICIT
    assert ("a", REPLACED) in seen
    assert ("a", EVICTED) in seen
    assert ("b", EXPLICIT) in seen


def test_cache_ttl_expired_reason():
    now = [0.0]
    seen = []
    c = Cache("t.ttl2", ttl_s=1.0, clock=lambda: now[0],
              removal_listener=lambda k, v, r: seen.append(r))
    c.put("k", "v")
    now[0] = 2.0
    c.get("k")
    assert seen == [EXPIRED]


def test_cache_get_or_load():
    calls = []
    c = Cache("t.load")

    def loader():
        calls.append(1)
        return 42
    assert c.get_or_load("k", loader) == 42
    assert c.get_or_load("k", loader) == 42
    assert len(calls) == 1


def test_cache_breaker_accounting_eviction_and_release():
    svc = CircuitBreakerService({"breaker.request.limit": 100,
                                 "breaker.total.limit": 1000})
    c = Cache("t.breaker", weigher=lambda k, v: 40, breaker=svc.request)
    c.put("a", 1)
    c.put("b", 1)
    assert svc.request.used == 80
    # a third 40b entry would trip the 100b breaker: the cache sheds its
    # own LRU tail instead of failing
    assert c.put("c", 1) is True
    assert svc.request.used == 80 and len(c) == 2
    assert c.get("a") is None        # a was the LRU victim
    c.invalidate_all()
    assert svc.request.used == 0     # reservations fully released


def test_cache_breaker_full_from_elsewhere_skips_caching():
    svc = CircuitBreakerService({"breaker.request.limit": 100,
                                 "breaker.total.limit": 1000})
    svc.request.add_estimate(90, "other-component")
    c = Cache("t.breaker2", weigher=lambda k, v: 40, breaker=svc.request)
    assert c.put("a", 1) is False    # not ours to evict; don't cache
    assert svc.request.used == 90
    svc.request.release(90)


def test_attached_cache_reuses_and_releases_on_owner_death():
    class Owner:
        pass
    svc = CircuitBreakerService({"breaker.request.limit": 1000,
                                 "breaker.total.limit": 2000})
    o = Owner()
    c1 = attached_cache(o, "_x_cache", name="t.attached",
                        weigher=lambda k, v: 50, breaker=svc.request)
    c2 = attached_cache(o, "_x_cache", name="t.attached")
    assert c1 is c2
    c1.put("k", "v")
    assert svc.request.used == 50
    del o, c1, c2
    gc.collect()
    assert svc.request.used == 0     # finalizer released the accounting


def test_estimate_weight_shapes():
    import numpy as np
    assert estimate_weight(b"abcd") == 4
    assert estimate_weight(np.zeros(10, np.int64)) == 80
    assert estimate_weight({"a": 1}) > 8
    assert estimate_weight(None) == 8


def test_cache_invalidate_if_and_resize():
    c = Cache("t.inv", weigher=lambda k, v: 10)
    for i in range(6):
        c.put(i, i)
    assert c.invalidate_if(lambda k, v: k % 2 == 0) == 3
    assert len(c) == 3
    c.set_max_weight(10)             # dynamic shrink evicts immediately
    assert len(c) == 1


# -- REST end-to-end -------------------------------------------------------

@pytest.fixture(scope="module")
def node(tmp_path_factory):
    from opensearch_tpu.node import Node
    n = Node(str(tmp_path_factory.mktemp("rcnode")), port=0).start()
    yield n
    n.stop()


def call(node, method, path, body=None, raw=False):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, (payload if raw else json.loads(payload))
    return 200, (payload if raw else
                 json.loads(payload) if payload else {})


@pytest.fixture(scope="module")
def books(node):
    call(node, "PUT", "/rcbooks", {
        "settings": {"number_of_shards": 1},
        "mappings": {"properties": {"t": {"type": "text"},
                                    "n": {"type": "long"}}}})
    for i in range(8):
        call(node, "PUT", f"/rcbooks/_doc/{i}",
             {"t": f"caching is fast {i}", "n": i})
    call(node, "POST", "/rcbooks/_refresh")
    return "rcbooks"


def _node_rc_stats(node):
    _, body = call(node, "GET", "/_nodes/stats")
    nid = next(iter(body["nodes"]))
    return body["nodes"][nid]["indices"]["request_cache"]


def test_request_cache_hit_is_byte_identical(node, books):
    before = _node_rc_stats(node)
    q = {"query": {"match": {"t": "caching"}}, "size": 5}
    s1, raw1 = call(node, "POST",
                    f"/{books}/_search?request_cache=true", q, raw=True)
    s2, raw2 = call(node, "POST",
                    f"/{books}/_search?request_cache=true", q, raw=True)
    assert s1 == 200 and s2 == 200
    assert raw1 == raw2              # byte-identical, took included
    after = _node_rc_stats(node)
    assert after["hit_count"] == before["hit_count"] + 1
    assert after["miss_count"] == before["miss_count"] + 1
    assert after["memory_size_in_bytes"] > 0


def test_refresh_and_write_invalidate(node, books):
    q = {"query": {"match": {"t": "caching"}}, "size": 20}
    _, r1 = call(node, "POST",
                 f"/{books}/_search?request_cache=true", q)
    before = _node_rc_stats(node)
    call(node, "PUT", f"/{books}/_doc/new1",
         {"t": "caching brand new", "n": 100})
    call(node, "POST", f"/{books}/_refresh")
    _, r2 = call(node, "POST",
                 f"/{books}/_search?request_cache=true", q)
    after = _node_rc_stats(node)
    assert after["miss_count"] == before["miss_count"] + 1   # no stale hit
    assert r2["hits"]["total"]["value"] == \
        r1["hits"]["total"]["value"] + 1                      # fresh data


def test_request_cache_param_must_be_boolean(node, books):
    status, body = call(node, "POST",
                        f"/{books}/_search?request_cache=banana",
                        {"query": {"match_all": {}}})
    assert status == 400
    assert "request_cache" in json.dumps(body)


def test_request_cache_false_and_scroll_rejection(node, books):
    before = _node_rc_stats(node)
    q = {"query": {"term": {"n": 3}}, "size": 0}
    # explicit false wins over the default size=0 caching
    call(node, "POST", f"/{books}/_search?request_cache=false", q)
    call(node, "POST", f"/{books}/_search?request_cache=false", q)
    after = _node_rc_stats(node)
    assert after["hit_count"] == before["hit_count"]
    assert after["miss_count"] == before["miss_count"]
    status, _ = call(
        node, "POST",
        f"/{books}/_search?scroll=1m&request_cache=true",
        {"query": {"match_all": {}}})
    assert status == 400


def test_default_caches_only_size0(node, books):
    before = _node_rc_stats(node)
    q = {"query": {"match": {"t": "fast"}}, "size": 3}
    call(node, "POST", f"/{books}/_search", q)
    call(node, "POST", f"/{books}/_search", q)
    mid = _node_rc_stats(node)
    assert mid["hit_count"] == before["hit_count"]      # size>0: no cache
    q0 = {"query": {"match": {"t": "fast"}}, "size": 0}
    call(node, "POST", f"/{books}/_search", q0)
    call(node, "POST", f"/{books}/_search", q0)
    after = _node_rc_stats(node)
    assert after["hit_count"] == mid["hit_count"] + 1   # size=0: cached


def test_index_setting_disables_default_caching(node):
    call(node, "PUT", "/rcoff", {
        "settings": {"number_of_shards": 1,
                     "index": {"requests": {"cache": {"enable": False}}}},
        "mappings": {"properties": {"t": {"type": "text"}}}})
    call(node, "PUT", "/rcoff/_doc/1", {"t": "hello"})
    call(node, "POST", "/rcoff/_refresh")
    before = _node_rc_stats(node)
    q = {"query": {"match_all": {}}, "size": 0}
    call(node, "POST", "/rcoff/_search", q)
    call(node, "POST", "/rcoff/_search", q)
    mid = _node_rc_stats(node)
    assert mid["hit_count"] == before["hit_count"]      # setting: off
    # the explicit request-level param overrides the index setting
    call(node, "POST", "/rcoff/_search?request_cache=true", q)
    call(node, "POST", "/rcoff/_search?request_cache=true", q)
    after = _node_rc_stats(node)
    assert after["hit_count"] == mid["hit_count"] + 1


def test_eviction_under_cache_size_setting(node, books):
    _, r = call(node, "PUT", "/_cluster/settings",
                {"transient": {"indices.requests.cache.size": 2048}})
    assert r["acknowledged"]
    try:
        for i in range(12):
            call(node, "POST",
                 f"/{books}/_search?request_cache=true",
                 {"query": {"term": {"n": i}}, "size": 2})
        stats = _node_rc_stats(node)
        assert stats["memory_size_in_bytes"] <= 2048
        assert stats["evictions"] > 0
    finally:
        call(node, "PUT", "/_cluster/settings",
             {"transient": {"indices.requests.cache.size": None}})


def test_cache_clear_endpoint_resets(node, books):
    q = {"query": {"match": {"t": "caching"}}, "size": 4}
    call(node, "POST", f"/{books}/_search?request_cache=true", q)
    call(node, "POST", f"/{books}/_search?request_cache=true", q)
    _, st = call(node, "GET", f"/{books}/_stats")
    rc = st["indices"][books]["primaries"]["request_cache"]
    assert rc["entries"] > 0 and rc["memory_size_in_bytes"] > 0
    assert rc["hit_count"] > 0
    # ?request=false leaves the request cache alone
    status, _ = call(node, "POST",
                     f"/{books}/_cache/clear?request=false")
    assert status == 200
    _, st = call(node, "GET", f"/{books}/_stats")
    assert st["indices"][books]["primaries"]["request_cache"][
        "entries"] == rc["entries"]
    status, body = call(node, "POST",
                        f"/{books}/_cache/clear?request=true")
    assert status == 200 and body["_shards"]["failed"] == 0
    _, st = call(node, "GET", f"/{books}/_stats")
    rc2 = st["indices"][books]["primaries"]["request_cache"]
    assert rc2["entries"] == 0 and rc2["memory_size_in_bytes"] == 0
    assert rc2["hit_count"] == 0     # counters reset with the entries


# -- cluster mode: the data-node cache behind the scatter-gather -----------

def wait_until(pred, timeout=8.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def cluster(tmp_path):
    from opensearch_tpu.cluster.node import ClusterNode
    from opensearch_tpu.transport.service import (LocalTransport,
                                                  TransportService)
    hub = LocalTransport.Hub()
    ids = ["n0", "n1", "n2"]
    nodes = {}
    for nid in ids:
        svc = TransportService(nid, LocalTransport(hub))
        nodes[nid] = ClusterNode(nid, str(tmp_path / nid), svc, ids)
    assert nodes["n0"].start_election()
    wait_until(lambda: all(
        nodes[i].coordinator.state().master_node == "n0" for i in ids))
    yield hub, ids, nodes
    for n in nodes.values():
        n.stop()


def test_cluster_mode_hit_counted_on_data_node(cluster):
    """A remote coordinator's repeated query phase is served from the
    DATA node's request cache: the hit counter increments and the shard
    does NOT re-execute (search.queries execution counter is flat)."""
    from opensearch_tpu.common.telemetry import metrics
    hub, ids, nodes = cluster
    nodes["n0"].create_index("rc", {
        "settings": {"number_of_shards": 1, "number_of_replicas": 0},
        "mappings": {"properties": {"v": {"type": "long"}}}})
    wait_until(lambda: all(
        "rc" in nodes[i].coordinator.state().indices for i in ids))
    primary = nodes["n0"].coordinator.state().routing["rc"][0]["primary"]
    coord = next(i for i in ids if i != primary)
    wait_until(lambda: "rc" in nodes[primary].indices)
    for i in range(10):
        nodes[coord].index_doc("rc", str(i), {"v": i})
    nodes[coord].refresh("rc")

    body = {"query": {"range": {"v": {"gte": 2}}}, "size": 5,
            "request_cache": True}
    before = request_cache().stats()
    r1 = nodes[coord].search("rc", dict(body))
    mid = request_cache().stats()
    assert mid["miss_count"] == before["miss_count"] + 1
    executed = metrics().counter("search.queries").value
    r2 = nodes[coord].search("rc", dict(body))
    after = request_cache().stats()
    assert after["hit_count"] == mid["hit_count"] + 1
    # the cached hit avoided a full shard re-execution on the data node
    assert metrics().counter("search.queries").value == executed
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2,
                                                        sort_keys=True)


def test_cluster_failover_recomputes_then_caches(cluster):
    """Fault-injection: dropping the primary's query-phase RPC fails the
    request over to the in-sync replica, whose OWN cache takes the miss
    and serves the follow-up hit — cached results never cross copies."""
    from opensearch_tpu.cluster.node import A_SEARCH_SHARDS
    from opensearch_tpu.cluster.state import copies_of
    from opensearch_tpu.testing.fault_injection import FaultInjector
    hub, ids, nodes = cluster
    nodes["n0"].create_index("ha", {
        "settings": {"number_of_shards": 1, "number_of_replicas": 1},
        "mappings": {"properties": {"v": {"type": "long"}}}})

    def in_sync_full():
        routing = nodes["n0"].coordinator.state().routing.get("ha", [])
        return routing and all(
            set(e["in_sync"]) == {e["primary"], *e["replicas"]}
            and len(e["replicas"]) >= 1 for e in routing)
    assert wait_until(in_sync_full)
    for i in range(12):
        nodes["n0"].index_doc("ha", str(i), {"v": i})
    nodes["n0"].refresh("ha")

    entry = nodes["n0"].coordinator.state().routing["ha"][0]
    primary = entry["primary"]
    coord = next(i for i in ids if i not in copies_of(entry))

    body = {"query": {"match_all": {}}, "size": 20,
            "request_cache": True}
    r1 = nodes[coord].search("ha", dict(body))     # primes the PRIMARY
    assert r1["hits"]["total"]["value"] == 12

    stats_before = request_cache().stats()
    FaultInjector(hub, seed=7).drop(A_SEARCH_SHARDS, target=primary,
                                    times=1)
    r2 = nodes[coord].search("ha", dict(body))     # replica recomputes
    assert r2["hits"]["total"]["value"] == 12
    assert r2["_shards"]["failed"] == 0            # failover, not failure
    stats_mid = request_cache().stats()
    assert stats_mid["miss_count"] == stats_before["miss_count"] + 1

    r3 = nodes[coord].search("ha", dict(body))     # now a hit (primary)
    stats_after = request_cache().stats()
    assert stats_after["hit_count"] == stats_mid["hit_count"] + 1
    assert json.dumps(r2["hits"], sort_keys=True) == \
        json.dumps(r3["hits"], sort_keys=True)


# -- tools/check_ad_hoc_caches.py lint -------------------------------------

def test_check_ad_hoc_caches_lint_passes():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "check_ad_hoc_caches.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_check_ad_hoc_caches_lint_catches_violations(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "class X:\n"
        "    def f(self):\n"
        "        self._term_cache = {}\n"          # attribute dict
        "GLOBAL_RESULT_CACHE = dict()\n"           # module-level ctor
        "class Y:\n"
        "    def g(self):\n"
        "        # bounded-cache: one entry per shard\n"
        "        self._ok_cache = {}\n")            # annotated: allowed
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "check_ad_hoc_caches.py"),
         str(tmp_path)],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "bad.py:3" in proc.stdout
    assert "GLOBAL_RESULT_CACHE" in proc.stdout
    assert "_ok_cache" not in proc.stdout
