"""Persistent tasks: durable background jobs resumed after restart (ref
persistent/PersistentTasksService.java:47); reindex integration via
wait_for_completion=false."""

import json
import time
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.common.errors import (IllegalArgumentError,
                                          ResourceNotFoundError)
from opensearch_tpu.common.persistent_tasks import PersistentTasksService
from opensearch_tpu.node import Node


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


def test_service_lifecycle(tmp_path):
    svc = PersistentTasksService(str(tmp_path))
    runs = []
    svc.register_executor("echo", lambda p: (runs.append(p)
                                             or {"ok": p["v"]}))
    with pytest.raises(IllegalArgumentError):
        svc.submit("unknown", {})
    tid = svc.submit("echo", {"v": 7})
    done = svc.wait(tid)
    assert done["state"] == "completed" and done["result"] == {"ok": 7}
    assert runs == [{"v": 7}]
    # failures are recorded, not raised
    svc.register_executor("boom", lambda p: 1 / 0)
    t2 = svc.submit("boom", {})
    assert "ZeroDivisionError" in svc.wait(t2)["error"]
    with pytest.raises(ResourceNotFoundError):
        svc.get("nope")


def test_incomplete_task_resumes_after_restart(tmp_path):
    svc = PersistentTasksService(str(tmp_path))
    svc.register_executor("noop", lambda p: {})
    # simulate a crash: record a started task without running it
    svc._tasks["dead1"] = {"action": "noop", "params": {"x": 1},
                           "state": "started"}
    svc._persist()
    # 'restart': a fresh service over the same path re-executes it
    svc2 = PersistentTasksService(str(tmp_path))
    runs = []
    svc2.register_executor("noop", lambda p: runs.append(p) or {"r": 1})
    assert svc2.resume_incomplete() == ["dead1"]
    assert svc2.wait("dead1")["state"] == "completed"
    assert runs == [{"x": 1}]


def test_reindex_as_persistent_task(tmp_path):
    node = Node(str(tmp_path / "node"), port=0).start()
    try:
        call(node, "PUT", "/src", {})
        for i in range(10):
            call(node, "PUT", f"/src/_doc/{i}", {"n": i})
        call(node, "POST", "/src/_refresh")
        code, body = call(node, "POST",
                          "/_reindex?wait_for_completion=false",
                          {"source": {"index": "src"},
                           "dest": {"index": "dst"}})
        assert code == 200 and "task" in body
        tid = body["task"]
        node.persistent_tasks.wait(tid)
        code, status = call(node, "GET", f"/_tasks/{tid}")
        assert code == 200 and status["completed"] is True
        assert status["response"]["total"] == 10
        call(node, "POST", "/dst/_refresh")
        assert call(node, "GET", "/dst/_count")[1]["count"] == 10
        code, listing = call(node, "GET", "/_persistent_tasks")
        assert any(t["id"] == tid and t["state"] == "completed"
                   for t in listing["tasks"])
    finally:
        node.stop()


def test_unfinished_reindex_resumes_at_boot(tmp_path):
    node = Node(str(tmp_path / "node"), port=0).start()
    call(node, "PUT", "/src", {})
    for i in range(5):
        call(node, "PUT", f"/src/_doc/{i}", {"n": i})
    call(node, "POST", "/src/_refresh")
    call(node, "POST", "/src/_flush")
    # crash mid-task: durable record exists, work never ran
    node.persistent_tasks._tasks["t-crash"] = {
        "action": "indices:data/write/reindex",
        "params": {"source": {"index": "src"},
                   "dest": {"index": "dst"}},
        "state": "started"}
    node.persistent_tasks._persist()
    node.stop()
    node2 = Node(str(tmp_path / "node"), port=0).start()
    try:
        node2.persistent_tasks.wait("t-crash")
        deadline = time.time() + 10
        while time.time() < deadline:
            code, body = call(node2, "GET", "/_tasks/t-crash")
            if body.get("completed"):
                break
            time.sleep(0.2)
        assert body["completed"] is True, body
        call(node2, "POST", "/dst/_refresh")
        assert call(node2, "GET", "/dst/_count")[1]["count"] == 5
    finally:
        node2.stop()


def test_async_reindex_validates_at_submit(tmp_path):
    """Review regressions: malformed async bodies must 400 at submit
    (reproduced live pre-fix: {} returned 200 + a persisted failed
    task); terminal records are bounded."""
    node = Node(str(tmp_path / "node"), port=0).start()
    try:
        code, body = call(node, "POST",
                          "/_reindex?wait_for_completion=false", {})
        assert code == 400, body
        call(node, "PUT", "/self", {})
        code, _ = call(node, "POST",
                       "/_reindex?wait_for_completion=false",
                       {"source": {"index": "self"},
                        "dest": {"index": "self"}})
        assert code == 400
        assert call(node, "GET",
                    "/_persistent_tasks")[1]["tasks"] == []
    finally:
        node.stop()


def test_terminal_tasks_are_bounded(tmp_path):
    svc = PersistentTasksService(str(tmp_path))
    svc.register_executor("noop", lambda p: {})
    ids = [svc.submit("noop", {"i": i}) for i in range(10)]
    for tid in ids:
        svc.wait(tid)
    svc.MAX_TERMINAL = 3
    tid = svc.submit("noop", {})
    svc.wait(tid)
    terminal = [t for t in svc.list() if t["state"] != "started"]
    assert len(terminal) <= 4          # 3 kept + the one just finished
