"""Chaos-soak harness (PR 7; ROADMAP item 5): seeded mixed workload +
fault schedule + SLO verdicts over a 3-node ClusterNode cluster — the
regression gate that turns the robustness spine (PRs 2/4/6) into a
recorded bench trajectory.  Plus the PR's satellites: single-search
replica spill, the unified shed/admission budget, and the seeded-RNG
lint."""

import importlib.util
import json
import subprocess
import sys

import pytest

from opensearch_tpu.cluster import response_collector as rc
from opensearch_tpu.cluster.node import ClusterNode
from opensearch_tpu.cluster.state import copies_of
from opensearch_tpu.common.telemetry import metrics
from opensearch_tpu.node import Node
from opensearch_tpu.testing.workload import (FaultSchedule, MixedWorkload,
                                             SoakConfig, SoakRunner,
                                             run_soak, zipf_query_log)
from opensearch_tpu.transport.service import (LocalTransport,
                                              TransportService)

REPO = __file__.rsplit("/tests/", 1)[0]
TOOLS = REPO + "/tools"


# -- workload generator determinism ----------------------------------------

def test_workload_stream_is_seed_deterministic():
    a = MixedWorkload(SoakConfig(seed=11)).ops()
    b = MixedWorkload(SoakConfig(seed=11)).ops()
    c = MixedWorkload(SoakConfig(seed=12)).ops()
    assert a == b
    assert a != c
    # every op class shows up in the mix
    assert {op["op"] for op in a} == {"search", "msearch", "bulk",
                                      "agg", "scroll"}


def test_fault_schedule_is_seed_deterministic():
    s1 = FaultSchedule.generate(SoakConfig(seed=42))
    s2 = FaultSchedule.generate(SoakConfig(seed=42))
    s3 = FaultSchedule.generate(SoakConfig(seed=43))
    assert s1 == s2
    assert s1 != s3
    faults = [d["fault"] for d in s1]
    # the full chaos menu: kill-and-recover AND the disk fault class
    # (corrupt segment + unhealthy fsync, PR-8's storage faults)
    assert {"slow_node", "drop_write", "stall_search", "induce_duress",
            "partition", "heal_partition", "kill_leader",
            "restart_killed", "corrupt_segment", "disk_unhealthy",
            "disk_heal"} <= set(faults)
    # steps are sorted and inside the op stream
    steps = [d["step"] for d in s1]
    assert steps == sorted(steps)
    assert all(0 <= s < SoakConfig().n_ops for s in steps)


def test_zipf_query_log_matches_bench_shape():
    log = zipf_query_log(16, 1000, seed=7)
    assert log == zipf_query_log(16, 1000, seed=7)
    assert all(0 <= a < 1000 and 0 <= b < 1000 for a, b in log)


# -- the acceptance bar: fixed-seed smoke soak ------------------------------

def test_smoke_soak_deterministic_verdicts_and_convergence(tmp_path):
    """Same seed ⇒ identical fault schedule and identical SLO verdicts
    across two full runs; zero unexpected 5xx; and the post-fault
    convergence check (doc count + checksum vs the uninjected control
    run) passes with a killed-and-recovered node in the schedule."""
    r1 = run_soak(str(tmp_path / "a"), seed=42)
    r2 = run_soak(str(tmp_path / "b"), seed=42)

    assert r1["chaos"]["schedule"] == r2["chaos"]["schedule"]
    v1 = [(v["slo"], v["ok"]) for v in r1["verdicts"]]
    v2 = [(v["slo"], v["ok"]) for v in r2["verdicts"]]
    assert v1 == v2

    # client-visible-error budget: 429/partial allowed, 5xx budget zero
    assert r1["chaos"]["unexpected_errors"] == []
    assert r1["slo_ok"], r1["verdicts"]

    # the schedule really killed and recovered a node (plus a partition
    # round-trip AND both disk faults: a corrupted-then-re-recovered
    # segment and an unhealthy-fsync eviction) and the cluster converged
    # with the control run anyway
    applied = {d["fault"] for d in r1["chaos"]["applied"]}
    assert {"kill_leader", "restart_killed", "partition",
            "heal_partition", "corrupt_segment", "disk_unhealthy",
            "disk_heal"} <= applied
    corrupt = next(d for d in r1["chaos"]["applied"]
                   if d["fault"] == "corrupt_segment")
    assert corrupt.get("detected"), corrupt
    conv = next(v for v in r1["verdicts"] if v["slo"] == "convergence")
    assert conv["ok"], conv
    assert r1["chaos"]["final_state"] == r1["control"]["final_state"]
    assert r1["chaos"]["final_state"]["doc_count"] > 0
    # degradation was actually exercised, not absent
    assert r1["chaos"]["recoveries"] >= 3
    assert r1["chaos"]["reroutes"] > 0


def test_partition_heal_roundtrip_converges(tmp_path):
    """A focused partition→heal schedule: the isolated follower is
    evicted, its copies promote, writes route around it, the heal
    re-admits it, peer recovery catches it up, and doc count + checksum
    match the uninjected control run."""
    cfg = SoakConfig(seed=5, n_ops=16, schedule=[
        {"step": 3, "fault": "partition", "node": "n2"},
        {"step": 9, "fault": "heal_partition", "node": "n2"}])
    r = SoakRunner(str(tmp_path), cfg).run()
    assert [d["fault"] for d in r["chaos"]["applied"]] == \
        ["partition", "heal_partition"]
    assert r["chaos"]["unexpected_errors"] == []
    conv = next(v for v in r["verdicts"] if v["slo"] == "convergence")
    assert conv["ok"], conv
    assert r["chaos"]["recoveries"] >= 1


def test_slo_breach_is_reported_not_swallowed(tmp_path):
    """An unmeetable p99 SLO must surface as a failed verdict and flip
    slo_ok — the runner records breaches, it never raises them away or
    hides them."""
    cfg = SoakConfig(seed=42, n_ops=10, control_run=False, slos={
        "p99_ms": {"search": 0.0001},
        "max_rejection_rate": 1.0,
        "max_unexpected_errors": 1_000,
        "require_convergence": False})
    r = SoakRunner(str(tmp_path), cfg).run()
    assert r["slo_ok"] is False
    breached = [v for v in r["verdicts"] if not v["ok"]]
    assert breached
    assert breached[0]["slo"] == "p99_ms.search"
    assert breached[0]["observed"] > breached[0]["limit"]


def test_bench_soak_phase_emits_slo_line(tmp_path, monkeypatch):
    """bench.py's `soak` phase appends one SLO line (p99 per op class,
    rejection_rate, sheds, reroutes, recoveries, convergence) to the
    phases file — the bench-trajectory surface of this harness."""
    phases = tmp_path / "phases.jsonl"
    monkeypatch.setenv("OSTPU_BENCH_PHASES", str(phases))
    monkeypatch.setenv("OSTPU_BENCH_SOAK_OPS", "24")
    spec = importlib.util.spec_from_file_location("bench_under_test",
                                                  REPO + "/bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench.run_soak_phase("cpu")
    lines = [json.loads(ln) for ln in phases.read_text().splitlines()]
    assert len(lines) == 1
    line = lines[0]
    assert line["phase"] == "soak"
    assert {"slo_ok", "rejection_rate", "sheds", "reroutes",
            "recoveries", "convergence",
            "p99_search_ms", "p99_bulk_ms",
            "fenced_ops", "stale_primary_rejections",
            "durability_checked_ops"} <= set(line)
    assert line["durability_checked_ops"] > 0
    assert line["unexpected_errors"] == 0
    assert line["convergence"] is True


@pytest.mark.slow
def test_full_soak_configuration(tmp_path):
    """The production-sized soak (more ops, bigger corpus, concurrent
    workers) — the nightly gate; tier-1 runs the smoke configuration
    above instead."""
    r = run_soak(str(tmp_path), full=True, seed=42)
    assert r["chaos"]["unexpected_errors"] == []
    conv = next(v for v in r["verdicts"] if v["slo"] == "convergence")
    assert conv["ok"], conv
    assert r["slo_ok"], r["verdicts"]


@pytest.mark.slow
@pytest.mark.parametrize("seed", [7, 101, 202])
def test_multi_seed_soak_sweep_verdicts_deterministic(tmp_path, seed):
    """Multi-seed sweep (nightly; marked slow so tier-1 keeps its
    budget — the fixed-seed smoke above stays the tier-1 gate): each
    seed produces a DIFFERENT schedule but the two-run determinism
    contract holds per seed — identical schedule, identical verdicts,
    zero unexpected errors, convergence with the uninjected control."""
    r1 = run_soak(str(tmp_path / "a"), seed=seed)
    r2 = run_soak(str(tmp_path / "b"), seed=seed)
    assert r1["chaos"]["schedule"] == r2["chaos"]["schedule"]
    v1 = [(v["slo"], v["ok"]) for v in r1["verdicts"]]
    v2 = [(v["slo"], v["ok"]) for v in r2["verdicts"]]
    assert v1 == v2
    assert r1["chaos"]["unexpected_errors"] == []
    conv = next(v for v in r1["verdicts"] if v["slo"] == "convergence")
    assert conv["ok"], conv


# -- satellite: single-search replica spill ---------------------------------

def test_single_search_spill_rotates_off_busy_preferred(tmp_path):
    """A plain _search scatter rotates off the preferred copy once its
    outstanding-request count exceeds search.replica_selection.
    spill_outstanding, counted under the reroutes metric."""
    hub = LocalTransport.Hub()
    svc = TransportService("a", LocalTransport(hub))
    node = ClusterNode("a", str(tmp_path / "a"), svc, ["a"])
    try:
        entry = {"primary": "b", "replicas": ["c"],
                 "in_sync": ["b", "c"], "primary_term": 1}
        collector = node.response_collector
        # below the threshold: legacy order stands
        assert node._copy_candidates(entry) == ["b", "c"]
        for _ in range(rc.SPILL_OUTSTANDING + 1):
            collector.incr_outstanding("b")
        before = metrics().counter(
            "search.replica_selection.reroutes").value
        assert node._copy_candidates(entry) == ["c", "b"]   # spilled
        assert metrics().counter(
            "search.replica_selection.reroutes").value == before + 1
        # msearch batch members keep their own rotation (spill offset)
        assert node._copy_candidates(entry, spill=1) == ["c", "b"]
        # both copies equally busy: no pointless rotation
        for _ in range(rc.SPILL_OUTSTANDING + 1):
            collector.incr_outstanding("c")
        assert node._copy_candidates(entry) == ["b", "c"]
        # disabled via the dynamic knob
        rc.SPILL_OUTSTANDING = 0
        try:
            for _ in range(20):
                collector.incr_outstanding("b")
            assert node._copy_candidates(entry) == ["b", "c"]
        finally:
            rc.SPILL_OUTSTANDING = 8     # module global: always restore
    finally:
        node.stop()


def test_spill_and_shed_occupancy_dynamic_settings(tmp_path):
    node = Node(str(tmp_path / "node"), port=0)
    try:
        assert rc.SPILL_OUTSTANDING == 8 and rc.SHED_OCCUPANCY == 0.0
        node.update_cluster_settings(transient={
            "search.replica_selection.spill_outstanding": 3,
            "search.replica_selection.shed_occupancy": 0.75})
        assert rc.SPILL_OUTSTANDING == 3
        assert rc.SHED_OCCUPANCY == 0.75
        node.update_cluster_settings(transient={
            "search.replica_selection.spill_outstanding": None,
            "search.replica_selection.shed_occupancy": None})
        assert rc.SPILL_OUTSTANDING == 8 and rc.SHED_OCCUPANCY == 0.0
    finally:
        rc.SPILL_OUTSTANDING = 8         # module globals: always restore
        rc.SHED_OCCUPANCY = 0.0
        node.stop()


# -- satellite: unified shed/admission budget -------------------------------

def wait_until(pred, timeout=8.0):
    import time
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:   # deadline
        if pred():
            return True
        time.sleep(0.05)                     # deadline
    return False


@pytest.fixture
def cluster(tmp_path):
    hub = LocalTransport.Hub()
    ids = ["n0", "n1", "n2"]
    nodes = {}
    for nid in ids:
        svc = TransportService(nid, LocalTransport(hub))
        node = ClusterNode(nid, str(tmp_path / nid), svc, ids)
        node.search_backpressure.trackers["cpu_usage"].probe = lambda: 0.0
        nodes[nid] = node
    assert nodes["n0"].start_election()
    assert wait_until(lambda: all(
        nodes[i].coordinator.state().master_node == "n0" for i in ids))
    yield hub, ids, nodes
    for n in nodes.values():
        n.stop()


def test_shed_consults_admission_occupancy(cluster):
    """Below search.replica_selection.shed_occupancy the coordinator
    still tries an all-duress shard as a last resort; at/above it the
    shard sheds fast — and the shed draws from the SAME rejection
    ledger as the admission gate's edge 429s."""
    hub, ids, nodes = cluster
    nodes["n0"].create_index("budget", {
        "settings": {"number_of_shards": 1, "number_of_replicas": 1},
        "mappings": {"properties": {"v": {"type": "long"}}}})
    assert wait_until(lambda: all(
        set(e["in_sync"]) == {e["primary"], *e["replicas"]}
        for e in nodes["n0"].coordinator.state().routing.get("budget",
                                                             [{}])
        if e))
    for i in range(8):
        nodes["n0"].index_doc("budget", str(i), {"v": i})
    nodes["n0"].refresh("budget")
    entry = nodes["n0"].coordinator.state().routing["budget"][0]
    coord = next(i for i in ids if i not in copies_of(entry))
    assert coord != "n0", "allocator change broke this test's setup"
    node = nodes[coord]

    def seed_duress():
        for nid in copies_of(entry):
            node.response_collector.record_duress(nid, True)

    try:
        rc.SHED_OCCUPANCY = 0.9
        seed_duress()
        # idle coordinator (occupancy ≈ 0): last-resort try, not a shed
        r = node.search("budget", {"query": {"match_all": {}},
                                   "size": 10})
        assert r["_shards"]["failed"] == 0
        assert r["hits"]["total"]["value"] == 8

        # saturate the gate to 90%: the same search now sheds, and the
        # shed lands on the admission controller's shared ledger
        admission = node.search_backpressure.admission
        admission.max_concurrent = 10
        import contextlib
        seed_duress()
        sheds_before = admission.stats()["shed_count"]
        with contextlib.ExitStack() as stack:
            for _ in range(9):
                stack.enter_context(admission.acquire("held"))
            assert admission.occupancy() == pytest.approx(0.9)
            r = node.search("budget", {"query": {"match_all": {}}})
        assert r["_shards"]["failed"] == 1
        assert r["_shards"]["failures"][0]["reason"]["type"] == \
            "node_duress_exception"
        stats = admission.stats()
        assert stats["shed_count"] == sheds_before + 1
        assert stats["rejected_total"] == \
            stats["rejected_count"] + stats["shed_count"]
    finally:
        rc.SHED_OCCUPANCY = 0.0          # module global: always restore


def test_cluster_search_draws_from_admission_budget(cluster):
    """Coordinator-scope searches hold a permit from the same gate the
    REST edge uses: a saturated gate 429s the scatter instead of
    queueing it."""
    from opensearch_tpu.search.backpressure import SearchRejectedError

    hub, ids, nodes = cluster
    nodes["n0"].create_index("adm", {
        "settings": {"number_of_shards": 1},
        "mappings": {"properties": {"v": {"type": "long"}}}})
    assert wait_until(lambda: all(
        "adm" in nodes[i].coordinator.state().indices for i in ids))
    nodes["n0"].index_doc("adm", "1", {"v": 1})
    nodes["n0"].refresh("adm")
    admission = nodes["n0"].search_backpressure.admission
    admission.max_concurrent = 1
    try:
        with admission.acquire("held"):
            with pytest.raises(SearchRejectedError):
                nodes["n0"].search("adm", {"query": {"match_all": {}}})
        # permit released: service resumes
        r = nodes["n0"].search("adm", {"query": {"match_all": {}}})
        assert r["hits"]["total"]["value"] == 1
    finally:
        admission.max_concurrent = 256


def test_nodes_stats_exposes_shared_budget(tmp_path):
    """The unified budget surfaces in _nodes/stats under BOTH
    search_backpressure (admission_control) and adaptive_selection
    (budget) — same numbers, one gate."""
    node = Node(str(tmp_path / "node"), port=0)
    try:
        node.search_backpressure.admission.record_shed(2)
        status, resp = node.rest.dispatch("GET", "/_nodes/stats", {},
                                          None)
        assert status == 200
        stats = resp["nodes"][node.node_id]
        bp_block = stats["search_backpressure"]["admission_control"]
        ars_block = stats["adaptive_selection"]["budget"]
        assert bp_block == ars_block
        assert ars_block["shed_count"] == 2
        assert ars_block["rejected_total"] == \
            ars_block["rejected_count"] + 2
        assert "occupancy" in ars_block
    finally:
        node.stop()


# -- satellite: symmetric partition directive -------------------------------

def test_partition_is_symmetric_and_healable():
    from opensearch_tpu.common.errors import NodeDisconnectedError
    from opensearch_tpu.testing.fault_injection import FaultInjector

    hub = LocalTransport.Hub()
    a = TransportService("a", LocalTransport(hub))
    b = TransportService("b", LocalTransport(hub))
    c = TransportService("c", LocalTransport(hub))
    for svc in (a, b, c):
        svc.register_handler("ping", lambda payload: {"pong": True})
    try:
        faults = FaultInjector(hub, seed=3)
        rule = faults.partition({"a"}, {"b", "c"})
        for src, dst in (("a", "b"), ("b", "a"), ("a", "c")):
            with pytest.raises(NodeDisconnectedError):
                {"a": a, "b": b, "c": c}[src].send_request(
                    dst, "ping", {}, timeout=2.0)
        # intra-side traffic is untouched
        assert b.send_request("c", "ping", {}, timeout=5.0)["pong"]
        assert faults.heal_partition(rule)
        assert a.send_request("b", "ping", {}, timeout=5.0)["pong"]
        assert not faults.heal_partition(rule)   # second heal no-ops
    finally:
        a.close()
        b.close()
        c.close()


# -- seeded-RNG lint (tier-1 CI hook) ---------------------------------------

def test_check_seeded_rng_lint_passes_repo():
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_seeded_rng.py"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_check_seeded_rng_lint_catches_violations(tmp_path):
    (tmp_path / "bad.py").write_text(
        "import random\n"
        "import numpy as np\n"
        "r1 = random.Random()\n"                       # line 3: flagged
        "r2 = random.Random(42)\n"
        "r3 = np.random.default_rng()\n"               # line 5: flagged
        "r4 = np.random.default_rng(seed=7)\n"
        "r5 = random.Random()  # seeded-elsewhere\n"
        "# seeded-elsewhere\n"
        "r6 = np.random.default_rng()\n")
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_seeded_rng.py", str(tmp_path)],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert "bad.py:3" in out.stdout
    assert "bad.py:5" in out.stdout
    assert "bad.py:4" not in out.stdout
    assert "bad.py:7" not in out.stdout
    assert "bad.py:9" not in out.stdout
