"""Cluster-state diff publication + dynamic voting reconfiguration
(VERDICT r4 item 8; ref cluster/Diff.java, DiffableUtils.java,
cluster/coordination/Reconfigurator.java)."""

import json

import pytest

from opensearch_tpu.cluster.coordination import Coordinator, Mode
from opensearch_tpu.cluster.state import (ClusterState, apply_diff,
                                          diff_states)
from opensearch_tpu.transport.service import LocalTransport, TransportService


def make_cluster(n=3, check_retries=2):
    hub = LocalTransport.Hub()
    ids = [f"node_{i}" for i in range(n)]
    coords = {}
    for node_id in ids:
        svc = TransportService(node_id, LocalTransport(hub))
        coords[node_id] = Coordinator(node_id, svc, voting_nodes=ids,
                                      node_info={"name": node_id},
                                      check_retries=check_retries)
    return hub, ids, coords


def teardown(coords):
    for c in coords.values():
        c.stop()
        c.transport.close()


def big_state(n_indices=200):
    indices = {f"idx_{i}": {"settings": {"number_of_shards": 3},
                            "mappings": {"properties": {
                                "f": {"type": "keyword"}}}}
               for i in range(n_indices)}
    routing = {f"idx_{i}": [{"shard": s, "primary": "node_0",
                             "replicas": [], "in_sync": ["node_0"],
                             "primary_term": 1}
                            for s in range(3)] for i in range(n_indices)}
    return ClusterState(term=3, version=10, master_node="node_0",
                        nodes={"node_0": {"name": "node_0"}},
                        indices=indices, routing=routing,
                        voting=("node_0",))


def test_diff_roundtrip_and_size():
    old = big_state()
    # one index changes, one is added, one removed
    indices = dict(old.indices)
    indices["idx_0"] = {"settings": {"number_of_shards": 3,
                                     "refresh_interval": -1},
                        "mappings": indices["idx_0"]["mappings"]}
    indices["brand_new"] = {"settings": {}, "mappings": {}}
    del indices["idx_7"]
    new = old.with_(version=11, indices=indices)
    d = diff_states(old, new)
    rebuilt = apply_diff(old, d)
    assert rebuilt.to_payload() == new.to_payload()
    # the wire win: the diff is a small fraction of the full state
    full_bytes = len(json.dumps(new.to_payload()))
    diff_bytes = len(json.dumps(d))
    assert diff_bytes < full_bytes / 10, (diff_bytes, full_bytes)


def test_diff_base_mismatch_detected():
    old = big_state()
    new = old.with_(version=11)
    d = diff_states(old, new)
    assert (d["base_term"], d["base_version"]) == (old.term, old.version)


def test_publication_uses_diffs_with_full_fallback():
    hub, ids, coords = make_cluster()
    try:
        assert coords["node_0"].start_election()
        leader = coords["node_0"]
        # capture the wire: count diff vs full publishes
        seen = {"diff": 0, "full": 0}
        orig = leader.transport.send_request

        def spy(target, action, payload, **kw):
            if action.endswith("publish"):
                seen["diff" if "diff" in payload else "full"] += 1
            return orig(target, action, payload, **kw)
        leader.transport.send_request = spy
        leader.submit_state_update(
            lambda s: s.with_(indices={**s.indices,
                                       "a": {"settings": {},
                                             "mappings": {}}}))
        assert seen["diff"] >= 2 and seen["full"] == 0
        # a fresh node (no accepted state) forces the full fallback
        svc = TransportService("node_3", LocalTransport(hub))
        coords["node_3"] = Coordinator("node_3", svc,
                                       voting_nodes=ids,
                                       node_info={"name": "node_3"})
        seen["diff"] = seen["full"] = 0
        leader.add_node("node_3", {"name": "node_3"})
        assert seen["full"] >= 1          # node_3 needed the full state
        assert coords["node_3"].state().version == \
            leader.state().version
    finally:
        teardown(coords)


def test_voting_config_grows_and_shrinks():
    hub, ids, coords = make_cluster(3)
    try:
        assert coords["node_0"].start_election()
        leader = coords["node_0"]
        assert set(leader.state().voting) == set(ids)
        # two more master-eligible nodes join -> config grows to 5
        for nid in ("node_3", "node_4"):
            svc = TransportService(nid, LocalTransport(hub))
            coords[nid] = Coordinator(nid, svc, voting_nodes=ids,
                                      node_info={"name": nid})
            leader.add_node(nid, {"name": nid})
        assert len(leader.state().voting) == 5
        # one leaves -> trimmed back to an odd size (never even)
        leader.remove_node("node_4")
        assert len(leader.state().voting) % 2 == 1
        assert "node_4" not in leader.state().voting
    finally:
        teardown(coords)


def test_replace_a_voting_node():
    """Planned node replacement: add the replacement, remove the old
    voter, and the cluster keeps committing — the scenario a static
    voting config cannot survive (VERDICT r4 missing #7)."""
    hub, ids, coords = make_cluster(3)
    try:
        assert coords["node_0"].start_election()
        leader = coords["node_0"]
        svc = TransportService("node_9", LocalTransport(hub))
        coords["node_9"] = Coordinator("node_9", svc, voting_nodes=ids,
                                       node_info={"name": "node_9"})
        leader.add_node("node_9", {"name": "node_9"})
        leader.remove_node("node_2")
        hub.disconnect("node_2")                   # old voter is gone
        assert set(leader.state().voting) == {"node_0", "node_1",
                                              "node_9"}
        # the reconfigured cluster still commits with the NEW quorum
        leader.submit_state_update(
            lambda s: s.with_(indices={**s.indices,
                                       "post": {"settings": {},
                                                "mappings": {}}}))
        assert "post" in leader.state().indices
        assert "post" in coords["node_9"].state().indices
    finally:
        teardown(coords)


def test_even_config_trims_to_odd():
    hub, ids, coords = make_cluster(3)
    try:
        assert coords["node_0"].start_election()
        leader = coords["node_0"]
        svc = TransportService("node_3", LocalTransport(hub))
        coords["node_3"] = Coordinator("node_3", svc, voting_nodes=ids,
                                       node_info={"name": "node_3"})
        leader.add_node("node_3", {"name": "node_3"})
        # 4 eligible nodes -> 3 voters, leader always kept
        voting = leader.state().voting
        assert len(voting) == 3 and "node_0" in voting
    finally:
        teardown(coords)
