"""Distributed scatter-gather search on the virtual 8-device CPU mesh:
8-shard results must be identical to 1-shard results on the same corpus
(VERDICT round-1 item 8's 'done' bar)."""

import numpy as np
import pytest

import jax

from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.parallel import dist_search
from opensearch_tpu.search.executor import ShardSearcher

MAPPING = {"properties": {"body": {"type": "text"}}}
VOCAB = ("alpha bravo charlie delta echo foxtrot golf hotel india juliet "
         "kilo lima").split()


def build_sharded_corpus(n_shards=8, docs_per_shard=40, seed=3):
    rng = np.random.default_rng(seed)
    mapper = DocumentMapper(MAPPING)
    writer = SegmentWriter()
    segments = []
    doc_no = 0
    for si in range(n_shards):
        parsed = []
        for _ in range(docs_per_shard):
            body = " ".join(rng.choice(VOCAB, size=rng.integers(4, 20)))
            d = mapper.parse(str(doc_no), {"body": body})
            d.seq_no = doc_no
            parsed.append(d)
            doc_no += 1
        segments.append(writer.build(parsed, f"shard_{si}"))
    return mapper, segments


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_topk_matches_single_shard():
    mapper, segments = build_sharded_corpus()
    terms = ["alpha", "echo"]
    k = 10

    mesh = dist_search.make_mesh(8)
    stacked, meta = dist_search.prepare_match_query(segments, "body", terms)
    on_mesh = dist_search.put_on_mesh(stacked, mesh)
    step = dist_search.sharded_bm25_topk(mesh, n_pad=meta["n_pad"],
                                         budget=meta["budget"], k=k)
    vals, gids = step(on_mesh["offsets"], on_mesh["doc_ids"], on_mesh["tfs"],
                      on_mesh["doc_lens"], on_mesh["tids"], on_mesh["active"],
                      on_mesh["idfs"], on_mesh["weights"], on_mesh["avgdl"])
    vals = np.asarray(vals)
    gids = np.asarray(gids)

    # reference: the same 8 segments searched as one shard (global stats
    # are identical by construction)
    searcher = ShardSearcher(segments, mapper)
    resp = searcher.search({"query": {"match": {"body": "alpha echo"}},
                            "size": k})
    ref = resp["hits"]["hits"]

    n_pad = meta["n_pad"]
    got_ids = []
    for gid in gids:
        shard, local = divmod(int(gid), n_pad)
        got_ids.append(segments[shard].doc_ids[local])
    assert got_ids == [h["_id"] for h in ref]
    np.testing.assert_allclose(vals, [h["_score"] for h in ref], rtol=1e-5)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_topk_term_missing_on_some_shards():
    mapper, segments = build_sharded_corpus(docs_per_shard=12, seed=9)
    mesh = dist_search.make_mesh(8)
    stacked, meta = dist_search.prepare_match_query(segments, "body",
                                                    ["juliet"])
    on_mesh = dist_search.put_on_mesh(stacked, mesh)
    step = dist_search.sharded_bm25_topk(mesh, n_pad=meta["n_pad"],
                                         budget=meta["budget"], k=5)
    vals, gids = step(on_mesh["offsets"], on_mesh["doc_ids"], on_mesh["tfs"],
                      on_mesh["doc_lens"], on_mesh["tids"], on_mesh["active"],
                      on_mesh["idfs"], on_mesh["weights"], on_mesh["avgdl"])
    searcher = ShardSearcher(segments, mapper)
    resp = searcher.search({"query": {"match": {"body": "juliet"}}, "size": 5})
    exp_scores = [h["_score"] for h in resp["hits"]["hits"]]
    got = [v for v in np.asarray(vals) if v > 0]
    np.testing.assert_allclose(got, exp_scores, rtol=1e-5)
