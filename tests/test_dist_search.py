"""Distributed scatter-gather search on the virtual 8-device CPU mesh:
8-shard results must be identical to 1-shard results on the same corpus
(VERDICT round-1 item 8's 'done' bar)."""

import numpy as np
import pytest

import jax

from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.parallel import dist_search
from opensearch_tpu.search.executor import ShardSearcher

MAPPING = {"properties": {"body": {"type": "text"}}}
VOCAB = ("alpha bravo charlie delta echo foxtrot golf hotel india juliet "
         "kilo lima").split()


def build_sharded_corpus(n_shards=8, docs_per_shard=40, seed=3):
    rng = np.random.default_rng(seed)
    mapper = DocumentMapper(MAPPING)
    writer = SegmentWriter()
    segments = []
    doc_no = 0
    for si in range(n_shards):
        parsed = []
        for _ in range(docs_per_shard):
            body = " ".join(rng.choice(VOCAB, size=rng.integers(4, 20)))
            d = mapper.parse(str(doc_no), {"body": body})
            d.seq_no = doc_no
            parsed.append(d)
            doc_no += 1
        segments.append(writer.build(parsed, f"shard_{si}"))
    return mapper, segments


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_topk_matches_single_shard():
    mapper, segments = build_sharded_corpus()
    terms = ["alpha", "echo"]
    k = 10

    mesh = dist_search.make_mesh(8)
    stacked, meta = dist_search.prepare_match_query(segments, "body", terms)
    assert "impacts" in stacked and "tfs" not in stacked \
        and "doc_lens" not in stacked       # the port actually landed
    on_mesh = dist_search.put_on_mesh(stacked, mesh)
    step = dist_search.sharded_impact_topk(mesh, n_pad=meta["n_pad"],
                                           budget=meta["budget"], k=k)
    vals, gids = step(on_mesh["offsets"], on_mesh["doc_ids"],
                      on_mesh["impacts"], on_mesh["tids"],
                      on_mesh["active"], on_mesh["idfs"],
                      on_mesh["weights"])
    vals = np.asarray(vals)
    gids = np.asarray(gids)

    # reference: the same 8 segments searched as one shard (global stats
    # are identical by construction)
    searcher = ShardSearcher(segments, mapper)
    resp = searcher.search({"query": {"match": {"body": "alpha echo"}},
                            "size": k})
    ref = resp["hits"]["hits"]

    n_pad = meta["n_pad"]
    got_ids = []
    for gid in gids:
        shard, local = divmod(int(gid), n_pad)
        got_ids.append(segments[shard].doc_ids[local])
    assert got_ids == [h["_id"] for h in ref]
    # BYTE-parity with the host path: both read the same eager impact
    # table in the same accumulation order (the PR-5 invariant extended
    # to the mesh), so scores are bitwise equal, not merely close
    assert [np.float32(v) for v in vals] \
        == [np.float32(h["_score"]) for h in ref]


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_topk_term_missing_on_some_shards():
    mapper, segments = build_sharded_corpus(docs_per_shard=12, seed=9)
    mesh = dist_search.make_mesh(8)
    stacked, meta = dist_search.prepare_match_query(segments, "body",
                                                    ["juliet"])
    on_mesh = dist_search.put_on_mesh(stacked, mesh)
    step = dist_search.sharded_impact_topk(mesh, n_pad=meta["n_pad"],
                                           budget=meta["budget"], k=5)
    vals, gids = step(on_mesh["offsets"], on_mesh["doc_ids"],
                      on_mesh["impacts"], on_mesh["tids"],
                      on_mesh["active"], on_mesh["idfs"],
                      on_mesh["weights"])
    searcher = ShardSearcher(segments, mapper)
    resp = searcher.search({"query": {"match": {"body": "juliet"}}, "size": 5})
    exp_scores = [np.float32(h["_score"]) for h in resp["hits"]["hits"]]
    got = [np.float32(v) for v in np.asarray(vals) if v > 0]
    assert got == exp_scores           # byte-parity, not approximate


def _build_sharded_corpus(n_shards=8, per=40, seed=3):
    import numpy as np

    from opensearch_tpu.index.segment import SegmentWriter
    from opensearch_tpu.mapping.mapper import DocumentMapper
    from opensearch_tpu.search.executor import ShardSearcher

    vocab = ("alpha bravo charlie delta echo foxtrot golf hotel india "
             "juliet kilo lima".split())
    rng = np.random.default_rng(seed)
    mapper = DocumentMapper({"properties": {
        "body": {"type": "text"}, "n": {"type": "long"},
        "tag": {"type": "keyword"}}})
    writer = SegmentWriter()
    searchers = []
    doc_no = 0
    for si in range(n_shards):
        parsed = []
        for _ in range(per):
            src = {"body": " ".join(rng.choice(vocab,
                                               size=rng.integers(3, 12))),
                   "n": int(rng.integers(0, 100)),
                   "tag": str(rng.choice(["a", "b", "c"]))}
            d = mapper.parse(str(doc_no), src)
            d.seq_no = doc_no
            parsed.append(d)
            doc_no += 1
        seg = writer.build(parsed, f"s{si}_seg0")
        searchers.append(ShardSearcher([seg], mapper,
                                       index_name="mesh_idx", shard_id=si))
    return searchers


def _host_merge(searchers, body):
    """Reference scatter-gather: per-shard search + coordinator merge —
    the exact semantics MeshSearcher's collective merge must reproduce."""
    from opensearch_tpu.search.executor import merge_hit_rows

    size = int(body.get("size", 10)) + int(body.get("from", 0))
    sub = dict(body, size=size)
    sub["from"] = 0
    rows = []
    total = 0
    for si, s in enumerate(searchers):
        r = s.search(sub)
        total += r["hits"]["total"]["value"]
        for pos, h in enumerate(r["hits"]["hits"]):
            rows.append((h, si, pos))
    hits = merge_hit_rows(rows, None)
    from_ = int(body.get("from", 0))
    return hits[from_: from_ + int(body.get("size", 10))], total


QUERIES = [
    {"query": {"match": {"body": "alpha echo"}}, "size": 10},
    {"query": {"bool": {
        "must": [{"match": {"body": "alpha"}}],
        "filter": [{"range": {"n": {"gte": 20, "lte": 80}}}]}},
     "size": 15},
    {"query": {"bool": {
        "should": [{"match": {"body": "delta"}},
                   {"term": {"tag": "b"}}]}}, "size": 10, "from": 5},
    {"query": {"range": {"n": {"gte": 90}}}, "size": 20},
    {"query": {"constant_score": {
        "filter": {"term": {"tag": "a"}}, "boost": 2.0}}, "size": 10},
]


def test_mesh_searcher_matches_host_merge():
    """The collective all-gather merge must reproduce the host
    scatter-gather bit-for-bit for arbitrary compiled plans (VERDICT r3
    item 3: the mesh path generalized past bag-of-terms)."""
    from opensearch_tpu.parallel.dist_search import MeshSearcher

    searchers = _build_sharded_corpus()
    mesh_s = MeshSearcher(searchers)
    for body in QUERIES:
        host_hits, host_total = _host_merge(searchers, body)
        resp = mesh_s.search(body)
        assert resp["hits"]["total"]["value"] == host_total, body
        got = [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]]
        want = [(h["_id"], h["_score"]) for h in host_hits]
        assert got == want, (body, got, want)


def test_mesh_searcher_empty_and_unmatched():
    from opensearch_tpu.parallel.dist_search import MeshSearcher

    searchers = _build_sharded_corpus(n_shards=4)
    mesh_s = MeshSearcher(searchers)
    resp = mesh_s.search({"query": {"match": {"body": "zzznope"}}})
    assert resp["hits"]["total"]["value"] == 0
    assert resp["hits"]["hits"] == []
    assert resp["hits"]["max_score"] is None


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_mesh_metric_aggs_collective_reduce():
    """size:0 metric aggs reduce ON the mesh via one psum/pmin/pmax
    collective — results identical to the host-path reduce (VERDICT r4
    weak #5)."""
    mapper = DocumentMapper({"properties": {"body": {"type": "text"},
                                            "n": {"type": "long"}}})
    writer = SegmentWriter()
    rng = np.random.default_rng(5)
    segments = []
    doc_no = 0
    for si in range(8):
        parsed = []
        for _ in range(25):
            body = " ".join(rng.choice(VOCAB, size=rng.integers(4, 12)))
            parsed.append(mapper.parse(
                str(doc_no), {"body": body, "n": int(rng.integers(0, 100))}))
            doc_no += 1
        segments.append(writer.build(parsed, f"m_{si}"))
    shards = [ShardSearcher([s], mapper) for s in segments]
    ms = dist_search.MeshSearcher(shards, dist_search.make_mesh(8))
    aggs = {"tot": {"sum": {"field": "n"}},
            "lo": {"min": {"field": "n"}},
            "hi": {"max": {"field": "n"}},
            "mean": {"avg": {"field": "n"}},
            "cnt": {"value_count": {"field": "n"}},
            "st": {"stats": {"field": "n"}}}
    assert ms.supports_mesh_aggs(aggs)
    body = {"size": 0, "query": {"match": {"body": "alpha"}}}
    got = ms.mesh_metric_aggs(body, aggs)
    want = ShardSearcher(segments, mapper).search({**body, "aggs": aggs})
    assert got["hits"]["total"]["value"] == \
        want["hits"]["total"]["value"]
    for name in ("tot", "lo", "hi", "mean", "cnt"):
        assert got["aggregations"][name]["value"] == pytest.approx(
            want["aggregations"][name]["value"])
    for k in ("count", "min", "max", "avg", "sum"):
        assert got["aggregations"]["st"][k] == pytest.approx(
            want["aggregations"]["st"][k])
    # nested / bucket aggs stay on the host path
    assert not ms.supports_mesh_aggs(
        {"t": {"terms": {"field": "n"}}})
    assert not ms.supports_mesh_aggs(
        {"s": {"sum": {"field": "n"}, "aggs": {"x": {"max":
                                                     {"field": "n"}}}}})
