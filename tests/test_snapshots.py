"""Snapshots: fs repository registration, incremental segment-file
snapshot, restore (fresh name + rename), delete w/ blob GC — the
round-trip 'done' bar from VERDICT r3 item 5 (ref
snapshots/SnapshotsService.java:262, BlobStoreRepository.java:1)."""

import json
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.node import Node


@pytest.fixture()
def node(tmp_path):
    n = Node(str(tmp_path / "node"), port=0, path_repo=[str(tmp_path)]).start()
    yield n
    n.stop()


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


def seed(node, index, n, offset=0):
    call(node, "PUT", f"/{index}", {"mappings": {"properties": {
        "msg": {"type": "text"}, "n": {"type": "long"}}}})
    for i in range(offset, offset + n):
        call(node, "PUT", f"/{index}/_doc/{i}",
             {"msg": f"message {i}", "n": i})
    call(node, "POST", f"/{index}/_refresh")


def test_snapshot_restore_round_trip(node, tmp_path):
    seed(node, "src", 12)
    code, _ = call(node, "PUT", "/_snapshot/backups", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo")}})
    assert code == 200
    code, resp = call(node, "PUT", "/_snapshot/backups/snap1", {})
    assert code == 200
    assert resp["snapshot"]["state"] == "SUCCESS"
    assert resp["snapshot"]["indices"] == ["src"]

    # destructive change after the snapshot
    call(node, "DELETE", "/src/_doc/0")
    call(node, "DELETE", "/src")
    code, resp = call(node, "GET", "/src/_search")
    assert code == 404

    code, resp = call(node, "POST", "/_snapshot/backups/snap1/_restore", {})
    assert code == 200
    code, resp = call(node, "POST", "/src/_search",
                      {"query": {"match_all": {}}, "size": 50})
    assert code == 200
    assert resp["hits"]["total"]["value"] == 12
    # restored docs searchable AND gettable (version map rebuilt from
    # restored segments)
    code, resp = call(node, "GET", "/src/_doc/0")
    assert code == 200 and resp["_source"]["n"] == 0
    # restored index accepts new writes
    code, _ = call(node, "PUT", "/src/_doc/new", {"msg": "fresh", "n": 99})
    assert code in (200, 201)


def test_snapshot_incremental_reuses_blobs(node, tmp_path):
    seed(node, "inc", 8)
    call(node, "PUT", "/_snapshot/backups", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo")}})
    call(node, "PUT", "/_snapshot/backups/first", {})
    # add a new segment; old segments' blobs must be REUSED
    seed(node, "inc", 4, offset=100)
    code, resp = call(node, "PUT", "/_snapshot/backups/second", {})
    assert code == 200
    m = json.loads(
        (tmp_path / "repo" / "snap" / "second.json").read_text())
    assert m["reused_files"] > 0
    assert m["total_files"] > m["reused_files"]


def test_snapshot_restore_rename(node, tmp_path):
    seed(node, "orig", 5)
    call(node, "PUT", "/_snapshot/backups", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo")}})
    call(node, "PUT", "/_snapshot/backups/s1", {})
    code, resp = call(node, "POST", "/_snapshot/backups/s1/_restore", {
        "indices": "orig", "rename_pattern": "orig",
        "rename_replacement": "copy"})
    assert code == 200 and resp["snapshot"]["indices"] == ["copy"]
    code, resp = call(node, "POST", "/copy/_search",
                      {"query": {"match": {"msg": "message"}}, "size": 10})
    assert resp["hits"]["total"]["value"] == 5
    # original untouched
    code, resp = call(node, "POST", "/orig/_count")
    assert resp["count"] == 5
    # restoring over an OPEN index is rejected
    code, resp = call(node, "POST", "/_snapshot/backups/s1/_restore", {})
    assert code == 400


def test_snapshot_delete_gcs_unreferenced_blobs(node, tmp_path):
    seed(node, "gc", 6)
    call(node, "PUT", "/_snapshot/backups", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo")}})
    call(node, "PUT", "/_snapshot/backups/a", {})
    seed(node, "gc", 3, offset=50)
    call(node, "PUT", "/_snapshot/backups/b", {})
    blobs_dir = tmp_path / "repo" / "blobs"
    n_with_both = len(list(blobs_dir.iterdir()))
    code, _ = call(node, "DELETE", "/_snapshot/backups/b")
    assert code == 200
    n_after = len(list(blobs_dir.iterdir()))
    assert n_after < n_with_both            # b-only blobs collected
    # snapshot a still restorable after the GC
    call(node, "DELETE", "/gc")
    code, resp = call(node, "POST", "/_snapshot/backups/a/_restore", {})
    assert code == 200
    code, resp = call(node, "POST", "/gc/_count")
    assert resp["count"] == 6


def test_snapshot_error_shapes(node, tmp_path):
    code, resp = call(node, "PUT", "/_snapshot/bad", {"type": "s3"})
    assert code == 400
    code, resp = call(node, "GET", "/_snapshot/nope")
    assert code == 404
    call(node, "PUT", "/_snapshot/backups", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo")}})
    code, resp = call(node, "GET", "/_snapshot/backups/missing")
    assert code == 404
    code, resp = call(node, "PUT", "/_snapshot/backups/BAD~NAME", {})
    assert code == 400
    seed(node, "dup", 2)
    call(node, "PUT", "/_snapshot/backups/dup1", {})
    code, resp = call(node, "PUT", "/_snapshot/backups/dup1", {})
    assert code == 400                      # duplicate snapshot name
    # fs repo without location
    code, resp = call(node, "PUT", "/_snapshot/noloc", {"type": "fs"})
    assert code == 500 or code == 400


def test_fs_repo_location_outside_path_repo_rejected(node, tmp_path):
    """ADVICE r4: arbitrary fs locations are rejected unless under a
    path.repo root (Environment.resolveRepoFile analog)."""
    code, resp = call(node, "PUT", "/_snapshot/evil", {
        "type": "fs", "settings": {"location": "/etc/cron.d"}})
    assert code == 400
    assert "path.repo" in resp["error"]["reason"]
    # traversal out of an allowed root is caught by realpath resolution
    code, _ = call(node, "PUT", "/_snapshot/sneaky", {
        "type": "fs",
        "settings": {"location": str(tmp_path) + "/../outside"}})
    assert code == 400


def test_manifest_file_name_validation():
    from opensearch_tpu.index.remote_store import validate_manifest_name
    import pytest as _pytest
    from opensearch_tpu.common.errors import IllegalArgumentError

    assert validate_manifest_name("seg_0.npz") == "seg_0.npz"
    for bad in ("../../x", "a/b", ".hidden", ""):
        with _pytest.raises(IllegalArgumentError):
            validate_manifest_name(bad)
