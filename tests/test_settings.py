import pytest

from opensearch_tpu.common.errors import IllegalArgumentError
from opensearch_tpu.common.settings import (
    Setting,
    Settings,
    SettingsRegistry,
    parse_bytes,
    parse_time,
)


def test_parse_time_units():
    assert parse_time("500ms") == 0.5
    assert parse_time("30s") == 30.0
    assert parse_time("2m") == 120.0
    assert parse_time("1h") == 3600.0
    assert parse_time(5) == 5.0
    assert parse_time("-1") == -1.0


def test_parse_bytes_units():
    assert parse_bytes("1kb") == 1024
    assert parse_bytes("512mb") == 512 * 1024**2
    assert parse_bytes("2gb") == 2 * 1024**3
    assert parse_bytes(100) == 100


def test_settings_flatten_and_nest():
    s = Settings({"index": {"number_of_shards": 4, "refresh_interval": "1s"}})
    assert s.get_raw("index.number_of_shards") == 4
    assert s.as_nested_dict() == {
        "index": {"number_of_shards": 4, "refresh_interval": "1s"}
    }


def test_typed_setting_defaults_and_validation():
    shards = Setting.int_setting("index.number_of_shards", 1, min_value=1, max_value=1024)
    assert shards.get(Settings.EMPTY) == 1
    assert shards.get(Settings({"index.number_of_shards": "8"})) == 8
    with pytest.raises(IllegalArgumentError):
        shards.get(Settings({"index.number_of_shards": 0}))


def test_computed_default():
    replicas = Setting.int_setting("index.number_of_replicas", 1)
    derived = Setting(
        "index.auto_expand_floor",
        lambda s: replicas.get(s) + 1,
        int,
    )
    assert derived.get(Settings({"index.number_of_replicas": 3})) == 4


def test_registry_rejects_unknown_and_non_dynamic():
    static = Setting.int_setting("node.workers", 4)
    dyn = Setting.bool_setting("cluster.routing.allocation.enable", True, dynamic=True)
    reg = SettingsRegistry(Settings.EMPTY, [static, dyn])
    with pytest.raises(IllegalArgumentError):
        reg.apply_update({"bogus.key": 1})
    with pytest.raises(IllegalArgumentError):
        reg.apply_update({"node.workers": 8})
    reg.apply_update({"cluster.routing.allocation.enable": "false"})
    assert reg.get(dyn) is False


def test_registry_update_consumer_fires():
    dyn = Setting.time_setting("index.refresh_interval", "1s", dynamic=True)
    reg = SettingsRegistry(Settings.EMPTY, [dyn])
    seen = []
    reg.add_settings_update_consumer(dyn, seen.append)
    reg.apply_update({"index.refresh_interval": "5s"})
    assert seen == [5.0]
    reg.apply_update({"index.refresh_interval": None})  # reset to default
    assert seen == [5.0, 1.0]
