import pytest

from opensearch_tpu.common.errors import MapperParsingError
from opensearch_tpu.mapping import DocumentMapper
from opensearch_tpu.mapping.types import parse_date_millis, parse_ip_long


MAPPING = {
    "properties": {
        "title": {"type": "text", "analyzer": "standard"},
        "tags": {"type": "keyword"},
        "views": {"type": "long"},
        "rating": {"type": "double"},
        "published": {"type": "date"},
        "active": {"type": "boolean"},
        "addr": {"type": "ip"},
        "embedding": {"type": "dense_vector", "dims": 4},
        "author": {"properties": {"name": {"type": "keyword"}}},
    }
}


@pytest.fixture
def mapper():
    return DocumentMapper(MAPPING)


def test_text_field_tokenized(mapper):
    doc = mapper.parse("1", {"title": "Hello Brave World"})
    assert [t for t, _ in doc.tokens["title"]] == ["hello", "brave", "world"]
    assert doc.field_lengths["title"] == 3


def test_keyword_not_tokenized(mapper):
    doc = mapper.parse("1", {"tags": "New York"})
    assert doc.tokens["tags"] == [("New York", 0)]
    assert doc.ordinals["tags"] == "New York"


def test_numeric_date_bool_ip_doc_values(mapper):
    doc = mapper.parse(
        "1",
        {"views": 42, "rating": 4.5, "published": "2024-01-15", "active": True, "addr": "10.0.0.1"},
    )
    assert doc.longs["views"] == 42
    assert doc.doubles["rating"] == 4.5
    assert doc.longs["published"] == parse_date_millis("2024-01-15")
    assert doc.longs["active"] == 1
    assert doc.longs["addr"] == parse_ip_long("10.0.0.1")


def test_nested_object_path(mapper):
    doc = mapper.parse("1", {"author": {"name": "kafka"}})
    assert doc.ordinals["author.name"] == "kafka"


def test_array_values_multi_token_with_position_gap(mapper):
    doc = mapper.parse("1", {"title": ["foo bar", "baz"]})
    terms = [t for t, _ in doc.tokens["title"]]
    assert terms == ["foo", "bar", "baz"]
    positions = [p for _, p in doc.tokens["title"]]
    assert positions[2] - positions[1] >= 100  # array position gap


def test_dense_vector_dims_checked(mapper):
    doc = mapper.parse("1", {"embedding": [1, 2, 3, 4]})
    assert doc.vectors["embedding"] == [1.0, 2.0, 3.0, 4.0]
    with pytest.raises(MapperParsingError):
        mapper.parse("2", {"embedding": [1, 2]})


def test_dynamic_mapping_string_gets_keyword_subfield():
    mapper = DocumentMapper()
    doc = mapper.parse("1", {"city": "San Francisco", "count": 3, "score": 1.5, "flag": False})
    assert [t for t, _ in doc.tokens["city"]] == ["san", "francisco"]
    assert doc.ordinals["city.keyword"] == "San Francisco"
    assert doc.longs["count"] == 3
    assert doc.doubles["score"] == 1.5
    assert doc.longs["flag"] == 0
    m = mapper.to_mapping()["properties"]
    assert m["city"]["type"] == "text"
    assert m["count"]["type"] == "long"


def test_dynamic_false_ignores_unknown():
    mapper = DocumentMapper({"dynamic": False, "properties": {"a": {"type": "long"}}})
    doc = mapper.parse("1", {"a": 1, "unknown": "x"})
    assert doc.longs["a"] == 1
    assert "unknown" not in doc.tokens and "unknown" not in doc.ordinals


def test_type_conflict_rejected(mapper):
    with pytest.raises(MapperParsingError):
        mapper.merge({"properties": {"views": {"type": "text"}}})


def test_out_of_range_integer():
    mapper = DocumentMapper({"properties": {"n": {"type": "short"}}})
    with pytest.raises(MapperParsingError):
        mapper.parse("1", {"n": 1 << 20})


def test_ignore_above_keyword():
    mapper = DocumentMapper({"properties": {"k": {"type": "keyword", "ignore_above": 3}}})
    doc = mapper.parse("1", {"k": "toolong"})
    assert "k" not in doc.tokens and "k" not in doc.ordinals


def test_date_formats():
    assert parse_date_millis("2024-01-15T10:30:00Z") == parse_date_millis("2024-01-15T10:30:00+00:00")
    assert parse_date_millis(1700000000000) == 1700000000000
    assert parse_date_millis("2024-01-15") % 86400000 == 0


def test_multifield_roundtrip_mapping(mapper):
    mapper2 = DocumentMapper(mapper.to_mapping())
    doc = mapper2.parse("1", {"tags": "x", "views": 1})
    assert doc.ordinals["tags"] == "x"
