import pytest

from opensearch_tpu.common.errors import MapperParsingError
from opensearch_tpu.mapping import DocumentMapper
from opensearch_tpu.mapping.types import parse_date_millis, parse_ip_long


MAPPING = {
    "properties": {
        "title": {"type": "text", "analyzer": "standard"},
        "tags": {"type": "keyword"},
        "views": {"type": "long"},
        "rating": {"type": "double"},
        "published": {"type": "date"},
        "active": {"type": "boolean"},
        "addr": {"type": "ip"},
        "embedding": {"type": "dense_vector", "dims": 4},
        "author": {"properties": {"name": {"type": "keyword"}}},
    }
}


@pytest.fixture
def mapper():
    return DocumentMapper(MAPPING)


def test_text_field_tokenized(mapper):
    doc = mapper.parse("1", {"title": "Hello Brave World"})
    assert [t for t, _ in doc.tokens["title"]] == ["hello", "brave", "world"]
    assert doc.field_lengths["title"] == 3


def test_keyword_not_tokenized(mapper):
    doc = mapper.parse("1", {"tags": "New York"})
    assert doc.tokens["tags"] == [("New York", 0)]
    assert doc.ordinals["tags"] == ["New York"]


def test_numeric_date_bool_ip_doc_values(mapper):
    doc = mapper.parse(
        "1",
        {"views": 42, "rating": 4.5, "published": "2024-01-15", "active": True, "addr": "10.0.0.1"},
    )
    assert doc.longs["views"] == [42]
    assert doc.doubles["rating"] == [4.5]
    assert doc.longs["published"] == [parse_date_millis("2024-01-15")]
    assert doc.longs["active"] == [1]
    assert doc.longs["addr"] == [parse_ip_long("10.0.0.1")]


def test_nested_object_path(mapper):
    doc = mapper.parse("1", {"author": {"name": "kafka"}})
    assert doc.ordinals["author.name"] == ["kafka"]


def test_array_values_multi_token_with_position_gap(mapper):
    doc = mapper.parse("1", {"title": ["foo bar", "baz"]})
    terms = [t for t, _ in doc.tokens["title"]]
    assert terms == ["foo", "bar", "baz"]
    positions = [p for _, p in doc.tokens["title"]]
    assert positions[2] - positions[1] >= 100  # array position gap


def test_dense_vector_dims_checked(mapper):
    doc = mapper.parse("1", {"embedding": [1, 2, 3, 4]})
    assert doc.vectors["embedding"] == [1.0, 2.0, 3.0, 4.0]
    with pytest.raises(MapperParsingError):
        mapper.parse("2", {"embedding": [1, 2]})


def test_dynamic_mapping_string_gets_keyword_subfield():
    mapper = DocumentMapper()
    doc = mapper.parse("1", {"city": "San Francisco", "count": 3, "score": 1.5, "flag": False})
    assert [t for t, _ in doc.tokens["city"]] == ["san", "francisco"]
    assert doc.ordinals["city.keyword"] == ["San Francisco"]
    assert doc.longs["count"] == [3]
    assert doc.doubles["score"] == [1.5]
    assert doc.longs["flag"] == [0]
    m = mapper.to_mapping()["properties"]
    assert m["city"]["type"] == "text"
    assert m["count"]["type"] == "long"


def test_dynamic_false_ignores_unknown():
    mapper = DocumentMapper({"dynamic": False, "properties": {"a": {"type": "long"}}})
    doc = mapper.parse("1", {"a": 1, "unknown": "x"})
    assert doc.longs["a"] == [1]
    assert "unknown" not in doc.tokens and "unknown" not in doc.ordinals


def test_type_conflict_rejected(mapper):
    with pytest.raises(MapperParsingError):
        mapper.merge({"properties": {"views": {"type": "text"}}})


def test_out_of_range_integer():
    mapper = DocumentMapper({"properties": {"n": {"type": "short"}}})
    with pytest.raises(MapperParsingError):
        mapper.parse("1", {"n": 1 << 20})


def test_ignore_above_keyword():
    mapper = DocumentMapper({"properties": {"k": {"type": "keyword", "ignore_above": 3}}})
    doc = mapper.parse("1", {"k": "toolong"})
    assert "k" not in doc.tokens and "k" not in doc.ordinals


def test_date_formats():
    assert parse_date_millis("2024-01-15T10:30:00Z") == parse_date_millis("2024-01-15T10:30:00+00:00")
    assert parse_date_millis(1700000000000) == 1700000000000
    assert parse_date_millis("2024-01-15") % 86400000 == 0


def test_multifield_roundtrip_mapping(mapper):
    mapper2 = DocumentMapper(mapper.to_mapping())
    doc = mapper2.parse("1", {"tags": "x", "views": 1})
    assert doc.ordinals["tags"] == ["x"]


def test_object_array_flattened(mapper):
    # ADVICE: {"comments": [{"author": "a"}, ...]} must index sub-fields
    mapper.merge({"properties": {"comments": {"properties": {"author": {"type": "keyword"}}}}})
    doc = mapper.parse("1", {"comments": [{"author": "a"}, {"author": "b"}]})
    assert doc.ordinals["comments.author"] == ["a", "b"]


def test_multi_valued_doc_values(mapper):
    doc = mapper.parse("1", {"views": [1, 2, 3], "tags": ["x", "y"]})
    assert doc.longs["views"] == [1, 2, 3]
    assert doc.ordinals["tags"] == ["x", "y"]


def test_dynamic_strict_rejects_unknown():
    from opensearch_tpu.common.errors import StrictDynamicMappingError

    mapper = DocumentMapper({"dynamic": "strict", "properties": {"a": {"type": "long"}}})
    mapper.parse("1", {"a": 1})
    with pytest.raises(StrictDynamicMappingError):
        mapper.parse("2", {"a": 1, "unknown": "x"})


def test_meta_only_mapping_does_not_crash():
    # ADVICE: {"dynamic": false} without properties must not TypeError
    mapper = DocumentMapper({"dynamic": False})
    doc = mapper.parse("1", {"anything": "x"})
    assert not doc.tokens


def test_malformed_mapping_raises():
    with pytest.raises(MapperParsingError):
        DocumentMapper({"properties": {"a": {"type": "long"}}, "bogus": 42})


def test_ip_long_order_preserving():
    # ADVICE: v6 encoding must be monotone and fit int64
    vals = ["::", "::1", "4000::", "8000::", "ffff::1", "ffff:ffff::"]
    enc = [parse_ip_long(v) for v in vals]
    assert enc[0] < enc[2] < enc[3] < enc[4] <= enc[5]
    assert all(-(2**63) <= e < 2**63 for e in enc)
    assert parse_ip_long("255.255.255.255") < parse_ip_long("::")
    assert parse_ip_long("4000::") != parse_ip_long("::")


def test_failed_merge_is_atomic(mapper):
    # A rejected merge must not change dynamic mode or add fields
    with pytest.raises(MapperParsingError):
        mapper.merge({"dynamic": "strict", "bogus": 42, "properties": {"new_f": {"type": "long"}}})
    assert mapper.dynamic == "true"
    assert mapper.field_type("new_f") is None
    with pytest.raises(MapperParsingError):
        mapper.merge({"properties": {"ok_f": {"type": "long"}, "views": {"type": "text"}}})
    assert mapper.field_type("ok_f") is None  # partial merge rolled back


def test_to_mapping_preserves_dynamic_mode():
    m = DocumentMapper({"dynamic": "strict", "properties": {"a": {"type": "long"}}})
    m2 = DocumentMapper(m.to_mapping())
    assert m2.dynamic == "strict"
