"""Real query profiling (PR 9): phase-attributed Profile API, Prometheus
metrics exposition, and the SLO-breach flight recorder.

Pinned invariants:

- profiled and unprofiled responses have byte-identical ``hits`` across
  the sequential host fast path, the XLA device path, and the
  msearch-batched path (profiling is observation, never execution);
- the per-phase breakdown keeps the OpenSearch response shape
  (``shards[].searches[].query[].breakdown``), ``rewrite_time`` is real,
  and query/collector sections are no longer double-stamped with the
  same number;
- segments scanned + pruned (+ not reached) always sums to the
  searcher's segment count, and cluster-mode shard sections sum to the
  same corpus-wide totals as a single-node profile;
- ``profile:true`` responses are never served from or stored into the
  request cache (the indices/service.py admission guard, end-to-end);
- ``GET /_metrics`` parses as Prometheus text format and reports the
  SAME bucket data ``Histogram.stats()`` now exposes as JSON;
- a slow-log trip or a soak SLO breach lands a non-empty capture in the
  flight recorder ring (``GET /_nodes/flight_recorder``).
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from opensearch_tpu.common.telemetry import (
    Histogram,
    MetricsRegistry,
    flight_recorder,
    metrics,
    tracer,
)
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.ops import bm25 as bm25_ops
from opensearch_tpu.search.executor import ShardSearcher

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")

PHASES = ("rewrite", "plan_cache", "compile", "prepare", "can_match",
          "dispatch", "reduce", "fetch")


@pytest.fixture(autouse=True)
def _clean_telemetry():
    from opensearch_tpu.indices import service as indices_mod
    tracer().reset()
    flight_recorder().reset()
    yield
    tracer().reset()
    flight_recorder().reset()
    indices_mod.SLOWLOG_DEFAULTS.clear()


def build_searcher(n_docs=60, seg_sizes=(20, 20, 20), vocab=40, seed=3):
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    writer = SegmentWriter()
    rng = np.random.default_rng(seed)
    docs = [{"body": " ".join(
        f"w{int(t)}" for t in (rng.zipf(1.4, size=12) - 1).clip(0, vocab))}
        for _ in range(n_docs)]
    segs, i = [], 0
    for si, size in enumerate(seg_sizes):
        batch = [mapper.parse(str(i + j), d)
                 for j, d in enumerate(docs[i: i + size])]
        segs.append(writer.build(batch, f"p{si}"))
        i += size
    return ShardSearcher(segs, mapper, index_name="profix")


Q = {"query": {"match": {"body": "w1 w2"}}, "size": 5}


def hits_bytes(resp) -> bytes:
    return json.dumps(resp["hits"], sort_keys=True).encode()


# -- profile response shape -------------------------------------------------

def test_breakdown_shape_and_consistency():
    s = build_searcher()
    resp = s.search(dict(Q, profile=True))
    shards = resp["profile"]["shards"]
    assert len(shards) == 1
    sec = shards[0]
    assert sec["id"] == "[profix][0]"
    search = sec["searches"][0]
    query = search["query"][0]
    bd = query["breakdown"]
    # the OpenSearch client-parseable shape, with our phase keys
    for p in PHASES:
        assert p in bd and f"{p}_count" in bd, p
        assert bd[p] >= 0
    # the stub's lies are gone: rewrite_time is the measured parse time
    # (0 only on a plan-cache hit), and query/collector sections carry
    # DIFFERENT numbers (phases, not one double-stamped elapsed)
    assert search["rewrite_time"] == bd["rewrite"]
    assert query["time_in_nanos"] == sum(
        bd[p] for p in ("rewrite", "plan_cache", "compile", "prepare",
                        "can_match", "dispatch"))
    assert search["collector"][0]["time_in_nanos"] == bd["reduce"]
    assert query["time_in_nanos"] != search["collector"][0][
        "time_in_nanos"] or bd["reduce"] == 0
    # phases sum consistently with took (took is ms-truncated, so the
    # phase sum must not exceed took+1ms; monotonic clock ⇒ no negatives)
    phase_sum_ns = sum(bd[p] for p in PHASES)
    assert phase_sum_ns <= (resp["took"] + 1) * 1_000_000
    # segments pruned vs scanned sums to the segment count
    segsum = sec["engine"]["segments"]
    assert segsum["total"] == 3
    assert (segsum["scanned"] + segsum["pruned_can_match"]
            + segsum["pruned_min_score"] + segsum["pruned_kth"]
            + segsum["not_reached"]) == segsum["total"]
    assert len(sec["segments"]) == segsum["scanned"] + sum(
        segsum[k] for k in ("pruned_can_match", "pruned_min_score",
                            "pruned_kth"))


def test_cache_attribution_hit_on_repeat():
    s = build_searcher()
    first = s.search(dict(Q, profile=True))
    second = s.search(dict(Q, profile=True))
    e1 = first["profile"]["shards"][0]["engine"]
    e2 = second["profile"]["shards"][0]["engine"]
    assert e1["plan_cache"] == "miss"
    assert e2["plan_cache"] == "hit"
    # a plan-cache hit does zero parse/compile work
    bd2 = second["profile"]["shards"][0]["searches"][0]["query"][0][
        "breakdown"]
    assert bd2["rewrite"] == 0 and bd2["compile"] == 0
    assert e1["request_cache"] == "bypass"
    assert e1["execution_path"] in ("host", "device")


def test_min_score_pruning_attribution():
    s = build_searcher()
    # a min_score far above any reachable BM25 score prunes via the
    # block-max bound; totals stay exact (pruned docs can't match)
    resp = s.search({"query": {"match": {"body": "w1"}},
                     "min_score": 1e6, "profile": True, "size": 5})
    segsum = resp["profile"]["shards"][0]["engine"]["segments"]
    assert segsum["pruned_min_score"] + segsum["pruned_can_match"] > 0
    assert resp["hits"]["total"]["value"] == 0


# -- byte-identical hits ----------------------------------------------------

@pytest.mark.parametrize("host_scoring", [True, False])
def test_hits_byte_identical_sequential(host_scoring):
    s = build_searcher()
    saved = bm25_ops.HOST_SCORING
    bm25_ops.HOST_SCORING = host_scoring
    try:
        plain = s.search(dict(Q))
        profiled = s.search(dict(Q, profile=True))
    finally:
        bm25_ops.HOST_SCORING = saved
    assert hits_bytes(plain) == hits_bytes(profiled)
    assert "profile" not in plain
    path = profiled["profile"]["shards"][0]["engine"]["execution_path"]
    assert path == ("host" if host_scoring else "device")


def test_hits_byte_identical_msearch_batched():
    s = build_searcher()
    # same (field, size) coalesce into one group; the odd size forms
    # its own group
    bodies = [dict(Q), {"query": {"match": {"body": "w3"}}, "size": 5},
              {"query": {"match": {"body": "w1"}}, "size": 4}]
    plain = s.msearch([dict(b) for b in bodies])
    profiled = s.msearch([dict(b, profile=True) for b in bodies])
    for p, pr in zip(plain, profiled):
        assert hits_bytes(p) == hits_bytes(pr)
        assert "profile" in pr and "profile" not in p
    # coalescing attribution: coalesced members report the SAME group
    groups = [r["profile"]["shards"][0]["engine"]["batch"]
              for r in profiled]
    assert groups[0] == groups[1]
    assert groups[0]["queries"] == 2
    assert sorted(groups[0]["positions"]) == [0, 1]
    assert groups[2]["queries"] == 1 and groups[2]["positions"] == [2]
    assert profiled[0]["profile"]["shards"][0]["engine"][
        "execution_path"] in ("host_batched", "device_batched")


def test_field_sorted_profile_consistent():
    s = build_searcher()
    body = {"query": {"match": {"body": "w1"}},
            "sort": [{"_doc": "asc"}], "size": 5}
    plain = s.search(dict(body))
    profiled = s.search(dict(body, profile=True))
    assert hits_bytes(plain) == hits_bytes(profiled)
    segsum = profiled["profile"]["shards"][0]["engine"]["segments"]
    assert segsum["scanned"] + segsum["not_reached"] + sum(
        segsum[k] for k in ("pruned_can_match", "pruned_min_score",
                            "pruned_kth")) == segsum["total"]


# -- request-cache guard (end-to-end) ---------------------------------------

def test_profile_never_request_cached(tmp_path):
    from opensearch_tpu.indices.request_cache import request_cache
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "n"), port=0)
    try:
        node.rest.dispatch("PUT", "/rc", {}, json.dumps({
            "mappings": {"properties": {"body": {"type": "text"}}}
        }).encode())
        for i in range(8):
            node.rest.dispatch("PUT", f"/rc/_doc/{i}", {}, json.dumps(
                {"body": f"w{i % 3} common"}).encode())
        node.rest.dispatch("GET", "/rc/_refresh", {}, None)
        body = json.dumps({"query": {"match": {"body": "common"}},
                           "size": 0}).encode()
        # size=0 requests cache by default: miss then hit
        s0 = request_cache().stats()
        node.rest.dispatch("POST", "/rc/_search", {}, body)
        node.rest.dispatch("POST", "/rc/_search", {}, body)
        s1 = request_cache().stats()
        assert s1["miss_count"] - s0["miss_count"] == 1
        assert s1["hit_count"] - s0["hit_count"] == 1
        # the same query with profile:true NEVER touches the cache —
        # not served from it (the response must carry a fresh profile)
        # and not stored into it
        pbody = json.dumps({"query": {"match": {"body": "common"}},
                            "size": 0, "profile": True}).encode()
        st, resp = node.rest.dispatch("POST", "/rc/_search", {}, pbody)
        assert st == 200 and resp.get("profile"), \
            "profiled request served without a profile section"
        s2 = request_cache().stats()
        assert s2["hit_count"] == s1["hit_count"]
        assert s2["miss_count"] == s1["miss_count"]
        assert s2["entries"] == s1["entries"]
        # and the cached unprofiled entry is still served clean
        st, resp = node.rest.dispatch("POST", "/rc/_search", {}, body)
        assert st == 200 and "profile" not in resp
        s3 = request_cache().stats()
        assert s3["hit_count"] - s2["hit_count"] == 1
    finally:
        node.stop()


# -- cluster-mode merge -----------------------------------------------------

def _wait(pred, timeout=20.0):
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:   # deadline
        if pred():
            return
        import time as _t
        _t.sleep(0.02)                   # deadline
    raise AssertionError("timed out")


def test_cluster_profile_merge_matches_single_node(tmp_path):
    from opensearch_tpu.cluster.node import ClusterNode
    from opensearch_tpu.transport.service import (LocalTransport,
                                                  TransportService)
    hub = LocalTransport.Hub()
    ids = ["n0", "n1", "n2"]
    nodes = {}
    for nid in ids:
        svc = TransportService(nid, LocalTransport(hub))
        node = ClusterNode(nid, str(tmp_path / nid), svc, ids)
        node.search_backpressure.trackers["cpu_usage"].probe = \
            lambda: 0.0
        nodes[nid] = node
    try:
        assert nodes["n0"].start_election()
        _wait(lambda: all(nodes[i].coordinator.state().master_node
                          == "n0" for i in ids))
        nodes["n0"].create_index("cp", {
            "settings": {"number_of_shards": 2,
                         "number_of_replicas": 1},
            "mappings": {"properties": {"body": {"type": "text"}}}})

        def in_sync():
            routing = nodes["n0"].coordinator.state().routing.get(
                "cp", [])
            return routing and all(
                set(e["in_sync"]) == {e["primary"], *e["replicas"]}
                for e in routing)
        _wait(in_sync)
        docs = [{"body": f"w{i % 4} w{(i + 1) % 5} common"}
                for i in range(24)]
        for i, d in enumerate(docs):
            nodes["n0"].index_doc("cp", str(i), d)
        nodes["n0"].refresh("cp")

        body = {"query": {"match": {"body": "common w1"}}, "size": 10}
        plain = nodes["n1"].search("cp", dict(body))
        profiled = nodes["n1"].search("cp", dict(body, profile=True))
        # profiling never changes cluster results either
        assert hits_bytes(plain) == hits_bytes(profiled)
        prof = profiled["profile"]
        assert prof["coordinator"]["sources"] >= 1
        assert prof["coordinator"]["reduce_time_in_nanos"] >= 0
        assert prof["coordinator"]["scatter_time_in_nanos"] > 0
        sections = prof["shards"]
        assert sections, "cluster profile lost its shard sections"
        total_cluster_segments = 0
        for sec in sections:
            group = sec["shard_group"]
            # every section names the copy that served it + provenance
            assert group["node"] in ids
            assert "c3_rank" in group and "in_duress" in group
            assert group["failover_attempts"] >= 0
            assert all("rerouted" in p and "legacy_order" in p
                       for p in group.get("selection", []))
            segsum = sec["engine"]["segments"]
            reached = sum(segsum[k] for k in (
                "scanned", "pruned_can_match", "pruned_min_score",
                "pruned_kth", "not_reached"))
            assert reached == segsum["total"]
            total_cluster_segments += segsum["total"]

        # shard sections sum consistently with a single-node view of
        # the same corpus: same doc->shard routing, same refresh point
        # => the same total segment count, just partitioned over nodes
        from opensearch_tpu.node import Node
        solo = Node(str(tmp_path / "solo"), port=0)
        try:
            solo.rest.dispatch("PUT", "/cp", {}, json.dumps({
                "settings": {"number_of_shards": 2},
                "mappings": {"properties": {"body": {"type": "text"}}},
            }).encode())
            for i, d in enumerate(docs):
                solo.rest.dispatch("PUT", f"/cp/_doc/{i}", {},
                                   json.dumps(d).encode())
            solo.rest.dispatch("GET", "/cp/_refresh", {}, None)
            st, resp = solo.rest.dispatch(
                "POST", "/cp/_search", {},
                json.dumps(dict(body, profile=True)).encode())
            assert st == 200
            solo_sections = resp["profile"]["shards"]
            solo_total = sum(s["engine"]["segments"]["total"]
                             for s in solo_sections)
            assert total_cluster_segments == solo_total
            # both report the same phase vocabulary
            solo_bd = solo_sections[0]["searches"][0]["query"][0][
                "breakdown"]
            cluster_bd = sections[0]["searches"][0]["query"][0][
                "breakdown"]
            assert set(solo_bd) == set(cluster_bd)
        finally:
            solo.stop()
    finally:
        for n in nodes.values():
            n.stop()


# -- /_metrics Prometheus exposition ----------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})? "
    r"[-+]?[0-9]+(\.[0-9]+)?([eE][-+]?[0-9]+)?$")


def test_metrics_endpoint_is_valid_prometheus_text(tmp_path):
    from opensearch_tpu.node import Node
    from opensearch_tpu.rest.controller import PlainText
    node = Node(str(tmp_path / "n"), port=0)
    try:
        node.rest.dispatch("PUT", "/m", {}, b"{}")
        node.rest.dispatch("PUT", "/m/_doc/1", {},
                           json.dumps({"x": 1}).encode())
        node.rest.dispatch("GET", "/m/_refresh", {}, None)
        node.rest.dispatch("POST", "/m/_search", {}, json.dumps(
            {"query": {"match_all": {}}}).encode())
        st, payload = node.rest.dispatch("GET", "/_metrics", {}, None)
        assert st == 200 and isinstance(payload, PlainText)
        assert payload.content_type.startswith("text/plain")
        text = payload.text
        assert text.endswith("\n")
        names_typed = {}
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("# TYPE "):
                _, _, name, kind = line.split(" ")
                names_typed[name] = kind
                continue
            if line.startswith("#"):
                continue
            assert _PROM_LINE.match(line), f"invalid line: {line!r}"
        assert any(k == "counter" for k in names_typed.values())
        assert any(k == "histogram" for k in names_typed.values())

        # histogram series are complete and cumulative, and report the
        # same underlying data as the JSON stats() buckets
        hname = "search_query_ms"
        buckets = []
        sum_v = count_v = None
        for line in text.splitlines():
            if line.startswith(f"{hname}_bucket"):
                le = line.split('le="')[1].split('"')[0]
                buckets.append((le, int(line.rsplit(" ", 1)[1])))
            elif line.startswith(f"{hname}_sum "):
                sum_v = float(line.rsplit(" ", 1)[1])
            elif line.startswith(f"{hname}_count "):
                count_v = int(line.rsplit(" ", 1)[1])
        assert buckets and buckets[-1][0] == "+Inf"
        counts = [c for _le, c in buckets]
        assert counts == sorted(counts)          # cumulative
        assert counts[-1] == count_v and sum_v is not None
        jstats = metrics().histogram("search.query_ms").stats()
        assert [b["count"] for b in jstats["buckets"]] == counts
    finally:
        node.stop()


def test_histogram_stats_buckets_unit():
    h = Histogram("t.unit", buckets=(1, 10, 100))
    for v in (0.5, 5, 5, 50, 5000):
        h.observe(v)
    st = h.stats()
    assert [b["le"] for b in st["buckets"]] == [1.0, 10.0, 100.0,
                                                "+Inf"]
    assert [b["count"] for b in st["buckets"]] == [1, 3, 4, 5]
    assert st["count"] == 5
    # prometheus rendering agrees with the JSON readout
    reg = MetricsRegistry()
    reg.histogram("t.unit", buckets=(1, 10, 100))
    for v in (0.5, 5, 5, 50, 5000):
        reg.histogram("t.unit").observe(v)
    text = reg.prometheus_text()
    assert 't_unit_ms_bucket{le="10"} 3' in text
    assert 't_unit_ms_bucket{le="+Inf"} 5' in text
    assert "t_unit_ms_count 5" in text


# -- flight recorder --------------------------------------------------------

def test_slowlog_trip_records_flight_capture(tmp_path):
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "n"), port=0)
    try:
        node.rest.dispatch("PUT", "/fr", {}, json.dumps({
            "settings": {"index": {"search": {"slowlog": {"threshold": {
                "query": {"warn": "0ms"}}}}}},
            "mappings": {"properties": {"body": {"type": "text"}}},
        }).encode())
        node.rest.dispatch("PUT", "/fr/_doc/1", {},
                           json.dumps({"body": "hello"}).encode())
        node.rest.dispatch("GET", "/fr/_refresh", {}, None)
        node.rest.dispatch("POST", "/fr/_search", {}, json.dumps(
            {"query": {"match": {"body": "hello"}},
             "profile": True}).encode())
        caps = flight_recorder().captures()
        assert caps and caps[0]["trigger"] == "slow_log"
        assert caps[0]["detail"]["index"] == "fr"
        assert caps[0]["detail"]["profile"]["shards"]
        assert caps[0]["counters"]
        # retrievable over REST
        st, resp = node.rest.dispatch("GET", "/_nodes/flight_recorder",
                                      {}, None)
        assert st == 200
        rest_caps = resp["nodes"][node.node_id]["captures"]
        assert rest_caps and rest_caps[0]["trigger"] == "slow_log"
    finally:
        node.stop()


def test_soak_breach_attaches_flight_capture(tmp_path):
    """A forced SLO breach (impossible p99 limit) must ship a non-empty
    flight-recorder capture ON the breach verdict."""
    from opensearch_tpu.testing.workload import SoakConfig, SoakRunner
    cfg = SoakConfig.smoke(
        n_ops=8, n_docs=8, faults_enabled=False, control_run=False,
        slos={"p99_ms": {"search": -1.0},
              "max_rejection_rate": 1.0,
              "max_unexpected_errors": 1000,
              "require_convergence": False})
    report = SoakRunner(str(tmp_path), cfg).run()
    breached = [v for v in report["verdicts"] if not v["ok"]]
    assert breached, "forced breach did not breach"
    for v in breached:
        cap = v["flight_recorder"]
        assert cap["trigger"] == "slo_breach"
        assert v["slo"] in cap["reason"]
        assert cap["counters"], "capture carries no evidence"
        assert cap["detail"]["limit"] == v["limit"]
    assert not report["slo_ok"]


def test_client_metrics_and_flight_recorder_roundtrip(tmp_path):
    """The Python client surfaces both new endpoints: ``metrics()``
    returns the raw Prometheus text, ``nodes.flight_recorder()`` the
    capture ring."""
    from opensearch_tpu.client import OpenSearch
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "n"), port=0).start()
    try:
        client = OpenSearch(
            [{"host": "127.0.0.1", "port": node.port}])
        client.index("c", {"x": 1}, id="1")
        text = client.metrics()
        assert isinstance(text, str) and "_total" in text
        flight_recorder().record("slow_log", "test capture")
        resp = client.nodes.flight_recorder()
        caps = resp["nodes"][node.node_id]["captures"]
        assert caps and caps[0]["reason"] == "test capture"
    finally:
        node.stop()


# -- metric-name lint -------------------------------------------------------

def test_check_metric_names_lint_passes():
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_metric_names.py")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_check_metric_names_lint_catches_violations(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f(term):\n"
        "    metrics().counter(f\"q.{term}.hits\").inc()\n"
        "    metrics().histogram(\"UpperCase.Name\").observe(1)\n"
        "    metrics().counter(\"noDotsHere\").inc()\n"
        "    metrics().counter(\"fine.dotted.name\").inc()\n"
        "    metrics().counter(f\"q.{term}\").inc()  # metric-name-ok\n")
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_metric_names.py"),
         str(bad)],
        capture_output=True, text=True)
    assert r.returncode == 1
    assert "3 metric-name violation(s)" in r.stdout
