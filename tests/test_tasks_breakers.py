"""Task management / cooperative cancellation and circuit breakers
(VERDICT r3 item 10; ref tasks/TaskManager.java:1,
indices/breaker/HierarchyCircuitBreakerService.java:1)."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from opensearch_tpu.common.breakers import (CircuitBreakerService,
                                            CircuitBreakingError, install,
                                            breaker_service)
from opensearch_tpu.common.tasks import (TaskCancelledException,
                                         TaskManager, check_current,
                                         reset_current, set_current)
from opensearch_tpu.node import Node


@pytest.fixture()
def node(tmp_path):
    n = Node(str(tmp_path / "node"), port=0).start()
    yield n
    n.stop()


def call(node, method, path, body=None):
    url = f"http://127.0.0.1:{node.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else {}


# -- task manager unit ------------------------------------------------------


def test_task_register_cancel_cooperative():
    tm = TaskManager()
    t = tm.register("indices:data/read/search", "test query")
    assert tm.get(t.id) is t
    token = set_current(t)
    try:
        check_current()                     # not cancelled: no-op
        t.cancel("test reason")
        with pytest.raises(TaskCancelledException):
            check_current()
    finally:
        reset_current(token)
    tm.unregister(t)
    assert tm.get(t.id) is None


def test_task_cancel_by_action_pattern():
    tm = TaskManager()
    s1 = tm.register("indices:data/read/search")
    s2 = tm.register("indices:data/read/search")
    b = tm.register("indices:data/write/bulk")
    done = tm.cancel(actions="indices:data/read/*")
    assert {t.id for t in done} == {s1.id, s2.id}
    assert not b.cancelled


def test_search_aborts_between_segments(tmp_path):
    """A task cancelled mid-search stops at the next segment boundary."""
    from opensearch_tpu.index.segment import SegmentWriter
    from opensearch_tpu.mapping.mapper import DocumentMapper
    from opensearch_tpu.search.executor import ShardSearcher

    mapper = DocumentMapper({"properties": {"t": {"type": "text"}}})
    writer = SegmentWriter()
    segs = [writer.build([mapper.parse(f"{s}-{i}", {"t": "word common"})
                          for i in range(4)], f"c{s}") for s in range(3)]
    searcher = ShardSearcher(segs, mapper)
    tm = TaskManager()
    t = tm.register("indices:data/read/search")
    t.cancel("pre-cancelled")
    token = set_current(t)
    try:
        with pytest.raises(TaskCancelledException):
            searcher.search({"query": {"match": {"t": "common"}}})
    finally:
        reset_current(token)


# -- tasks REST -------------------------------------------------------------


def test_tasks_rest_surface(node):
    code, resp = call(node, "GET", "/_tasks")
    assert code == 200
    tasks = resp["nodes"][node.node_id]["tasks"]
    # the _tasks request itself is a registered task
    assert any(t["action"] == "rest:h_tasks_list" for t in tasks.values())
    code, resp = call(node, "GET", "/_tasks/999999")
    assert code == 404
    code, resp = call(node, "POST", "/_tasks/999999/_cancel")
    assert code == 404
    code, resp = call(node, "POST",
                      "/_tasks/_cancel?actions=indices:data/read/*")
    assert code == 200


def test_cancel_running_scroll_task(node):
    """Cancel a real in-flight search via the REST task API: a slow
    request observed in /_tasks, cancelled, aborts with 400."""
    call(node, "PUT", "/big", {"mappings": {"properties": {
        "t": {"type": "text"}}}})
    for i in range(50):
        call(node, "PUT", f"/big/_doc/{i}", {"t": "common filler"})
        if i % 10 == 9:
            call(node, "POST", "/big/_refresh")   # several segments
    call(node, "POST", "/big/_refresh")

    results = {}

    def slow_search():
        results["resp"] = call(node, "POST", "/big/_search",
                               {"query": {"match": {"t": "common"}}})

    # race a cancel-all against the search; whichever wins, the system
    # stays consistent — assert the cancel path produces a 400 when it
    # lands first by pre-cancelling via the action filter repeatedly
    thread = threading.Thread(target=slow_search)
    canceller = threading.Thread(
        target=lambda: [call(node, "POST",
                             "/_tasks/_cancel?actions=indices:data/read/search")
                        for _ in range(50)])
    thread.start()
    canceller.start()
    thread.join()
    canceller.join()
    code, _body = results["resp"]
    assert code in (200, 400)              # completed or cleanly cancelled


# -- breakers ---------------------------------------------------------------


def test_breaker_child_and_parent_trip():
    svc = CircuitBreakerService({"breaker.total.limit": 1000,
                                 "breaker.fielddata.limit": 600,
                                 "breaker.request.limit": 600})
    svc.fielddata.add_estimate(500, "a")
    with pytest.raises(CircuitBreakingError):
        svc.fielddata.add_estimate(200, "b")       # child limit
    svc.request.add_estimate(400, "c")
    with pytest.raises(CircuitBreakingError):
        svc.request.add_estimate(150, "d")         # parent limit
    svc.fielddata.release(500)
    svc.request.add_estimate(150, "e")             # parent freed
    stats = svc.stats()
    assert stats["fielddata"]["tripped"] == 1
    assert stats["parent"]["tripped"] == 1
    assert stats["request"]["estimated_size_in_bytes"] == 550


def test_staging_rejected_when_over_budget():
    """A segment whose staged footprint exceeds the fielddata budget is
    rejected with 429 BEFORE any device allocation."""
    from opensearch_tpu.index.segment import SegmentWriter
    from opensearch_tpu.mapping.mapper import DocumentMapper

    mapper = DocumentMapper({"properties": {"t": {"type": "text"}}})
    writer = SegmentWriter()
    seg = writer.build([mapper.parse(str(i), {"t": f"word{i} common"})
                        for i in range(200)], "budget0")
    tiny = CircuitBreakerService({"breaker.total.limit": 4096,
                                  "breaker.fielddata.limit": 2048})
    prev = breaker_service()
    install(tiny)
    try:
        with pytest.raises(CircuitBreakingError):
            seg.device()
    finally:
        install(prev)
    seg.device()                            # fine under the default budget


def test_breakers_visible_in_node_stats(node):
    code, resp = call(node, "GET", "/_nodes/stats")
    assert code == 200
    breakers = resp["nodes"][node.node_id]["breakers"]
    for name in ("fielddata", "request", "in_flight_requests", "parent"):
        assert name in breakers
        assert "limit_size_in_bytes" in breakers[name]


def test_review_fixes_round4(node):
    """Regressions from the round-4 review: bad scroll keepalive doesn't
    leak breaker bytes; script arity errors are 400; zero-sum weights
    rejected."""
    from opensearch_tpu.common.breakers import breaker_service
    call(node, "PUT", "/rf", {"mappings": {"properties": {
        "t": {"type": "text"}}}})
    call(node, "PUT", "/rf/_doc/1", {"t": "x common"})
    call(node, "POST", "/rf/_refresh")
    before = breaker_service().request.used
    code, _ = call(node, "POST", "/rf/_search?scroll=bogus",
                   {"query": {"match_all": {}}})
    assert code == 400
    assert breaker_service().request.used == before       # no leak
    code, _ = call(node, "POST", "/rf/_search", {"query": {"script_score": {
        "query": {"match_all": {}},
        "script": {"source": "min(1, 2, 3)"}}}})
    assert code == 400
    code, _ = call(node, "POST", "/rf/_search", {"query": {"script_score": {
        "query": {"match_all": {}},
        "script": {"source": "params.qv * 2",
                   "params": {"qv": ["a", "b"]}}}}})
    assert code == 400
    code, _ = call(node, "PUT", "/_search/pipeline/z", {
        "phase_results_processors": [{"normalization-processor": {
            "combination": {"technique": "arithmetic_mean",
                            "parameters": {"weights": [0, 0]}}}}]})
    assert code == 400


def test_in_flight_breaker_and_fs_health(node, tmp_path):
    """HTTP bodies charge the in_flight breaker (oversized -> 429 before
    the body is buffered); fs health probes report in _nodes/stats."""
    from opensearch_tpu.common.breakers import (CircuitBreakerService,
                                                breaker_service, install)
    from opensearch_tpu.common.fshealth import FsHealthService

    code, resp = call(node, "GET", "/_nodes/stats")
    assert resp["nodes"][node.node_id]["fs"]["health"]["status"] == \
        "healthy"
    tiny = CircuitBreakerService({"breaker.total.limit": 10_000,
                                  "breaker.inflight.limit": 64})
    prev = breaker_service()
    install(tiny)
    try:
        code, resp = call(node, "PUT", "/inflight/_doc/1",
                          {"pad": "x" * 500})
        assert code == 429
        assert tiny.in_flight.used == 0            # released after reject
        code, _ = call(node, "PUT", "/inflight/_doc/1", {"p": 1})
        assert code in (200, 201)                   # small body fine
    finally:
        install(prev)
    # fs health: unhealthy path reports the failure
    svc = FsHealthService(str(tmp_path / "nope" / "deeper"))
    assert svc.check() is False
    assert svc.stats()["status"] == "unhealthy"
    assert "reason" in svc.stats()


def test_thread_pool_stats_and_rejection(node):
    from opensearch_tpu.common.threadpool import (RejectedExecutionError,
                                                  ThreadPool, _Pool)
    code, resp = call(node, "GET", "/_nodes/stats")
    tp = resp["nodes"][node.node_id]["thread_pool"]
    for name in ("search", "write", "get", "generic", "snapshot",
                 "management"):
        assert name in tp and tp[name]["threads"] >= 1
    # bounded queue rejects with 429 semantics
    import threading as _t
    gate = _t.Event()
    pool = _Pool("t", size=1, queue_cap=1)
    try:
        f1 = pool.submit(gate.wait)            # occupies the worker...
        import time as _time
        deadline = _time.monotonic() + 5
        while pool.stats()["queue"] > 0:       # ...until the worker took it
            if _time.monotonic() > deadline:
                raise AssertionError("worker never dequeued f1")
            _time.sleep(0.01)
        f2 = pool.submit(gate.wait)            # queued
        import pytest as _pytest
        with _pytest.raises(RejectedExecutionError):
            pool.submit(gate.wait)
        assert pool.stats()["rejected"] == 1
    finally:
        gate.set()
        f1.result(timeout=5)
        f2.result(timeout=5)
        pool.shutdown()


def test_indexing_pressure_accounting_and_rejection():
    """ShardIndexingPressure analog: in-flight bytes tracked per shard,
    node limit rejects with 429, per-shard cap keeps one hot shard from
    starving the rest (VERDICT r4 item 9)."""
    import pytest as _pytest

    from opensearch_tpu.common.indexing_pressure import (
        IndexingPressure, IndexingPressureRejection)

    ip = IndexingPressure(limit_bytes=1000, shard_fraction=0.5)
    with ip.coordinating(("i", 0), 600):
        st = ip.stats()
        assert st["memory"]["current"]["coordinating_in_bytes"] == 600
        # node limit: 600 + 500 > 1000
        with _pytest.raises(IndexingPressureRejection):
            with ip.coordinating(("i", 1), 500):
                pass
        # per-shard cap with another shard active: shard 1 may take at
        # most 500 while shard 0 is in flight — 300 is fine
        with ip.coordinating(("i", 1), 300):
            pass
    # fully released
    st = ip.stats()
    assert st["memory"]["current"]["coordinating_in_bytes"] == 0
    assert st["memory"]["total"]["coordinating_rejections"] == 1
    # a single shard alone may use the whole node budget
    with ip.coordinating(("i", 0), 990):
        pass


def test_indexing_pressure_rejects_through_rest(tmp_path, monkeypatch):
    monkeypatch.setenv("OSTPU_INDEXING_PRESSURE_LIMIT", "200")
    from opensearch_tpu.node import Node
    node = Node(str(tmp_path / "ipnode"), port=0).start()
    try:
        code, resp = call(node, "PUT", "/ip/_doc/1", {"pad": "x" * 50})
        assert code == 201
        code, resp = call(node, "PUT", "/ip/_doc/2", {"pad": "x" * 500})
        assert code == 429
        assert "indexing_pressure" in resp["error"]["reason"]
        code, resp = call(node, "GET", "/_nodes/stats")
        stats = resp["nodes"][node.node_id]["indexing_pressure"]
        assert stats["memory"]["total"]["coordinating_rejections"] >= 1
        assert resp["nodes"][node.node_id]["process"][
            "open_file_descriptors"] != 0
    finally:
        node.stop()
