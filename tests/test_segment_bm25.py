"""Segment writer + BM25 kernel vs a scalar numpy oracle.

Mirrors the reference's correctness bar for the query phase: top-k ids and
scores must match doc-at-a-time BM25 (ContextIndexSearcher.java:318
semantics: ascending-doc-id tie-break, collection-wide idf/avgdl).
"""

import math
import random

import numpy as np
import pytest

from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.ops import bm25

K1, B = 1.2, 0.75

VOCAB = [f"w{i}" for i in range(50)]


@pytest.fixture(scope="module")
def corpus():
    rng = random.Random(42)
    docs = []
    for i in range(500):
        body = " ".join(rng.choice(VOCAB) for _ in range(rng.randint(3, 40)))
        docs.append({"body": body})
    return docs


@pytest.fixture(scope="module")
def segment(corpus):
    mapper = DocumentMapper({"properties": {"body": {"type": "text"}}})
    parsed = [mapper.parse(str(i), d) for i, d in enumerate(corpus)]
    return SegmentWriter().build(parsed, "seg0")


def oracle_scores(corpus, terms):
    """Doc-at-a-time float64 BM25 over whitespace-tokenized bodies."""
    tokenized = [d["body"].lower().split() for d in corpus]
    n = len(corpus)
    dls = [len(t) for t in tokenized]
    avgdl = sum(dls) / n
    scores = np.zeros(n)
    for term in terms:
        df = sum(1 for t in tokenized if term in t)
        if df == 0:
            continue
        idf = math.log(1 + (n - df + 0.5) / (df + 0.5))
        for i, toks in enumerate(tokenized):
            tf = toks.count(term)
            if tf:
                norm = K1 * (1 - B + B * dls[i] / avgdl)
                scores[i] += idf * tf / (tf + norm)
    return scores


def run_kernel(segment, corpus, terms, k=10):
    dev = segment.device()
    pf = segment.postings["body"]
    arrs = dev.postings["body"]
    n = segment.n_docs
    avgdl = pf.total_len / max(pf.docs_with_field, 1)
    tids, idfs, active = [], [], []
    for t in terms:
        tid = pf.term_id(t)
        if tid < 0:
            tids.append(0), idfs.append(0.0), active.append(False)
        else:
            tids.append(tid)
            idfs.append(bm25.idf(int(pf.df[tid]), n))
            active.append(True)
    total = sum(int(pf.df[t]) for t, a in zip(tids, active) if a)
    budget = max(8, 1 << (total - 1).bit_length())
    scores = bm25.bm25_scores(
        arrs["offsets"], arrs["doc_ids"], arrs["tfs"], arrs["doc_lens"],
        np.asarray(tids, np.int32), np.asarray(active),
        np.asarray(idfs, np.float32), np.ones(len(tids), np.float32),
        np.float32(avgdl), n_pad=dev.n_pad, budget=budget)
    scores = np.asarray(scores)
    vals, idx = bm25.topk(np.where(np.arange(dev.n_pad) < n, scores, -np.inf), k)
    return np.asarray(scores[:n]), np.asarray(vals), np.asarray(idx)


def test_single_term_matches_oracle(segment, corpus):
    want = oracle_scores(corpus, ["w3"])
    got, _, _ = run_kernel(segment, corpus, ["w3"])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_multi_term_matches_oracle(segment, corpus):
    terms = ["w1", "w7", "w33"]
    want = oracle_scores(corpus, terms)
    got, vals, idx = run_kernel(segment, corpus, terms)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # top-10 ordering matches oracle ordering (score desc, doc id asc)
    order = sorted(range(len(want)), key=lambda i: (-want[i], i))[:10]
    assert list(idx) == order


def test_absent_term_contributes_nothing(segment, corpus):
    got, _, _ = run_kernel(segment, corpus, ["nosuchterm", "w5"])
    want = oracle_scores(corpus, ["w5"])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_match_count_conjunction(segment, corpus):
    terms = ["w1", "w2"]
    dev = segment.device()
    pf = segment.postings["body"]
    arrs = dev.postings["body"]
    tids = np.asarray([pf.term_id(t) for t in terms], np.int32)
    counts = bm25.match_count(
        arrs["offsets"], arrs["doc_ids"], arrs["tfs"], tids,
        np.asarray([True, True]), n_pad=dev.n_pad, budget=2048)
    counts = np.asarray(counts)[: segment.n_docs]
    for i, d in enumerate(corpus):
        toks = set(d["body"].split())
        assert counts[i] == sum(1 for t in terms if t in toks)


def test_multivalued_numeric_dv(segment):
    # built from a different mapper run: array fields land all values
    mapper = DocumentMapper({"properties": {"n": {"type": "long"}}})
    docs = [mapper.parse(str(i), {"n": v}) for i, v in
            enumerate([[3, 1, 2], 7, [], [5, 5]])]
    seg = SegmentWriter().build(docs, "s")
    dv = seg.numeric_dv["n"]
    assert dv.values.tolist() == [1, 2, 3, 7, 5, 5]
    assert dv.value_docs.tolist() == [0, 0, 0, 1, 3, 3]
    assert dv.minv[0] == 1 and dv.maxv[0] == 3
    assert not dv.exists[2]
