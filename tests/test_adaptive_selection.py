"""Adaptive replica selection & coordinator-side load shedding (PR 6;
ref node/ResponseCollectorService.java + the C3 rank in
ComputedNodeStats, OperationRouting.rankShardsAndUpdateStats): per-node
response/service/queue EWMAs piggybacked on shard responses and
fault-detection pings, C3-ranked copy ordering with duress derank,
msearch replica spill, and duress shedding into partial results — all
deterministic (injectable clocks, seeded fault injection)."""

import json
import subprocess
import sys
import threading
import time

import pytest

from opensearch_tpu.cluster.node import A_SEARCH_SHARDS, ClusterNode
from opensearch_tpu.cluster import response_collector as rc
from opensearch_tpu.cluster.response_collector import (
    Ewma, ResponseCollectorService)
from opensearch_tpu.cluster.state import copies_of
from opensearch_tpu.common.telemetry import metrics
from opensearch_tpu.node import Node
from opensearch_tpu.testing.fault_injection import FaultInjector
from opensearch_tpu.transport.service import (LocalTransport,
                                              TransportService)

TOOLS = __file__.rsplit("/tests/", 1)[0] + "/tools"


def wait_until(pred, timeout=8.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:   # deadline-bounded poll
        if pred():
            return True
        time.sleep(0.05)
    return False


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- EWMA + rank unit layer -------------------------------------------------

def test_ewma_first_sample_then_decay():
    e = Ewma(alpha=0.3)
    assert e.value is None               # "no evidence" != "fast"
    assert e.add(100.0) == 100.0         # first sample seeds the average
    assert e.add(200.0) == pytest.approx(0.3 * 200 + 0.7 * 100)
    # decay toward a sustained new level
    for _ in range(50):
        e.add(10.0)
    assert e.value == pytest.approx(10.0, rel=1e-3)


def test_rank_reflects_response_service_and_queue():
    clock = FakeClock()
    c = ResponseCollectorService(clock=clock)
    c.record_response("fast", 1e6, {"queue_size": 0,
                                    "service_time_ewma_nanos": 1e6})
    c.record_response("slow", 80e6, {"queue_size": 0,
                                     "service_time_ewma_nanos": 80e6})
    c.record_response("queued", 1e6, {"queue_size": 40,
                                      "service_time_ewma_nanos": 1e6})
    assert c.rank("fast") < c.rank("slow")
    assert c.rank("fast") < c.rank("queued")   # cubed queue term bites
    assert c.rank("missing") is None


def test_rank_copies_without_evidence_preserves_legacy_order():
    c = ResponseCollectorService(clock=FakeClock())
    ordered, rerouted = c.rank_copies(["n2", "n0", "n1"])
    assert ordered == ["n2", "n0", "n1"]
    assert rerouted is False


def test_rank_copies_deranks_slow_node_and_flags_reroute():
    c = ResponseCollectorService(clock=FakeClock())
    c.record_response("n2", 300e6, {"service_time_ewma_nanos": 300e6})
    c.record_response("n0", 1e6, {"service_time_ewma_nanos": 1e6})
    ordered, rerouted = c.rank_copies(["n2", "n0"])
    assert ordered == ["n0", "n2"] and rerouted is True
    # an unprobed replica ranks at the fleet mean: it beats the watched
    # slow copy but does not displace a copy performing at par
    ordered, rerouted = c.rank_copies(["n2", "n1"])
    assert ordered == ["n1", "n2"] and rerouted is True
    ordered, rerouted = c.rank_copies(["n0", "n1"])
    assert ordered[0] == "n0" and rerouted is False


def test_record_failure_penalizes_harder_each_time():
    c = ResponseCollectorService(clock=FakeClock())
    c.record_response("n1", 1e6, {"service_time_ewma_nanos": 1e6})
    c.record_response("n2", 1e6, {"service_time_ewma_nanos": 1e6})
    r0 = c.rank("n2")
    c.record_failure("n2", 0.5e9)
    r1 = c.rank("n2")
    c.record_failure("n2", 0.5e9)        # repeated timeouts compound
    r2 = c.rank("n2")
    assert r0 < r1 < r2
    assert c.rank_copies(["n2", "n1"])[0] == ["n1", "n2"]
    assert c.stats()["n2"]["failure_count"] == 2


def test_duress_flag_expires_on_injectable_clock():
    clock = FakeClock()
    c = ResponseCollectorService(clock=clock, duress_ttl_s=5.0)
    c.record_duress("n1", True)
    assert c.in_duress("n1")
    clock.advance(4.9)
    assert c.in_duress("n1")             # still fresh
    clock.advance(0.2)
    assert not c.in_duress("n1")         # stale: probe the node again
    c.record_ping_load("n1", {"duress": True, "queue_size": 1})
    assert c.in_duress("n1")             # ping refreshed the horizon
    c.record_ping_load("n1", {"duress": False, "queue_size": 0})
    assert not c.in_duress("n1")


def test_duress_deranks_but_retains():
    c = ResponseCollectorService(clock=FakeClock())
    c.record_duress("n0", True)
    ordered, rerouted = c.rank_copies(["n0", "n1", "n2"])
    assert ordered == ["n1", "n2", "n0"]   # last resort, never dropped
    assert rerouted is True


def test_stats_block_shape():
    clock = FakeClock()
    c = ResponseCollectorService(clock=clock)
    c.record_response("n1", 2e6, {"queue_size": 3, "duress": True,
                                  "service_time_ewma_nanos": 1e6})
    clock.advance(1.5)
    s = c.stats()["n1"]
    assert s["avg_response_time_ms"] == pytest.approx(2.0)
    assert s["avg_service_time_ms"] == pytest.approx(1.0)
    assert s["avg_queue_size"] == pytest.approx(3.0)
    assert s["in_duress"] is True and s["response_count"] == 1
    assert s["since_last_update_s"] == pytest.approx(1.5)
    assert isinstance(s["rank"], float)


# -- cluster fixture --------------------------------------------------------

@pytest.fixture
def cluster(tmp_path):
    hub = LocalTransport.Hub()
    ids = ["n0", "n1", "n2"]
    nodes = {}
    for nid in ids:
        svc = TransportService(nid, LocalTransport(hub))
        node = ClusterNode(nid, str(tmp_path / nid), svc, ids)
        # neutralize the real CPU probe: a loaded CI host must not leak
        # genuine duress into these deterministic scenarios
        node.search_backpressure.trackers["cpu_usage"].probe = lambda: 0.0
        nodes[nid] = node
    assert nodes["n0"].start_election()
    wait_until(lambda: all(
        nodes[i].coordinator.state().master_node == "n0" for i in ids))
    yield hub, ids, nodes
    for n in nodes.values():
        n.stop()


def _make_index(nodes, name, shards, replicas):
    nodes["n0"].create_index(name, {
        "settings": {"number_of_shards": shards,
                     "number_of_replicas": replicas},
        "mappings": {"properties": {"v": {"type": "long"}}}})

    def in_sync_full():
        routing = nodes["n0"].coordinator.state().routing.get(name, [])
        return routing and all(
            set(e["in_sync"]) == {e["primary"], *e["replicas"]}
            and len(e["replicas"]) >= replicas for e in routing)
    assert wait_until(in_sync_full)
    for i in range(20):
        nodes["n0"].index_doc(name, str(i), {"v": i})
    nodes["n0"].refresh(name)


def _count_search_rpcs(node):
    """Wrap a data node's query-phase handler with a counter."""
    counter = {"n": 0}
    inner = node.transport._handlers[A_SEARCH_SHARDS]

    def counting(payload):
        counter["n"] += 1
        return inner(payload)
    node.transport.register_handler(A_SEARCH_SHARDS, counting)
    return counter


# -- the acceptance bar: slow node gets deranked, queries reroute ----------

def test_delayed_node_deranked_queries_reroute_cleanly(cluster):
    """With n2 fault-injected slow, the coordinator's EWMA spikes, the
    C3 rank deranks every n2 copy, and subsequent searches run entirely
    on healthy replicas: zero `_shards.failures[]`, the reroute counter
    moves, and `adaptive_selection` stats show the deranked node."""
    hub, ids, nodes = cluster
    _make_index(nodes, "ars", 4, 1)
    routing = nodes["n0"].coordinator.state().routing["ars"]
    # a coordinator whose first candidate for some shard IS n2
    coord = next(n for n in ("n0", "n1")
                 if any(e["primary"] == "n2" and n not in copies_of(e)
                        for e in routing))

    faults = FaultInjector(hub, seed=11)
    faults.slow_search_node("n2", 0.3)
    # first search: no evidence yet, legacy order dispatches to n2 —
    # slow but successful, and the coordinator records the spike
    slow = nodes[coord].search("ars", {"query": {"match_all": {}}})
    assert slow["_shards"]["failed"] == 0

    n2_rpcs = _count_search_rpcs(nodes["n2"])
    before = metrics().counter("search.replica_selection.reroutes").value
    resp = nodes[coord].search("ars", {"query": {"match_all": {}},
                                       "size": 30})
    assert resp["hits"]["total"]["value"] == 20
    assert resp["_shards"]["failed"] == 0          # reroute, not failure
    assert n2_rpcs["n"] == 0                       # n2 never dispatched
    assert metrics().counter(
        "search.replica_selection.reroutes").value > before
    stats = nodes[coord].response_collector.stats()
    healthy = [s["rank"] for n, s in stats.items()
               if n != "n2" and s["rank"] is not None]
    assert stats["n2"]["rank"] > max(healthy)      # visibly deranked


def test_scatter_timeout_penalizes_collector_before_failover(cluster):
    """The PR-4-era bug: a timed-out scatter RPC advanced to the next
    copy without teaching the collector anything.  Now the failure
    penalizes the node's EWMA first, so repeated timeouts derank it."""
    hub, ids, nodes = cluster
    _make_index(nodes, "tmo", 2, 1)
    routing = nodes["n0"].coordinator.state().routing["tmo"]
    coord = next(n for n in ("n0", "n1")
                 if any(e["primary"] == "n2" and n not in copies_of(e)
                        for e in routing))
    nodes[coord].search_rpc_timeout = 0.3          # keep the test fast

    faults = FaultInjector(hub, seed=23)
    faults.drop(A_SEARCH_SHARDS, target="n2", times=1, silent=True)
    resp = nodes[coord].search("tmo", {"query": {"match_all": {}},
                                       "size": 30})
    assert resp["_shards"]["failed"] == 0          # failover succeeded
    assert resp["hits"]["total"]["value"] == 20
    st = nodes[coord].response_collector.stats()["n2"]
    assert st["failure_count"] >= 1
    # and the penalty deranks n2 for the follow-up
    n2_rpcs = _count_search_rpcs(nodes["n2"])
    assert nodes[coord].search("tmo", {"query": {"match_all": {}}})[
        "_shards"]["failed"] == 0
    assert n2_rpcs["n"] == 0


# -- the acceptance bar: all copies in duress shed into partial results ----

def test_all_copies_in_duress_sheds_into_partial_results(cluster):
    """Duress progression: the first search learns the primary is in
    duress (piggyback), the second deranks it onto the replica (reroute)
    and learns the replica is drowning too, the third sheds fast into
    `_shards.failures[]` — and once duress clears, traffic resumes."""
    hub, ids, nodes = cluster
    _make_index(nodes, "duress", 1, 1)
    entry = nodes["n0"].coordinator.state().routing["duress"][0]
    primary, replica = entry["primary"], entry["replicas"][0]
    coord = next(i for i in ids if i not in copies_of(entry))
    # the step-by-step progression below requires a coordinator WITHOUT
    # the leader's background ping piggyback (which would teach it both
    # duress flags between searches and shed one step early)
    assert coord != "n0", "allocator change broke this test's setup"
    faults = FaultInjector(hub, seed=7)
    for nid in (primary, replica):
        bp = nodes[nid].search_backpressure
        bp.num_successive_breaches = 1
        faults.induce_search_duress(bp, ticks=1)
        bp.run_once()
        assert bp.in_duress()

    # 1: dispatched to the primary; its duress flag rides back
    r1 = nodes[coord].search("duress", {"query": {"match_all": {}}})
    assert r1["_shards"]["failed"] == 0
    assert nodes[coord].response_collector.in_duress(primary)

    # 2: primary deranked-but-retained → replica serves (a reroute),
    # and now the coordinator knows BOTH copies are drowning
    before = metrics().counter("search.replica_selection.reroutes").value
    r2 = nodes[coord].search("duress", {"query": {"match_all": {}}})
    assert r2["_shards"]["failed"] == 0
    assert metrics().counter(
        "search.replica_selection.reroutes").value > before
    assert nodes[coord].response_collector.in_duress(replica)

    # 3: every in-sync copy in duress → shed fast, no dispatch at all
    sheds_before = metrics().counter(
        "search.replica_selection.sheds").value
    rpcs = {nid: _count_search_rpcs(nodes[nid])
            for nid in (primary, replica)}
    r3 = nodes[coord].search("duress", {"query": {"match_all": {}}})
    assert r3["_shards"]["failed"] == 1
    assert r3["_shards"]["failures"][0]["reason"]["type"] == \
        "node_duress_exception"
    assert r3["hits"]["hits"] == []
    assert metrics().counter(
        "search.replica_selection.sheds").value == sheds_before + 1
    assert all(c["n"] == 0 for c in rpcs.values())

    # all-or-nothing clients are NOT shed: they asked to wait
    r4 = nodes[coord].search("duress", {
        "query": {"match_all": {}}, "size": 30,
        "allow_partial_search_results": False})
    assert r4["_shards"]["failed"] == 0
    assert r4["hits"]["total"]["value"] == 20

    # recovery: duress clears on the data nodes; once the coordinator's
    # flag goes stale it probes again and full service resumes
    for nid in (primary, replica):
        nodes[nid].search_backpressure.run_once()   # streak resets
        assert not nodes[nid].search_backpressure.in_duress()
    nodes[coord].response_collector.duress_ttl_s = 0.05
    time.sleep(0.1)
    r5 = nodes[coord].search("duress", {"query": {"match_all": {}},
                                        "size": 30})
    assert r5["_shards"]["failed"] == 0
    assert r5["hits"]["total"]["value"] == 20
    assert not nodes[coord].response_collector.in_duress(primary)


# -- msearch batch spill ----------------------------------------------------

def test_msearch_spills_batch_across_replicas(cluster):
    """A same-index msearch burst round-robins each shard's healthy
    copies instead of piling every sub-request onto the preferred one."""
    hub, ids, nodes = cluster
    _make_index(nodes, "spill", 1, 1)
    entry = nodes["n0"].coordinator.state().routing["spill"][0]
    coord = next(i for i in ids if i not in copies_of(entry))
    counters = {nid: _count_search_rpcs(nodes[nid])
                for nid in copies_of(entry)}

    body = {"query": {"match_all": {}}, "size": 5}
    out = nodes[coord].msearch("spill", [dict(body) for _ in range(4)])
    assert len(out["responses"]) == 4
    for resp in out["responses"]:
        assert "error" not in resp
        assert resp["hits"]["total"]["value"] == 20
    served = {nid: c["n"] for nid, c in counters.items()}
    assert all(n >= 2 for n in served.values()), served   # both copies


def test_msearch_isolates_per_subrequest_errors(cluster):
    hub, ids, nodes = cluster
    _make_index(nodes, "mix", 1, 0)
    out = nodes["n0"].msearch("mix", [
        {"query": {"match_all": {}}},
        {"query": {"no_such_query": {}}},
        {"query": {"match_all": {}}, "size": 1},
    ])
    assert out["responses"][0]["hits"]["total"]["value"] == 20
    assert "error" in out["responses"][1]
    assert len(out["responses"][2]["hits"]["hits"]) == 1


# -- piggyback freshness + lifecycle ---------------------------------------

def test_fault_detection_pings_refresh_collector(cluster):
    """The leader's follower checks carry each peer's load snapshot, so
    duress/queue stay fresh on an idle coordinator (no search traffic)."""
    hub, ids, nodes = cluster
    nodes["n0"].coordinator.run_checks_once()
    stats = nodes["n0"].response_collector.stats()
    assert {"n1", "n2"} <= set(stats)
    for nid in ("n1", "n2"):
        assert stats[nid]["avg_queue_size"] is not None
        assert stats[nid]["rank"] is None    # pings alone never rank
    # a follower's leader check refreshes ITS view of the leader
    nodes["n1"].coordinator.run_checks_once()
    assert "n0" in nodes["n1"].response_collector.stats()


def test_evicted_node_loses_its_stats(cluster):
    hub, ids, nodes = cluster
    nodes["n0"].coordinator.run_checks_once()
    assert "n2" in nodes["n0"].response_collector.tracked()
    FaultInjector(hub, seed=5).disconnect("n2")
    retries = nodes["n0"].coordinator.follower_checker.settings.retries
    for _ in range(retries):
        nodes["n0"].coordinator.run_checks_once()
    assert wait_until(
        lambda: "n2" not in nodes["n0"].coordinator.state().nodes)
    assert wait_until(
        lambda: "n2" not in nodes["n0"].response_collector.tracked())


def test_monitor_thread_wired_into_cluster_node_lifecycle(tmp_path):
    """ClusterNode.start() runs the backpressure monitor (duress is
    detected between admissions); stop() joins it promptly."""
    hub = LocalTransport.Hub()
    svc = TransportService("solo", LocalTransport(hub))
    node = ClusterNode("solo", str(tmp_path / "solo"), svc, ["solo"])
    assert not node.search_backpressure.monitor_alive()
    node.start()
    assert node.search_backpressure.monitor_alive()
    done = threading.Event()

    def stop():
        node.stop()
        done.set()
    threading.Thread(target=stop, daemon=True).start()
    assert done.wait(timeout=8.0), "ClusterNode.stop() hung"
    assert wait_until(
        lambda: not node.search_backpressure.monitor_alive(), timeout=6.0)


# -- REST + settings surfaces ----------------------------------------------

@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "node"), port=0)
    yield n
    n.stop()


def test_nodes_stats_exposes_adaptive_selection(node):
    node.response_collector.record_response(
        "peer", 5e6, {"queue_size": 2, "duress": True,
                      "service_time_ewma_nanos": 4e6})
    status, resp = node.rest.dispatch("GET", "/_nodes/stats", {}, None)
    assert status == 200
    block = resp["nodes"][node.node_id]["adaptive_selection"]
    assert block["nodes"]["peer"]["in_duress"] is True
    assert block["nodes"]["peer"]["avg_response_time_ms"] == \
        pytest.approx(5.0)
    assert {"reroutes", "sheds"} <= set(block)


def test_cat_nodes_shows_ranks(node):
    node.response_collector.record_response(
        "peer", 5e6, {"service_time_ewma_nanos": 4e6})
    status, rows = node.rest.dispatch("GET", "/_cat/nodes", {}, None)
    assert status == 200
    by_name = {r["name"]: r for r in rows}
    assert by_name[node.name]["master"] == "*"
    assert by_name[node.name]["search.rank"] == "-"   # no samples on self
    assert float(by_name["peer"]["search.rank"]) > 0
    assert by_name["peer"]["search.duress"] == "false"


def test_replica_selection_dynamic_settings(node):
    try:
        assert rc.ADAPTIVE_ENABLED is True and rc.SHED_ON_DURESS is True
        node.update_cluster_settings(transient={
            "search.replica_selection.adaptive": False,
            "search.replica_selection.shed_on_duress": False})
        assert rc.ADAPTIVE_ENABLED is False
        assert rc.SHED_ON_DURESS is False
        node.update_cluster_settings(transient={
            "search.replica_selection.adaptive": None,
            "search.replica_selection.shed_on_duress": None})
        assert rc.ADAPTIVE_ENABLED is True and rc.SHED_ON_DURESS is True
    finally:
        rc.ADAPTIVE_ENABLED = True       # module globals: always restore
        rc.SHED_ON_DURESS = True


def test_adaptive_disabled_keeps_legacy_order(tmp_path):
    """search.replica_selection.adaptive=false reverts _copy_candidates
    to the static local→primary→replicas order, evidence or not."""
    hub = LocalTransport.Hub()
    svc = TransportService("a", LocalTransport(hub))
    node = ClusterNode("a", str(tmp_path / "a"), svc, ["a"])
    try:
        node.response_collector.record_response(
            "c", 300e6, {"service_time_ewma_nanos": 300e6})
        node.response_collector.record_response(
            "b", 1e6, {"service_time_ewma_nanos": 1e6})
        entry = {"primary": "c", "replicas": ["b"],
                 "in_sync": ["c", "b"], "primary_term": 1}
        assert node._copy_candidates(entry) == ["b", "c"]   # ranked
        rc.ADAPTIVE_ENABLED = False
        try:
            assert node._copy_candidates(entry) == ["c", "b"]  # legacy
        finally:
            rc.ADAPTIVE_ENABLED = True   # module global: always restore
    finally:
        node.stop()


# -- monotonic/injectable-clock lint (tier-1 CI hook) ----------------------

def test_check_monotonic_lint_passes_repo():
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_monotonic.py"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_check_monotonic_strict_clock_rule(tmp_path):
    """cluster/response_collector.py is an injectable-clock module: a
    naked time.monotonic reference fails the lint; the annotated default
    parameter passes."""
    pkg = tmp_path / "cluster"
    pkg.mkdir()
    (pkg / "response_collector.py").write_text(
        "import time\n"
        "def bad():\n"
        "    return time.monotonic()\n"
        "def ok(clock=time.monotonic):  # clock-default\n"
        "    return clock()\n")
    (tmp_path / "other.py").write_text(
        "import time\nt = time.monotonic()\n")   # non-strict module: fine
    out = subprocess.run(
        [sys.executable, TOOLS + "/check_monotonic.py", str(tmp_path)],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert "response_collector.py:3" in out.stdout
    assert "response_collector.py:4" not in out.stdout
    assert "other.py" not in out.stdout
