"""Can-match segment skipping, search profiling, slow logs (SURVEY §5
long-context analog + observability; ref CanMatchPreFilterSearchPhase.java:73,
search/profile/, index/SearchSlowLog.java:61)."""

import logging

import numpy as np
import pytest

from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.compiler import compile_query
from opensearch_tpu.search.executor import ShardSearcher
from opensearch_tpu.search.query_dsl import parse_query

MAPPING = {"properties": {"t": {"type": "text"}, "ts": {"type": "long"}}}


def build():
    mapper = DocumentMapper(MAPPING)
    writer = SegmentWriter()
    segs = []
    for si in range(4):
        docs = [mapper.parse(f"{si}-{i}",
                             {"t": f"seg{si} common word{si}_{i}",
                              "ts": si * 1000 + i})
                for i in range(10)]
        segs.append(writer.build(docs, f"cm{si}"))
    return ShardSearcher(segs, mapper), mapper


def test_can_match_range_prunes_segments():
    searcher, _ = build()
    plan, bind = compile_query(parse_query(
        {"range": {"ts": {"gte": 2000, "lt": 3000}}}), searcher.ctx,
        scored=False)
    matches = [plan.can_match(bind, seg) for seg in searcher.segments]
    assert matches == [False, False, True, False]
    # results identical to the unpruned semantics
    resp = searcher.search({"query": {"range": {"ts": {"gte": 2000,
                                                       "lt": 3000}}},
                            "size": 50})
    assert resp["hits"]["total"]["value"] == 10
    assert all(h["_id"].startswith("2-") for h in resp["hits"]["hits"])


def test_can_match_terms_and_phrase():
    searcher, _ = build()
    # a term unique to segment 1 prunes the other three
    plan, bind = compile_query(parse_query(
        {"match": {"t": "seg1"}}), searcher.ctx)
    assert [plan.can_match(bind, seg)
            for seg in searcher.segments] == [False, True, False, False]
    # AND across terms from different segments can never match
    plan, bind = compile_query(parse_query(
        {"match": {"t": {"query": "seg0 seg1", "operator": "and"}}}),
        searcher.ctx)
    assert not any(plan.can_match(bind, seg)
                   for seg in searcher.segments)
    resp = searcher.search({"query": {"match": {
        "t": {"query": "seg0 seg1", "operator": "and"}}}})
    assert resp["hits"]["total"]["value"] == 0
    # bool filter prunes through composition
    plan, bind = compile_query(parse_query({"bool": {
        "must": [{"match": {"t": "common"}}],
        "filter": [{"range": {"ts": {"gte": 3000}}}]}}), searcher.ctx)
    assert [plan.can_match(bind, seg)
            for seg in searcher.segments] == [False, False, False, True]
    # phrase needs every term
    plan, bind = compile_query(parse_query(
        {"match_phrase": {"t": "seg2 common"}}), searcher.ctx)
    assert [plan.can_match(bind, seg)
            for seg in searcher.segments] == [False, False, True, False]


def test_profile_response_shape():
    searcher, _ = build()
    resp = searcher.search({"query": {"match": {"t": "common"}},
                            "profile": True})
    prof = resp["profile"]["shards"][0]
    q = prof["searches"][0]["query"][0]
    assert q["type"] == "TermBagPlan"
    assert q["time_in_nanos"] > 0
    assert "common" in q["description"]


def test_search_slowlog(tmp_path, caplog):
    from opensearch_tpu.indices.service import IndexService

    svc = IndexService("slow", str(tmp_path / "slow"),
                       {"search.slowlog.threshold.query.warn": "0ms"},
                       {"properties": {"t": {"type": "text"}}})
    svc.index_doc("1", {"t": "hello"})
    svc.refresh()
    with caplog.at_level(logging.WARNING,
                         logger="opensearch_tpu.index.search.slowlog"):
        svc.search({"query": {"match": {"t": "hello"}}})
    assert any("took" in r.message or "took" in r.getMessage()
               for r in caplog.records)
    # disabled threshold logs nothing
    svc2 = IndexService("fast", str(tmp_path / "fast"), {},
                        {"properties": {"t": {"type": "text"}}})
    svc2.index_doc("1", {"t": "hello"})
    svc2.refresh()
    with caplog.at_level(logging.WARNING,
                         logger="opensearch_tpu.index.search.slowlog"):
        n_before = len(caplog.records)
        svc2.search({"query": {"match": {"t": "hello"}}})
    assert len(caplog.records) == n_before
