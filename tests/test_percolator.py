"""Percolator: index queries, match documents against them
(ref modules/percolator)."""

import pytest

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.index.segment import SegmentWriter
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher

MAPPING = {"properties": {
    "query": {"type": "percolator"},
    "title": {"type": "text"},
    "price": {"type": "long"},
}}

QUERIES = [
    {"query": {"match": {"title": "laptop"}}},
    {"query": {"bool": {"must": [{"match": {"title": "phone"}},
                                 {"range": {"price": {"lte": 500}}}]}}},
    {"query": {"range": {"price": {"gte": 1000}}}},
]


@pytest.fixture(scope="module")
def searcher():
    mapper = DocumentMapper(MAPPING)
    writer = SegmentWriter()
    seg = writer.build([mapper.parse(str(i), q)
                        for i, q in enumerate(QUERIES)], "perc0")
    return ShardSearcher([seg], mapper)


def ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


def test_percolate_matches_stored_queries(searcher):
    resp = searcher.search({"query": {"percolate": {
        "field": "query",
        "document": {"title": "new laptop stand", "price": 30}}},
        "size": 10})
    assert ids(resp) == ["0"]
    resp = searcher.search({"query": {"percolate": {
        "field": "query",
        "document": {"title": "budget phone", "price": 199}}},
        "size": 10})
    assert ids(resp) == ["1"]
    resp = searcher.search({"query": {"percolate": {
        "field": "query",
        "document": {"title": "luxury phone", "price": 1200}}},
        "size": 10})
    assert ids(resp) == ["2"]               # price>=1000, phone>500
    # multiple candidate documents: any match counts
    resp = searcher.search({"query": {"percolate": {
        "field": "query",
        "documents": [{"title": "boring desk"},
                      {"title": "gaming laptop", "price": 2000}]}},
        "size": 10})
    assert ids(resp) == ["0", "2"]


def test_percolator_field_validates_at_index_time():
    mapper = DocumentMapper(MAPPING)
    with pytest.raises(OpenSearchTpuError):
        mapper.parse("bad", {"query": {"no_such_query": {}}})


def test_percolate_errors(searcher):
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"percolate": {
            "field": "title", "document": {"x": 1}}}})
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"percolate": {"field": "query"}}})


def test_percolate_isolation_and_malformed(searcher):
    """Review regressions: candidate docs never mutate the live mapping;
    non-dict stored values never match; non-dict candidates are 400."""
    before = set(searcher.mapper.field_types())
    searcher.search({"query": {"percolate": {
        "field": "query",
        "document": {"brand_new_field": 42, "title": "laptop"}}},
        "size": 10})
    assert set(searcher.mapper.field_types()) == before
    with pytest.raises(OpenSearchTpuError):
        searcher.search({"query": {"percolate": {
            "field": "query", "documents": ["nope"]}}})


def test_percolator_rejects_query_arrays():
    mapper = DocumentMapper(MAPPING)
    with pytest.raises(OpenSearchTpuError):
        mapper.parse("multi", {"query": [
            {"match": {"title": "a"}}, {"match": {"title": "b"}}]})
