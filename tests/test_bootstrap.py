"""Bootstrap checks (ref bootstrap/BootstrapChecks.java:70): warn in dev
mode, abort with ALL failures listed in production mode."""

import pytest

from opensearch_tpu.bootstrap import (BootstrapCheck, BootstrapCheckError,
                                      default_checks,
                                      run_bootstrap_checks)
from opensearch_tpu.node import Node


def test_default_checks_run_and_report_cleanly(tmp_path):
    """Host limits differ per machine (this container ships a low
    vm.max_map_count, for instance) — assert the probes run and any
    failure is a well-formed actionable message, not that this
    particular host is production-ready."""
    fails = run_bootstrap_checks(default_checks(str(tmp_path)),
                                 enforce=False)
    for f in fails:
        assert f.startswith("[") and (
            "too low" in f or "unavailable" in f or "not writable" in f
            or "could not run" in f)
    names = {c.name for c in default_checks(str(tmp_path))}
    assert names == {"file descriptors", "vm.max_map_count",
                     "max threads", "data path writable",
                     "accelerator runtime"}


def test_enforce_reports_all_failures():
    checks = [BootstrapCheck("ok", lambda: None),
              BootstrapCheck("a", lambda: "first problem"),
              BootstrapCheck("b", lambda: "second problem")]
    with pytest.raises(BootstrapCheckError) as e:
        run_bootstrap_checks(checks, enforce=True)
    msg = str(e.value)
    assert "[a] first problem" in msg and "[b] second problem" in msg


def test_dev_mode_warns_instead_of_raising(caplog):
    import logging

    checks = [BootstrapCheck("a", lambda: "problem")]
    with caplog.at_level(logging.WARNING,
                         logger="opensearch_tpu.bootstrap"):
        fails = run_bootstrap_checks(checks, enforce=False)
    assert fails == ["[a] problem"]
    assert any("dev mode" in r.message for r in caplog.records)


def test_broken_probe_is_a_failure():
    def boom():
        raise OSError("probe exploded")

    fails = run_bootstrap_checks([BootstrapCheck("x", boom)],
                                 enforce=False)
    assert fails and "could not run" in fails[0]


def test_node_start_enforces_checks(tmp_path, monkeypatch):
    """Node.start wiring: enforce mode aborts boot on a failing check,
    dev (loopback) mode starts anyway.  The failing check is injected —
    real host limits vary by machine (and root bypasses permission-bit
    probes)."""
    import opensearch_tpu.bootstrap as bootstrap

    monkeypatch.setattr(
        bootstrap, "default_checks",
        lambda path: [BootstrapCheck("injected", lambda: "bad host")])
    monkeypatch.setenv("OSTPU_ENFORCE_BOOTSTRAP", "1")
    with pytest.raises(BootstrapCheckError) as e:
        Node(str(tmp_path / "n1"), port=0).start()
    assert "[injected] bad host" in str(e.value)
    # loopback dev mode: same failing check only warns
    monkeypatch.delenv("OSTPU_ENFORCE_BOOTSTRAP")
    n = Node(str(tmp_path / "n2"), port=0).start()
    try:
        assert n.port > 0
    finally:
        n.stop()
